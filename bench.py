"""Benchmark: FM training-step throughput (examples/sec) on one chip.

Measures the full fused SGD hot path — gather [w,V] rows, FM forward
(SpMV + 2×SpMM sum-of-squares), logit objective + AUC, backward, FTRL/AdaGrad
scatter update — on synthetic Criteo-like batches (V_dim=64, ~39 nnz/row),
the north-star config of BASELINE.md.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against an *estimated* 32-worker ps-lite CPU
aggregate throughput on the same workload (the reference publishes no numbers
— BASELINE.json.published is empty; see BASELINE.md). Estimate: 32 workers ×
~15k examples/s/worker for FM V_dim=64 ≈ 5e5 examples/s. The driver-set target
is vs_baseline >= 20 on a full v5e-8 (i.e. >= 2.5 per chip × 8).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

# estimated 32-worker ps-lite CPU examples/sec on Criteo FM V_dim=64 (see
# module docstring; the reference repo publishes no quantitative baseline)
REF_PSLITE_32W_EPS = 5.0e5


def build_step(V_dim: int, capacity: int):
    import jax

    from difacto_tpu.losses import create
    from difacto_tpu.step import make_step_fns
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                                  make_fns)

    param = SGDUpdaterParam(V_dim=V_dim, V_threshold=0, lr=0.1, l1=1e-4,
                            l2=1e-4)
    fns = make_fns(param)
    loss = create("fm", V_dim)
    state = init_state(param, capacity)
    if V_dim:
        import jax.numpy as jnp
        state = state._replace(v_live=jnp.ones(capacity, dtype=bool))

    _, train_step, _ = make_step_fns(fns, loss)
    # raw (unjitted) step: bench runs it inside its own jitted lax.scan;
    # callers wanting a standalone step should jit it themselves
    return train_step, state


def make_batches(n: int, B: int, nnz_per_row: int, U: int, capacity: int,
                 seed: int = 0):
    """Pre-generate host-side localized batches (COO + slot vectors)."""
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.ops.batch import pad_batch

    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        offset = np.arange(B + 1, dtype=np.int64) * nnz_per_row
        index = rng.randint(0, U, B * nnz_per_row).astype(np.uint32)
        blk = RowBlock(
            offset=offset,
            label=rng.choice([0.0, 1.0], B).astype(np.float32),
            index=index,
            value=None,  # binary features, like criteo
        )
        batch = pad_batch(blk, num_uniq=U, batch_cap=B,
                          nnz_cap=B * nnz_per_row)
        slots = (rng.permutation(capacity - 1)[:U] + 1).astype(np.int32)
        out.append((batch, np.sort(slots)))
    return out


def run_e2e(args) -> None:
    """End-to-end mode: generate criteo-format text, train FM through the
    full stack (native parse -> localize -> slot map -> fused step) and
    report pipeline examples/sec — the honest number including host work."""
    import tempfile
    import time as _t

    from difacto_tpu.learners import Learner

    rng = np.random.RandomState(0)
    nrows = args.e2e_rows
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/criteo.txt"
        with open(path, "w") as f:
            for _ in range(nrows):
                ints = "\t".join(str(rng.randint(0, 1000))
                                 for _ in range(13))
                cats = "\t".join(f"c{rng.randint(0, 100000):x}"
                                 for _ in range(26))
                f.write(f"{rng.randint(0, 2)}\t{ints}\t{cats}\n")

        learner = Learner.create("sgd")
        learner.init([("data_in", path), ("data_format", "criteo"),
                      ("loss", "fm"), ("V_dim", str(args.vdim)),
                      ("V_threshold", "0"), ("lr", "0.1"), ("l1", "1e-4"),
                      ("batch_size", str(args.batch_size)), ("shuffle", "0"),
                      ("max_num_epochs", "1"), ("num_jobs_per_epoch", "1"),
                      ("report_interval", "0"), ("stop_rel_objv", "0"),
                      ("hash_capacity", str(args.capacity))])
        t0 = _t.perf_counter()
        learner.run()
        dt = _t.perf_counter() - t0
    eps = nrows / dt
    print(json.dumps({
        "metric": "fm_e2e_criteo_examples_per_sec",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": round(eps / REF_PSLITE_32W_EPS, 3),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8192)
    ap.add_argument("--vdim", type=int, default=64)
    ap.add_argument("--nnz-per-row", type=int, default=39)  # criteo density
    ap.add_argument("--uniq", type=int, default=1 << 17)
    ap.add_argument("--capacity", type=int, default=1 << 21)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--e2e", action="store_true",
                    help="full text->train pipeline instead of device step")
    ap.add_argument("--e2e-rows", type=int, default=100_000)
    args = ap.parse_args()

    if args.e2e:
        run_e2e(args)
        return

    import jax
    import jax.numpy as jnp

    step, state = build_step(args.vdim, args.capacity)
    host_batches = make_batches(8, args.batch_size, args.nnz_per_row,
                                args.uniq, args.capacity)

    # stack the batches on device and run ALL steps inside one lax.scan:
    # a single dispatch + single block_until_ready, so the measurement is
    # pure device execution (host dispatch / tunnel RTT per step would
    # otherwise dominate or, worse, under-report an async chain)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[b for b, _ in host_batches])
    slots = jnp.stack([jnp.asarray(s) for _, s in host_batches])
    n_bk = len(host_batches)

    def scan_body(state, i):
        batch = jax.tree_util.tree_map(lambda x: x[i % n_bk], stacked)
        state, objv, auc = step(state, batch, slots[i % n_bk])
        return state, objv

    @jax.jit
    def run_steps(state):
        return jax.lax.scan(scan_body, state,
                            jnp.arange(args.steps, dtype=jnp.int32))

    # warmup / compile
    state, objvs = run_steps(state)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    state, objvs = run_steps(state)
    jax.block_until_ready((state, objvs))
    dt = time.perf_counter() - t0

    eps = args.steps * args.batch_size / dt
    print(json.dumps({
        "metric": "fm_v64_train_examples_per_sec",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": round(eps / REF_PSLITE_32W_EPS, 3),
    }))


if __name__ == "__main__":
    main()
