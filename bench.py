"""Benchmark: FM training-step throughput (examples/sec) on one chip.

Measures the full fused SGD hot path — gather [w,V] rows, FM forward
(SpMV + 2xSpMM sum-of-squares), logit objective + AUC, backward, FTRL/AdaGrad
scatter update — on synthetic Criteo-like batches (V_dim=64, 39 nnz/row),
the north-star config of BASELINE.md.

Defaults reflect the TPU-native operating point: batch 65536 (synchronous
large-batch steps replace the reference's 50-worker async pipelining,
SURVEY §7 hard part (b); distinct-feature rows saturate, so the per-row
table costs amortize), zipf-skewed feature draws (criteo categoricals are
heavy-tailed; --dist uniform gives the adversarial flat draw), bfloat16
embedding storage (V_dtype).

Prints ONE JSON line. ``vs_baseline`` compares against an *estimated*
32-worker ps-lite CPU aggregate (the reference publishes no numbers —
BASELINE.json.published is empty): 32 workers x ~15k ex/s/worker for FM
V_dim=64 ~= 5e5 ex/s. The driver-set target is vs_baseline >= 20 on a full
v5e-8 (>= 2.5 per chip x 8). ``roofline`` reports the step's HBM traffic
against this chip's measured ~87 GiB/s streaming bandwidth so progress is
measurable without the baseline fiction.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

# estimated 32-worker ps-lite CPU examples/sec on Criteo FM V_dim=64 (see
# module docstring; the reference repo publishes no quantitative baseline)
REF_PSLITE_32W_EPS = 5.0e5
MEASURED_HBM_GBPS = 87.0  # 1GiB stream mul+reduce, this chip via tunnel


def build_step(V_dim: int, capacity: int, v_dtype: str,
               chunks_sorted: bool = True, fused_kernel: str = "auto",
               mesh=None):
    import dataclasses

    from difacto_tpu.losses import create
    from difacto_tpu.step import make_step_fns
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                                  make_fns)

    param = SGDUpdaterParam(V_dim=V_dim, V_threshold=0, lr=0.1, l1=1e-4,
                            l2=1e-4, V_dtype=v_dtype,
                            fused_kernel=fused_kernel)
    fns = make_fns(param, mesh=mesh)
    loss = create("fm", V_dim)
    if not chunks_sorted:
        loss = dataclasses.replace(loss, chunks_sorted=False)
    state = init_state(param, capacity)
    if V_dim:
        from difacto_tpu.updaters.sgd_updater import set_all_live
        state = set_all_live(param, state)

    # under a mesh the train step must pin its returned state to the fs
    # key-range layout (step.state_constrainer) — otherwise GSPMD output
    # inference is free to re-partition the donated table (the bench
    # would silently measure an unpinned program the product never runs)
    state_shardings = None
    if mesh is not None:
        from difacto_tpu.parallel import sharding_tree, state_sharding
        state_shardings = sharding_tree(state, state_sharding(mesh))
    _, train_step, _ = make_step_fns(fns, loss,
                                     state_shardings=state_shardings)
    # raw (unjitted) step: the bench jits it with a donated state and
    # dispatches per step, the production replay pattern
    return train_step, state, fns, loss, param


def make_batches(n: int, B: int, nnz_per_row: int, uniq_space: int,
                 capacity: int, dist: str, seed: int = 0,
                 chunk_multiple: int = 1):
    """Host-side localized PANEL batches (fixed-width [B, F] index matrix,
    the criteo layout) + sorted-unique slot vectors padded with ascending
    out-of-bounds indices (the device-kernel contract).
    ``chunk_multiple`` > 1 pads the chunk arrays' C axis up to a multiple
    (mesh runs shard C over the dp axis, which needs even division)."""
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.ops.batch import bucket, pad_panel
    from difacto_tpu.store.local import pad_slots_oob

    rng = np.random.RandomState(seed)
    raw = []
    u_cap = 8
    for _ in range(n):
        if dist == "zipf":
            idx = ((rng.zipf(1.25, B * nnz_per_row) - 1)
                   % uniq_space).astype(np.int64)
        else:
            idx = rng.randint(0, uniq_space, B * nnz_per_row)
        uniq, inverse = np.unique(idx, return_inverse=True)
        raw.append((uniq, inverse))
        u_cap = max(u_cap, bucket(len(uniq)))

    import jax
    import jax.numpy as jnp

    from difacto_tpu.ops.batch import panel_chunk_tokens
    chunker = jax.jit(panel_chunk_tokens, static_argnums=(1,))

    out = []
    for uniq, inverse in raw:
        offset = np.arange(B + 1, dtype=np.int64) * nnz_per_row
        blk = RowBlock(
            offset=offset,
            label=rng.choice([0.0, 1.0], B).astype(np.float32),
            index=inverse.astype(np.uint32),
            value=None,  # binary features, like criteo
        )
        batch = pad_panel(blk, num_uniq=len(uniq), batch_cap=B,
                          width=nnz_per_row)
        # chunked-run backward layout: the bench models the steady-state
        # cached replay, which stages the layout once (panel_chunk_tokens)
        # and takes the chunked FM backward every step
        if chunk_multiple > 1:
            # mesh runs shard the C axis over dp: build host-side with C
            # rounded up (the same path learners/sgd.py _panel_host_batch
            # takes), instead of the device chunker
            from difacto_tpu.ops.batch import (chunk_cap,
                                               panel_chunk_tokens_np)
            C = -(-chunk_cap(u_cap, B * nnz_per_row) // chunk_multiple) \
                * chunk_multiple
            ci, cl, cv = panel_chunk_tokens_np(
                inverse.astype(np.int32), None, u_cap, B, nnz_per_row, C=C)
            batch = batch._replace(chunk_idx=jnp.asarray(ci),
                                   chunk_lane=jnp.asarray(cl),
                                   chunk_vals=cv)
        else:
            batch = chunker(batch, u_cap)
        slots = np.sort(rng.permutation(capacity - 1)[:len(uniq)] + 1)
        out.append((batch, pad_slots_oob(slots.astype(np.int32), u_cap,
                                         capacity)))
    return out


def roofline(nnz: int, u_cap: int, V_dim: int, v_bytes: int,
             dt_sec: float, vvg_cols: int = 0) -> dict:
    """Approximate HBM bytes moved per step vs measured stream bandwidth.

    Models the production step as benched: storage-dtype forward token
    gather + the CHUNKED backward (docs/perf_notes.md) whose f32
    [~nnz, V_dim+1] contribution stream moves once through the chunk
    gather and once through the partial reduction, plus the chunk-layout
    index reads. ``vvg_cols`` is the ACTUAL stored row width (pad_v_rows
    lane-pads narrow V to the 128-lane tile; defaults to the compact
    2*V_dim)."""
    if not vvg_cols:
        vvg_cols = 2 * V_dim
    # fused-row g+s: the row carries V, Vg AND the FTRL scalar lanes
    # (updaters/sgd_updater.py row_layout), so there is no separate
    # scalar-table term; V_dim=0 keeps the flat f32 w/z/sqrt_g arrays
    table = (u_cap * vvg_cols * v_bytes * 2 if V_dim
             else u_cap * 3 * 4 * 2)
    tokens = (nnz * (V_dim + 1) * v_bytes      # fwd [w|V] token gather
              + nnz * (V_dim + 1) * 4 * 2      # bwd f32 contribs (chunk
                                               # gather + partial reduce)
              + nnz * 4 * 2)                   # chunk_idx/lane reads (~)
    total = table + tokens
    return {
        "approx_bytes_per_step": int(total),
        "achieved_gbps": round(total / dt_sec / 1e9, 1),
        "stream_bw_gbps_this_chip": MEASURED_HBM_GBPS,
        "bw_fraction": round(total / dt_sec / 1e9 / MEASURED_HBM_GBPS, 3),
    }


def run_kernel_bench(args, host_batches, nnz: int) -> dict:
    """``kernel`` block (ISSUE 13 satellite): per-backend roofline
    attribution of the fused v64 step. For every available
    ``fused_kernel`` backend the FULL step is timed fresh (own table,
    donated dispatch chain — same harness as the headline), emitting
    examples/sec + ``bw_fraction``; then the step is split into its
    four legs — dedup / gather / interaction (forward+backward from
    pre-gathered rows) / scatter-update — each as its own jitted
    program over the same staged batches, so BENCH_r* attributes the
    roofline gap to a leg instead of guessing. Pallas is included only
    on TPU backends (interpret mode is a parity harness, not a perf
    number)."""
    import jax
    import jax.numpy as jnp

    from difacto_tpu.losses import FMParams
    from difacto_tpu.ops import fused as fused_ops
    from difacto_tpu.utils import jaxtrace

    v_bytes = 2 if args.vdtype == "bfloat16" else 4
    backends = ["off", "jnp"]
    if fused_ops.pallas_importable() and not fused_ops.interpret_mode():
        backends.append("pallas")
    steps = args.steps
    out: dict = {"requested": args.fused_kernel, "backends": {},
                 "measured": backends}

    def _chain(step, state, batches, slots_l):
        state, objv, _ = step(state, batches[0], slots_l[0])
        jaxtrace.fetch(objv, point="bench.fence")
        t0 = time.perf_counter()
        for i in range(steps):
            state, objv, _ = step(state, batches[i % len(batches)],
                                  slots_l[i % len(slots_l)])
        jaxtrace.fetch(objv, point="bench.fence")
        return time.perf_counter() - t0, state

    u_cap = len(host_batches[0][1])
    for b in backends:
        step_raw, state, _, _, _ = build_step(
            args.vdim, args.capacity, args.vdtype, fused_kernel=b)
        # lint: ok(jax-recompile) one jit per BACKEND leg — this loop
        # IS the kernel-bench matrix (off/jnp/pallas); each leg
        # compiles exactly once by construction
        step = jax.jit(step_raw, donate_argnums=0)
        batches = [jax.device_put(bb) for bb, _ in host_batches]
        slots_l = [jnp.asarray(s) for _, s in host_batches]
        dt, state = _chain(step, state, batches, slots_l)
        vvg_cols = int(state.VVg.shape[1])
        del state
        roof = roofline(args.batch_size * nnz, u_cap, args.vdim,
                        v_bytes, dt / steps, vvg_cols=vvg_cols)
        out["backends"][b] = {
            "examples_per_sec": round(steps * args.batch_size / dt, 1),
            "bw_fraction": roof["bw_fraction"],
            "approx_bytes_per_step": roof["approx_bytes_per_step"],
        }

    # ------------------------------------------------------------ legs
    resolved = fused_ops.resolve_backend(
        args.fused_kernel if args.fused_kernel != "off" else "auto",
        V_dim=args.vdim)
    step_raw, state, fns, loss, param = build_step(
        args.vdim, args.capacity, args.vdtype, fused_kernel=resolved)
    batches = [jax.device_put(bb) for bb, _ in host_batches]
    slots_l = [jnp.asarray(s) for _, s in host_batches]
    # token lanes in table-slot space: the device-dedup leg's input
    toks = [jnp.asarray(np.asarray(s)[np.asarray(bb.idx).reshape(-1)])
            for bb, s in host_batches]

    dedup_fn = jax.jit(
        lambda t: fused_ops.dedup_tokens(t, u_cap, args.capacity))
    gather_fn = jax.jit(
        lambda T, s: fused_ops.gather_rows(T, s, resolved))

    def interact(state, rows, pb):
        w, V, vm = fns.rows_to_params(state, rows)
        params = FMParams(w=w, V=V, v_mask=vm)
        pred, xv = loss.predict_xv(params, pb)
        objv = loss.evaluate(pred, pb)
        gw, gV = loss.calc_grad(params, pb, pred, xv)
        return objv, gw, gV, vm

    interact_fn = jax.jit(interact)
    scatter_fn = jax.jit(fns.apply_grad_rows, donate_argnums=0)

    n_bk = len(batches)
    rows_l = [gather_fn(state.VVg, s) for s in slots_l]
    grads_l = [interact_fn(state, rows_l[i], batches[i])
               for i in range(n_bk)]

    def _leg(fn, argsets, fence):
        # warm + chain like the headline: async dispatch pipelines the
        # RTT, the scalar fetch is the completion fence
        r = fn(*argsets[0])
        jaxtrace.fetch(fence(r), point="bench.fence")
        t0 = time.perf_counter()
        for i in range(steps):
            r = fn(*argsets[i % len(argsets)])
        jaxtrace.fetch(fence(r), point="bench.fence")
        return (time.perf_counter() - t0) / steps * 1e3

    legs = {
        "dedup_ms": _leg(dedup_fn, [(t,) for t in toks],
                         lambda r: r[2]),
        "gather_ms": _leg(gather_fn,
                          [(state.VVg, s) for s in slots_l],
                          lambda r: r[0, 0]),
        "interaction_ms": _leg(
            interact_fn,
            [(state, rows_l[i], batches[i]) for i in range(n_bk)],
            lambda r: r[0]),
    }
    # scatter leg donates/rebinds the table state
    _, gw0, gV0, vm0 = grads_l[0]
    st = state
    st = scatter_fn(st, slots_l[0], rows_l[0], gw0, gV0, vm0)
    jaxtrace.fetch(fns.evaluate(st)[0], point="bench.fence")
    t0 = time.perf_counter()
    for i in range(steps):
        j = i % n_bk
        _, gw_i, gV_i, vm_i = grads_l[j]
        st = scatter_fn(st, slots_l[j], rows_l[j], gw_i, gV_i, vm_i)
    jaxtrace.fetch(fns.evaluate(st)[0], point="bench.fence")
    legs["scatter_ms"] = (time.perf_counter() - t0) / steps * 1e3
    out["legs_ms"] = {k: round(v, 3) for k, v in legs.items()}
    out["legs_backend"] = resolved
    return out


def _gen_criteo_text(path: str, nrows: int, seed: int = 0) -> None:
    """Vectorised synthetic criteo-format text (zipf-skewed categoricals)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 2, nrows).astype(str)
    ints = rng.randint(0, 1000, (nrows, 13)).astype(str)
    cats_raw = ((rng.zipf(1.25, (nrows, 26)) - 1) % 100000)
    cats = np.char.add("c", cats_raw.astype(str))
    cols = np.concatenate([labels[:, None], ints, cats], axis=1)
    with open(path, "w") as f:
        f.write("\n".join("\t".join(r) for r in cols) + "\n")


def run_e2e(args) -> dict:
    """End-to-end mode: criteo text -> rec binary cache (task=convert, the
    reference's CRB fast path, members aligned to the training batch size)
    -> training through the full stack (rec read -> hashed localize ->
    panel pack -> fused step). Reports BOTH steady-state regimes (round-4
    verdict weak #2 — the 1TB config cannot replay from HBM, so the
    streamed rate is the honest number at scale):

      replay   : epochs 1+ replay device-cached packed batches from HBM
                 (zero host->device traffic) — the small/cached-dataset
                 regime;
      streamed : device_cache_mb=0, every epoch runs the full host pack +
                 transfer + step pipeline — the >HBM-dataset regime.

    Epoch 0 (jit compiles + staging) is excluded from both."""
    import tempfile
    import time as _t

    from difacto_tpu.data.converter import Converter
    from difacto_tpu.learners import Learner

    nrows = args.e2e_rows
    epochs = 4
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/criteo.txt"
        _gen_criteo_text(path, nrows)

        conv = Converter()
        conv.init([("data_in", path), ("data_format", "criteo"),
                   ("data_out", f"{d}/criteo.rec"),
                   ("data_out_format", "rec"),
                   # align members to the training batch so cached batches
                   # never straddle members and shapes stay on the pinned
                   # schedule (round-3 verdict #1c)
                   ("rec_batch_size", str(args.e2e_batch))])
        conv.run()
        # per-stage convert accounting (ISSUE 7 satellite): Converter.run
        # fills stats with rows/eps/convert_s plus parse_s/write_s and the
        # worker-process count, so a convert regression localizes to a
        # stage just like the streamed epochs do
        convert_stats = dict(conv.stats)
        convert_eps = convert_stats.get("eps", 0.0)

        def train(cache_mb: int, n_epochs: int,
                  producer_mode: str = "thread"):
            learner = Learner.create("sgd")
            learner.init([("data_in", f"{d}/criteo.rec"),
                          ("data_format", "rec"),
                          ("loss", "fm"), ("V_dim", str(args.vdim)),
                          ("V_threshold", "0"), ("lr", "0.1"),
                          ("l1", "1e-4"),
                          ("batch_size", str(args.e2e_batch)),
                          ("shuffle", "0"),
                          ("max_num_epochs", str(n_epochs)),
                          ("num_jobs_per_epoch", "1"),
                          ("report_interval", "0"), ("stop_rel_objv", "0"),
                          ("V_dtype", args.vdtype),
                          ("device_cache_mb", str(cache_mb)),
                          ("producer_mode", producer_mode),
                          ("hash_capacity", str(args.capacity))])
            marks = []
            learner.add_epoch_end_callback(
                lambda e, t, v: marks.append(_t.perf_counter()))
            learner.run()
            rate = (n_epochs - 1) * nrows / (marks[-1] - marks[0])
            return rate, learner.device_cache_info(), learner.stage_stats()

        # the streamed regime has no staging warm-up to amortize, so a
        # shorter window (2 timed epochs) keeps the bench bounded; its
        # epoch count is reported alongside so the two regimes are never
        # mistaken for like-for-like windows
        streamed_epochs = 3
        # 4 GB cache: the 1.8M-row window at batch 65536 stages ~2.2 GB of
        # packed+chunked batches — comfortably inside this 16 GB chip next
        # to the ~1.1 GB fused-row table, and the bigger batch halves the
        # per-step dispatch overhead (~1.28M ex/s replay as of round 5;
        # run-to-run spread on the tunneled chip is a few percent)
        replay, cache_info, _ = train(4096, epochs)
        # the streamed run drives the requested producer transport
        # (--producer-mode; auto = process on multi-core hosts) and keeps
        # the per-stage decomposition so the headline is attributable:
        # pack/transfer overlapping the device steps shows up as epoch
        # wall-clock < the serial stage sum
        streamed, _, streamed_stages = train(
            0, streamed_epochs, producer_mode=args.producer_mode)
    # a frozen training cache means the "replay" window was a MIXED
    # regime (staged prefix replayed, tail streamed) — label it so the
    # number is never mistaken for full-HBM replay at larger --e2e-rows
    from difacto_tpu.learners.sgd import K_TRAINING
    train_cache = cache_info.get(K_TRAINING, {})
    out = {
        "metric": "fm_e2e_criteo_examples_per_sec",
        "value": round(replay, 1),
        "unit": "examples/sec",
        "vs_baseline": round(replay / REF_PSLITE_32W_EPS, 3),
        "replay_cache": train_cache,
        "streamed": {
            "metric": "fm_e2e_criteo_streamed_examples_per_sec",
            "value": round(streamed, 1),
            "vs_baseline": round(streamed / REF_PSLITE_32W_EPS, 3),
            "epochs_timed": streamed_epochs - 1,
            # which producer transport ran, and where the run's seconds
            # went (whole-run totals incl. epoch 0), SOURCED FROM THE OBS
            # REGISTRY (learner.stage_stats over stage_seconds_total —
            # ISSUE 4): parse/pack/ring-wait arrive from the producer
            # worker processes through their snapshot channel, so the
            # breakdown survives the process boundary and a streamed
            # regression localizes to a stage instead of hiding in the
            # headline
            "producer_mode": streamed_stages.pop("producer_mode"),
            "stages": streamed_stages,
        },
        "config": {"rows": nrows, "batch": args.e2e_batch,
                   "epochs_timed": epochs - 1,
                   "text_to_rec_convert_eps": round(convert_eps, 1)},
        "convert": convert_stats,
    }
    out["streamed"].update(_vs_prev_bench(streamed, streamed_stages))
    return out


def _vs_prev_bench(streamed_eps: float, stages: dict) -> dict:
    """Compare this run's streamed rate + per-stage seconds against the
    newest ``BENCH_r*.json`` next to bench.py (the driver's trajectory
    files), so a stage regression is visible IN the bench output instead
    of requiring a by-hand diff of two trajectory files. Older trajectory
    entries predate the stages breakdown — missing pieces just elide."""
    import glob
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    runs = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not runs:
        return {}
    try:
        with open(runs[-1]) as f:
            parsed = json.load(f).get("parsed") or {}
    except (OSError, ValueError):
        return {}
    # the driver runs bench.py bare (e2e nested under "e2e"); a by-hand
    # `--e2e` run IS the e2e dict at top level
    e2e = parsed.get("e2e") or parsed
    prev = (e2e.get("streamed") if isinstance(e2e, dict) else None) or {}
    if not prev.get("value"):
        return {}
    out: dict = {"prev_run": os.path.basename(runs[-1]),
                 "vs_prev": round(streamed_eps / prev["value"], 3)}
    prev_stages = prev.get("stages") or {}
    delta = {k: round(v - prev_stages[k], 3)
             for k, v in stages.items()
             if isinstance(v, (int, float)) and k in prev_stages
             and isinstance(prev_stages[k], (int, float))}
    if delta:
        out["stages_delta_s"] = delta
    return out


def _gen_serve_rows(n_rows: int, nnz_per_row: int, id_space: int,
                    seed: int = 0) -> list:
    """Synthetic libsvm request lines for the serving bench."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n_rows):
        ids = np.sort(rng.choice(id_space, nnz_per_row, replace=False))
        rows.append(("0 " + " ".join(f"{i}:1" for i in ids)).encode())
    return rows


def run_serve_bench(args) -> dict:
    """serve.* section: online-serving latency/throughput trajectory,
    tracked like the training numbers. An in-process ServeServer over a
    synthetic hashed model takes an open-loop Poisson load (tools/
    loadgen.py) at --serve-qps; a short warmup run compiles the shape
    buckets first, so ``steady_state_compiles`` reports the acceptance
    gate directly (0 = every measured dispatch was a bucket hit)."""
    import os
    import sys

    import tempfile
    import time as _time

    from difacto_tpu.serve import ModelReloader, ServeClient, ServeServer
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  set_all_live)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from loadgen import run_loadgen

    # l1_shrk off so the all-zero-w synthetic model still exercises the
    # full [w|V] gather + FM interaction path the real service pays
    param = SGDUpdaterParam(V_dim=args.serve_vdim, l1_shrk=False,
                            hash_capacity=args.serve_capacity)
    store = SlotStore(param, read_only=True)
    if args.serve_vdim:
        store.state = set_all_live(param, store.state)
    rows = _gen_serve_rows(512, args.nnz_per_row, 1 << 17)
    # takeover=True (SO_REUSEPORT): the takeover-gap measurement below
    # binds a successor to the same port, and the kernel requires every
    # binder of the pair to set the option
    server = ServeServer(store, batch_size=args.serve_batch,
                         max_delay_ms=args.serve_delay_ms,
                         queue_cap=args.serve_queue_cap, takeover=True)
    server.start()
    drain_s = 0.0
    bluegreen_ms = 0.0
    warm_parallel_ms = 0.0
    takeover_gap_ms = 0.0
    reload_ms: list = []
    try:
        # warmup at the TARGET rate: micro-batch occupancy (and so the
        # sticky shape caps) depends on the arrival rate, so warming at a
        # lower rate would leave the measured window to pay the compiles
        run_loadgen(server.host, server.port, rows, qps=args.serve_qps,
                    duration_s=2.0)
        before = server.executor.stats()["buckets_compiled"]
        rep = run_loadgen(server.host, server.port, rows,
                          qps=args.serve_qps,
                          duration_s=args.serve_seconds,
                          zipf_alpha=args.zipf_alpha)
        after = server.executor.stats()["buckets_compiled"]
        snap = server.stats_snapshot()
        # resilience cost (ISSUE 3): hot-reload latency over the wire —
        # save the serving table as a real checkpoint, then time full
        # #reload cycles (verify + weights-only load + atomic swap)
        with tempfile.TemporaryDirectory() as td:
            model = os.path.join(td, "model")
            store.save(model)
            server.reloader = ModelReloader(server.executor, model,
                                            server=server)
            with ServeClient(server.host, server.port) as c:
                for _ in range(5):
                    store.save(model)  # bump the generation
                    t0 = _time.monotonic()
                    res = c.reload()
                    dt = (_time.monotonic() - t0) * 1e3
                    if res.get("ok"):
                        reload_ms.append(dt)
                # blue/green cost (ISSUE 5): a GEOMETRY-CHANGING reload
                # (different V_dim) warms a second executor on the live
                # warm-set and swaps it under the batcher — time the
                # whole build+warm+swap the old design answered with
                # "restart the server"
                param2 = SGDUpdaterParam(
                    V_dim=args.serve_vdim + 4, l1_shrk=False,
                    hash_capacity=args.serve_capacity)
                store2 = SlotStore(param2, read_only=True)
                store2.state = set_all_live(param2, store2.state)
                model2 = os.path.join(td, "model2")
                store2.save(model2)
                t0 = _time.monotonic()
                res = c.reload(model2)
                if res.get("ok"):
                    bluegreen_ms = (_time.monotonic() - t0) * 1e3
                    # the warm-set portion alone, now compiled on a
                    # thread pool (serve/reload.py warm_workers) — the
                    # number the parallel-warm satellite moves
                    warm_parallel_ms = server.reloader.last_warm_ms
        # SO_REUSEPORT takeover gap: bind a successor to the SAME port,
        # drain the incumbent, and measure handoff-start -> first fresh
        # connection answered ready by the successor (the client-visible
        # upper bound; the successor accepts throughout, so ~drain time)
        import threading as _threading
        succ = ServeServer(store2, batch_size=args.serve_batch,
                           max_delay_ms=args.serve_delay_ms,
                           host=server.host, port=server.port,
                           takeover=True).start()
        succ_id = succ.health_snapshot()["server_id"]
        gap_box: dict = {}
        t0 = _time.monotonic()

        def _probe():
            while _time.monotonic() - t0 < 15.0:
                try:
                    with ServeClient(server.host, server.port,
                                     timeout=2.0) as pc:
                        h = pc.health()
                    if h.get("server_id") == succ_id \
                            and h.get("status") == "ready":
                        gap_box["ms"] = (_time.monotonic() - t0) * 1e3
                        return
                except (OSError, ConnectionError, ValueError):
                    pass
                _time.sleep(0.005)

        probe = _threading.Thread(target=_probe)
        probe.start()
        # graceful-drain time with the queue already empty (the floor an
        # orchestrator pays per rotation) doubles as the handoff
        drain_s = server.drain()
        probe.join()
        takeover_gap_ms = gap_box.get("ms", 0.0)
        succ.close()
    finally:
        server.close()

    # elastic-autoscaling leg (ISSUE 18): one deliberately under-
    # provisioned replica takes the diurnal peak behind a router while
    # the autoscaler watches its #health — the numbers tracked are how
    # many spawns/drains the cycle produced and how long the fleet took
    # to settle (scale-up decision -> queue/shed back under threshold)
    auto_spawns = auto_drains = 0
    auto_settle_s = 0.0
    from difacto_tpu.serve import Autoscaler, RouterServer
    from loadgen import run_loadgen_failover
    # slow flush cadence + small queue: the diurnal 1.6x peak visibly
    # queues on the base (frac > up_queue_frac) while the 0.3x trough
    # does not — the scale-up is deterministic, not a scheduler race
    base = ServeServer(store, batch_size=args.serve_batch,
                       max_delay_ms=50.0, queue_cap=64)
    base.start()
    extra: list = []

    def _spawn(_idx):
        s = ServeServer(store, batch_size=args.serve_batch,
                        max_delay_ms=args.serve_delay_ms,
                        queue_cap=args.serve_queue_cap)
        s.start()
        extra.append(s)
        return (s.host, s.port)

    router = RouterServer([(base.host, base.port)])
    router.start()
    scaler_t0 = _time.monotonic()
    scaler = Autoscaler([(base.host, base.port)], _spawn,
                        router=(router.host, router.port),
                        min_replicas=1, max_replicas=3, poll_s=0.1,
                        ewma=1.0, up_queue_frac=0.4, up_shed_rate=0.01,
                        down_queue_frac=0.2, up_ticks=1, down_ticks=10,
                        cooldown_s=0.5)
    scaler.start()
    try:
        run_loadgen_failover([(router.host, router.port)], rows,
                             qps=args.serve_qps, duration_s=4.0,
                             profile="diurnal")
        t_up = next((e["t"] for e in scaler.events
                     if e["action"] == "up"), None)
        if t_up is not None:
            # settle: from the scale-up decision until the aggregated
            # queue/shed signals are back under the scale-up threshold
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline:
                m = scaler.poll()
                if m["queue_frac"] < 0.5 and m["shed_rate"] <= 0.01:
                    auto_settle_s = (_time.monotonic() - scaler_t0) - t_up
                    break
                _time.sleep(0.05)
        scaler.close()
        # idle fleet: the scale-down path must walk back to min_replicas
        end = _time.monotonic() + 3.0
        while _time.monotonic() < end and len(scaler.endpoints()) > 1:
            scaler.step()
            _time.sleep(0.05)
        auto_spawns = sum(1 for e in scaler.events
                          if e["action"] == "up")
        auto_drains = sum(1 for e in scaler.events
                          if e["action"] == "down")
    finally:
        scaler.close()
        router.close()
        for s in extra:
            s.close()
        base.close()
    return {
        "reload_p99_ms": round(float(np.percentile(reload_ms, 99)), 3)
        if reload_ms else 0.0,
        "drain_s": round(drain_s, 3),
        "bluegreen_swap_ms": round(bluegreen_ms, 3),
        "warm_parallel_ms": round(warm_parallel_ms, 3),
        "takeover_gap_ms": round(takeover_gap_ms, 3),
        "autoscale_spawns": auto_spawns,
        "autoscale_drains": auto_drains,
        "autoscale_settle_s": round(auto_settle_s, 3),
        "p50_ms": rep.get("p50_ms", 0.0),
        "p95_ms": rep.get("p95_ms", 0.0),
        "p99_ms": rep.get("p99_ms", 0.0),
        "qps": rep["achieved_qps"],
        "shed_rate": rep["shed_rate"],
        "target_qps": args.serve_qps,
        "offered_qps": rep["offered_qps"],
        "batch_occupancy": snap["batch_occupancy"],
        "steady_state_compiles": after - before,
        "buckets_compiled": after,
        "config": {"batch": args.serve_batch,
                   "max_delay_ms": args.serve_delay_ms,
                   "queue_cap": args.serve_queue_cap,
                   "V_dim": args.serve_vdim,
                   "nnz_per_row": args.nnz_per_row,
                   "seconds": args.serve_seconds},
    }


def run_online_bench(args) -> dict:
    """online.* section: steady state of the serve→log→train→reload
    loop (docs/serving.md "Continuous learning"). One in-process server
    logs served rows into an OnlineLog while the feedback loadgen
    scores + labels them (#score/#label) and a REAL ``task=online``
    trainer subprocess tails the log, committing generations back over
    ``#reload``. Freshness is read from the trainer's own metrics JSONL
    (every flush carries the train_behind_serve_s gauge), so the p99 is
    measured across the run, not a final-state snapshot."""
    import os
    import subprocess
    import sys
    import tempfile

    from difacto_tpu.__main__ import main as difacto_main
    from difacto_tpu.online.log import OnlineLog
    from difacto_tpu.serve import ModelReloader, ServeServer, \
        open_serving_store
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from loadgen import run_loadgen_feedback

    repo = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as td:
        # a small labeled synthetic set: seed model + request stream
        data = os.path.join(td, "train.libsvm")
        with open(data, "w") as f:
            for i in range(256):
                ids = np.sort(rng.choice(1 << 14, args.nnz_per_row,
                                         replace=False))
                f.write(f"{i % 2} "
                        + " ".join(f"{j}:1" for j in ids) + "\n")
        with open(data, "rb") as f:
            rows = [l for l in f.read().splitlines() if l.strip()]
        model = os.path.join(td, "model")
        difacto_main([f"data_in={data}", "lr=0.1", "batch_size=100",
                      "max_num_epochs=1", "shuffle=0",
                      "num_jobs_per_epoch=1", "report_interval=0",
                      f"model_out={model}"])
        log_dir = os.path.join(td, "log")
        online_log = OnlineLog(log_dir,
                               segment_rows=args.online_segment_rows,
                               label_delay_s=args.online_label_delay_s,
                               label_default="negative")
        store, _meta, _rem = open_serving_store(model, [])
        server = ServeServer(store, batch_size=args.serve_batch,
                             max_delay_ms=args.serve_delay_ms,
                             queue_cap=args.serve_queue_cap,
                             online_log=online_log)
        server.reloader = ModelReloader(server.executor, model,
                                        server=server)
        server.start()
        metrics = os.path.join(td, "trainer.metrics.jsonl")
        trainer = subprocess.Popen(
            [sys.executable, "-m", "difacto_tpu", "task=online",
             f"online_log_dir={log_dir}", f"model_out={model}",
             "lr=0.1", "batch_size=100", "report_interval=0",
             f"online_ckpt_interval_s={args.online_ckpt_s}",
             f"online_endpoints={server.host}:{server.port}",
             f"metrics_path={metrics}", "metrics_interval_s=0.5"],
            cwd=repo,
            env=dict(os.environ, PYTHONPATH=repo))
        try:
            rep = run_loadgen_feedback(
                server.host, server.port, rows,
                qps=args.online_qps, duration_s=args.online_seconds,
                label_delay_s=args.online_label_delay_s,
                label_rate=args.online_label_rate)
            # terminate the log; the trainer drains the sealed tail,
            # commits the final generation, and exits 0
            online_log.end()
            trainer_rc = trainer.wait(timeout=180)
            reloads = server.reloader.stats()["reloads"]
            generation = server.executor.stats()["model_generation"]
        finally:
            if trainer.poll() is None:
                trainer.kill()
                trainer.wait()
            server.close()
        behind = []
        for p in (metrics + ".1", metrics):
            if not os.path.exists(p):
                continue
            with open(p) as f:
                for line in f:
                    try:
                        snap = json.loads(line)["metrics"]
                    except (ValueError, KeyError):
                        continue
                    series = snap.get("gauges", {}).get(
                        "train_behind_serve_s", {})
                    behind.extend(series.values())
        log_stats = online_log.stats()
    return {
        "rows_per_s": rep["achieved_qps"],
        "train_behind_serve_s_p99":
            round(float(np.percentile(behind, 99)), 3) if behind else 0.0,
        "reload_count": reloads,
        "label_join_rate":
            round(rep["labels_acked"] / max(rep["sent"], 1), 4),
        "model_generation": generation,
        "trainer_rc": trainer_rc,
        "ok": rep["ok"],
        "err": rep["err"],
        "shed_rate": rep["shed_rate"],
        "labels_sent": rep["labels_sent"],
        "labels_acked": rep["labels_acked"],
        "rows_logged": log_stats["rows_logged"],
        "segments_sealed": log_stats["next_seg"],
        "config": {"qps": args.online_qps,
                   "seconds": args.online_seconds,
                   "segment_rows": args.online_segment_rows,
                   "label_rate": args.online_label_rate,
                   "label_delay_s": args.online_label_delay_s,
                   "ckpt_interval_s": args.online_ckpt_s},
    }


def run_durability_bench(args) -> dict:
    """durability.* section (ISSUE 20): what the write-ahead delta log
    costs and what it buys. Three numbers over one synthetic labeled
    set: ``wal_overhead_pct`` — wall-clock cost of logging touched rows
    every ``--durability-flush`` batches vs the identical WAL-off run
    (target <= 5%); ``recovery_s`` — time for a FRESH learner to climb
    the recovery ladder (checkpoint load + WAL replay) after the chain
    loses its newest delta segment, the simulated mid-window crash; and
    ``rpo_batches`` — batches of work that loss actually cost, which
    the WAL bounds at one flush window (the RPO the knob buys, asserted
    exactly in tests/test_durability.py's kill leg)."""
    import os
    import tempfile
    import time

    from difacto_tpu.__main__ import main as difacto_main
    from difacto_tpu.durability import wal as _wal
    from difacto_tpu.learners.sgd import SGDLearner

    rng = np.random.RandomState(0)
    flush = args.durability_flush
    with tempfile.TemporaryDirectory() as td:
        data = os.path.join(td, "train.libsvm")
        with open(data, "w") as f:
            for i in range(2000):
                ids = np.sort(rng.choice(1 << 14, args.nnz_per_row,
                                         replace=False))
                f.write(f"{i % 2} "
                        + " ".join(f"{j}:1" for j in ids) + "\n")
        common = [f"data_in={data}", "lr=0.1", "batch_size=100",
                  "max_num_epochs=2", "shuffle=0", "seed=7",
                  "num_jobs_per_epoch=2", "report_interval=0",
                  "hash_capacity=65536", "V_dim=8", "slot_dtype=fp32",
                  # WAL forces device_cache_mb=0; pin it off in the
                  # baseline too so overhead compares identical programs
                  "device_cache_mb=0"]
        # untimed warmup leg: the first run pays JIT compile for the
        # fused step; timing it would swamp the <=5% WAL overhead target
        difacto_main(common + [f"model_out={os.path.join(td, 'warm')}"])
        t0 = time.perf_counter()
        difacto_main(common + [f"model_out={os.path.join(td, 'base')}"])
        base_s = time.perf_counter() - t0
        model = os.path.join(td, "wal")
        t0 = time.perf_counter()
        difacto_main(common + [f"model_out={model}", "ckpt_interval=1",
                               "auto_resume=1",
                               f"wal_flush_batches={flush}"])
        wal_s = time.perf_counter() - t0

        # simulated mid-window crash inside the LAST epoch: the epoch's
        # checkpoint and the final model never landed (deleted), and the
        # newest delta window died with the process (newest real segment
        # dropped) — the fresh learner must climb checkpoint(epoch-1) +
        # WAL replay of the surviving verified prefix
        import glob as _glob
        import re as _re
        epochs = sorted({int(m.group(1))
                         for f in _glob.glob(model + "_iter-*")
                         for m in [_re.search(r"_iter-(\d+)_", f)] if m})
        for f in (_glob.glob(model + f"_iter-{epochs[-1]}_*")
                  + _glob.glob(model + "_part-*")
                  + _glob.glob(model + ".meta*")):
            os.remove(f)
        wdir = _wal.wal_dir(model)
        gen = _wal.chain_generations(wdir, 0)[0]
        chain = _wal.chain_segments(wdir, 0, gen)
        head_full, dropped = 0, 0
        for seq, seg in reversed(chain):
            meta, _ = _wal.read_segment(seg)
            head_full = max(head_full, int(meta["step_hi"]))
            os.remove(seg)
            dropped += 1
            if meta["step_hi"] > meta["step_lo"]:
                break
        ln = SGDLearner()
        ln.init([tuple(kv.split("=", 1)) for kv in common]
                + [("model_out", model), ("ckpt_interval", "1"),
                   ("auto_resume", "1"),
                   ("wal_flush_batches", str(flush))])
        t0 = time.perf_counter()
        ln._try_resume()
        recovery_s = time.perf_counter() - t0
        ln.stop()
        with open(model + ".recovery.json") as f:
            stamp = json.load(f)
        head_after = int(stamp["head"]["step"])
    return {
        "wal_overhead_pct": round(100.0 * (wal_s - base_s)
                                  / max(base_s, 1e-9), 2),
        "recovery_s": round(recovery_s, 3),
        "rpo_batches": head_full - head_after,
        "wal_flush_batches": flush,
        "segments_dropped": dropped,
        "recovery_rungs": stamp["rungs"],
        "baseline_s": round(base_s, 3),
        "wal_s": round(wal_s, 3),
    }


def run_multichip(args) -> dict:
    """multichip.* section: the capacity-scaling trajectory of the
    fs-sharded slot table (difacto_tpu/parallel/capacity.py) — for each
    fs rung the table is ``--capacity * fs`` rows over fs devices, so
    the legs show max trainable hash_capacity growing with the mesh at
    ~constant per-device bytes while ex/s reports the collective cost.
    The driver's MULTICHIP_r*.json gets the same metric from
    __graft_entry__.dryrun_multichip (small shapes); this leg is the
    full-size version for by-hand runs on the 8-chip box.

    The ``delay`` block rides along: bounded-delay (τ) pipelining legs
    at hosts x {1,2,4} simulated straggler timelines x τ (--delay-taus,
    default {0,1,4}) over the same fused fs-sharded step — {hosts, tau,
    ex/s} plus the delay-vs-AUC trajectory leg (auc_delta vs τ=0), each
    leg carrying its compiled hlo.{table_collectives, peak_temp_bytes}
    scan (difacto_tpu/parallel/capacity.bounded_delay_report)."""
    from difacto_tpu.parallel.capacity import (bounded_delay_report,
                                               capacity_scaling_report)

    rep = capacity_scaling_report(
        base_capacity=args.multichip_capacity,
        V_dim=args.vdim, batch=args.batch_size,
        nnz_per_row=args.nnz_per_row, steps=args.steps,
        v_dtype=args.vdtype)
    rep["delay"] = bounded_delay_report(
        hosts_values=(1, 2, 4),
        taus=tuple(int(t) for t in args.delay_taus.split(",")),
        base_capacity=args.multichip_capacity,
        V_dim=args.vdim, batch=args.batch_size,
        nnz_per_row=args.nnz_per_row, steps=max(args.steps, 6),
        v_dtype=args.vdtype)
    return rep


def _gen_capacity_libsvm(path: str, nrows: int, nfeat: int, alpha: float,
                         seed: int, w: np.ndarray) -> None:
    """Synthetic planted-model libsvm rows: zipf(alpha)-ranked feature
    ids, labels drawn from the logistic of the planted weights — so a
    config's validation AUC measures how much signal its table kept."""
    rng = np.random.RandomState(seed)
    nnz = 8
    ranks = (rng.zipf(alpha, (nrows, nnz)) - 1) % nfeat
    with open(path, "w") as f:
        for r in ranks:
            ids = np.unique(r)
            p = 1.0 / (1.0 + np.exp(-w[ids].sum()))
            y = 1 if rng.random_sample() < p else 0
            f.write(f"{y} " + " ".join(f"{i}:1" for i in ids) + "\n")


def run_capacity_bench(args) -> dict:
    """``--capacity`` (bare) mode — the quality-vs-capacity story of the
    three table-capacity levers (ISSUE 19; docs/perf_notes.md "Table
    capacity"):

      quality : train the same planted-model data at equal-ish per-device
                byte budgets: fp32 at the base capacity vs int8/fp8 legs
                at 2x/4x/8x the rows (the 8x leg stacks the cold tier on
                int8), each leg reporting validation AUC, its delta vs
                the fp32 baseline, and the store's own capacity_stats
                accounting (bytes/device, effective rows, multiplier);
      tier    : cold-tier hit rate across >= 2 zipf skews — the number
                that says whether a half-resident table serves the hot
                set from device rows.
    """
    import tempfile

    from difacto_tpu.learners import Learner
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    base_cap = args.capacity_base
    vdim = 4
    nfeat = base_cap * 16
    rng = np.random.RandomState(7)
    w_true = rng.randn(nfeat) * 0.7

    def cap_stats(slot_dtype: str, cap: int, cold: int) -> dict:
        p, _ = SGDUpdaterParam.init_allow_unknown([
            ("V_dim", str(vdim)), ("hash_capacity", str(cap)),
            ("slot_dtype", slot_dtype), ("cold_tier_rows", str(cold))])
        return SlotStore(p).capacity_stats()

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.libsvm", f"{d}/val.libsvm"
        _gen_capacity_libsvm(train_p, 3000, nfeat, 1.3, 1, w_true)
        _gen_capacity_libsvm(val_p, 1500, nfeat, 1.3, 2, w_true)

        def train_auc(slot_dtype: str, cap: int, cold: int = 0) -> float:
            aucs = []
            learner = Learner.create("sgd")
            learner.init([
                ("data_in", train_p), ("data_val", val_p),
                ("data_format", "libsvm"), ("loss", "fm"),
                ("V_dim", str(vdim)), ("V_threshold", "0"),
                ("lr", "0.1"), ("l1", "1e-5"),
                ("batch_size", "256"), ("shuffle", "0"),
                ("max_num_epochs", "3"), ("num_jobs_per_epoch", "1"),
                ("report_interval", "0"), ("stop_rel_objv", "0"),
                ("stop_val_auc", "0"), ("device_cache_mb", "0"),
                ("hash_capacity", str(cap)),
                ("slot_dtype", slot_dtype),
                ("cold_tier_rows", str(cold))])
            learner.add_epoch_end_callback(
                lambda e, t, v: aucs.append(v.auc / max(v.nrows, 1.0)))
            learner.run()
            return aucs[-1]

        base_auc = train_auc("fp32", base_cap)
        base_stats = cap_stats("fp32", base_cap, 0)
        base_bytes = max(base_stats["table_bytes_per_device"], 1)
        legs = []
        for slot_dtype, mult, cold_frac in (("int8", 2, 0.0),
                                            ("int8", 4, 0.0),
                                            ("fp8", 4, 0.0),
                                            ("int8", 8, 0.5)):
            cap = base_cap * mult
            cold = int(cap * cold_frac)
            auc = train_auc(slot_dtype, cap, cold)
            stats = cap_stats(slot_dtype, cap, cold)
            legs.append({
                "slot_dtype": slot_dtype,
                "capacity_mult": mult,
                "cold_tier_rows": cold,
                "auc": round(auc, 5),
                "auc_delta_vs_fp32": round(auc - base_auc, 5),
                "bytes_ratio_vs_fp32": round(
                    stats["table_bytes_per_device"] / base_bytes, 3),
                "capacity_stats": stats,
            })

    # tier hit-rate across skews: stream zipf keys through a
    # half-resident table and read the tier's own counters
    def tier_hit_rate(alpha: float, cap: int = 4096,
                      steps: int = 50, batch: int = 512) -> dict:
        p, _ = SGDUpdaterParam.init_allow_unknown([
            ("V_dim", "4"), ("hash_capacity", str(cap)),
            ("cold_tier_rows", str(cap // 2))])
        store = SlotStore(p)
        krng = np.random.RandomState(int(alpha * 100))
        h0 = store.tier._hits.value()
        m0 = store.tier._misses.value()
        for _ in range(steps):
            keys = np.unique(
                ((krng.zipf(alpha, batch) - 1) % (cap * 4)).astype(np.int64))
            store.pull(keys)
        h = store.tier._hits.value() - h0
        m = store.tier._misses.value() - m0
        return {"zipf_alpha": alpha,
                "hit_rate": round(h / max(h + m, 1), 4),
                "hits": int(h), "misses": int(m)}

    tier_legs = [tier_hit_rate(a) for a in args.capacity_alphas]
    x8 = legs[-1]["capacity_stats"]
    return {
        "baseline": {"slot_dtype": "fp32", "auc": round(base_auc, 5),
                     "capacity_stats": base_stats},
        "quality_vs_capacity": legs,
        "tier_hit_rate": tier_legs,
        # the acceptance number: logical rows per device of the stacked
        # int8+tier leg over the fp32/no-tier rows the same per-device
        # bytes would hold
        "effective_rows_per_device": x8["effective_rows_per_device"],
        "capacity_multiplier_x8_leg": x8["capacity_multiplier"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=65536)
    ap.add_argument("--vdim", type=int, default=64)
    ap.add_argument("--nnz-per-row", type=int, default=39)  # criteo density
    ap.add_argument("--uniq", type=int, default=1 << 17,
                    help="feature-id space each batch draws from")
    ap.add_argument("--capacity", nargs="?", const="bench",
                    default=1 << 21,
                    help="table rows when given a value; passed BARE it "
                         "selects the table-capacity bench instead "
                         "(quantized-slot AUC legs at 2x/4x/8x effective "
                         "capacity + cold-tier hit-rate across zipf "
                         "skews; docs/perf_notes.md \"Table capacity\")")
    ap.add_argument("--capacity-base", type=int, default=1024,
                    help="fp32 baseline hash_capacity of the --capacity "
                         "bench quality legs")
    ap.add_argument("--capacity-alphas", default="1.1,1.6",
                    help="comma-separated zipf skews for the --capacity "
                         "bench tier hit-rate legs")
    ap.add_argument("--zipf-alpha", type=float, default=0.0,
                    help="serve-bench request skew: forwarded to the "
                         "loadgen row picker (tools/loadgen.py "
                         "make_picker); 0 keeps the round-robin cycle")
    ap.add_argument("--dist", choices=("zipf", "uniform"), default="zipf",
                    help="feature frequency skew (criteo is heavy-tailed)")
    ap.add_argument("--vdtype", choices=("float32", "bfloat16"),
                    default="bfloat16")
    ap.add_argument("--fused-kernel", default="auto",
                    choices=("auto", "pallas", "jnp", "off"),
                    help="table-kernel backend of the fused step "
                         "(updaters/sgd_updater.py fused_kernel): the "
                         "headline rides this; the kernel block times "
                         "every available backend regardless")
    ap.add_argument("--steps", type=int, default=40)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--e2e", action="store_true",
                      help="full text->train pipeline ONLY (skip device "
                           "step)")
    mode.add_argument("--device-only", action="store_true",
                      help="device step only (skip the e2e pipeline run)")
    mode.add_argument("--serve", action="store_true",
                      help="online-serving latency/throughput ONLY: "
                           "in-process server + open-loop Poisson loadgen")
    mode.add_argument("--online", action="store_true",
                      help="serve→log→train→reload loop steady state "
                           "ONLY: in-process server + feedback loadgen "
                           "+ a task=online trainer subprocess")
    mode.add_argument("--multichip", action="store_true",
                      help="fs-sharded table capacity-scaling ONLY: "
                           "table of --multichip-capacity * fs rows per "
                           "fs rung in {1,2,4,8}, ex/s + per-device "
                           "bytes per leg")
    mode.add_argument("--durability", action="store_true",
                      help="WAL overhead + recovery cost ONLY: WAL-off "
                           "vs WAL-on wall clock, then a simulated "
                           "mid-window crash recovered through the "
                           "ladder (durability.{wal_overhead_pct, "
                           "recovery_s, rpo_batches})")
    ap.add_argument("--durability-flush", type=int, default=8,
                    help="wal_flush_batches for the --durability legs "
                         "(the RPO bound under test)")
    ap.add_argument("--delay-taus", default="0,1,4",
                    help="comma-separated bounded-delay windows for the "
                         "--multichip delay legs (τ batches of permitted "
                         "staleness; 0 = synchronous)")
    ap.add_argument("--multichip-capacity", type=int, default=1 << 20,
                    help="per-fs-rung base hash_capacity of the "
                         "--multichip sweep (table = base * fs rows)")
    ap.add_argument("--serve-qps", type=float, default=500.0,
                    help="target offered rate for the serve bench")
    ap.add_argument("--serve-seconds", type=float, default=5.0)
    ap.add_argument("--serve-vdim", type=int, default=8)
    ap.add_argument("--serve-capacity", type=int, default=1 << 16)
    ap.add_argument("--serve-batch", type=int, default=256)
    ap.add_argument("--serve-delay-ms", type=float, default=2.0)
    ap.add_argument("--serve-queue-cap", type=int, default=1024)
    ap.add_argument("--online-qps", type=float, default=200.0,
                    help="offered rate for the --online loop bench")
    ap.add_argument("--online-seconds", type=float, default=6.0)
    ap.add_argument("--online-segment-rows", type=int, default=64,
                    help="rows per sealed training-log segment")
    ap.add_argument("--online-label-rate", type=float, default=0.5,
                    help="fraction of served rows the feedback loadgen "
                         "labels back")
    ap.add_argument("--online-label-delay-s", type=float, default=0.5,
                    help="feedback-join horizon (labels go out at half)")
    ap.add_argument("--online-ckpt-s", type=float, default=1.0,
                    help="trainer generation commit cadence (wall s)")
    ap.add_argument("--e2e-rows", type=int, default=1_800_000,
                    help="rows in the e2e window; large enough that the "
                         "fixed epoch-boundary cost (final metric fetch, "
                         "~2 RTT on a tunneled chip) amortizes")
    ap.add_argument("--e2e-batch", type=int, default=65536,
                    help="training batch size for the e2e pipeline run")
    ap.add_argument("--producer-mode", default="auto",
                    choices=("auto", "thread", "process"),
                    help="streamed-regime producer transport: in-process "
                         "threads or spawn worker processes + shared-"
                         "memory ring (auto = process when >= 4 cores)")
    ap.add_argument("--profile", metavar="DIR", default="",
                    help="capture a device trace of the timed step window "
                         "into DIR (view with xprof/TensorBoard)")
    ap.add_argument("--mesh", metavar="DPxFS", default="",
                    help="run the SAME panel/chunked step as a sharded "
                         "program over a (dp, fs) jax.sharding.Mesh "
                         "(e.g. 1x1 on one chip proves the sharded "
                         "lowering keeps the flat-path rate; 2x4 on the "
                         "virtual CPU mesh checks multi-device)")
    args = ap.parse_args()
    # bare --capacity is the capacity-bench mode; with a value it stays
    # the table-rows knob every other mode reads
    capacity_mode = args.capacity == "bench"
    args.capacity = (1 << 21) if capacity_mode else int(args.capacity)
    args.capacity_alphas = tuple(
        float(a) for a in str(args.capacity_alphas).split(",") if a)

    # honor an explicit JAX_PLATFORMS=cpu (the documented virtual-mesh
    # usage, e.g. --mesh 2x4 with 8 forced host devices) before the
    # first backend touch
    from difacto_tpu.utils.platform import apply_env_platform
    apply_env_platform()

    if capacity_mode:
        print(json.dumps({"capacity": run_capacity_bench(args)}))
        return
    if args.e2e:
        print(json.dumps(run_e2e(args)))
        return
    if args.serve:
        print(json.dumps({"serve": run_serve_bench(args)}))
        return
    if args.online:
        print(json.dumps({"online": run_online_bench(args)}))
        return
    if args.multichip:
        print(json.dumps({"multichip": run_multichip(args)}))
        return
    if args.durability:
        print(json.dumps({"durability": run_durability_bench(args)}))
        return

    import jax
    import jax.numpy as jnp

    mesh = None
    if args.mesh:
        from difacto_tpu.parallel import make_mesh
        dp, fs = (int(v) for v in args.mesh.lower().split("x"))
        mesh = make_mesh(dp=dp, fs=fs)

    step_raw, state, _, _, _ = build_step(
        args.vdim, args.capacity, args.vdtype,
        chunks_sorted=mesh is None or mesh.shape["dp"] == 1,
        fused_kernel=args.fused_kernel if mesh is None else
        ("jnp" if args.fused_kernel == "pallas" else args.fused_kernel),
        mesh=mesh)
    host_batches = make_batches(4, args.batch_size, args.nnz_per_row,
                                args.uniq, args.capacity, args.dist,
                                chunk_multiple=(mesh.shape["dp"]
                                                if mesh else 1))

    # per-step dispatch with a DONATED state — the production replay
    # pattern (learners/sgd.py replays cached batches one jitted call per
    # step). A lax.scan harness measures the same body ~6% slower: XLA
    # inserts carry copies for the gather-then-scatter table inside a
    # while loop, a cost the product never pays (docs/perf_notes.md,
    # "scan replay — negative result"). JAX async dispatch pipelines the
    # per-call RTT, so the chained wall time is pure device execution;
    # the final value fetch is the completion fence (block_until_ready is
    # unreliable through the device tunnel, pitfall #1).
    step = jax.jit(step_raw, donate_argnums=0)
    if mesh is not None:
        from difacto_tpu.parallel import (batch_sharding, replicated,
                                          shard_pytree, state_sharding)
        state = shard_pytree(state, state_sharding(mesh))
        batches = [shard_pytree(b, batch_sharding(mesh))
                   for b, _ in host_batches]
        slots_l = [jax.device_put(np.asarray(s), replicated(mesh))
                   for _, s in host_batches]
    else:
        batches = [jax.device_put(b) for b, _ in host_batches]
        slots_l = [jnp.asarray(s) for _, s in host_batches]
    n_bk = len(host_batches)
    u_cap = slots_l[0].shape[0]

    # warmup / compile (fetch forces completion; jaxtrace declares the
    # sync so the jax-host-sync pass knows it is the harness fence)
    from difacto_tpu.utils import jaxtrace
    state, objv, _ = step(state, batches[0], slots_l[0])
    jaxtrace.fetch(objv, point="bench.fence")

    import contextlib

    from difacto_tpu.utils.profiling import device_trace
    trace = (device_trace(args.profile) if args.profile
             else contextlib.nullcontext())
    with trace:
        t0 = time.perf_counter()
        for i in range(args.steps):
            state, objv, _ = step(state, batches[i % n_bk], slots_l[i % n_bk])
        jaxtrace.fetch(objv, point="bench.fence")
        dt = time.perf_counter() - t0

    eps = args.steps * args.batch_size / dt
    v_bytes = 2 if args.vdtype == "bfloat16" else 4
    out = {
        "metric": ("fm_v64_train_examples_per_sec" if mesh is None else
                   f"fm_v64_mesh{args.mesh}_train_examples_per_sec"),
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": round(eps / REF_PSLITE_32W_EPS, 3),
        "baseline": "estimated 5e5 ex/s (32-worker ps-lite CPU; the "
                    "reference publishes no numbers)",
        "config": {"batch": args.batch_size, "V_dim": args.vdim,
                   "dist": args.dist, "V_dtype": args.vdtype,
                   "uniq_rows_per_step": u_cap},
        "roofline": roofline(args.batch_size * args.nnz_per_row, u_cap,
                             args.vdim, v_bytes, dt / args.steps,
                             vvg_cols=int(state.VVg.shape[1])),
    }
    if mesh is None and args.vdim > 0:
        # per-backend roofline attribution of the fused step (ISSUE 13):
        # every available fused_kernel backend full-step timed, plus the
        # dedup/gather/interaction/scatter leg split
        out["kernel"] = run_kernel_bench(args, host_batches,
                                         args.nnz_per_row)
    if not args.device_only and mesh is None:
        # the product number rides the default output so a pipeline
        # regression is driver-visible (round-3 verdict #10)
        out["e2e"] = run_e2e(args)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
