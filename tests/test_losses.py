"""Loss kernel tests against the reference's golden constants.

Mirrors tests/cpp/fm_loss_test.cc: build deterministic weights indexed by the
original feature id over the first 100-row rcv1 batch, check the logit
objective and squared gradient norm. Golden values from the reference suite
(fm_loss_test.cc:35-39, 78-82): NoV 147.4672 / 90.5817; HasV(V_dim=5)
330.628 / 1237.8.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from difacto_tpu.base import reverse_bytes
from difacto_tpu.data import BatchReader, compact
from difacto_tpu.losses import FMParams, create, metrics
from difacto_tpu.losses.fm import fm_grad, fm_predict, logit_objv
from difacto_tpu.ops import pad_batch, spmv, spmv_t


@pytest.fixture(scope="module")
def batch100(rcv1_path):
    blk = next(iter(BatchReader(rcv1_path, batch_size=100)))
    cblk, uniq, _ = compact(blk)
    orig_ids = reverse_bytes(uniq)  # original feature ids, like utils.h:126-136
    dev = pad_batch(cblk, num_uniq=len(uniq))
    return dev, orig_ids, cblk


def test_fm_loss_nov_golden(batch100):
    dev, ids, _ = batch100
    U = len(ids)
    w = np.zeros(dev.cols.max() + 1 if U == 0 else U, dtype=np.float32)
    w[:] = ids.astype(np.float64) / 5e4
    params = FMParams(w=jnp.asarray(w))
    pred = fm_predict(params, dev)
    objv = float(logit_objv(pred, dev))
    assert abs(objv - 147.4672) < 1e-3

    gw, gV = fm_grad(params, dev, pred)
    assert gV is None
    norm2 = float(np.sum(np.asarray(gw, dtype=np.float64) ** 2))
    assert abs(norm2 - 90.5817) < 1e-3


def test_fm_loss_hasv_golden(batch100):
    dev, ids, _ = batch100
    V_dim = 5
    U = len(ids)
    w = (ids.astype(np.float64) / 5e4).astype(np.float32)
    V = np.empty((U, V_dim), dtype=np.float32)
    for j in range(V_dim):
        V[:, j] = (ids.astype(np.float64) * (j + 1) / 5e5)
    params = FMParams(w=jnp.asarray(w), V=jnp.asarray(V))
    pred = fm_predict(params, dev)
    objv = float(logit_objv(pred, dev))
    assert abs(objv - 330.628) < 1e-3

    gw, gV = fm_grad(params, dev, pred)
    norm2 = float(np.sum(np.asarray(gw, dtype=np.float64) ** 2)
                  + np.sum(np.asarray(gV, dtype=np.float64) ** 2))
    assert abs(norm2 - 1237.8) < 1e-1


def test_fm_vs_dense_brute_force():
    """FM forward/backward vs a dense numpy re-derivation on random data."""
    rng = np.random.RandomState(0)
    B, U, k, nnz_per_row = 16, 30, 4, 5
    rows, cols, vals = [], [], []
    for r in range(B):
        cs = rng.choice(U, nnz_per_row, replace=False)
        for c in cs:
            rows.append(r); cols.append(c); vals.append(rng.randn())
    X = np.zeros((B, U))
    for r, c, v in zip(rows, cols, vals):
        X[r, c] = v
    w = rng.randn(U).astype(np.float32)
    V = (rng.randn(U, k) * 0.1).astype(np.float32)
    label = rng.choice([0.0, 1.0], B).astype(np.float32)

    from difacto_tpu.data.rowblock import RowBlock
    order = np.lexsort((cols, rows))
    r_s = np.array(rows)[order]; c_s = np.array(cols)[order]
    v_s = np.array(vals)[order].astype(np.float32)
    offset = np.zeros(B + 1, dtype=np.int64)
    for r in r_s:
        offset[r + 1] += 1
    np.cumsum(offset, out=offset)
    blk = RowBlock(offset=offset, label=label,
                   index=c_s.astype(np.uint32), value=v_s)
    dev = pad_batch(blk, num_uniq=U)

    params = FMParams(w=jnp.asarray(w), V=jnp.asarray(V))
    pred = np.asarray(fm_predict(params, dev))[:B]

    XV = X @ V
    dense_pred = X @ w + 0.5 * ((XV ** 2).sum(1) - (X ** 2) @ (V ** 2).sum(1))
    dense_pred = np.clip(dense_pred, -20, 20)
    np.testing.assert_allclose(pred, dense_pred, rtol=2e-5, atol=2e-5)

    gw, gV = fm_grad(params, dev, jnp.asarray(np.asarray(fm_predict(params, dev))))
    y = np.where(label > 0, 1.0, -1.0)
    p = -y / (1 + np.exp(y * dense_pred))
    dense_gw = X.T @ p
    dense_gV = X.T @ (p[:, None] * XV) - ((X ** 2).T @ p)[:, None] * V
    np.testing.assert_allclose(np.asarray(gw), dense_gw, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gV), dense_gV, rtol=2e-4, atol=2e-5)


def test_v_mask_matches_absent_embeddings(batch100):
    """v_mask zeroes both the forward contribution and the V gradient —
    the reference's V_pos == -1 semantics (fm_loss.h:97-99,186-191)."""
    dev, ids, _ = batch100
    U = len(ids)
    rng = np.random.RandomState(1)
    w = rng.randn(U).astype(np.float32) * 0.01
    V = rng.randn(U, 3).astype(np.float32) * 0.1
    mask = (rng.random_sample(U) < 0.5).astype(np.float32)

    pm = FMParams(w=jnp.asarray(w), V=jnp.asarray(V), v_mask=jnp.asarray(mask))
    pz = FMParams(w=jnp.asarray(w), V=jnp.asarray(V * mask[:, None]))
    pred_m = np.asarray(fm_predict(pm, dev))
    pred_z = np.asarray(fm_predict(pz, dev))
    np.testing.assert_allclose(pred_m, pred_z, rtol=1e-6)

    _, gV_m = fm_grad(pm, dev, jnp.asarray(pred_m))
    assert np.all(np.asarray(gV_m)[mask == 0] == 0)


def test_spmv_roundtrip_identity():
    rng = np.random.RandomState(2)
    nnz, B, U = 64, 8, 12
    rows = jnp.asarray(rng.randint(0, B, nnz), dtype=jnp.int32)
    cols = jnp.asarray(rng.randint(0, U, nnz), dtype=jnp.int32)
    vals = jnp.asarray(rng.randn(nnz), dtype=jnp.float32)
    x = jnp.asarray(rng.randn(U), dtype=jnp.float32)
    p = jnp.asarray(rng.randn(B), dtype=jnp.float32)
    # <Ax, p> == <x, A'p>
    lhs = float(jnp.dot(spmv(vals, rows, cols, x, B), p))
    rhs = float(jnp.dot(x, spmv_t(vals, rows, cols, p, U)))
    assert abs(lhs - rhs) < 1e-3


def test_auc_device_matches_host(batch100):
    dev, _, cblk = batch100
    rng = np.random.RandomState(3)
    pred = rng.randn(dev.batch_cap).astype(np.float32)
    host = metrics.auc_times_n(cblk.label, pred[:cblk.size])
    devv = float(metrics.auc_times_n_jnp(
        dev.labels, jnp.asarray(pred), dev.row_mask))
    assert abs(host - devv) < 1e-3
    # degenerate: all positive
    assert metrics.auc_times_n(np.ones(5), rng.randn(5)) == 1.0


def test_loss_factory():
    assert create("logit", 7).V_dim == 0
    assert create("fm", 7).V_dim == 7
    with pytest.raises(ValueError):
        create("hinge")


def test_panel_matches_coo():
    """PanelBatch kernels reproduce the COO kernels on ragged data
    (uniform-width binary AND ragged weighted rows)."""
    import numpy as np
    import jax.numpy as jnp
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.losses import FMParams, fm_grad, fm_grad_panel, \
        fm_predict, fm_predict_panel
    from difacto_tpu.ops.batch import pad_batch, pad_panel, panel_width

    rng = np.random.RandomState(7)
    U, k, B = 64, 4, 16

    def check(blk, num_uniq, width):
        w = jnp.asarray(rng.randn(U).astype(np.float32))
        V = jnp.asarray(rng.randn(U, k).astype(np.float32) * 0.1)
        vm = jnp.asarray((rng.rand(U) > 0.3).astype(np.float32))
        params = FMParams(w=w, V=V, v_mask=vm)
        coo = pad_batch(blk, num_uniq=num_uniq, batch_cap=B)
        pb = pad_panel(blk, num_uniq, B, width)
        pred_c = fm_predict(params, coo)
        pred_p = fm_predict_panel(params, pb)
        mask = np.asarray(coo.row_mask) > 0
        np.testing.assert_allclose(np.asarray(pred_c)[mask],
                                   np.asarray(pred_p)[mask], rtol=1e-5)
        gw_c, gV_c = fm_grad(params, coo, pred_c)
        gw_p, gV_p = fm_grad_panel(params, pb, pred_p)
        # rtol 5e-5: panel and COO sum token contributions in different
        # orders, and the widest case runs 70-term row sums
        np.testing.assert_allclose(np.asarray(gw_c), np.asarray(gw_p),
                                   rtol=5e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gV_c), np.asarray(gV_p),
                                   rtol=5e-5, atol=1e-6)
        # linear (V=None) path too
        lp = FMParams(w=w, V=None, v_mask=None)
        np.testing.assert_allclose(
            np.asarray(fm_predict(lp, coo))[mask],
            np.asarray(fm_predict_panel(lp, pb))[mask], rtol=1e-5)

    # uniform-width binary rows (criteo shape), full batch
    F = 5
    blk_u = RowBlock(
        offset=np.arange(B + 1, dtype=np.int64) * F,
        label=rng.choice([0.0, 1.0], B).astype(np.float32),
        index=rng.randint(0, U, B * F).astype(np.uint32),
        value=None)
    assert panel_width(blk_u, B) == F  # uniform width is panel-eligible
    check(blk_u, U, F)

    # ragged weighted rows, partial batch (12 of 16)
    counts = rng.randint(1, 7, 12)
    off = np.zeros(13, dtype=np.int64)
    np.cumsum(counts, out=off[1:])
    blk_r = RowBlock(
        offset=off,
        label=rng.choice([0.0, 1.0], 12).astype(np.float32),
        index=rng.randint(0, U, off[-1]).astype(np.uint32),
        value=rng.rand(off[-1]).astype(np.float32),
        weight=rng.rand(12).astype(np.float32))
    check(blk_r, U, int(counts.max()))

    # wider than _COLLOOP_MAX_WIDTH: the forward's single-gather fallback
    from difacto_tpu.losses.fm import _COLLOOP_MAX_WIDTH
    Fw = _COLLOOP_MAX_WIDTH + 6
    blk_w = RowBlock(
        offset=np.arange(B + 1, dtype=np.int64) * Fw,
        label=rng.choice([0.0, 1.0], B).astype(np.float32),
        index=rng.randint(0, U, B * Fw).astype(np.uint32),
        value=None)
    check(blk_w, U, Fw)


def test_chunked_backward_matches_unsorted():
    """The chunked-run panel backward (panel_chunk_tokens +
    _fm_grad_panel_chunked) reproduces the unsorted scatter backward on
    binary, valued/ragged, and V=None panels, including zipf-skewed lanes
    (runs longer than CHUNK_L split across chunks)."""
    import numpy as np
    import jax.numpy as jnp
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.losses import FMParams, fm_grad_panel, fm_predict_panel
    from difacto_tpu.ops.batch import pad_panel, panel_chunk_tokens

    rng = np.random.RandomState(12)
    U, k, B = 96, 6, 48

    def check(blk, width, V_dim):
        w = jnp.asarray(rng.randn(U).astype(np.float32))
        V = (jnp.asarray(rng.randn(U, V_dim).astype(np.float32) * 0.1)
             if V_dim else None)
        vm = jnp.asarray((rng.rand(U) > 0.3).astype(np.float32))
        params = FMParams(w=w, V=V, v_mask=vm if V_dim else None)
        pb = pad_panel(blk, U, B, width)
        pred = fm_predict_panel(params, pb)
        gw_u, gV_u = fm_grad_panel(params, pb, pred)
        pbc = panel_chunk_tokens(pb, U)
        assert pbc.chunk_lane is not None
        gw_c, gV_c = fm_grad_panel(params, pbc, pred)
        np.testing.assert_allclose(np.asarray(gw_u), np.asarray(gw_c),
                                   rtol=2e-5, atol=1e-6)
        if V_dim:
            np.testing.assert_allclose(np.asarray(gV_u), np.asarray(gV_c),
                                       rtol=2e-5, atol=1e-6)
        else:
            assert gV_u is None and gV_c is None

    # uniform binary rows with zipf-skewed lanes: hot lanes get token runs
    # far longer than CHUNK_L, exercising multi-chunk runs
    F = 7
    idx_z = ((rng.zipf(1.3, B * F) - 1) % U).astype(np.uint32)
    blk_z = RowBlock(
        offset=np.arange(B + 1, dtype=np.int64) * F,
        label=rng.choice([0.0, 1.0], B).astype(np.float32),
        index=idx_z,
        value=None)
    check(blk_z, F, V_dim=k)
    check(blk_z, F, V_dim=0)

    # ragged weighted rows, partial batch (pad rows + pad cells)
    counts = rng.randint(1, 7, 29)
    off = np.zeros(30, dtype=np.int64)
    np.cumsum(counts, out=off[1:])
    blk_r = RowBlock(
        offset=off,
        label=rng.choice([0.0, 1.0], 29).astype(np.float32),
        index=rng.randint(0, U, off[-1]).astype(np.uint32),
        value=rng.rand(off[-1]).astype(np.float32),
        weight=rng.rand(29).astype(np.float32))
    check(blk_r, int(counts.max()), V_dim=k)
    check(blk_r, int(counts.max()), V_dim=0)


def test_panel_chunk_layout_invariants():
    """panel_chunk_tokens_flat: chunk lanes ascend, every token row id
    appears exactly once among its lane's chunk cells, pads point out of
    bounds, and the layout stays within the static chunk_cap bound."""
    import numpy as np
    import jax.numpy as jnp
    from difacto_tpu.ops.batch import (CHUNK_L, chunk_cap,
                                       panel_chunk_tokens_flat)

    rng = np.random.RandomState(13)
    B, F, u_cap = 64, 5, 40
    flat = ((rng.zipf(1.3, B * F) - 1) % u_cap).astype(np.int32)
    ci, cl, cv = panel_chunk_tokens_flat(jnp.asarray(flat), None, u_cap,
                                         B, F)
    ci, cl = np.asarray(ci), np.asarray(cl)
    assert ci.shape == (chunk_cap(u_cap, B * F), CHUNK_L)
    used = cl < u_cap
    # used chunks form a prefix with ascending lanes
    assert used[:used.sum()].all()
    assert (np.diff(cl[used]) >= 0).all()
    # padded chunks carry no real cells
    assert (ci[~used] == B).all()
    # per lane: the multiset of (row) tokens matches the panel
    for lane in range(u_cap):
        toks = ci[cl == lane]
        toks = toks[toks < B]
        want = np.flatnonzero(flat == lane) // F
        np.testing.assert_array_equal(np.sort(toks), np.sort(want))


def test_numpy_chunker_and_unsorted_chunks_match():
    """panel_chunk_tokens_np (the host-side twin the mesh paths use)
    produces the same reduction as the jit chunker, including explicit-C
    rounding and row_base offsets; and the chunked backward with
    sorted_chunks=False (the dp>1 mesh setting) equals sorted_chunks=True."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.losses import FMParams, fm_grad_panel, fm_predict_panel
    from difacto_tpu.ops.batch import (chunk_cap, pad_panel,
                                       panel_chunk_tokens,
                                       panel_chunk_tokens_np)

    rng = np.random.RandomState(5)
    B, F, u_cap = 48, 6, 40
    flat = ((rng.zipf(1.3, B * F) - 1) % u_cap).astype(np.int32)
    vals = rng.rand(B * F).astype(np.float32)

    from difacto_tpu.ops.batch import panel_chunk_tokens_flat
    ci_j, cl_j, cv_j = jax.jit(
        panel_chunk_tokens_flat, static_argnums=(2, 3, 4))(
            jnp.asarray(flat), jnp.asarray(vals), u_cap, B, F)
    ci_n, cl_n, cv_n = panel_chunk_tokens_np(flat, vals, u_cap, B, F)

    def reduce(ci, cl, cv, row_q, nrows):
        ci, cl, cv = np.asarray(ci), np.asarray(cl), np.asarray(cv)
        toks = np.where(ci[:, :, None] < nrows,
                        row_q[np.minimum(ci, nrows - 1)], 0.0)
        part = (toks * cv[:, :, None]).sum(axis=1)
        out = np.zeros((u_cap, row_q.shape[1]))
        m = cl < u_cap
        np.add.at(out, cl[m], part[m])
        return out

    row_q = rng.rand(B, 4)
    np.testing.assert_allclose(reduce(ci_j, cl_j, cv_j, row_q, B),
                               reduce(ci_n, cl_n, cv_n, row_q, B),
                               rtol=1e-5)

    # explicit C (mesh dp rounding) + row_base (global dp row space)
    C = -(-chunk_cap(u_cap, B * F) // 3) * 3
    ci2, cl2, cv2 = panel_chunk_tokens_np(flat, vals, u_cap, 2 * B, F,
                                          C=C, row_base=B)
    assert ci2.shape[0] == C
    rq2 = np.concatenate([np.zeros_like(row_q), row_q])
    np.testing.assert_allclose(reduce(ci2, cl2, cv2, rq2, 2 * B),
                               reduce(ci_j, cl_j, cv_j, row_q, B),
                               rtol=1e-5)

    # sorted_chunks=False backward (dp>1 meshes) == sorted backward
    k = 5
    blk = RowBlock(offset=np.arange(B + 1, dtype=np.int64) * F,
                   label=rng.choice([0.0, 1.0], B).astype(np.float32),
                   index=flat.astype(np.uint32),
                   value=vals)
    w = jnp.asarray(rng.randn(u_cap).astype(np.float32))
    V = jnp.asarray(rng.randn(u_cap, k).astype(np.float32) * 0.1)
    vm = jnp.asarray((rng.rand(u_cap) > 0.3).astype(np.float32))
    params = FMParams(w=w, V=V, v_mask=vm)
    pb = panel_chunk_tokens(pad_panel(blk, u_cap, B, F), u_cap)
    pred = fm_predict_panel(params, pb)
    gw_s, gV_s = fm_grad_panel(params, pb, pred, sorted_chunks=True)
    gw_u, gV_u = fm_grad_panel(params, pb, pred, sorted_chunks=False)
    np.testing.assert_allclose(np.asarray(gw_s), np.asarray(gw_u),
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gV_s), np.asarray(gV_u),
                               rtol=2e-5, atol=1e-6)
