"""Multi-host L-BFGS worker for tests/test_multihost_lbfgs.py (run through
launch.py): each process reads its byte range, partial (objv, auc, grad)
sums meet in the DCN allreduce, and every host runs identical two-loop /
Wolfe math. Writes its per-epoch objective trajectory as JSON."""
import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from difacto_tpu.parallel.multihost import initialize  # noqa: E402

initialize()

from difacto_tpu.learners import Learner  # noqa: E402

out_dir, data = sys.argv[1], sys.argv[2]
rank = jax.process_index()

ln = Learner.create("lbfgs")
ln.init([("data_in", data), ("m", "5"), ("V_dim", "0"), ("l2", "0"),
         ("init_alpha", "1"), ("tail_feature_filter", "0"),
         ("max_num_epochs", "19")])
seen = []
ln.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
ln.run()

with open(os.path.join(out_dir, f"traj-{rank}.json"), "w") as f:
    json.dump(seen, f)
print(f"rank {rank} done")
