"""Data pipeline tests — analogs of the reference's batch_reader_test.cc,
localizer_test.cc, and data-format roundtrips."""

import numpy as np
import pytest

from difacto_tpu.base import reverse_bytes, encode_fea_grp_id, decode_fea_grp_id
from difacto_tpu.config import Param, parse_cli_args, parse_config_file
from difacto_tpu.data import (BatchReader, Reader, RecWriter, RowBlock,
                              compact, read_rec_block)
from difacto_tpu.data.parsers import parse_adfea, parse_criteo, parse_libsvm


def load_all(uri, **kw):
    blocks = list(Reader(uri, "libsvm", **kw))
    return RowBlock.concat(blocks) if blocks else None


def test_parse_libsvm_fixture(rcv1_path):
    blk = load_all(rcv1_path)
    assert blk.size == 100
    assert blk.nnz == int(blk.offset[-1])
    assert blk.index.max() <= 47149  # fixture property (tests/README.md)
    assert set(np.unique(blk.label)) <= {0.0, 1.0, -1.0}
    # spot-check the first row's first entry: "1 440:0.033906..."
    assert blk.label[0] == 1.0
    assert blk.index[0] == 440
    np.testing.assert_allclose(blk.value[0], 0.033906222568727, rtol=1e-6)


def test_reader_sharding_partition(rcv1_path):
    """Each row appears in exactly one part (InputSplit contract)."""
    whole = load_all(rcv1_path)
    rows = []
    for p in range(4):
        blk = load_all(rcv1_path, part_idx=p, num_parts=4)
        if blk is not None:
            rows.append(blk)
    merged = RowBlock.concat(rows)
    assert merged.size == whole.size
    assert merged.nnz == whole.nnz
    # parts are contiguous line ranges, so concatenation in part order
    # reproduces the file exactly
    np.testing.assert_array_equal(merged.label, whole.label)
    np.testing.assert_array_equal(merged.index, whole.index)


def test_reader_small_chunks_equal_one_chunk(rcv1_path):
    a = load_all(rcv1_path)
    b = load_all(rcv1_path, chunk_bytes=1000)
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.index, b.index)
    np.testing.assert_array_equal(a.value, b.value)


def test_batch_reader_exact_boundaries(rcv1_path):
    sizes = [b.size for b in BatchReader(rcv1_path, batch_size=32)]
    assert sizes == [32, 32, 32, 4]


def test_batch_reader_shuffle_preserves_multiset(rcv1_path):
    plain = RowBlock.concat(list(BatchReader(rcv1_path, batch_size=100)))
    shuf = RowBlock.concat(list(
        BatchReader(rcv1_path, batch_size=10, shuffle_buf_size=50, seed=3)))
    assert shuf.size == plain.size
    assert shuf.nnz == plain.nnz
    # per-row nnz multiset invariant under permutation
    assert sorted(np.diff(shuf.offset)) == sorted(np.diff(plain.offset))
    assert np.sort(shuf.label).tolist() == np.sort(plain.label).tolist()


def test_batch_reader_neg_sampling(rcv1_path):
    full = RowBlock.concat(list(BatchReader(rcv1_path, batch_size=100)))
    sub = RowBlock.concat(list(
        BatchReader(rcv1_path, batch_size=100, neg_sampling=0.3, seed=1)))
    n_pos = int((full.label > 0).sum())
    assert int((sub.label > 0).sum()) == n_pos  # positives always kept
    assert int((sub.label <= 0).sum()) < int((full.label <= 0).sum())


def test_reverse_bytes_involution():
    rng = np.random.RandomState(0)
    x = rng.randint(0, 2**63, size=1000).astype(np.uint64)
    np.testing.assert_array_equal(reverse_bytes(reverse_bytes(x)), x)
    assert reverse_bytes(reverse_bytes(12345)) == 12345


def test_fea_grp_id_roundtrip():
    assert decode_fea_grp_id(encode_fea_grp_id(98765, 11, 12), 12) == 11


def test_localizer_compact(rcv1_path):
    blk = load_all(rcv1_path)
    out, uniq, cnt = compact(blk, need_counts=True)
    assert (np.diff(uniq.astype(np.int64) if uniq.max() < 2**63 else uniq)
            > 0).all() or len(uniq) == 1  # sorted strictly ascending
    assert out.index.max() == len(uniq) - 1
    # remapping is consistent: reversed original id == uniq[compact index]
    np.testing.assert_array_equal(uniq[out.index], reverse_bytes(blk.index))
    # counts sum to nnz
    assert int(cnt.sum()) == blk.nnz
    # brute-force count check on a few ids
    rev = reverse_bytes(blk.index)
    for i in [0, len(uniq) // 2, len(uniq) - 1]:
        assert cnt[i] == (rev == uniq[i]).sum()


def test_rec_roundtrip(rcv1_path, tmp_path):
    blk = load_all(rcv1_path)
    w = RecWriter(str(tmp_path / "data.rec"))
    for b in BatchReader(rcv1_path, batch_size=40):
        w.write(b)
    assert w.num_blocks == 3
    back = RowBlock.concat(list(Reader(str(tmp_path / "data.rec"), "rec")))
    np.testing.assert_array_equal(back.offset, blk.offset)
    np.testing.assert_array_equal(back.index, blk.index)
    np.testing.assert_allclose(back.value, blk.value)
    # rec sharding partitions members across parts
    tot = sum(b.size for p in range(2)
              for b in Reader(str(tmp_path / "data.rec"), "rec", p, 2))
    assert tot == 100


def test_parse_criteo():
    ints1 = [b"3", b""] + [b"5"] + [b""] * 10      # 13 integer columns
    ints2 = [b"", b"7"] + [b""] * 11
    row1 = b"\t".join([b"1"] + ints1 + [b"deadbeef", b"cafe0123"])
    row2 = b"\t".join([b"0"] + ints2 + [b"deadbeef"])
    chunk = row1 + b"\n" + row2 + b"\n"
    blk = parse_criteo(chunk)
    assert blk.size == 2
    assert blk.label.tolist() == [1.0, 0.0]
    assert np.diff(blk.offset).tolist() == [4, 2]
    # group ids live in the low 12 bits
    gids = (blk.index & np.uint64(4095)).astype(int)
    assert gids.tolist() == [0, 2, 13, 14, 1, 13]
    # same token+column hashes identically across rows
    assert blk.index[2] == blk.index[5]


def test_parse_adfea():
    chunk = b"100 2 1 5:1 7:2\n101 3 0 9:1\n"
    blk = parse_adfea(chunk)
    assert blk.size == 2
    assert blk.label.tolist() == [1.0, 0.0]
    assert np.diff(blk.offset).tolist() == [2, 1]
    assert decode_fea_grp_id(int(blk.index[0]), 12) == 1
    assert int(blk.index[0]) >> 12 == 5


def test_config_chain(tmp_path):
    from dataclasses import dataclass, field

    @dataclass
    class P1(Param):
        lr: float = field(default=0.01, metadata=dict(lo=0))
        batch_size: int = 100

    @dataclass
    class P2(Param):
        l1: float = 1.0

    conf = tmp_path / "c.conf"
    conf.write_text("lr = 0.5\n# comment\nl1 = 4\n")
    kwargs = parse_cli_args([str(conf), "batch_size=32"])
    p1, remain = P1.init_allow_unknown(kwargs)
    assert p1.lr == 0.5 and p1.batch_size == 32
    p2, remain = P2.init_allow_unknown(remain)
    assert p2.l1 == 4.0
    assert remain == []
    with pytest.raises(ValueError):
        P1.init_allow_unknown([("lr", "-1")])
