"""L-BFGS learner tests against the reference's golden trajectories
(tests/cpp/lbfgs_learner_test.cc, tests/cpp/lbfgs_twoloop_test.cc).
"""

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from difacto_tpu.learners.twoloop import (calc_delta, calc_direction,
                                          naive_two_loop)

OBJV_BASIC = [
    34.603421, 12.655075, 5.224232, 2.713903, 1.290586, 0.645131, 0.317889,
    0.156723, 0.075331, 0.032091, 0.018044, 0.008562, 0.004336, 0.002132,
    0.001051, 0.000506, 0.000227, 0.000119, 0.000059,
]

OBJV_TAIL = [
    43.865008, 21.728511, 10.893458, 5.038567, 2.293318, 1.064151, 0.518891,
    0.257997, 0.128646, 0.064974, 0.028329, 0.016543, 0.007910, 0.004053,
    0.002001, 0.000978, 0.000437, 0.000216, 0.000112,
]

OBJV_WITHV = [
    35.224265, 21.631514, 18.394319, 16.077692, 12.389012, 8.888516,
    8.446880, 8.146090, 8.023501, 7.981967, 7.955119, 7.937092, 7.922456,
    7.880596, 7.861660, 7.838057, 7.807892, 7.784401, 7.756756, 7.728613,
    7.724718, 7.709527, 7.705667,
]


def test_twoloop_matches_naive():
    """Vector-free Gram-basis two-loop == textbook two-loop
    (lbfgs_twoloop_test.cc:40-90)."""
    rng = np.random.RandomState(0)
    n, m = 40, 5
    s = [rng.randn(n) for _ in range(m)]
    y = [rng.randn(n) for _ in range(m)]
    g = rng.randn(n)
    got = calc_direction(s, y, g)
    want = naive_two_loop(s, y, g)
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_twoloop_empty_history():
    g = np.array([1.0, -2.0, 3.0])
    np.testing.assert_allclose(calc_direction([], [], g), -g)


def run_lbfgs(rcv1_path, **over):
    args = [("data_in", rcv1_path), ("m", "5"), ("V_dim", "0"), ("l2", "0"),
            ("init_alpha", "1"), ("tail_feature_filter", "0"),
            ("max_num_epochs", "19")]
    d = dict(args)
    d.update({k: str(v) for k, v in over.items()})
    learner = Learner.create("lbfgs")
    remain = learner.init(list(d.items()))
    assert remain == []
    return learner


def test_lbfgs_basic_golden(rcv1_path):
    """tests/cpp/lbfgs_learner_test.cc:9-47 to the reference's 1e-5."""
    learner = run_lbfgs(rcv1_path)
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    assert len(seen) == 19
    err = np.abs(np.array(seen) - np.array(OBJV_BASIC))
    assert err.max() < 1e-5, list(zip(seen, OBJV_BASIC))


def test_lbfgs_tail_filter_golden(rcv1_path):
    """tests/cpp/lbfgs_learner_test.cc:49-86."""
    learner = run_lbfgs(rcv1_path, tail_feature_filter="2")
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    err = np.abs(np.array(seen) - np.array(OBJV_TAIL))
    assert err.max() < 1e-5, list(zip(seen, OBJV_TAIL))


def test_lbfgs_withv_golden(rcv1_path):
    """tests/cpp/lbfgs_learner_test.cc:88-146: FM V_dim=5 with the
    deterministic weight initializer.

    Tolerance 2e-4 (reference uses 1e-4 for its own arithmetic ordering; our
    segment-sum reductions order differently, and fp32 noise accumulates over
    23 epochs — the reference itself had to comment out one epoch value,
    lbfgs_learner_test.cc:103)."""
    learner = run_lbfgs(rcv1_path, V_dim="5", l2="0.1", V_l2="0.01",
                        V_threshold="0", rho="0.5",
                        max_num_epochs=str(len(OBJV_WITHV)))

    def initializer(lens, weights):
        # (lbfgs_learner_test.cc:128-140): V[j] = (j - V_dim/2) * .01
        n = 0
        for l in lens:
            for i in range(l):
                if i > 0:
                    weights[n] = (i - (l - 1) / 2) * 0.01
                n += 1
        return weights

    learner.set_weight_initializer(initializer)
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    err = np.abs(np.array(seen) - np.array(OBJV_WITHV))
    assert err.max() < 2e-4, list(zip(seen, OBJV_WITHV))


def test_lbfgs_auc_and_nnz(rcv1_path):
    learner = run_lbfgs(rcv1_path, max_num_epochs="3")
    progs = []
    learner.add_epoch_end_callback(lambda e, p: progs.append(p))
    learner.run()
    assert 0.5 < progs[-1].auc <= 1.0
    assert progs[-1].nnz_w > 0
