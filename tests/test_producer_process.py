"""Process-based producer pipeline: ProcessProducerPool determinism vs the
thread pool, worker-death/exception retry (exactly-once), straggler
re-issue, and shared-memory ring hygiene — no leaked /dev/shm segments on
any exit path (clean, consumer break, worker raise; ISSUE 1).

Every test runs under an explicit SIGALRM deadline: a deadlocked
multiprocess pipeline must fail the suite loudly, not hang the tier-1
command. Workers are ``spawn``-ed and inherit JAX_PLATFORMS=cpu (the pool
sets it for its workers regardless; conftest.py sets it for this parent).
All make_iter callables live at module level so spawn can pickle them by
reference.
"""

import contextlib
import os
import signal
import time

import numpy as np
import pytest

from difacto_tpu.data.producer_pool import (OrderedProducerPool,
                                            ProcessProducerPool)


@contextlib.contextmanager
def deadline(seconds: int):
    """Hard per-test timeout: multiprocess bugs hang, and a hang must be
    a failure, not an 870 s tier-1 timeout."""
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def ring_segments() -> set:
    try:
        return {n for n in os.listdir("/dev/shm")
                if n.startswith("difacto_ring")}
    except FileNotFoundError:  # pragma: no cover - non-Linux
        return set()


# ---------------------------------------------------------- make_iters
# (module-level: spawn pickles them by reference)

def seeded_items(part):
    """Deterministic per-part item stream: mixed structure (tuple + dict +
    arrays + scalars) to exercise the ring's encode/decode walk."""
    rng = np.random.RandomState(1000 + part)
    for j in range(5):
        yield {"part": part, "j": j,
               "a": rng.randint(0, 1 << 30, 64).astype(np.int32),
               "b": rng.rand(33).astype(np.float32),
               "meta": ("x", j)}


def slow_items(part):
    for j in range(12):
        time.sleep(0.03)
        yield (part, j, np.full(8, part * 100 + j, dtype=np.int64))


def failing_part1(part):
    if part == 1:
        raise RuntimeError("persistent boom")
    for j in range(3):
        yield (part, j)


def hang_once_items(marker_dir, part):
    """Attempt 1 of the last part hangs (after dropping a marker file);
    the re-issued attempt sees the marker and proceeds — the process
    twin of test_cached.test_producer_pool_straggler_reissue."""
    if part == 11:
        marker = os.path.join(marker_dir, f"attempt_{part}")
        if not os.path.exists(marker):
            open(marker, "w").close()
            time.sleep(120)  # hung IO; terminated at pool shutdown
    for j in range(3):
        yield (part, j)


def _snap(items):
    """Copy-out + normalize a pool's yields for comparison (process-pool
    arrays are ring views valid for one iteration)."""
    out = []
    for part, item in items:
        arrays = []
        from difacto_tpu.data.shm_ring import decode_item, encode_item
        spec = encode_item(item, arrays)
        out.append((part, decode_item(spec, [np.array(a) for a in arrays])))
    return out


# -------------------------------------------------------------- tests

def test_process_pool_matches_thread_pool_bytes():
    """Determinism contract: the process pool yields the byte-identical
    (part, item) sequence the thread pool yields for the same seeded
    parts."""
    with deadline(120):
        before = ring_segments()
        expect = _snap(OrderedProducerPool(4, seeded_items, n_workers=2))
        got = _snap(ProcessProducerPool(4, seeded_items, n_workers=2,
                                        slot_bytes=1 << 20))
    assert len(got) == len(expect) == 20
    for (pe, ie), (pg, ig) in zip(expect, got):
        assert pe == pg
        assert ie["part"] == ig["part"] and ie["j"] == ig["j"]
        assert ie["meta"] == ig["meta"]
        np.testing.assert_array_equal(ie["a"], ig["a"])
        np.testing.assert_array_equal(ie["b"], ig["b"])
    assert ring_segments() == before  # no leaked segments, clean path


def test_process_pool_survives_worker_kill():
    """A worker SIGKILLed mid-part is detected, its part re-queued
    (pool.reset) and resumed by a live worker exactly after the items
    already delivered — no duplicates, no gaps (the generation guard
    across the process boundary)."""
    with deadline(120):
        before = ring_segments()
        pool = ProcessProducerPool(2, slow_items, n_workers=2, depth=4,
                                   slot_bytes=1 << 20)
        got = []
        killed = False
        for part, item in pool:
            got.append((part, item[1], int(item[2][0])))
            if not killed and len(got) == 3:
                # part 0 is assigned to worker 0 (lowest part to the
                # first-fed worker); kill it mid-part
                os.kill(pool._procs[0].pid, signal.SIGKILL)
                killed = True
        assert killed
        expect = [(p, j, p * 100 + j) for p in range(2) for j in range(12)]
        assert got == expect
        assert ring_segments() == before


def test_process_pool_escalates_after_max_retries():
    """A persistently raising part escalates to the consumer after
    max_retries, after delivering the preceding parts — and the ring is
    still unlinked on the error path."""
    with deadline(120):
        before = ring_segments()
        pool = ProcessProducerPool(2, failing_part1, n_workers=2,
                                   max_retries=1, slot_bytes=1 << 20)
        got = []
        with pytest.raises(RuntimeError, match="persistent boom"):
            for part, item in pool:
                got.append((part, item))
        assert got == [(0, (0, j)) for j in range(3)]
        assert ring_segments() == before


def test_process_pool_straggler_reissue(tmp_path):
    """A part stuck on a hung worker process is re-issued through
    WorkloadPool.remove_stragglers; delivery stays exactly-once."""
    import functools

    from difacto_tpu.tracker.workload_pool import (WorkloadPool,
                                                   WorkloadPoolParam)
    with deadline(120):
        before = ring_segments()
        wp = WorkloadPool(WorkloadPoolParam(straggler_timeout=0.5))
        pool = ProcessProducerPool(
            12, functools.partial(hang_once_items, str(tmp_path)),
            n_workers=3, pool=wp, slot_bytes=1 << 20, join_timeout=2.0)
        items = list(pool)
        assert items == [(p, (p, j)) for p in range(12) for j in range(3)]
        assert os.path.exists(tmp_path / "attempt_11")  # it DID hang
        assert ring_segments() == before


def test_ring_no_leak_on_consumer_break():
    """Consumer early-exit (break mid-epoch) tears the ring down."""
    with deadline(120):
        before = ring_segments()
        pool = ProcessProducerPool(3, seeded_items, n_workers=2,
                                   slot_bytes=1 << 20)
        for i, (part, item) in enumerate(pool):
            if i == 2:
                break
        assert ring_segments() == before


def test_ring_oversize_item_falls_back_to_pickle():
    """An item larger than a ring slot travels the pickled channel —
    slower, never wrong — and is counted for observability."""
    with deadline(120):
        pool = ProcessProducerPool(2, seeded_items, n_workers=1,
                                   slot_bytes=256)  # < one item's arrays
        got = _snap(pool)
        assert [g[1]["j"] for g in got] == list(range(5)) * 2
        assert pool.overflow_items == 10


def test_ring_encode_decode_roundtrip_and_header():
    """ShmRing slot round-trip: structure, dtypes, zero-copy reads, and
    the tail header's (part, seq, gen) identity."""
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.data.shm_ring import ShmRing
    blk = RowBlock(offset=np.array([0, 2, 5], np.int64),
                   label=np.array([1.0, 0.0], np.float32),
                   index=np.arange(5, dtype=np.uint32),
                   value=None)
    item = ("ready", blk, ("panel", np.arange(12, dtype=np.int32),
                           np.zeros(3, np.float32), True, 2, 6, 8))
    ring = ShmRing(n_slots=2, slot_bytes=1 << 16)
    try:
        ring.write(0, item, part=3, seq=7, gen=2)
        out, part, seq, gen = ring.read(0)
        assert (part, seq, gen) == (3, 7, 2)
        kind, oblk, payload = out
        assert kind == "ready" and payload[0] == "panel"
        assert isinstance(oblk, RowBlock) and oblk.value is None
        np.testing.assert_array_equal(oblk.offset, blk.offset)
        np.testing.assert_array_equal(payload[1],
                                      np.arange(12, dtype=np.int32))
        assert payload[3:] == (True, 2, 6, 8)
        del out, oblk, payload  # drop the zero-copy views before close
    finally:
        ring.unlink()
    assert ring.name not in ring_segments()


def test_learner_process_mode_matches_thread_trajectory(rcv1_path):
    """End-to-end: the SGD learner's streamed hashed path produces the
    same training trajectory with producer_mode=process as with threads
    (same batches, same canonical order), and reports the transport +
    stage decomposition it ran."""
    from difacto_tpu.learners import Learner
    base = [("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"),
            ("l1", "1"), ("lr", "1"), ("num_jobs_per_epoch", "2"),
            ("batch_size", "50"), ("max_num_epochs", "2"),
            ("shuffle", "0"), ("report_interval", "0"),
            ("stop_rel_objv", "0"), ("device_cache_mb", "0"),
            ("hash_capacity", "4096"), ("num_producers", "1")]

    def run(mode):
        ln = Learner.create("sgd")
        ln.init(base + [("producer_mode", mode)])
        seen = []
        ln.add_epoch_end_callback(
            lambda e, t, v: seen.append((t.nrows, t.loss)))
        ln.run()
        return seen, ln.stage_stats()

    with deadline(300):
        before = ring_segments()
        t_seen, t_stats = run("thread")
        p_seen, p_stats = run("process")
    assert t_stats["producer_mode"] == "thread"
    assert p_stats["producer_mode"] == "process"
    assert p_stats["pack_s"] > 0  # worker-side pack time was collected
    assert [n for n, _ in t_seen] == [n for n, _ in p_seen]
    np.testing.assert_allclose([ls for _, ls in t_seen],
                               [ls for _, ls in p_seen], rtol=1e-6)
    assert ring_segments() == before


def test_no_leaked_segments_overall():
    """The ISSUE 1 acceptance check: whatever ran before this test, no
    difacto ring segment may be live in /dev/shm between tests (every
    pool unlinks on its own exit paths; atexit is only the crash net)."""
    assert ring_segments() == set()
