"""Hashed store mode and host-part plumbing tests."""

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from difacto_tpu.parallel.multihost import host_part


def test_host_part_single_controller():
    assert host_part() == (0, 1)


def test_hashed_store_trains(rcv1_path):
    """Hashed fixed-capacity mode: no dictionary, objective decreases,
    save/load round-trips."""
    import tempfile, os
    d = tempfile.mkdtemp()
    m = os.path.join(d, "hm")
    args = [("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"), ("l1", "1"),
            ("lr", "1"), ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "10"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", str(1 << 20)), ("model_out", m)]
    ln = Learner.create("sgd")
    assert ln.init(list(args)) == []
    assert ln.store.hashed
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    ln.run()
    assert seen[-1] < seen[0] * 0.8
    # 2^20 slots vs ~7k rcv1 features: ~23 expected collisions, trajectory
    # close to the exact-dictionary golden run (GOLDEN[9], 10th epoch)
    assert abs(seen[-1] - 47.698351) < 0.5

    l2 = Learner.create("sgd")
    l2.init(list(args))
    n = l2.store.load(l2._model_name(m, -1))
    assert n > 0
    np.testing.assert_allclose(np.asarray(l2.store.state.w),
                               np.asarray(ln.store.state.w))


def test_multihost_dictionary_store_rejected_without_mesh(rcv1_path,
                                                          monkeypatch):
    """Multi-host + dictionary store WITHOUT a mesh must error (outside
    the synchronized-step schedule there is no id exchange, so per-host
    slot assignment would train independent replicas), pointing at
    hash_capacity. WITH a mesh the dictionary store is supported — the
    control plane ships raw ids (tests/test_multihost_spmd.py)."""
    import difacto_tpu.parallel.multihost as mh
    monkeypatch.setattr(mh, "host_part", lambda: (0, 2))
    ln = Learner.create("sgd")
    with pytest.raises(ValueError, match="hash_capacity"):
        ln.init([("data_in", rcv1_path)])


def test_map_keys_deferred_growth():
    """map_keys(grow=False) records inserts without touching the device
    state; grow_to applies the doubling later (the SPMD lookahead thread
    protocol — learners/sgd.py exchange())."""
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
    st = SlotStore(SGDUpdaterParam.init_allow_unknown(
        [("init_capacity", "4")])[0])
    assert st.state.capacity == 4
    keys = np.arange(1, 11, dtype=np.uint64)
    slots = st.map_keys(keys, grow=False)
    # slots assigned beyond the device capacity, state untouched
    assert st.next_slot == 11
    assert st.state.capacity == 4
    cap = 4
    while st.next_slot > cap:
        cap *= 2
    st.grow_to(cap)
    assert st.state.capacity == 16
    # the mapping is stable and a second lookup agrees
    np.testing.assert_array_equal(st.map_keys(keys), slots)
    # grown rows are addressable
    w, _, _ = st.pull(keys)
    assert w.shape == (10,)


def test_hashed_push_collision_aggregates():
    """In-batch slot collisions must alias (sum) the colliding features'
    updates, not nondeterministically drop one (scatter .set needs unique
    slots). Keys 5 and 12 both map to slot 6 at hash_capacity=8."""
    from difacto_tpu.store.local import K_GRADIENT, SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    param = SGDUpdaterParam(V_dim=2, V_threshold=0, lr=1.0, l1=0.0, l2=0.0,
                            hash_capacity=8)
    s1 = SlotStore(param)
    keys = np.array([5, 12], dtype=np.uint64)
    assert (s1.map_keys(keys) == 6).all()
    slots, remap, _ = s1.map_keys_dedup(keys)
    assert list(slots) == [6] and list(remap) == [0, 0]

    gv = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    s1.push(keys, K_GRADIENT, np.array([1.0, 2.0], np.float32), gv,
            np.array([1.0, 1.0], np.float32))

    s2 = SlotStore(param)
    s2.push(np.array([5], dtype=np.uint64), K_GRADIENT,
            np.array([3.0], np.float32), gv.sum(0, keepdims=True),
            np.array([1.0], np.float32))
    # the fused rows carry w, the FTRL aux AND the embeddings — one
    # array compare covers the whole table
    np.testing.assert_allclose(np.asarray(s1.state.VVg),
                               np.asarray(s2.state.VVg))


def test_hashed_learner_with_heavy_collisions(rcv1_path):
    """Tiny hash_capacity => every batch has in-batch collisions; the COO
    remap path must keep training deterministic and finite."""
    def run():
        ln = Learner.create("sgd")
        ln.init([("data_in", rcv1_path), ("V_dim", "2"), ("V_threshold", "0"),
                 ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
                 ("batch_size", "50"), ("max_num_epochs", "2"),
                 ("shuffle", "0"), ("report_interval", "0"),
                 ("stop_rel_objv", "0"), ("num_jobs_per_epoch", "1"),
                 ("hash_capacity", "64")])
        seen = []
        ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
        ln.run()
        from difacto_tpu.updaters.sgd_updater import col_w
        return np.asarray(col_w(ln.store.param, ln.store.state)), seen

    w1, seen1 = run()
    w2, seen2 = run()
    np.testing.assert_array_equal(w1, w2)
    assert np.isfinite(seen1).all() and seen1 == seen2


def test_hashed_store_deterministic_across_instances(rcv1_path):
    """Two independent runs produce identical tables (the multi-controller
    requirement: no insertion-order-dependent state)."""
    def run():
        ln = Learner.create("sgd")
        ln.init([("data_in", rcv1_path), ("V_dim", "2"), ("V_threshold", "2"),
                 ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
                 ("batch_size", "50"), ("max_num_epochs", "2"),
                 ("shuffle", "0"), ("report_interval", "0"),
                 ("stop_rel_objv", "0"), ("num_jobs_per_epoch", "1"),
                 ("hash_capacity", "32768")])
        ln.run()
        from difacto_tpu.updaters.sgd_updater import col_w
        return np.asarray(col_w(ln.store.param, ln.store.state))

    np.testing.assert_array_equal(run(), run())


def test_pull_unsorted_and_colliding_keys():
    """pull must honor the device kernels' sorted+unique declaration even
    when the caller's key order is unsorted (dictionary slots follow
    insertion order) or keys collide (hashed mode), remapping rows back to
    the caller's order (advisor round-2 finding)."""
    from difacto_tpu.store.local import K_GRADIENT, SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    # dictionary store: insert in an order whose slots are NOT sorted when
    # the keys are pulled sorted
    param = SGDUpdaterParam(V_dim=0, lr=1.0, l1=0.0, l2=0.0)
    s = SlotStore(param)
    s.map_keys(np.array([30, 10, 20], dtype=np.uint64))  # slots 1,2,3
    s.push(np.array([30, 10, 20], dtype=np.uint64), K_GRADIENT,
           np.array([-3.0, -1.0, -2.0], np.float32))
    w, _, _ = s.pull(np.array([10, 20, 30], dtype=np.uint64))
    w_single = [s.pull(np.array([k], dtype=np.uint64))[0][0]
                for k in (10, 20, 30)]
    np.testing.assert_allclose(w, w_single)
    assert w[0] != w[1] and w[1] != w[2]

    # hashed store: colliding keys must both see the shared row
    ph = SGDUpdaterParam(V_dim=0, lr=1.0, l1=0.0, l2=0.0, hash_capacity=8)
    sh = SlotStore(ph)
    keys = np.array([5, 12], dtype=np.uint64)  # both -> slot 6
    sh.push(keys, K_GRADIENT, np.array([-1.0, -1.0], np.float32))
    w, _, _ = sh.pull(keys)
    assert w[0] == w[1] != 0


def test_mesh_dim_min_divisibility():
    """Every bucket rung from mesh_dim_min(dp) must divide by dp — incl.
    non-power-of-two dp (advisor round-2 finding: dp=3 with floor 8 gave
    rungs 8/16 that cannot shard over a 3-way axis)."""
    from difacto_tpu.ops.batch import bucket, mesh_dim_min

    for dp in (1, 2, 3, 4, 5, 6, 8):
        m = mesh_dim_min(dp)
        assert m >= 8 and m % (2 * dp) == 0
        for n in list(range(1, 70)) + [100, 1000, 12345]:
            b = bucket(n, m)
            assert b >= n and b % dp == 0, (dp, n, b)


def test_collision_stats():
    """collision_stats quantifies the hashed store's id aliasing (round-4
    verdict missing #1 — the reference never aliases, its servers key by
    exact 64-bit id, src/sgd/sgd_updater.h:141-176). Checked against a
    brute-force slot map at small capacity."""
    from difacto_tpu.base import reverse_bytes
    from difacto_tpu.store.local import collision_stats

    rng = np.random.RandomState(3)
    ids = rng.randint(1, 1 << 48, 500, dtype=np.uint64)
    cap = 257
    st = collision_stats(ids, cap)
    uids = np.unique(ids)
    slots = (reverse_bytes(uids) % np.uint64(cap - 1) + np.uint64(1))
    occ = {}
    for s in slots:
        occ[int(s)] = occ.get(int(s), 0) + 1
    collided = sum(c for c in occ.values() if c > 1)
    assert st["n_ids"] == len(uids)
    assert st["slots_used"] == len(occ)
    assert st["collided_frac"] == round(collided / len(uids), 4)
    # generous capacity -> few collisions; tiny capacity -> nearly all
    assert collision_stats(uids, 1 << 20)["collided_frac"] < 0.01
    assert collision_stats(uids, 64)["collided_frac"] > 0.9
