"""Sharded-mesh tests on the 8-device virtual CPU mesh (conftest.py).

Validates the SPMD "parameter server" layout (parallel/mesh.py): the slot
table sharded over the fs axis, batches over dp, and the full SGD train step
compiling and matching the single-device golden trajectory — the TPU analog of
the reference's property that the same learner code runs under local and
distributed stores (SURVEY §4).
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from difacto_tpu.learners import Learner
from difacto_tpu.parallel import (batch_sharding, make_mesh, shard_pytree,
                                  state_sharding)
from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam, init_state

GOLDEN_FINAL = 44.109764  # tests/cpp/sgd_learner_test.cc:38


def test_make_mesh_shapes():
    mesh = make_mesh(dp=2, fs=4)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dp", "fs")
    with pytest.raises(ValueError):
        make_mesh(dp=4, fs=4)  # only 8 virtual devices


def test_state_sharded_over_fs():
    from difacto_tpu.updaters.sgd_updater import col_V
    mesh = make_mesh(dp=2, fs=4)
    param = SGDUpdaterParam(V_dim=4)
    state = init_state(param, 1 << 10)
    sharded = shard_pytree(state, state_sharding(mesh))
    assert sharded.VVg.sharding == NamedSharding(mesh, P("fs", None))
    np.testing.assert_array_equal(np.asarray(col_V(param, sharded)),
                                  np.asarray(col_V(param, state)))


def _run(rcv1_path, **over):
    args = [("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"), ("l1", "1"),
            ("lr", "1"), ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "20"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0")]
    args += [(k, str(v)) for k, v in over.items()]
    learner = Learner.create("sgd")
    assert learner.init(args) == []
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    return learner, seen


def test_sgd_sharded_matches_golden(rcv1_path):
    """Full training over a 2x4 mesh reproduces the reference trajectory."""
    learner, seen = _run(rcv1_path, mesh_dp=2, mesh_fs=4)
    assert learner.mesh is not None
    assert abs(seen[-1] - GOLDEN_FINAL) < 5e-5
    # the table stayed in its fs-sharded layout through all updates
    assert learner.store.state.w.sharding.spec == P("fs")


def test_sgd_sharded_fm_matches_single_device(rcv1_path):
    """FM path (V_dim=2) under dp-only and fs-only meshes agrees with the
    unsharded run (the collectives must be numerically transparent)."""
    base_over = dict(V_dim=2, V_threshold=2, lr=0.1, l1=0.1, l2=0,
                     max_num_epochs=3)
    _, ref = _run_cached_single(rcv1_path, base_over)
    for mesh_over in (dict(mesh_dp=8), dict(mesh_fs=8),
                      dict(mesh_dp=4, mesh_fs=2)):
        _, seen = _run(rcv1_path, **base_over, **mesh_over)
        np.testing.assert_allclose(seen, ref, rtol=1e-4)


_single_cache = {}


def _run_cached_single(rcv1_path, over):
    key = tuple(sorted(over.items()))
    if key not in _single_cache:
        _single_cache[key] = _run(rcv1_path, **over)
    return _single_cache[key]


@pytest.fixture(scope="module")
def uniform_path(tmp_path_factory):
    """Synthetic uniform-width libsvm data (8 features/row): the panel
    layout engages, so the mesh dispatches the panel + chunked-run step
    instead of COO (round-4 verdict #1)."""
    from conftest import write_uniform_libsvm
    return write_uniform_libsvm(
        tmp_path_factory.mktemp("uniform") / "uniform.libsvm")


def test_mesh_panel_matches_single_device(uniform_path):
    """The mesh panel + chunked-run train step (the round-5 fast path —
    previously the mesh fell back to the unsorted COO backward) matches
    the unsharded trajectory under dp-sharded, fs-sharded, and mixed
    meshes, and actually engages (panel step counter)."""
    base = dict(V_dim=2, V_threshold=2, lr=0.1, l1=0.1, l2=0,
                max_num_epochs=3)
    ref_ln, ref = _run(uniform_path, **base)
    assert getattr(ref_ln, "_mesh_panel_steps", 0) == 0
    for mesh_over in (dict(mesh_dp=2, mesh_fs=4), dict(mesh_dp=8),
                      dict(mesh_fs=8)):
        ln, seen = _run(uniform_path, **base, **mesh_over)
        # streamed epochs dispatch through the panel path; replayed epochs
        # rerun the staged PanelBatch payloads
        assert getattr(ln, "_mesh_panel_steps", 0) > 0, mesh_over
        np.testing.assert_allclose(seen, ref, rtol=1e-4)
