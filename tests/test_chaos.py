"""Fault-injection chaos suite (ISSUE 3): the resilience layer, proven
under the failures it exists for.

Covers the four acceptance legs end to end — SIGKILL mid-checkpoint then
auto-resume from the previous verified generation; hot-reload under live
loadgen traffic with zero errors; SIGTERM graceful drain under load;
client retry through injected socket closes — plus the corrupt-
checkpoint matrix (truncated npz, bit-flipped array, missing manifest)
against auto_resume / task=pred / task=serve, the fault-registry
mechanics, the atomic remote save, and relaunch backoff.

Conventions: every network/subprocess-bearing test runs under an
explicit SIGALRM deadline (the test_serve.py/test_producer_process.py
convention) and carries the ``chaos`` marker (conftest.py) so the suite
is selectable alone with ``-m chaos`` while staying in tier-1.
"""

import contextlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from difacto_tpu.__main__ import main
from difacto_tpu.utils import faultinject

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.chaos


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No injected fault may leak across tests."""
    yield
    faultinject.configure("")


def fixture_rows(rcv1_path):
    with open(rcv1_path, "rb") as f:
        return [l for l in f.read().splitlines() if l.strip()]


def train_args(rcv1_path, model, epochs=3, extra=()):
    # stop_rel_objv=0: the generation tests count on exactly ``epochs``
    # interval checkpoints, so relative-loss early stop is disabled
    return [f"data_in={rcv1_path}", "lr=1", "l1=1", "l2=1",
            "batch_size=100", f"max_num_epochs={epochs}", "shuffle=0",
            "num_jobs_per_epoch=1", "report_interval=0",
            "stop_rel_objv=0", f"model_out={model}", *extra]


@pytest.fixture(scope="module")
def ckpt_model(rcv1_path, tmp_path_factory):
    """A trained model WITH interval checkpoints: ``_iter-0..2_part-0``
    (+ manifests), the final ``_part-0`` and the ``.meta`` marker — the
    generation family the recovery tests corrupt and walk."""
    d = tmp_path_factory.mktemp("chaos_model")
    model = str(d / "model")
    assert main(train_args(rcv1_path, model,
                           extra=("ckpt_interval=1",))) == 0
    for e in range(3):
        assert os.path.exists(f"{model}_iter-{e}_part-0")
        assert os.path.exists(f"{model}_iter-{e}_part-0.manifest.json")
    return model


def corrupt_flip(path):
    """Flip a byte inside the 'w' array payload (past the zip member
    name + npy header) — a bit flip the manifest digest / zip CRC must
    catch."""
    data = bytearray(open(path, "rb").read())
    i = data.find(b"w.npy") + 200
    assert i + 200 < len(data)
    data[i] ^= 0xFF
    open(path, "wb").write(bytes(data))


def corrupt_truncate(path):
    data = open(path, "rb").read()
    open(path, "wb").write(data[:len(data) // 2])


# ------------------------------------------------------ fault registry

def test_faultinject_parse_fire_and_disarm():
    from difacto_tpu.utils.faultinject import FaultInjected

    with pytest.raises(ValueError, match="bad DIFACTO_FAULTS"):
        faultinject.parse("garbage")
    with pytest.raises(ValueError, match="unknown kind"):
        faultinject.parse("p.x:explode@1")
    # after_n skips N calls, fires on the N+1-th, then re-arms
    faultinject.configure("p.x:close@1:2")
    assert [faultinject.fire("p.x") for _ in range(6)] == \
        [None, None, "close", None, None, "close"]
    assert faultinject.stats() == {"p.x": 2}
    # err raises the OSError subclass real IO paths already handle
    faultinject.configure("p.y:err@1")
    with pytest.raises(FaultInjected):
        faultinject.fire("p.y")
    assert isinstance(FaultInjected("x"), OSError)
    # unarmed = no-op
    faultinject.configure("")
    assert faultinject.fire("p.y") is None and not faultinject.armed()


def test_launch_relaunch_backoff():
    import random

    from launch import RELAUNCH_BACKOFF_CAP_S, _relaunch_delay
    rng = random.Random(7)
    d0 = [_relaunch_delay(0, 2.0, rng) for _ in range(50)]
    d3 = [_relaunch_delay(3, 2.0, rng) for _ in range(50)]
    # floored at one heartbeat timeout, exponential growth, jittered
    assert min(d0) >= 2.0 and max(d0) <= 2.0 * 1.5
    assert min(d3) >= 2.0 * 8 * 0.5 and max(d3) <= 2.0 * 8 * 1.5
    assert len(set(d0)) > 1, "no jitter"
    # capped: attempt 30 must not wait 2**30 heartbeats
    assert _relaunch_delay(30, 2.0, rng) <= RELAUNCH_BACKOFF_CAP_S * 1.5


# ------------------------------------------------- checkpoint verifying

def test_remote_save_npz_atomic_and_torn():
    """Satellite: remote saves upload to a .tmp key then finalize; an
    injected torn write leaves no manifest, so the checkpoint reads as
    incomplete instead of half-parsing."""
    fsspec = pytest.importorskip("fsspec")
    from difacto_tpu.utils import manifest as mft
    from difacto_tpu.utils import stream

    uri = "memory://chaos_atomic/ck.npz"
    stream.save_npz(uri, a=np.arange(7), manifest={"generation": 1})
    fs = fsspec.filesystem("memory")
    names = [e.rsplit("/", 1)[-1]
             for e in fs.ls("/chaos_atomic", detail=False)]
    assert "ck.npz" in names and "ck.npz.manifest.json" in names
    assert not any(n.endswith(".tmp") for n in names), names
    with stream.load_npz(uri) as z:
        assert z["a"].tolist() == list(range(7))
    assert mft.verify(uri)["generation"] == 1

    faultinject.configure("ckpt.write:truncate@1")
    stream.save_npz("memory://chaos_atomic/torn.npz", a=np.arange(64),
                    manifest={"generation": 1}, fault_point="ckpt.write")
    assert faultinject.stats() == {"ckpt.write": 1}
    faultinject.configure("")
    with pytest.raises(mft.CheckpointCorrupt, match="manifest missing"):
        mft.verify("memory://chaos_atomic/torn.npz",
                   require_manifest=True)


def test_corrupt_checkpoint_matrix(ckpt_model, tmp_path):
    """Satellite: truncated npz, bit-flipped array and missing manifest
    all surface as the typed CheckpointCorrupt, never a numpy crash."""
    import shutil

    from difacto_tpu.store.local import CheckpointCorrupt, SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
    from difacto_tpu.utils import manifest as mft

    src = f"{ckpt_model}_part-0"
    for name, corrupt in (("trunc", corrupt_truncate),
                          ("flip", corrupt_flip)):
        p = str(tmp_path / name)
        shutil.copy(src, p)
        shutil.copy(src + mft.MANIFEST_SUFFIX, p + mft.MANIFEST_SUFFIX)
        corrupt(p)
        with pytest.raises(CheckpointCorrupt) as ei:
            SlotStore(SGDUpdaterParam(V_dim=0)).load(p)
        assert p in str(ei.value)  # names the bad file
    # missing manifest: corruption where a manifest is required ...
    p = str(tmp_path / "nomanifest")
    shutil.copy(src, p)
    with pytest.raises(CheckpointCorrupt, match="manifest missing"):
        mft.verify(p, require_manifest=True)
    # ... but legacy-accepted (intact npz) where it is not
    assert mft.verify(p) is None
    assert SlotStore(SGDUpdaterParam(V_dim=0)).load(p) > 0


def test_pred_fails_typed_on_corrupt_model(ckpt_model, rcv1_path,
                                           tmp_path):
    """task=pred never falls back (predictions must come from the model
    asked for) — it fails with the typed error naming the bad file."""
    import shutil

    from difacto_tpu.store.local import CheckpointCorrupt

    model = str(tmp_path / "pmodel")
    shutil.copy(f"{ckpt_model}_part-0", model + "_part-0")
    shutil.copy(f"{ckpt_model}_part-0.manifest.json",
                model + "_part-0.manifest.json")
    corrupt_flip(model + "_part-0")
    with pytest.raises(CheckpointCorrupt) as ei:
        main(["task=pred", f"model_in={model}", f"data_val={rcv1_path}",
              f"pred_out={tmp_path / 'pred'}"])
    assert model + "_part-0" in str(ei.value)


def test_auto_resume_walks_back_generations(ckpt_model, rcv1_path,
                                            tmp_path):
    """auto_resume with the two newest interval checkpoints corrupted
    (bit flip / torn manifest-less) resumes from the oldest verified one
    instead of crashing — no manual cleanup."""
    import shutil

    model = str(tmp_path / "model")
    for e in range(3):
        for suf in ("", ".manifest.json"):
            shutil.copy(f"{ckpt_model}_iter-{e}_part-0{suf}",
                        f"{model}_iter-{e}_part-0{suf}")
    with open(model + ".meta", "w") as f:
        f.write(json.dumps({"last_epoch": 2}))
    corrupt_flip(model + "_iter-2_part-0")                # bit flip
    corrupt_truncate(model + "_iter-1_part-0")            # torn npz ...
    os.remove(model + "_iter-1_part-0.manifest.json")     # ... no marker
    # resume and run one more epoch: must come back from epoch 0
    assert main(train_args(rcv1_path, model, epochs=2,
                           extra=("auto_resume=1",
                                  "ckpt_interval=1"))) == 0
    # the resumed run wrote epoch 1's checkpoint over the torn file and
    # it verifies now
    from difacto_tpu.utils import manifest as mft
    assert mft.verify(model + "_iter-1_part-0",
                      require_manifest=True) is not None


def test_serve_falls_back_to_previous_generation(ckpt_model, tmp_path):
    """task=serve startup with a corrupt final model walks back to the
    newest interval generation that verifies and serves it."""
    import shutil

    from difacto_tpu.serve import open_serving_store

    model = str(tmp_path / "model")
    for e in range(3):
        for suf in ("", ".manifest.json"):
            shutil.copy(f"{ckpt_model}_iter-{e}_part-0{suf}",
                        f"{model}_iter-{e}_part-0{suf}")
    for suf in ("", ".manifest.json"):
        shutil.copy(f"{ckpt_model}_part-0{suf}", f"{model}_part-0{suf}")
    corrupt_flip(model + "_part-0")
    store, meta, _ = open_serving_store(model)
    assert meta["path"] == model + "_iter-2_part-0"
    assert store.read_only and store.num_features > 0


def test_ckpt_keep_prunes_old_generations(rcv1_path, tmp_path):
    """Satellite: ckpt_keep retires old interval checkpoints (and their
    manifests); the final model survives."""
    model = str(tmp_path / "model")
    assert main(train_args(rcv1_path, model, epochs=4,
                           extra=("ckpt_interval=1",
                                  "ckpt_keep=2"))) == 0
    kept = sorted(f for f in os.listdir(tmp_path)
                  if "_iter-" in f and not f.endswith(".json"))
    assert kept == ["model_iter-2_part-0", "model_iter-3_part-0"], kept
    assert not os.path.exists(f"{model}_iter-0_part-0.manifest.json")
    assert os.path.exists(f"{model}_part-0")


# ------------------------------------------------ crash + resume (leg 1)

def test_sigkill_mid_checkpoint_then_auto_resume(rcv1_path, tmp_path):
    """Acceptance leg 1: SIGKILL mid-checkpoint write (the injected
    ``kill`` tears the file exactly like a crash mid-upload), then the
    next run auto-resumes from the previous verified generation with no
    manual cleanup."""
    model = str(tmp_path / "model")
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    args = [sys.executable, "-m", "difacto_tpu"] + train_args(
        rcv1_path, model, extra=("ckpt_interval=1", "auto_resume=1"))
    with deadline(240):
        # epoch-0 checkpoint succeeds; the epoch-1 save is torn + killed
        env["DIFACTO_FAULTS"] = "ckpt.write:kill@1:1"
        p1 = subprocess.run(args, cwd=str(REPO), env=env,
                            capture_output=True, text=True, timeout=200)
        assert p1.returncode == -signal.SIGKILL, p1.stderr[-2000:]
        # the crash left a torn epoch-1 checkpoint under the FINAL name
        assert os.path.exists(f"{model}_iter-1_part-0")
        assert not os.path.exists(
            f"{model}_iter-1_part-0.manifest.json")
        # second run: no faults; must walk past the torn file to epoch 0
        env.pop("DIFACTO_FAULTS")
        p2 = subprocess.run(args, cwd=str(REPO), env=env,
                            capture_output=True, text=True, timeout=200)
        assert p2.returncode == 0, p2.stderr[-2000:]
        assert "auto-resumed from epoch 0" in p2.stderr
        assert "walking back" in p2.stderr  # the torn file was seen


# ---------------------------------------------- hot reload (leg 2)

def test_hot_reload_under_load(ckpt_model, rcv1_path):
    """Acceptance leg 2: hot-reload under ~2x steady loadgen traffic —
    zero !err responses, model_generation advances, in-flight batches on
    the old model still return; a corrupt reload keeps the old model."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen

    from difacto_tpu.serve import (ModelReloader, ServeClient,
                                   ServeServer, open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(180):
        store, _, _ = open_serving_store(ckpt_model)
        srv = ServeServer(store, batch_size=64, max_delay_ms=2.0).start()
        srv.reloader = ModelReloader(srv.executor, ckpt_model)
        rep = {}

        def load():
            # open-loop traffic throughout the swap window
            rep.update(run_loadgen(srv.host, srv.port, rows, qps=400,
                                   duration_s=3.0))

        try:
            t = threading.Thread(target=load)
            t.start()
            time.sleep(0.5)
            with ServeClient(srv.host, srv.port) as c:
                assert c.stats()["model_generation"] == 1
                res = c.reload()     # same path, re-verified + swapped
                assert res["ok"] and res["model_generation"] == 2, res
                # a corrupt candidate is rejected; the old model serves on
                res2 = c.reload(str(REPO / "README.md"))
                assert not res2["ok"], res2
                st = c.stats()
                assert st["model_generation"] == 2
                assert st["reloads"] == 1 and st["reload_failures"] == 1
                assert c.predict(rows[:5]) and all(
                    r is not None for r in c.predict(rows[:5]))
            t.join()
        finally:
            srv.close()
        assert rep["err"] == 0, rep          # zero !err through the swap
        assert rep["ok"] > 0, rep            # old-model in-flight returned


# ------------------------------------------------- SIGTERM drain (leg 3)

def test_sigterm_drains_and_exits_zero(ckpt_model, rcv1_path, tmp_path):
    """Acceptance leg 3: SIGTERM under open-loop load → the server stops
    accepting, answers new rows '!shed draining', resolves admitted work
    and exits 0 within drain_timeout_s."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen

    ready = str(tmp_path / "ready")
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    env.pop("DIFACTO_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "difacto_tpu", "task=serve",
         f"model_in={ckpt_model}", f"serve_ready_file={ready}",
         "serve_drain_timeout_s=10", "serve_max_seconds=120"],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        with deadline(240):
            while not os.path.exists(ready):
                time.sleep(0.05)
                assert proc.poll() is None, proc.communicate()[1][-2000:]
            host, port = open(ready).read().split()
            rows = fixture_rows(rcv1_path)
            rep = {}

            def load():
                rep.update(run_loadgen(host, int(port), rows, qps=300,
                                       duration_s=4.0))

            t = threading.Thread(target=load)
            t.start()
            time.sleep(1.0)   # mid-load
            t0 = time.monotonic()
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=30)
            drained_in = time.monotonic() - t0
            t.join()
        assert rc == 0, proc.communicate()[1][-2000:]
        assert drained_in < 15.0, drained_in
        # admitted rows were answered before exit; post-drain rows were
        # shed explicitly, not silently dropped
        assert rep["ok"] > 0, rep
    finally:
        if proc.poll() is None:  # pragma: no cover - deadline blew
            proc.kill()
            proc.wait()


# ------------------------------------------- client retry (leg 4)

def test_client_retries_through_socket_close(ckpt_model, rcv1_path):
    """Acceptance leg 4: the server's writer drops the connection every
    N responses (injected close); the retrying client reconnects,
    resends the unanswered tail and eventually scores every row."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(180):
        store, _, _ = open_serving_store(ckpt_model)
        srv = ServeServer(store, batch_size=100,
                          max_delay_ms=50.0).start()
        # every 31st response write tears the connection down
        faultinject.configure("serve.sock.write:close@1:30")
        try:
            with ServeClient(srv.host, srv.port, retries=10,
                             deadline_s=120.0) as c:
                got = c.predict(rows)
            fired = faultinject.stats()
        finally:
            faultinject.configure("")
            srv.close()
        assert fired.get("serve.sock.write", 0) >= 2, \
            f"injected close never fired: {fired}"
        assert len(got) == 100
        assert all(g is not None and 0.0 < g < 1.0 for g in got)
        # fail-fast client (retries=0) would have died on the same server

    # ... and !shed is retryable while !err is not (unit-level)
    with deadline(60):
        store, _, _ = open_serving_store(ckpt_model)
        srv = ServeServer(store, batch_size=8, max_delay_ms=1.0,
                          queue_cap=1).start()
        try:
            with ServeClient(srv.host, srv.port, retries=4) as c:
                # a malformed row is rejected, never retried
                assert c.predict([b"not a row::"]) == [None]
                assert c.stats()["errors"] >= 1
        finally:
            srv.close()


def test_client_retries_through_socket_read_close(ckpt_model, rcv1_path):
    """The READ half of the wire drill (the write half is above): the
    server's reader drops the connection mid-request stream (injected
    serve.sock.read close); the retrying client reconnects and resends
    the unanswered tail until every row is scored."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(180):
        store, _, _ = open_serving_store(ckpt_model)
        srv = ServeServer(store, batch_size=10,
                          max_delay_ms=5.0).start()
        # every 31st request read tears the connection down; 10-row
        # calls keep each retry attempt under the next fire (a 100-row
        # burst could be torn before any response flushes — no progress)
        faultinject.configure("serve.sock.read:close@1:30")
        got = []
        try:
            with ServeClient(srv.host, srv.port, retries=10,
                             deadline_s=120.0) as c:
                for i in range(0, len(rows), 10):
                    got.extend(c.predict(rows[i:i + 10]))
            fired = faultinject.stats()
        finally:
            faultinject.configure("")
            srv.close()
        assert fired.get("serve.sock.read", 0) >= 2, \
            f"injected read close never fired: {fired}"
        assert len(got) == 100
        assert all(g is not None and 0.0 < g < 1.0 for g in got)


def test_batcher_enqueue_fault_surfaces_as_err(ckpt_model, rcv1_path):
    """An injected admission failure (batcher.enqueue err) must surface
    as a per-row `!err` reply — counted, never retried, never a torn
    connection — and service must resume the moment the fault disarms."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)[:10]
    with deadline(120):
        store, _, _ = open_serving_store(ckpt_model)
        srv = ServeServer(store, batch_size=8, max_delay_ms=5.0).start()
        faultinject.configure("batcher.enqueue:err@1")
        try:
            with ServeClient(srv.host, srv.port, retries=2) as c:
                got = c.predict(rows)
                fired = faultinject.stats()
                assert got == [None] * len(rows)
                assert c.stats()["errors"] >= len(rows)
                faultinject.configure("")
                # same server, same connection: admission works again
                assert all(g is not None and 0.0 < g < 1.0
                           for g in c.predict(rows))
        finally:
            faultinject.configure("")
            srv.close()
        assert fired.get("batcher.enqueue", 0) >= len(rows), \
            f"injected enqueue fault never fired: {fired}"


def test_ckpt_read_fault_is_typed(ckpt_model):
    """An injected read failure on checkpoint open (ckpt.read err) keeps
    its OSError type through the verified-load path — it must look like
    the real disk failure it models, never a silent partial load (the
    corrupt-file walk-back catches CheckpointCorrupt only)."""
    from difacto_tpu.serve import open_serving_store
    from difacto_tpu.utils.faultinject import FaultInjected
    with deadline(60):
        faultinject.configure("ckpt.read:err@1")
        try:
            with pytest.raises(FaultInjected):
                open_serving_store(ckpt_model)
            fired = faultinject.stats()
        finally:
            faultinject.configure("")
        assert fired.get("ckpt.read", 0) >= 1
        # disarmed: the same family loads clean
        store, _, _ = open_serving_store(ckpt_model)
        assert store is not None


def test_producer_part_fault_is_retried(rcv1_path, tmp_path):
    """An injected producer failure rides the straggler/re-queue path:
    training still completes and writes a loadable model."""
    from difacto_tpu.serve import open_serving_store
    model = str(tmp_path / "model")
    # one producer thread + 4 parts: traversal order is serial, so
    # after_n=3 fires exactly once (part 4's first attempt) and its
    # retry passes — deterministic, and within max_retries=1
    faultinject.configure("producer.part:err@1:3")
    try:
        with deadline(180):
            # l1=0: one epoch over 25-row parts must leave nonzero
            # weights to assert on (l1=1 shrinks this tiny run to zero)
            assert main([f"data_in={rcv1_path}", "lr=1", "l1=0", "l2=1",
                         "batch_size=25", "max_num_epochs=1", "shuffle=0",
                         "num_jobs_per_epoch=4", "num_producers=1",
                         "report_interval=0",
                         f"model_out={model}"]) == 0
    finally:
        fired = faultinject.stats()
        faultinject.configure("")
    assert fired.get("producer.part", 0) > 0, \
        "fault never fired — the test proved nothing"
    store, _, _ = open_serving_store(model)
    assert store.num_features > 0


# ------------------------------------- new fault points (ISSUE 4 satellite)

def test_step_device_fault_fires_typed(rcv1_path, tmp_path):
    """``step.device`` (step.py fire_step_fault): an injected error at
    the host-side step dispatch surfaces as the typed FaultInjected
    (OSError) out of the learner — and BOTH observability surfaces saw
    it fire: faultinject.stats() and faults_fired_total{point,kind}."""
    from difacto_tpu.learners import Learner
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.utils.faultinject import FaultInjected

    before = REGISTRY.value("faults_fired_total", point="step.device",
                            kind="err")
    faultinject.configure("step.device:err@1")
    ln = Learner.create("sgd")
    ln.init([("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"),
             ("l1", "0"), ("lr", "1"), ("num_jobs_per_epoch", "1"),
             ("batch_size", "100"), ("max_num_epochs", "1"),
             ("shuffle", "0"), ("report_interval", "0"),
             ("device_cache_mb", "0"), ("hash_capacity", "1024"),
             ("producer_mode", "thread")])
    with deadline(120):
        with pytest.raises(FaultInjected):
            ln.run()
    assert faultinject.stats().get("step.device", 0) > 0, \
        "fault never fired — the test proved nothing"
    assert REGISTRY.value("faults_fired_total", point="step.device",
                          kind="err") > before


def test_dcn_collective_fault_fires_typed():
    """``dcn.collective`` (parallel/multihost.py): an injected error at
    the cross-host control exchange raises typed BEFORE the single-
    process fast path, so the chaos harness needs no cluster — and the
    fire lands in faults_fired_total."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.parallel.multihost import control_allgather_np
    from difacto_tpu.utils.faultinject import FaultInjected

    # unarmed: the exchange works and counts
    faultinject.configure("")
    dcn_before = REGISTRY.value("dcn_collectives_total")
    out = control_allgather_np(np.arange(4, dtype=np.int32))
    assert out.shape == (1, 4)
    assert REGISTRY.value("dcn_collectives_total") == dcn_before + 1

    before = REGISTRY.value("faults_fired_total", point="dcn.collective",
                            kind="err")
    faultinject.configure("dcn.collective:err@1")
    with pytest.raises(FaultInjected):
        control_allgather_np(np.arange(4, dtype=np.int32))
    assert faultinject.stats().get("dcn.collective", 0) > 0
    assert REGISTRY.value("faults_fired_total", point="dcn.collective",
                          kind="err") > before


# --------------------------- single-pass verified loads (ISSUE 4 satellite)

def test_single_pass_verified_load(ckpt_model, monkeypatch):
    """Satellite: a verified load opens/reads the npz ONCE (the old
    flow's separate verify pass read every byte twice), yields byte-
    identical state to an unverified load, and still raises the typed
    CheckpointCorrupt on a bit flip — before any state commits."""
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
    from difacto_tpu.utils import manifest as mft
    from difacto_tpu.utils import stream

    path = f"{ckpt_model}_part-0"
    opens = []
    real = stream.load_npz

    def counting(uri, fault_point=""):
        opens.append(uri)
        return real(uri, fault_point=fault_point)

    monkeypatch.setattr(stream, "load_npz", counting)

    st_v = SlotStore(SGDUpdaterParam(V_dim=0))
    st_v.load(path, require_manifest=True)   # verified, single pass
    assert opens == [path], opens

    opens.clear()
    st_raw = SlotStore(SGDUpdaterParam(V_dim=0))
    st_raw.load(path, verify=False)
    assert opens == [path]

    # byte-identical results: the hash-while-loading path changes no data
    a = st_v._state_np(st_v.state)
    b = st_raw._state_np(st_raw.state)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)
    np.testing.assert_array_equal(st_v._keys, st_raw._keys)

    # corruption still surfaces typed, with no partial state left behind
    import shutil
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad")
        shutil.copy(path, bad)
        shutil.copy(path + mft.MANIFEST_SUFFIX,
                    bad + mft.MANIFEST_SUFFIX)
        corrupt_flip(bad)
        st_c = SlotStore(SGDUpdaterParam(V_dim=0))
        cap0 = st_c.state.capacity
        with pytest.raises(mft.CheckpointCorrupt) as ei:
            st_c.load(bad)
        assert bad in str(ei.value)
        assert st_c.num_features == 0 and st_c.state.capacity == cap0


# ------------------------------------- serving continuity (ISSUE 5)

def _synth_model(dirpath, name: str, vdim: int, capacity: int = 4096):
    """A saved synthetic hashed model (manifest-stamped via store.save).
    Geometry comes from the args, so two calls with different ``vdim``
    give a geometry-changing reload its before/after pair without two
    training runs."""
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  set_all_live)

    param = SGDUpdaterParam(V_dim=vdim, l1_shrk=False,
                            hash_capacity=capacity)
    st = SlotStore(param, read_only=True)
    st.state = set_all_live(param, st.state)
    path = os.path.join(str(dirpath), name)
    st.save(path)
    return path


def _synth_rows(n_rows: int = 128, nnz: int = 8, space: int = 1 << 14,
                seed: int = 0) -> list:
    """Synthetic libsvm request rows with a FIXED nnz per row, so every
    single-row dispatch lands in one deterministic shape bucket."""
    rng = np.random.RandomState(seed)
    return [("0 " + " ".join(
        f"{i}:1" for i in np.sort(rng.choice(space, nnz,
                                             replace=False)))).encode()
        for _ in range(n_rows)]


def test_bluegreen_swap_under_load(tmp_path):
    """Acceptance (ISSUE 5 leg 1): a geometry-changing reload
    (different V_dim) under open-loop load runs the blue/green executor
    swap with ZERO !err replies; every bucket the live executor had
    compiled is pre-warmed on green before traffic can reach it, and
    serve_bluegreen_swaps_total counts exactly 1."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen

    from difacto_tpu.serve import (ModelReloader, ServeClient,
                                   ServeServer, open_serving_store)

    model_a = _synth_model(tmp_path, "ma", vdim=4)
    model_b = _synth_model(tmp_path, "mb", vdim=8)
    rows = _synth_rows()
    with deadline(300):
        store, _, _ = open_serving_store(model_a)
        srv = ServeServer(store, batch_size=64, max_delay_ms=2.0).start()
        srv.reloader = ModelReloader(srv.executor, model_a, server=srv)
        rep = {}
        t = threading.Thread(target=lambda: rep.update(
            run_loadgen(srv.host, srv.port, rows, qps=200,
                        duration_s=4.0)))
        try:
            t.start()
            time.sleep(1.0)
            blue = srv.executor
            _, warm_keys = blue.warm_set()
            assert warm_keys, "no traffic compiled before the swap"
            with ServeClient(srv.host, srv.port) as c:
                assert c.health()["swap_state"] == "idle"
                res = c.reload(model_b)
                assert res["ok"] and res["model_generation"] == 2, res
                green = srv.executor
                assert green is not blue
                assert green.store.param.V_dim == 8
                # warm-set replay: every blue bucket was registered on
                # green BY THE WARM LOOP, before any request hit it
                assert set(warm_keys) <= set(green._buckets)
                assert green._warmed >= len(warm_keys)
                st = c.stats()
                assert st["bluegreen_swaps"] == 1, st
                assert st["model_generation"] == 2
                assert st["swap_state"] == "idle"
                assert "serve_bluegreen_swaps_total 1" in c.metrics()
            t.join()
            # single-row requests pad to the live sticky caps — the
            # exact bucket key blue compiled and the swap warmed — so
            # green serves them with ZERO steady-state compiles
            base = green.stats()["buckets_compiled"]
            with ServeClient(srv.host, srv.port) as c:
                for r in rows[:5]:
                    assert c.predict([r])[0] is not None
                    time.sleep(0.02)
            assert green.stats()["buckets_compiled"] == base
        finally:
            srv.close()
        assert rep["err"] == 0, rep     # zero client-visible errors
        assert rep["ok"] > 0, rep       # traffic flowed through the swap


def test_reuseport_takeover_kills_incumbent_under_load(tmp_path):
    """Acceptance (leg 2): two replicas share one SO_REUSEPORT port;
    the incumbent is killed ABRUPTLY (no drain) mid-load and the
    multi-endpoint failover client sees zero errors — dropped tails
    reconnect onto the successor."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen_failover

    from difacto_tpu.serve import ServeServer, open_serving_store

    model = _synth_model(tmp_path, "m", vdim=4)
    rows = _synth_rows()
    with deadline(300):
        store, _, _ = open_serving_store(model)
        srv1 = ServeServer(store, batch_size=64, max_delay_ms=2.0,
                           takeover=True).start()
        # one logical service, two replica slots behind the same
        # address — the client treats them as a failover list
        endpoints = [(srv1.host, srv1.port), (srv1.host, srv1.port)]
        rep = {}
        t = threading.Thread(target=lambda: rep.update(
            run_loadgen_failover(endpoints, rows, qps=120,
                                 duration_s=4.0)))
        srv2 = None
        try:
            t.start()
            time.sleep(1.0)   # client established while srv1 is alone
            store2, _, _ = open_serving_store(model)
            srv2 = ServeServer(store2, batch_size=64, max_delay_ms=2.0,
                               host=srv1.host, port=srv1.port,
                               takeover=True).start()
            time.sleep(0.3)
            srv1.close()      # the abrupt kill: connections torn down
            t.join()
        finally:
            srv1.close()
            if srv2 is not None:
                srv2.close()
        assert rep["err"] == 0, rep
        assert rep["ok"] > 0, rep
        assert rep["failovers"] >= 1, rep


def test_takeover_driver_sequencing(tmp_path):
    """tools/takeover.py sequences spawn -> warm -> handoff -> exit —
    proven with an in-process successor (no second jax process). Also:
    a handoff SO_REUSEPORT mis-routed to the successor is refused by
    ready-file ownership, and the incumbent's #health exposed the
    successor's readiness."""
    sys.path.insert(0, str(REPO / "tools"))
    from takeover import run_takeover

    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)

    model = _synth_model(tmp_path, "m", vdim=4)
    with deadline(180):
        store, _, _ = open_serving_store(model)
        srv1 = ServeServer(store, takeover=True).start()
        box = {}

        class _InProc:
            def poll(self):
                return None

        def spawn(ready_file):
            st2, _, _ = open_serving_store(model)
            srv2 = ServeServer(st2, host=srv1.host, port=srv1.port,
                               takeover=True).start()
            srv2.ready_file = ready_file
            with open(ready_file, "w") as f:
                f.write(f"{srv2.host} {srv2.port}\n")
            box["srv2"] = srv2
            return _InProc()

        try:
            rep = run_takeover(srv1.host, srv1.port, spawn_fn=spawn,
                               wait_s=60.0)
            assert rep["ok"], rep
            assert rep["incumbent"] != rep["successor"], rep
            # the incumbent saw the ready file, reported it on #health
            # (successor_ready), then drained out. The driver can
            # return while the incumbent's drain is still finishing —
            # wait for the close before poking at it (and before the
            # mis-route check below, which needs fresh connections to
            # reach ONLY the successor).
            t0 = time.monotonic()
            while not srv1._closed and time.monotonic() - t0 < 60:
                time.sleep(0.05)
            assert srv1._closed
            assert srv1.successor_ready and srv1.draining
            assert srv1.health_snapshot()["successor_ready"] is True
            # mis-routed handoff: the successor refuses by name
            srv2 = box["srv2"]
            with ServeClient(srv1.host, srv1.port) as c:
                resp = c.score_lines(
                    [b"#handoff " + srv2.ready_file.encode()])[0]
                assert resp.startswith(b"!err"), resp
                assert b"successor" in resp
                assert c.health()["status"] == "ready"
            assert not srv2.draining
        finally:
            srv1.close()
            if "srv2" in box:
                box["srv2"].close()


def test_continuity_fault_points(tmp_path):
    """Satellite: the new ``serve.handoff`` and ``reload.warm`` fault
    points fire, land in faults_fired_total{point,kind}, and fail SAFE:
    a handoff fault refuses the handoff (no drain), a warm fault aborts
    the blue/green swap with the old model still serving. A bare
    reloader (no server) keeps the typed geometry refusal."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import (ModelReloader, ServeClient,
                                   ServeServer, open_serving_store)

    model_a = _synth_model(tmp_path, "ma", vdim=4)
    model_b = _synth_model(tmp_path, "mb", vdim=8)
    rows = _synth_rows(8)
    before_h = REGISTRY.value("faults_fired_total",
                              point="serve.handoff", kind="err")
    before_w = REGISTRY.value("faults_fired_total",
                              point="reload.warm", kind="err")
    with deadline(180):
        store, _, _ = open_serving_store(model_a)
        srv = ServeServer(store, batch_size=8, max_delay_ms=1.0).start()
        srv.reloader = ModelReloader(srv.executor, model_a, server=srv)
        try:
            with ServeClient(srv.host, srv.port) as c:
                assert c.predict(rows[:1])[0] is not None  # compile blue
                faultinject.configure("serve.handoff:err@1")
                resp = c.score_lines([b"#handoff"])[0]
                assert resp.startswith(b"!err"), resp
                assert not srv.draining
                faultinject.configure("reload.warm:err@1")
                res = c.reload(model_b)
                assert not res["ok"], res
                faultinject.configure("")
                st = c.stats()
                assert st["reload_failures"] == 1, st
                assert st["model_generation"] == 1, st
                assert st["bluegreen_swaps"] == 0, st
                assert st["swap_state"] == "idle", st
                # the old model still scores after the aborted swap
                assert c.predict(rows[:1])[0] is not None
            # no server attached -> no batcher to retarget: a geometry
            # change stays a reload failure naming the mismatch
            bare = ModelReloader(srv.executor, model_a)
            res = bare.reload(model_b)
            assert not res["ok"] and "geometry" in res["error"], res
        finally:
            faultinject.configure("")
            srv.close()
    assert REGISTRY.value("faults_fired_total", point="serve.handoff",
                          kind="err") > before_h
    assert REGISTRY.value("faults_fired_total", point="reload.warm",
                          kind="err") > before_w


def test_sigterm_during_bluegreen_warm_drains_cleanly(tmp_path):
    """Satellite race: SIGTERM while a blue/green warm is in flight —
    the server drains on the OLD executor and exits 0; the half-warmed
    green is abandoned, no crash, no hang. The injected ``reload.warm``
    delay holds the warm window open long enough to land the signal
    inside it deterministically."""
    model_a = _synth_model(tmp_path, "ma", vdim=4)
    model_b = _synth_model(tmp_path, "mb", vdim=8)
    ready = str(tmp_path / "ready")
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu",
               DIFACTO_FAULTS="reload.warm:delay_ms=3000@1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "difacto_tpu", "task=serve",
         f"model_in={model_a}", f"serve_ready_file={ready}",
         "serve_drain_timeout_s=10", "serve_max_seconds=180"],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        with deadline(240):
            while not os.path.exists(ready):
                time.sleep(0.05)
                assert proc.poll() is None, proc.communicate()[1][-2000:]
            host, port = open(ready).read().split()
            from difacto_tpu.serve import ServeClient
            with ServeClient(host, int(port)) as c:
                # compile at least one blue bucket so the warm loop has
                # work (and the injected delay a place to fire)
                assert c.predict([_synth_rows(1)[0]])[0] is not None

                def _bg_reload():
                    try:
                        with ServeClient(host, int(port)) as c2:
                            c2.reload(model_b)
                    except Exception:
                        pass   # the drain may tear this connection down

                threading.Thread(target=_bg_reload, daemon=True).start()
                t0 = time.monotonic()
                while time.monotonic() - t0 < 30:
                    if c.health().get("swap_state") != "idle":
                        break
                    time.sleep(0.05)
                else:
                    raise AssertionError("blue/green warm never started")
            proc.send_signal(signal.SIGTERM)   # mid-warm, by the poll
            rc = proc.wait(timeout=90)
        assert rc == 0, proc.communicate()[1][-2000:]
        err = proc.communicate()[1]
        assert "blue/green: warming" in err, err[-2000:]
    finally:
        if proc.poll() is None:  # pragma: no cover - deadline blew
            proc.kill()
            proc.wait()


# ------------------------------------- fleet-scale serving (ISSUE 6)

def _serve_or_skip(store, **kw):
    """Fleet tests bind several real ports; a box that cannot bind skips
    cleanly instead of erroring (the tier-1 contract for these tests)."""
    from difacto_tpu.serve import ServeServer
    try:
        return ServeServer(store, **kw).start()
    except OSError as e:  # pragma: no cover - loaded/locked-down CI box
        pytest.skip(f"cannot bind a serving port: {e}")


def _fleet(tmp_path, n=3, model=None):
    """n in-process takeover-ready replicas over one synthetic model.
    Returns (model, servers dict endpoint->list, endpoints list)."""
    from difacto_tpu.serve import open_serving_store
    model = model or _synth_model(tmp_path, "m", vdim=4)
    servers, endpoints = {}, []
    for _ in range(n):
        store, _, _ = open_serving_store(model)
        srv = _serve_or_skip(store, batch_size=64, max_delay_ms=2.0,
                             takeover=True)
        servers[f"{srv.host}:{srv.port}"] = [srv]
        endpoints.append((srv.host, srv.port))
    return model, servers, endpoints


def _inproc_spawn(model, servers):
    """spawn_fn for run_rolling_restart: an in-process successor on the
    shared SO_REUSEPORT port (no second jax process), registered in
    ``servers`` for teardown."""
    from difacto_tpu.serve import ServeServer, open_serving_store

    def spawn(i, host, port, ready_file):
        store, _, _ = open_serving_store(model)
        srv = ServeServer(store, host=host, port=port, batch_size=64,
                          max_delay_ms=2.0, takeover=True).start()
        srv.ready_file = ready_file
        with open(ready_file, "w") as f:
            f.write(f"{srv.host} {srv.port}\n")
        servers[f"{host}:{port}"].append(srv)
        return None

    return spawn


def _close_fleet(*groups):
    for g in groups:
        for lst in (g.values() if isinstance(g, dict) else [g]):
            for srv in (lst if isinstance(lst, list) else [lst]):
                srv.close()


def test_fleet_rolling_restart_behind_router_under_load(tmp_path):
    """Acceptance (ISSUE 6 headline): rolling restart of 3 replicas
    behind the router under open-loop loadgen — every replica replaced,
    ZERO client-visible !err lines (and zero sheds: the router converts
    each drain window into peer re-forwards), and the router reports the
    whole fleet ready afterwards."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen

    from difacto_tpu.serve import (RouterServer, ServeClient,
                                   run_rolling_restart)

    rows = _synth_rows(64)
    with deadline(600):
        model, servers, endpoints = _fleet(tmp_path, n=3)
        try:
            router = RouterServer(
                endpoints, blacklist=str(tmp_path / "blacklist")).start()
        except OSError as e:  # pragma: no cover
            _close_fleet(servers)
            pytest.skip(f"cannot bind the router port: {e}")
        rep = {}
        t = threading.Thread(target=lambda: rep.update(
            run_loadgen(router.host, router.port, rows, qps=100,
                        duration_s=6.0)))
        try:
            t.start()
            time.sleep(1.0)    # traffic established through the router
            roll = run_rolling_restart(
                endpoints, spawn_fn=_inproc_spawn(model, servers),
                wait_s=60.0)
            t.join()
            assert roll["ok"], roll
            assert len(roll["replicas"]) == 3, roll
            for r in roll["replicas"]:
                assert r["incumbent"] != r["successor"], r
            # the headline: a full fleet rotation cost the client NOTHING
            assert rep["err"] == 0, rep
            assert rep["shed"] == 0, rep
            assert rep["ok"] > 0, rep
            with ServeClient(router.host, router.port) as c:
                h = c.health()
                assert h["router"] and h["status"] == "ready"
                assert h["replicas_live"] == 3, h
                # every replica answering is a successor, and their
                # health payloads ride the aggregate
                ids = {r["server_id"] for r in h["replicas"]}
                assert ids == {r["successor"]
                               for r in roll["replicas"]}, h
                st = c.stats()
                assert st["rows"] >= rep["ok"], st
                assert sum(b["rows"] for b in st["backends"]) \
                    >= rep["ok"], st
        finally:
            router.close()
            _close_fleet(servers)


def test_fleet_rolling_restart_aborts_on_ready_timeout(tmp_path):
    """Acceptance (abort leg): replica 0 rolls, replica 1's successor
    never becomes ready — the rollout ABORTS with replica 1's incumbent
    still serving and replica 2 untouched."""
    from difacto_tpu.serve import run_rolling_restart
    from difacto_tpu.serve.fleet import fresh_health

    class _NeverReady:
        terminated = False

        def poll(self):
            return None

        def terminate(self):
            self.terminated = True

    with deadline(300):
        model, servers, endpoints = _fleet(tmp_path, n=3)
        good_spawn = _inproc_spawn(model, servers)
        stuck = _NeverReady()

        def spawn(i, host, port, ready_file):
            if i == 1:
                return stuck     # writes no ready file, ever
            return good_spawn(i, host, port, ready_file)

        try:
            before = {ep: fresh_health(*ep)["server_id"]
                      for ep in endpoints}
            roll = run_rolling_restart(endpoints, spawn_fn=spawn,
                                       wait_s=2.0, gate_wait_s=5.0)
            assert not roll["ok"], roll
            assert roll["aborted_at"] == 1, roll
            assert "ready" in roll["reason"], roll
            assert len(roll["completed"]) == 1, roll
            assert stuck.terminated    # the half-up successor was reaped
            # replica 1's incumbent kept serving; replica 2 untouched
            for ep in endpoints[1:]:
                h = fresh_health(*ep)
                assert h["status"] == "ready", h
                assert h["server_id"] == before[ep], h
            # replica 0 WAS replaced before the abort
            assert fresh_health(*endpoints[0])["server_id"] \
                != before[endpoints[0]]
        finally:
            _close_fleet(servers)


def test_fleet_handoff_fault_aborts_rollout(tmp_path):
    """Satellite + acceptance (abort leg): the ``fleet.handoff``
    injection point fires at the orchestrator's handoff step, lands in
    faults_fired_total{point,kind}, and an injected err mid-rollout
    aborts with the incumbent still serving."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import run_rolling_restart
    from difacto_tpu.serve.fleet import fresh_health

    before_f = REGISTRY.value("faults_fired_total",
                              point="fleet.handoff", kind="err")
    with deadline(300):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        try:
            before = {ep: fresh_health(*ep)["server_id"]
                      for ep in endpoints}
            # after_n=1: replica 0's handoff step passes, replica 1's
            # fires — a mid-rollout botched rotation
            faultinject.configure("fleet.handoff:err@1:1")
            roll = run_rolling_restart(
                endpoints, spawn_fn=_inproc_spawn(model, servers),
                wait_s=60.0, gate_wait_s=5.0)
            faultinject.configure("")
            assert not roll["ok"], roll
            assert roll["aborted_at"] == 1, roll
            assert "fleet.handoff" in roll["reason"], roll
            assert len(roll["completed"]) == 1, roll
            h = fresh_health(*endpoints[1])
            assert h["status"] == "ready", h
            assert h["server_id"] == before[endpoints[1]], \
                "the aborted replica's incumbent was disturbed"
        finally:
            faultinject.configure("")
            _close_fleet(servers)
    assert faultinject.stats() == {}, "registry should be disarmed"
    assert REGISTRY.value("faults_fired_total", point="fleet.handoff",
                          kind="err") > before_f


def test_fleet_rolling_restart_gate_rejects_unready_fleet(tmp_path):
    """Pre-handoff gate: a fleet with a draining replica never starts a
    rollout — the first health pass aborts before any successor spawns
    (ready=false is the first regression class the gate names)."""
    from difacto_tpu.serve import run_rolling_restart

    with deadline(300):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        try:
            # replica 1 reports draining (a rotation already in flight)
            list(servers.values())[1][0].draining = True
            spawned = []

            def spawn(i, host, port, ready_file):  # pragma: no cover
                spawned.append(i)
                return None

            roll = run_rolling_restart(endpoints, spawn_fn=spawn,
                                       wait_s=5.0, gate_wait_s=0.5)
            assert not roll["ok"], roll
            assert roll["aborted_at"] == 0 and not roll["completed"]
            assert "not ready" in roll["reason"], roll
            assert not spawned, "gate must abort before any spawn"
        finally:
            _close_fleet(servers)


def test_router_forward_fault_retries_on_peer(tmp_path):
    """Satellite: the ``router.forward`` injection point fires in the
    forward path, lands in faults_fired_total{point,kind}, and an
    injected mid-chunk close surfaces as a peer retry — the client sees
    every row answered, zero errors."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import RouterServer, ServeClient

    before_f = REGISTRY.value("faults_fired_total",
                              point="router.forward", kind="close")
    rows = _synth_rows(40)
    with deadline(300):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        try:
            router = RouterServer(endpoints, retries=4).start()
        except OSError as e:  # pragma: no cover
            _close_fleet(servers)
            pytest.skip(f"cannot bind the router port: {e}")
        try:
            # every 4th forward tears its backend connection mid-chunk
            faultinject.configure("router.forward:close@1:3")
            with ServeClient(router.host, router.port) as c:
                for k in range(0, 40, 5):
                    got = c.predict(rows[k:k + 5])
                    assert all(g is not None for g in got), (k, got)
            fired = faultinject.stats()
            assert fired.get("router.forward", 0) >= 1, \
                f"injected close never fired: {fired}"
            st = router.stats_snapshot()
            assert st["retries"] >= 1, st
            assert st["errors"] == 0, st
            # both backends carried rows: the retried tails crossed over
            assert all(b["rows"] > 0 for b in st["backends"]), st
        finally:
            faultinject.configure("")
            router.close()
            _close_fleet(servers)
    assert REGISTRY.value("faults_fired_total", point="router.forward",
                          kind="close") > before_f


# ------------------------------- family-wide pruning (ISSUE 4 satellite)

def test_ckpt_keep_prunes_whole_family(ckpt_model, rcv1_path, tmp_path):
    """Satellite: rank 0 prunes the WHOLE generation family — including
    another rank's ``_part-1`` files (previously each rank pruned only
    what it wrote, so an evicted rank's stale parts lingered forever)."""
    import shutil

    from difacto_tpu.utils import manifest as mft

    model = str(tmp_path / "model")
    # simulate an evicted rank 1: its epoch-0 and epoch-2 parts are on
    # disk, but the rank is gone and will never prune them itself
    for e in (0, 2):
        shutil.copy(f"{ckpt_model}_iter-{e}_part-0",
                    f"{model}_iter-{e}_part-1")
        shutil.copy(f"{ckpt_model}_iter-{e}_part-0{mft.MANIFEST_SUFFIX}",
                    f"{model}_iter-{e}_part-1{mft.MANIFEST_SUFFIX}")
    with deadline(180):
        assert main(train_args(rcv1_path, model,
                               extra=("ckpt_interval=1",
                                      "ckpt_keep=2"))) == 0
    # 3 epochs ran; keep=2 retires epoch 0 across ALL ranks
    assert not os.path.exists(f"{model}_iter-0_part-0")
    assert not os.path.exists(f"{model}_iter-0_part-1")
    assert not os.path.exists(
        f"{model}_iter-0_part-1{mft.MANIFEST_SUFFIX}")
    # newer generations keep every rank's parts
    assert os.path.exists(f"{model}_iter-2_part-0")
    assert os.path.exists(f"{model}_iter-2_part-1")


# ------------------------------ bounded-delay window (ISSUE 16 satellite)

def test_push_stale_fault_fires_typed(rcv1_path, tmp_path):
    """``push.stale`` (parallel/multihost.post_clock): the stale-push
    publication point of the bounded-delay window — fired BEFORE the
    single-process early return, so the chaos harness exercises a τ>0
    windowed run without a cluster. The injected error surfaces as the
    typed FaultInjected out of the learner, and both observability
    surfaces saw it: faultinject.stats() and
    faults_fired_total{point,kind}."""
    from difacto_tpu.learners import Learner
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.utils.faultinject import FaultInjected

    before = REGISTRY.value("faults_fired_total", point="push.stale",
                            kind="err")
    faultinject.configure("push.stale:err@1")
    ln = Learner.create("sgd")
    ln.init([("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"),
             ("l1", "0"), ("lr", "1"), ("num_jobs_per_epoch", "1"),
             ("batch_size", "100"), ("max_num_epochs", "1"),
             ("shuffle", "0"), ("report_interval", "0"),
             ("hash_capacity", "1024"), ("nnz_cap", "16384"),
             ("mesh_dp", "2"), ("mesh_fs", "4"),
             ("bounded_delay", "1")])
    with deadline(120):
        with pytest.raises(FaultInjected):
            ln.run()
    assert faultinject.stats().get("push.stale", 0) > 0, \
        "fault never fired — the windowed schedule never posted a clock"
    assert REGISTRY.value("faults_fired_total", point="push.stale",
                          kind="err") > before


# --------------------- router HA group + elastic autoscaling (ISSUE 18)

def _router_or_skip(endpoints, **kw):
    from difacto_tpu.serve import RouterServer
    try:
        return RouterServer(endpoints, **kw).start()
    except OSError as e:  # pragma: no cover - loaded/locked-down CI box
        pytest.skip(f"cannot bind a router port: {e}")


def _router_group(endpoints, n=2, **kw):
    """n in-process routers sharing ONE SO_REUSEPORT port. Returns the
    list of RouterServer instances (element 0 owns the advertised
    port)."""
    first = _router_or_skip(endpoints, takeover=True, **kw)
    group = [first]
    try:
        for _ in range(n - 1):
            group.append(_router_or_skip(
                endpoints, host=first.host, port=first.port,
                takeover=True, **kw))
    except BaseException:
        _close_fleet(group)
        raise
    return group


def _inproc_router_spawn(endpoints, group):
    """spawn_fn for run_router_group_roll: an in-process successor
    router on the shared port, registered in ``group`` for teardown."""
    from difacto_tpu.serve import RouterServer

    def spawn(i, host, port, ready_file):
        r = RouterServer(endpoints, host=host, port=port, takeover=True,
                         ready_file=ready_file).start()
        with open(ready_file, "w") as f:
            f.write(f"{r.host} {r.port}\n")
        group.append(r)
        return None

    return spawn


def test_router_group_survives_member_kill(tmp_path):
    """Two routers share one SO_REUSEPORT port; SIGKILL-equivalent close
    of one member mid-run costs the failover client ZERO errors — fresh
    connections hash to the survivor, the resent tail lands there."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen_failover

    with deadline(600):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        group = []
        try:
            group = _router_group(endpoints, n=2)
            addr = [(group[0].host, group[0].port)]
            rep = {}
            t = threading.Thread(target=lambda: rep.update(
                run_loadgen_failover(addr, _synth_rows(64), qps=80,
                                     duration_s=4.0)))
            t.start()
            time.sleep(1.0)   # connections established through the group
            group[0].close()  # abrupt: no drain, no handoff
            t.join()
            assert rep["err"] == 0, rep
            assert rep["ok"] > 0, rep
            # the survivor answers the shared port
            from difacto_tpu.serve.fleet import fresh_health
            h = fresh_health(*addr[0])
            assert h["router"] and h["status"] == "ready", h
        finally:
            _close_fleet(group, servers)


def test_router_group_roll_zero_errors(tmp_path):
    """run_router_group_roll replaces every member of a 2-router group
    (census by server_id, handoff on a HELD connection to the incumbent,
    wait-departed) while the failover client sees zero errors; the
    successors refuse nothing and the incumbents are gone."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen_failover

    from difacto_tpu.serve import run_router_group_roll

    with deadline(600):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        group = []
        try:
            group = _router_group(endpoints, n=2)
            addr = [(group[0].host, group[0].port)]
            rep = {}
            t = threading.Thread(target=lambda: rep.update(
                run_loadgen_failover(addr, _synth_rows(64), qps=80,
                                     duration_s=6.0)))
            t.start()
            time.sleep(1.0)
            roll = run_router_group_roll(
                group[0].host, group[0].port, group_size=2,
                spawn_fn=_inproc_router_spawn(endpoints, group),
                wait_s=120.0)
            t.join()
            assert roll["ok"], roll
            assert len(roll["routers"]) == 2, roll
            incumbents = {r["incumbent"] for r in roll["routers"]}
            successors = {r["successor"] for r in roll["routers"]}
            assert not (incumbents & successors), roll
            assert rep["err"] == 0, rep
            assert rep["ok"] > 0, rep
        finally:
            _close_fleet(group, servers)


def test_router_takeover_fault_refuses_roll(tmp_path):
    """Armed ``router.takeover:err@1``: the ``#handoff`` control line is
    refused as a typed ``!err`` BEFORE any drain state changes — the
    incumbent keeps routing, and both fault surfaces saw the fire."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import ServeClient

    before = REGISTRY.value("faults_fired_total",
                            point="router.takeover", kind="err")
    with deadline(600):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        router = None
        try:
            router = _router_or_skip(endpoints, takeover=True)
            faultinject.configure("router.takeover:err@1")
            with ServeClient(router.host, router.port) as c:
                # the !err reply is not JSON: the typed refusal surfaces
                with pytest.raises(ValueError):
                    c.handoff(str(tmp_path / "nonexistent.ready"))
                # the refusal left the router serving, not draining
                h = c.health()
                assert h["status"] == "ready", h
                got = c.predict(_synth_rows(8))
                assert all(g is not None for g in got), got
        finally:
            faultinject.configure("")
            if router is not None:
                router.close()
            _close_fleet(servers)
    assert REGISTRY.value("faults_fired_total", point="router.takeover",
                          kind="err") > before


def test_autoscale_spawn_fault_aborts_then_recovers(tmp_path):
    """Armed ``autoscale.spawn:err@1``: the scale-up decision is refused
    and counted in ``autoscale_aborts_total`` (the loop keeps running);
    disarmed, the SAME overload signal produces a real spawn."""
    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import Autoscaler

    before_f = REGISTRY.value("faults_fired_total",
                              point="autoscale.spawn", kind="err")
    before_a = REGISTRY.value("autoscale_aborts_total")
    before_s = REGISTRY.value("autoscale_spawns_total")
    spawned = []

    def spawn_fn(idx):
        spawned.append(idx)
        return ("127.0.0.1", 59000 + idx)

    box = {"p99": 1000.0}   # permanently past the SLO: always overloaded
    with deadline(120):
        scaler = Autoscaler(
            [("127.0.0.1", 1)],   # unreachable fleet counts as overload
            spawn_fn, min_replicas=1, max_replicas=3, poll_s=0.05,
            up_ticks=1, cooldown_s=0.0, up_p99_ms=10.0,
            latency_fn=lambda: box["p99"], timeout=0.2)
        faultinject.configure("autoscale.spawn:err@1")
        m = scaler.step()
        assert m["action"] == "abort", m
        assert spawned == [], "spawn_fn ran despite the injected refusal"
        assert len(scaler.endpoints()) == 1
        faultinject.configure("")
        m = scaler.step()
        assert m["action"] == "up", m
        assert spawned == [1], spawned
        assert len(scaler.endpoints()) == 2
        assert [e["action"] for e in scaler.events] == ["abort", "up"]
    assert REGISTRY.value("faults_fired_total", point="autoscale.spawn",
                          kind="err") > before_f
    assert REGISTRY.value("autoscale_aborts_total") > before_a
    assert REGISTRY.value("autoscale_spawns_total") > before_s


def test_fleet_chaos_compound_kill_roll_scale(tmp_path):
    """Acceptance (ISSUE 18 headline, `make fleet-chaos`): 2 routers x
    2 replicas under open-loop load; mid-run we SIGKILL one router
    (abrupt close), roll the replica fleet, AND force a scale-up — zero
    client-visible !err, the autoscaler's spawn lands in the surviving
    router's ring and its counter is visible through that router's
    ``#metrics``, and the settled fleet sheds nothing."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen_failover

    from difacto_tpu.obs import REGISTRY
    from difacto_tpu.serve import (Autoscaler, ServeClient,
                                   run_rolling_restart)

    before_s = REGISTRY.value("autoscale_spawns_total")
    rows = _synth_rows(64)
    with deadline(600):
        model, servers, endpoints = _fleet(tmp_path, n=2)
        group, extra = [], []
        scaler = None
        try:
            group = _router_group(endpoints, n=2)
            addr = [(group[0].host, group[0].port)]

            def spawn_fn(idx):
                from difacto_tpu.serve import open_serving_store
                store, _, _ = open_serving_store(model)
                srv = _serve_or_skip(store, batch_size=64,
                                     max_delay_ms=2.0, takeover=True)
                extra.append(srv)
                return (srv.host, srv.port)

            box = {"p99": 0.0}
            scaler = Autoscaler(
                endpoints, spawn_fn, router=addr[0],
                min_replicas=2, max_replicas=3, poll_s=0.1,
                up_ticks=1, down_ticks=10 ** 6, cooldown_s=0.5,
                up_p99_ms=50.0, latency_fn=lambda: box["p99"],
                ewma=1.0).start()
            rep = {}
            t = threading.Thread(target=lambda: rep.update(
                run_loadgen_failover(addr, rows, qps=80,
                                     duration_s=8.0)))
            t.start()
            time.sleep(1.0)      # traffic established through the group
            group[0].close()     # CHAOS 1: kill a router group member
            time.sleep(0.5)
            roll = run_rolling_restart(   # CHAOS 2: roll every replica
                endpoints, spawn_fn=_inproc_spawn(model, servers),
                wait_s=60.0)
            box["p99"] = 1000.0  # CHAOS 3: force a scale-up mid-run
            t_spawn = time.monotonic()
            while (not any(e["action"] == "up" for e in scaler.events)
                   and time.monotonic() - t_spawn < 20.0):
                time.sleep(0.05)
            box["p99"] = 0.0
            t.join()
            assert roll["ok"], roll
            assert rep["err"] == 0, rep
            assert rep["ok"] > 0, rep
            ups = [e for e in scaler.events if e["action"] == "up"]
            assert len(ups) >= 1, scaler.events
            assert REGISTRY.value("autoscale_spawns_total") > before_s
            # the spawn is OBSERVABLE through the surviving router: the
            # new replica joined its ring and the autoscaler's counter
            # rides the router's #metrics (global-registry merge)
            with ServeClient(*addr[0]) as c:
                h = c.health()
                assert h["router"] and h["status"] == "ready", h
                assert h["replicas_live"] == 3, h
                text = c.metrics()
                assert "autoscale_spawns_total" in text, text[:400]
                assert "router_affinity_hit_rate" in text, text[:400]
            # settled: a fresh post-chaos window sheds nothing and errs
            # nothing through the 3-replica ring
            rep2 = run_loadgen_failover(addr, rows, qps=80,
                                        duration_s=1.5)
            assert rep2["err"] == 0, rep2
            assert rep2["shed"] == 0, rep2
        finally:
            if scaler is not None:
                scaler.close()
            _close_fleet(group, servers, extra)


def test_router_group_supervisor_relaunches_dead_member():
    """tools/fleet.py run_router_group: a member that dies is relaunched
    on launch.py's backoff schedule (counted), live members are left
    alone, and teardown terminates the group."""
    sys.path.insert(0, str(REPO / "tools"))
    import fleet as fleet_cli

    from difacto_tpu.obs import REGISTRY

    before = REGISTRY.value("router_group_relaunches_total")
    sleeps = []

    def sleep_fn(d):
        sleeps.append(d)
        time.sleep(min(d, 0.02))

    def cmd_fn(i):
        # member 0 lives; member 1 exits immediately (the crash loop)
        if i == 0:
            return [sys.executable, "-c",
                    "import time; time.sleep(60)"]
        return [sys.executable, "-c", "pass"]

    with deadline(120):
        rep = fleet_cli.run_router_group(
            2, cmd_fn, max_seconds=3.0, poll_s=0.01,
            backoff_base_s=0.01, sleep_fn=sleep_fn,
            max_relaunches=3)
    assert rep["ok"], rep
    assert rep["relaunches"] == 3, rep
    assert len(sleeps) >= 3, sleeps
    assert REGISTRY.value("router_group_relaunches_total") >= before + 3
