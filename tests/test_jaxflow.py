"""JAX flow analyzer + runtime tracer suite (difacto-lint v4).

Three layers, all tier-1:

- **tracer units** (utils/jaxtrace.py) — disabled pass-through, per-site
  compile/call counting with the jit cache as ground truth (weak-typed
  scalars never over-count), static-argnum keys by value, fetch
  counting, dump/load round-trip;
- **the static model** (analysis/jaxflow.py) — the serve jit site is
  known and warm-declared on this very repo, declared fetch points
  include the executor's scores sync, rule scoping (local vs cross)
  matches the --changed-only contract, pass timings land in the JSON
  report;
- **the gate** — drive the REAL serve path (MicroBatcher ->
  PredictExecutor) in a subprocess under DIFACTO_JAXTRACE=1 and assert
  dynamic ⊆ static: every observed jit site is statically known AND
  warm-declared, compiles STOP GROWING after warm-up (the "zero
  steady-state recompiles" claim, previously only bench-measured),
  and every observed device->host transfer is a declared fetch point.
  Same shape as the RACETRACE gate in tests/test_lint.py.

Rule fixture twins (TP exactly once / negative / suppressed) live in
tests/test_lint.py next to every other rule's.
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from difacto_tpu.analysis import core
from difacto_tpu.analysis.cli import DEFAULT_PATHS
from difacto_tpu.analysis.cli import main as lint_main
from difacto_tpu.analysis.jaxflow import get_jax_model
from difacto_tpu.utils import jaxtrace

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def repo_model():
    project = core.Project(
        REPO_ROOT, [p for p in DEFAULT_PATHS if (REPO_ROOT / p).exists()])
    return get_jax_model(project)


# ---------------------------------------------------------------------------
# tracer units


def test_tracer_disabled_is_passthrough(monkeypatch):
    monkeypatch.delenv("DIFACTO_JAXTRACE", raising=False)
    jaxtrace.reset()
    f = jaxtrace.jit(lambda x: x + 1)
    import jax.numpy as jnp
    out = f(jnp.ones(3))
    assert out.shape == (3,)
    got = jaxtrace.fetch(out, point="unit")
    assert isinstance(got, np.ndarray)
    assert jaxtrace.sites() == {} and jaxtrace.fetches() == {}


def test_tracer_counts_compiles_per_shape(monkeypatch):
    monkeypatch.setenv("DIFACTO_JAXTRACE", "1")
    jaxtrace.reset()
    import jax.numpy as jnp
    f = jaxtrace.jit(lambda x: x + 1)
    f(jnp.ones(3))
    f(jnp.ones(3))
    f(jnp.ones(4))          # new shape -> new compile
    (site, rec), = jaxtrace.sites().items()
    assert site.startswith("tests/test_jaxflow.py:")
    assert rec["calls"] == 3
    assert rec["compiles"] == 2
    jaxtrace.reset()


def test_tracer_weak_scalars_do_not_overcount(monkeypatch):
    monkeypatch.setenv("DIFACTO_JAXTRACE", "1")
    jaxtrace.reset()
    import jax.numpy as jnp
    g = jaxtrace.jit(lambda x, a: x * a)
    arr = jnp.ones(3)
    g(arr, 2.0)
    g(arr, 3.0)             # weak-typed float: same compiled program
    (_, rec), = jaxtrace.sites().items()
    assert rec["calls"] == 2
    assert rec["compiles"] == 1
    jaxtrace.reset()


def test_tracer_statics_key_by_value(monkeypatch):
    monkeypatch.setenv("DIFACTO_JAXTRACE", "1")
    jaxtrace.reset()
    import jax.numpy as jnp

    def pad(x, n):
        return jnp.zeros(n).at[: x.shape[0]].set(x)

    h = jaxtrace.jit(pad, static_argnums=(1,))
    arr = jnp.ones(3)
    h(arr, 8)
    h(arr, 8)
    h(arr, 16)              # new static value -> new compile
    (_, rec), = jaxtrace.sites().items()
    assert rec["calls"] == 3
    assert rec["compiles"] == 2
    assert len(rec["keys"]) == 2
    jaxtrace.reset()


def test_pjit_same_site_identity_and_counts(monkeypatch):
    """jaxtrace.pjit (sharded-jit creation, ISSUE 12) records the SAME
    relpath:lineno site identity as jaxtrace.jit — mesh-sharded
    programs stay inside the compile/transfer gates."""
    monkeypatch.setenv("DIFACTO_JAXTRACE", "1")
    jaxtrace.reset()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "fs"))
    sh = NamedSharding(mesh, P("fs"))
    f = jaxtrace.pjit(lambda x: x * 2, out_shardings=sh)
    x = jax.device_put(jnp.ones(8), sh)
    f(x)
    f(x)
    (site, rec), = jaxtrace.sites().items()
    assert site.startswith("tests/test_jaxflow.py:")
    assert rec["calls"] == 2 and rec["compiles"] == 1
    jaxtrace.reset()


def test_pjit_site_in_static_model(repo_model):
    """The capacity bench's jaxtrace.pjit creation sites (the fs
    capacity sweep + the bounded-delay sweep, parallel/capacity.py)
    are discovered by the static model under the same identity scheme
    and are warm-declared (reasoned suppressions — one compile per fs
    rung / one per delay sweep)."""
    cap_sites = [s for s in repo_model.sites
                 if s.startswith("difacto_tpu/parallel/capacity.py:")]
    assert len(cap_sites) == 2, cap_sites
    for site in cap_sites:
        assert site in repo_model.known_warm(), site
    # its declared fetch point is known too
    assert any(s.startswith("difacto_tpu/parallel/capacity.py:")
               for s in repo_model.declared_fetches())


def test_fetch_counts_and_dump_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("DIFACTO_JAXTRACE", "1")
    jaxtrace.reset()
    import jax.numpy as jnp
    f = jaxtrace.jit(lambda x: x * 2)
    y = f(jnp.ones(4))
    for _ in range(3):
        got = jaxtrace.fetch(y, point="unit.sync")
    assert isinstance(got, np.ndarray) and got.shape == (4,)
    (fsite, frec), = jaxtrace.fetches().items()
    assert frec == {"point": "unit.sync", "count": 3}
    out = tmp_path / "jax.json"
    jaxtrace.dump(out)
    loaded = jaxtrace.load(out)
    assert fsite in loaded["fetches"]
    assert loaded["fetches"][fsite]["count"] == 3
    (site, rec), = loaded["sites"].items()
    assert rec["compiles"] == 1 and rec["calls"] == 1
    jaxtrace.reset()
    assert jaxtrace.sites() == {}


# ---------------------------------------------------------------------------
# the static model on this repo


def test_serve_jit_site_known_and_warm(repo_model):
    exec_sites = [s for s in repo_model.sites
                  if s.startswith("difacto_tpu/serve/executor.py:")]
    assert len(exec_sites) == 1, exec_sites
    assert repo_model.sites[exec_sites[0]].target_name == "packed_predict"
    assert exec_sites[0] in repo_model.known_warm()


def test_every_repo_site_is_warm_declared(repo_model):
    # the zero-findings scrub contract: every jit site is either proven
    # bounded or carries a reasoned jax-recompile suppression
    not_warm = set(repo_model.sites) - repo_model.known_warm()
    assert not_warm == set(), sorted(not_warm)


def test_serve_scores_fetch_is_declared(repo_model):
    declared = repo_model.declared_fetches()
    assert any(s.startswith("difacto_tpu/serve/executor.py:")
               for s in declared), sorted(declared)


def test_hot_roots_include_serve_dispatch_loop(repo_model):
    assert "difacto_tpu/serve/batcher.py::MicroBatcher._loop" \
        in repo_model.hot_roots


def test_model_json_shape(repo_model):
    doc = repo_model.to_json()
    assert doc["sites"] and doc["fetch_sites"] and doc["hot_roots"]
    for rec in doc["sites"].values():
        assert {"target", "bound", "static_argnums", "donate_argnums",
                "call_sites", "warm_bounded", "unbounded"} <= set(rec)


def test_jaxflow_rule_scoping_matches_changed_only_contract():
    # --changed-only narrows LOCAL rules to changed files while cross
    # rules always see the whole tree (cli.run_project contract): the
    # dtype pass is local, the three flow passes are cross
    rules = core.all_rules()
    assert not rules["jax-dtype64"].cross
    for rid in ("jax-recompile", "jax-host-sync", "jax-donate-flow"):
        assert rules[rid].cross


def test_rule_seconds_cover_jaxflow_passes(tmp_path, capsys):
    (tmp_path / "mod.py").write_text("import jax\n")
    rc = lint_main(["--root", str(tmp_path), "mod.py", "--format", "json",
                    "--rules",
                    "jax-recompile,jax-host-sync,jax-donate-flow,"
                    "jax-dtype64"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(doc["rule_seconds"]) == {
        "jax-recompile", "jax-host-sync", "jax-donate-flow",
        "jax-dtype64"}


# ---------------------------------------------------------------------------
# jitmap


def _load_jitmap():
    spec = importlib.util.spec_from_file_location(
        "difacto_jitmap", REPO_ROOT / "tools" / "jitmap.py")
    jitmap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(jitmap)
    return jitmap


def test_jitmap_static_build_and_text(repo_model):
    jitmap = _load_jitmap()
    graph = jitmap.build(REPO_ROOT)
    assert graph["sites"] and graph["fetch_sites"]
    txt = jitmap.to_text(graph)
    assert "packed_predict" in txt
    assert "declared fetch points" in txt


def test_jitmap_check_fails_on_unknown_dynamic_site(tmp_path, capsys,
                                                    repo_model):
    jitmap = _load_jitmap()
    good_site = sorted(repo_model.sites)[0]
    dump = tmp_path / "jax.json"
    dump.write_text(json.dumps({
        "version": 1,
        "sites": {
            good_site: {"label": "x", "calls": 3, "compiles": 1,
                        "keys": []},
            "nowhere.py:1": {"label": "ghost", "calls": 1,
                             "compiles": 1, "keys": []},
        },
        "fetches": {"nowhere.py:2": {"point": "ghost", "count": 1}},
    }))
    rc = jitmap.main(["--root", str(REPO_ROOT),
                      "--dynamic", str(dump), "--check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "UNKNOWN-SITES: nowhere.py:1" in out
    assert "UNKNOWN-FETCHES: nowhere.py:2" in out

    graph = jitmap.build(REPO_ROOT, dump)
    assert graph["unknown_sites"] == ["nowhere.py:1"]
    assert graph["unknown_fetches"] == ["nowhere.py:2"]
    assert good_site not in graph["unknown_sites"]


def test_jitmap_check_passes_on_model_subset(tmp_path, repo_model):
    jitmap = _load_jitmap()
    good_site = sorted(repo_model.sites)[0]
    good_fetch = sorted(repo_model.declared_fetches())[0]
    dump = tmp_path / "jax.json"
    dump.write_text(json.dumps({
        "version": 1,
        "sites": {good_site: {"label": "x", "calls": 5, "compiles": 1,
                              "keys": []}},
        "fetches": {good_fetch: {"point": "p", "count": 5}},
    }))
    rc = jitmap.main(["--root", str(REPO_ROOT),
                      "--dynamic", str(dump), "--check"])
    assert rc == 0


# ---------------------------------------------------------------------------
# the tier-1 JAXTRACE gate: dynamic compiles ⊆ static warm set on the
# REAL serve path, compiles stop growing after warm-up, transfers only
# at declared fetch points


def test_jaxtrace_gate_serve_steady_state(tmp_path, repo_model):
    warm_dump = tmp_path / "warm.json"
    final_dump = tmp_path / "final.json"
    scenario = textwrap.dedent(f"""
        import numpy as np
        from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
        from difacto_tpu.store.local import SlotStore
        from difacto_tpu.serve.batcher import MicroBatcher
        from difacto_tpu.serve.executor import PredictExecutor
        from difacto_tpu.data.rowblock import RowBlock
        from difacto_tpu.utils import jaxtrace

        store = SlotStore(SGDUpdaterParam(V_dim=4, hash_capacity=1024))
        ex = PredictExecutor(store)
        # batch_size == rows per request: each submit flushes exactly
        # one deterministic 4-row batch through the dispatch loop
        bat = MicroBatcher(ex.predict_scores, batch_size=4, queue_cap=64)
        bat.start()

        def blk():
            idx = (np.arange(16, dtype=np.uint32) * 7) % 97
            off = np.arange(0, 17, 4, dtype=np.int64)
            return RowBlock(offset=off,
                            label=np.zeros(4, np.float32),
                            index=idx, value=None, weight=None)

        for _ in range(3):          # warm-up: first bucket compiles
            fut = bat.submit(blk())
            assert fut is not None
            fut.result(60)
        jaxtrace.dump({str(warm_dump)!r})
        for _ in range(10):         # steady state: hits only
            fut = bat.submit(blk())
            assert fut is not None
            fut.result(60)
        bat.close()
        assert ex.stats()["dispatches"] == 13
        jaxtrace.dump({str(final_dump)!r})
    """)
    env = dict(os.environ, DIFACTO_JAXTRACE="1", JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", scenario],
                       cwd=REPO_ROOT, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    warm = jaxtrace.load(warm_dump)
    final = jaxtrace.load(final_dump)
    assert warm["sites"], "warm-up must have exercised a jit site"

    serve_site = [s for s in final["sites"]
                  if s.startswith("difacto_tpu/serve/executor.py:")]
    assert serve_site, final["sites"]

    known_warm = repo_model.known_warm()
    declared = repo_model.declared_fetches()
    for site, rec in sorted(final["sites"].items()):
        # dynamic ⊆ static: the tracer and the model key sites the
        # same way, so an unknown site is a discovery blind spot
        assert site in repo_model.sites, \
            f"jit site {site} unknown to the static model"
        assert site in known_warm, \
            f"jit site {site} is not statically warm-declared"
        # steady state: compiles frozen at the warm-up count while
        # calls kept growing — zero steady-state recompiles, proven
        w = warm["sites"].get(site)
        assert w is not None, f"{site} first compiled AFTER warm-up"
        assert rec["compiles"] == w["compiles"], \
            f"{site} recompiled in steady state: " \
            f"{w['compiles']} -> {rec['compiles']}"
        assert rec["calls"] > w["calls"]
    for site, rec in sorted(final["fetches"].items()):
        assert site in declared, \
            f"device->host transfer at undeclared site {site} " \
            f"({rec['point']})"
    # the serve loop's one declared sync actually fired per dispatch
    scores = [rec for rec in final["fetches"].values()
              if rec["point"] == "serve.scores"]
    assert scores and scores[0]["count"] == 13


# ---------------------------------------------------------------------------
# device-trace annotation (the PR 4 leftover): spans wrap
# jax.profiler.TraceAnnotation / StepTraceAnnotation under
# DIFACTO_TRACE_DEVICE, profiler artifacts land in the logdir


def test_trace_device_spans_and_profile_artifacts(tmp_path):
    logdir = tmp_path / "device"
    span_file = tmp_path / "trace.json"
    scenario = textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        from difacto_tpu.obs import trace

        assert trace.active(), "DIFACTO_TRACE must activate spans"
        with trace.span("gate.step", step_num=1):
            jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready()
        with trace.span("gate.host"):
            pass
    """)
    env = dict(os.environ,
               DIFACTO_TRACE=str(span_file),
               DIFACTO_TRACE_DEVICE=str(logdir),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", scenario],
                       cwd=REPO_ROOT, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    doc = json.loads(span_file.read_text())
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert {"gate.step", "gate.host"} <= names
    profile_files = [p for p in logdir.rglob("*") if p.is_file()]
    assert profile_files, \
        "jax profiler wrote nothing under DIFACTO_TRACE_DEVICE"


def test_trace_device_absent_knob_keeps_spans_plain(tmp_path,
                                                    monkeypatch):
    # without the knob the module never touches jax — spans stay the
    # cheap host-only path
    from difacto_tpu.obs import trace
    monkeypatch.delenv("DIFACTO_TRACE_DEVICE", raising=False)
    assert trace._annotate is None
