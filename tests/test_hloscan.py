"""hloscan unit tests + the tier-1 fs=4 HLO gate (difacto-lint v5).

Three layers:

- **scanner units** — the collective classifier over a synthetic HLO
  dump, the violations view over fabricated program records, and the
  dump/load round-trip;
- **a planted failure** — a `P('fs', None)` table jitted with
  replicated out_shardings MUST produce a table-axis all-gather on the
  virtual CPU mesh: the scanner is tested against the exact failure it
  gates;
- **the gate** — `tools/hlomap.py --scan --fs 4 --check` in a
  subprocess compiles the REAL fs-sharded train step
  (parallel/capacity.py) and serve executor (serve/executor.py) and
  must find zero table-axis collectives, zero budget breaches, and
  every scanned jit site inside the static shardflow model
  (dynamic ⊆ static, the same contract as the v2-v4 gates).
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys

import pytest

from difacto_tpu.utils import hloscan

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load_hlomap():
    spec = importlib.util.spec_from_file_location(
        "difacto_hlomap", REPO_ROOT / "tools" / "hlomap.py")
    hlomap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hlomap)
    return hlomap


# ---------------------------------------------------------------------------
# the collective classifier


HLO_TEXT = """\
ENTRY %main {
  %p = f32[128,4]{1,0} parameter(0)
  %ag = f32[512,4]{1,0} all-gather(f32[128,4]{1,0} %p), dimensions={0}
  %ar = f32[64]{0} all-reduce(f32[64]{0} %g), to_apply=%sum
  %add = f32[128,4]{1,0} add(%p, %p)
}
"""


def test_scan_text_classifies_table_axis():
    colls = hloscan.scan_text(HLO_TEXT, rows=512)
    assert {c["kind"] for c in colls} == {"all-gather", "all-reduce"}
    ag = next(c for c in colls if c["kind"] == "all-gather")
    # the gathered result carries the FULL table row count: table-axis
    assert ag["table_axis"] and 512 in ag["dims"]
    # all-reduce combines values, never axes — expected, not a hit
    ar = next(c for c in colls if c["kind"] == "all-reduce")
    assert not ar["table_axis"]
    # rows=0 disables the classification entirely
    assert all(not c["table_axis"]
               for c in hloscan.scan_text(HLO_TEXT, rows=0))
    # a different table size does not match this gather
    colls = hloscan.scan_text(HLO_TEXT, rows=4096)
    assert all(not c["table_axis"] for c in colls)


def test_violations_view_over_program_records():
    progs = {
        "a.py:1": {"label": "x",
                   "collectives": [{"kind": "all-gather",
                                    "dims": [128, 512],
                                    "table_axis": True, "line": ""}],
                   "table_collectives": 1, "peak_temp_bytes": 10,
                   "over_budget": False, "signatures": 1},
        "b.py:2": {"label": "y", "collectives": [],
                   "table_collectives": 0, "peak_temp_bytes": 999,
                   "over_budget": True, "signatures": 2},
        "c.py:3": {"label": "z", "collectives": [],
                   "table_collectives": 0, "peak_temp_bytes": 1,
                   "over_budget": False, "signatures": 1},
    }
    v = hloscan.violations(progs)
    assert sorted(x["kind"] for x in v) == ["table-collective",
                                            "temp-budget"]
    assert {x["site"] for x in v} == {"a.py:1", "b.py:2"}


def test_dump_load_round_trip(tmp_path, monkeypatch):
    hloscan.reset()
    monkeypatch.setenv("DIFACTO_HLOSCAN_ROWS", "512")
    monkeypatch.setenv("DIFACTO_HLOSCAN_BUDGET", "0")
    path = tmp_path / "scan.json"
    hloscan.dump(path)
    doc = hloscan.load(path)
    assert doc == {"rows": 512, "budget": 0, "programs": {}}
    # version gate: a foreign dump must be rejected, not misread
    path.write_text(json.dumps({"version": 99, "programs": {}}))
    with pytest.raises(ValueError):
        hloscan.load(path)
    hloscan.reset()


# ---------------------------------------------------------------------------
# the planted failure: forced replication of an fs-sharded table MUST
# surface as a table-axis all-gather


def test_planted_replication_is_detected():
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from difacto_tpu.parallel import make_mesh, replicated, state_sharding

    mesh = make_mesh(dp=1, fs=4)
    rows = 512
    table = jnp.zeros((rows, 4), jnp.float32)
    table = jax.device_put(table, state_sharding(mesh)(table))
    bad = jax.jit(lambda a: a * 2.0, out_shardings=replicated(mesh))
    compiled = bad.lower(table).compile()
    rec = hloscan.scan_compiled(compiled, rows=rows, label="planted")
    assert rec["table_collectives"] >= 1, rec["collectives"]
    assert any(c["kind"] == "all-gather" and c["table_axis"]
               for c in rec["collectives"])
    # and the registry/violations plumbing agrees
    hloscan.reset()
    hloscan.record("planted.py:1", compiled, label="planted", rows=rows)
    v = hloscan.violations()
    assert any(x["kind"] == "table-collective"
               and x["site"] == "planted.py:1" for x in v)
    hloscan.reset()


# ---------------------------------------------------------------------------
# hlomap --check over recorded dumps (no compile needed)


def _write_dump(tmp_path, programs):
    p = tmp_path / "scan.json"
    p.write_text(json.dumps({"version": 1, "rows": 512, "budget": 0,
                             "programs": programs}))
    return p


_CLEAN_REC = {"label": "x", "collectives": [], "table_collectives": 0,
              "peak_temp_bytes": 1, "over_budget": False,
              "signatures": 1}


def test_hlomap_check_fails_on_planted_violations(tmp_path, capsys):
    hlomap = _load_hlomap()
    graph = hlomap.build(REPO_ROOT)
    good_site = sorted(s for s in graph["sites"] if ":" in s)[0]
    bad_rec = dict(_CLEAN_REC)
    bad_rec["collectives"] = [{"kind": "all-gather", "dims": [512],
                               "table_axis": True, "line": ""}]
    bad_rec["table_collectives"] = 1
    dump = _write_dump(tmp_path, {good_site: bad_rec,
                                  "nowhere.py:1": dict(_CLEAN_REC)})
    rc = hlomap.main(["--root", str(REPO_ROOT),
                      "--dynamic", str(dump), "--check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "TABLE-HITS" in out
    assert "UNKNOWN-SITES: nowhere.py:1" in out

    merged = hlomap.build(REPO_ROOT, hloscan.load(dump))
    assert [v["site"] for v in merged["table_hits"]] == [good_site]
    assert merged["unknown_sites"] == ["nowhere.py:1"]


def test_hlomap_check_passes_on_clean_known_sites(tmp_path):
    hlomap = _load_hlomap()
    graph = hlomap.build(REPO_ROOT)
    good_site = sorted(s for s in graph["sites"] if ":" in s)[0]
    dump = _write_dump(tmp_path, {good_site: dict(_CLEAN_REC),
                                  "train_step": dict(_CLEAN_REC)})
    # non-site labels (explicit record() keys, e.g. capacity legs) are
    # exempt from the dynamic ⊆ static subset claim
    rc = hlomap.main(["--root", str(REPO_ROOT),
                      "--dynamic", str(dump), "--check"])
    assert rc == 0


# ---------------------------------------------------------------------------
# the tier-1 gate: compile the REAL fs=4 train step + serve executor
# and prove layout cleanliness end to end


def test_fs4_hlo_gate_train_and_serve(tmp_path):
    out = tmp_path / "hlomap.json"
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "hlomap.py"),
         "--scan", "--fs", "4", "--rows", "1024",
         "--json", str(out), "--check"],
        cwd=str(REPO_ROOT), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    scanned = set(doc["programs"])
    assert any("parallel/capacity.py" in s for s in scanned), scanned
    assert any("serve/executor.py" in s for s in scanned), scanned
    # zero table-axis collectives, zero budget breaches, and every
    # scanned jit site known to the static model: dynamic ⊆ static
    assert doc["table_hits"] == []
    assert doc["budget_hits"] == []
    assert doc["unknown_sites"] == []
    assert {s for s in scanned if ":" in s} <= set(doc["sites"])
    # the fs-scoped state programs all carry pin evidence
    for sid, rec in doc["state_programs"].items():
        assert rec["pinned"], (sid, rec)
