"""Smoke tests for the two driver-graded artifacts: bench.py and
__graft_entry__. Round 1 shipped both broken (BENCH_r01 rc=1,
MULTICHIP_r01 ok=false) because nothing executed them in CI; these tests
run them the way the driver does, on tiny shapes.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run(cmd, extra_env=None):
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(cmd, cwd=str(REPO), env=env,
                          capture_output=True, text=True, timeout=600)


def test_bench_device_mode_smoke():
    # --device-only: the default e2e window is 1.8M rows, far too slow
    # for a CPU smoke (the e2e path gets its own tiny-window test below)
    proc = _run([sys.executable, "bench.py", "--device-only",
                 "--steps", "2", "--batch-size", "128", "--uniq", "256",
                 "--capacity", "1024", "--vdim", "4"])
    assert proc.returncode == 0, proc.stderr
    line = proc.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["value"] > 0
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}


def test_bench_mesh_mode_smoke():
    # --mesh DPxFS runs the same step as a sharded program over a mesh —
    # on the 8 virtual CPU devices the conftest env provides. Guards the
    # JAX_PLATFORMS=cpu config override in bench.py: without it the
    # subprocess binds the pinned device platform (1 device) and dies
    # with "need 8 devices, have 1".
    proc = _run([sys.executable, "bench.py", "--device-only",
                 "--mesh", "2x4", "--steps", "2", "--batch-size", "128",
                 "--uniq", "256", "--capacity", "1024", "--vdim", "4"])
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert "mesh2x4" in rec["metric"]


def test_bench_e2e_smoke():
    proc = _run([sys.executable, "bench.py", "--e2e",
                 "--e2e-rows", "2000", "--e2e-batch", "256",
                 "--capacity", "4096", "--vdim", "4"])
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert rec["config"]["rows"] == 2000


def test_graft_entry_single_chip():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_dryrun_multichip_in_process():
    # conftest gives this process 8 virtual CPU devices: in-process path
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_dryrun_multichip_subprocess_fallback():
    # a fresh interpreter without the XLA flag has 1 CPU device, so
    # dryrun_multichip(4) must take the subprocess fallback and succeed
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import __graft_entry__; __graft_entry__.dryrun_multichip(4)")
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stderr
