"""KV algebra tests, mirroring the reference's kv_match/kv_union/find_position
suites (tests/cpp/kv_match_test.cc, kv_union_test.cc, find_position_test.cc):
random sorted-unique key sets checked against brute-force dict merges.
"""

import numpy as np
import pytest

from difacto_tpu.ops.kv import (find_position, kv_match, kv_match_varlen,
                                kv_union)


def gen_keys(rng, n, lo=0, hi=1000):
    return np.unique(rng.randint(lo, hi, n).astype(np.uint64))


def test_find_position():
    src = np.array([2, 4, 6, 8], dtype=np.uint64)
    dst = np.array([1, 2, 5, 6, 9], dtype=np.uint64)
    np.testing.assert_array_equal(find_position(src, dst),
                                  [-1, 0, -1, 2, -1])
    # empty src
    np.testing.assert_array_equal(
        find_position(np.array([], dtype=np.uint64), dst), [-1] * 5)


def test_find_position_rejects_unsorted():
    with pytest.raises(ValueError):
        find_position(np.array([3, 1], dtype=np.uint64),
                      np.array([1], dtype=np.uint64))


@pytest.mark.parametrize("val_len", [1, 3])
@pytest.mark.parametrize("op", ["assign", "add"])
def test_kv_match_random(val_len, op):
    rng = np.random.RandomState(0)
    for _ in range(5):
        src_k = gen_keys(rng, 100)
        dst_k = gen_keys(rng, 80)
        src_v = rng.randn(len(src_k) * val_len).astype(np.float32)
        dst_v = rng.randn(len(dst_k) * val_len).astype(np.float32)
        expect = dst_v.reshape(len(dst_k), val_len).copy()
        lut = {k: i for i, k in enumerate(src_k)}
        nmatch = 0
        for i, k in enumerate(dst_k):
            if k in lut:
                sv = src_v.reshape(-1, val_len)[lut[k]]
                expect[i] = sv if op == "assign" else expect[i] + sv
                nmatch += val_len
        got = dst_v.copy()
        n = kv_match(src_k, src_v, dst_k, got, op, val_len)
        assert n == nmatch
        np.testing.assert_allclose(got.reshape(-1, val_len), expect, rtol=1e-6)


def test_kv_match_varlen():
    """Variable lens: the [w, V...] layout (kv_match_test.cc:133)."""
    rng = np.random.RandomState(1)
    src_k = np.array([1, 3, 5, 7], dtype=np.uint64)
    src_lens = np.array([1, 3, 1, 3])
    src_v = rng.randn(int(src_lens.sum())).astype(np.float32)
    dst_k = np.array([0, 3, 5, 8], dtype=np.uint64)
    dst_lens = np.array([2, 3, 1, 1])
    dst_v = np.zeros(int(dst_lens.sum()), dtype=np.float32)
    n = kv_match_varlen(src_k, src_v, src_lens, dst_k, dst_v, dst_lens)
    assert n == 4  # key 3 (len 3) + key 5 (len 1)
    np.testing.assert_allclose(dst_v[2:5], src_v[1:4])  # key 3's V block
    np.testing.assert_allclose(dst_v[5], src_v[4])      # key 5's w
    assert (dst_v[:2] == 0).all() and dst_v[6] == 0

    # length disagreement on a matched key is an error (kv_match-inl.h:100)
    bad_lens = dst_lens.copy()
    bad_lens[1] = 2
    bad_v = np.zeros(int(bad_lens.sum()), dtype=np.float32)
    with pytest.raises(ValueError):
        kv_match_varlen(src_k, src_v, src_lens, dst_k, bad_v, bad_lens)


@pytest.mark.parametrize("op", ["add", "assign"])
def test_kv_union_random(op):
    rng = np.random.RandomState(2)
    for _ in range(5):
        ka = gen_keys(rng, 60)
        kb = gen_keys(rng, 60)
        va = rng.randn(len(ka)).astype(np.float32)
        vb = rng.randn(len(kb)).astype(np.float32)
        keys, vals = kv_union(ka, va, kb, vb, op)
        d = dict(zip(ka.tolist(), va.tolist()))
        for k, v in zip(kb.tolist(), vb.tolist()):
            if op == "add":
                d[k] = d.get(k, 0.0) + v
            else:
                d[k] = v
        assert sorted(d) == keys.tolist()
        np.testing.assert_allclose(vals, [d[k] for k in keys.tolist()],
                                   rtol=1e-5)
