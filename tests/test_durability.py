"""Durable-training-state suite (ISSUE 20): the write-ahead delta log,
peer-replicated shard checkpoints and the bounded-RPO recovery ladder
(difacto_tpu/durability/), proven under the failures they exist for.

Covers the acceptance legs — segment round-trip (fp32 AND quantized
container bytes), the corrupt/torn WAL matrix (truncated tail, bit
flip, missing middle: typed stops at the verified prefix, never
silently-wrong rows), trajectory invariance (WAL on == WAL off, byte
identical), the four armed fault points (``wal.append`` /
``wal.replay`` / ``replica.push`` / ``replica.fetch``), the
``ckpt_keep``-vs-live-chain pruning regression, replication
push/scrub/lag, the recovery ladder rungs, and the deterministic
SIGKILL-mid-window + disk-loss chaos leg (relaunch recovers via peer
replica + WAL replay; replayed-forward work bounded by one flush
window; byte-identical final state vs the unkilled reference run).

Conventions follow tests/test_chaos.py: SIGALRM deadlines around
subprocess legs, the ``chaos`` marker (tier-1; ``make
durability-chaos`` selects this file's tests), injected faults
disarmed after every test.
"""

import contextlib
import json
import os
import pathlib
import signal
import subprocess
import sys

import numpy as np
import pytest

from difacto_tpu.__main__ import main
from difacto_tpu.durability import replicate, wal
from difacto_tpu.durability.replicate import Replicator
from difacto_tpu.durability.wal import WalCorrupt, WalWriter
from difacto_tpu.learners.sgd import SGDLearner
from difacto_tpu.store.local import K_FEACOUNT, K_GRADIENT, SlotStore
from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
from difacto_tpu.utils import faultinject
from difacto_tpu.utils import manifest as mft

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.chaos

FLUSH = 4  # wal_flush_batches used by the learner-level legs


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No injected fault may leak across tests."""
    yield
    faultinject.configure("")


def train_args(rcv1_path, model, epochs=3, extra=()):
    # batch_size=10 -> 10 batches/epoch over the 100-row fixture, so a
    # FLUSH=4 window seals at steps 4, 8 and the epoch boundary (10);
    # hashed store: the WAL requires a stable replayable row space
    return [f"data_in={rcv1_path}", "lr=1", "l1=1", "l2=1",
            "batch_size=10", f"max_num_epochs={epochs}", "shuffle=0",
            "num_jobs_per_epoch=1", "report_interval=0",
            "stop_rel_objv=0", "hash_capacity=4096",
            f"model_out={model}", *extra]


def _mk_store(**kw) -> SlotStore:
    base = dict(hash_capacity=64, V_dim=4, V_threshold=0, lr=0.1,
                V_lr=0.1)
    base.update(kw)
    p, rest = SGDUpdaterParam.init_allow_unknown(
        [(k, str(v)) for k, v in base.items()])
    assert rest == []
    return SlotStore(p)


def _train_store(st: SlotStore, keys: np.ndarray, rounds: int = 3,
                 seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        k = np.sort(rng.choice(keys, size=min(8, len(keys)),
                               replace=False))
        st.push(k, K_FEACOUNT, np.ones(len(k), np.float32))
        st.pull(k)
        g = rng.standard_normal(len(k)).astype(np.float32) * 0.1
        gV = rng.standard_normal(
            (len(k), st.param.V_dim)).astype(np.float32) * 0.01
        st.push(k, K_GRADIENT, g, gV, np.ones(len(k), bool))


def _npz_arrays(path: str) -> dict:
    """Every array of a checkpoint file (np.load detects the zip by
    magic; checkpoint files carry no extension)."""
    with np.load(path, allow_pickle=False) as z:
        return {k: np.asarray(z[k]) for k in z.files}


def _assert_same_arrays(a: dict, b: dict) -> None:
    assert sorted(a) == sorted(b)
    for k in a:
        av, bv = a[k], b[k]
        assert av.dtype == bv.dtype and av.shape == bv.shape, k
        assert av.tobytes() == bv.tobytes(), f"array {k!r} differs"


# ------------------------------------------------- WAL segment format

def test_wal_segment_roundtrip_fp32_and_flat(tmp_path):
    """write/read round-trip of both payload layouts: fused VVg rows
    and the five flat V_dim=0 columns — dtype and bytes preserved."""
    rng = np.random.RandomState(3)
    meta = {"generation": 2, "seq": 0, "rank": 0, "epoch": 1,
            "step_lo": 0, "step_hi": 4, "boundary": False,
            "hash_capacity": 64, "capacity": 64, "V_dim": 4,
            "slot_dtype": "fp32", "row_width": 10}
    sects = {"slots": np.array([1, 5, 9], np.int32),
             "VVg": rng.randn(3, 10).astype(np.float32)}
    p = str(tmp_path / "seg.dfwal")
    n = wal.write_segment(p, meta, sects)
    assert n == os.path.getsize(p)
    got_meta, got = wal.read_segment(p)
    assert got_meta == meta
    assert got["slots"].tolist() == [1, 5, 9]
    assert got["VVg"].tobytes() == sects["VVg"].tobytes()
    assert got["VVg"].dtype == np.float32

    flat = {"slots": np.array([0, 2], np.int32),
            "w": rng.randn(2).astype(np.float32),
            "z": rng.randn(2).astype(np.float32),
            "sqrt_g": rng.rand(2).astype(np.float32),
            "cnt": np.array([3.0, 7.0], np.float32),
            "v_live": np.array([True, False])}
    p2 = str(tmp_path / "flat.dfwal")
    wal.write_segment(p2, meta, flat)
    _, got2 = wal.read_segment(p2)
    for k, v in flat.items():
        assert got2[k].dtype == v.dtype and got2[k].tobytes() == \
            v.tobytes(), k


def test_wal_segment_roundtrip_quantized_containers(tmp_path):
    """Quantization-aware: bf16 and fp8 CONTAINER rows (ml_dtypes — no
    buffer-protocol format char) round-trip bit-exact by name."""
    import ml_dtypes
    rng = np.random.RandomState(5)
    meta = {"epoch": 0, "step_lo": 0, "step_hi": 1}
    for dt in (ml_dtypes.bfloat16, ml_dtypes.float8_e4m3fn):
        rows = rng.randn(4, 6).astype(np.float32).astype(dt)
        p = str(tmp_path / f"{np.dtype(dt).name}.dfwal")
        wal.write_segment(p, meta, {"slots": np.arange(4, dtype=np.int32),
                                    "VVg": rows})
        _, got = wal.read_segment(p)
        assert got["VVg"].dtype == np.dtype(dt)
        assert got["VVg"].tobytes() == rows.tobytes()


def test_wal_corrupt_matrix_typed(tmp_path):
    """Truncated tail, payload bit flip, bad magic and a too-short file
    all surface as the typed WalCorrupt naming the file — never a
    struct crash or a silent short read."""
    meta = {"epoch": 0, "step_lo": 0, "step_hi": 1}
    good = str(tmp_path / "good.dfwal")
    wal.write_segment(good, meta, {
        "slots": np.arange(8, dtype=np.int32),
        "VVg": np.ones((8, 4), np.float32)})
    buf = open(good, "rb").read()

    torn = str(tmp_path / "torn.dfwal")
    open(torn, "wb").write(buf[:len(buf) // 2])
    flip = str(tmp_path / "flip.dfwal")
    fb = bytearray(buf)
    fb[-3] ^= 0xFF  # inside the last section's payload
    open(flip, "wb").write(bytes(fb))
    magic = str(tmp_path / "magic.dfwal")
    open(magic, "wb").write(b"NOTAWAL!" + buf[8:])
    short = str(tmp_path / "short.dfwal")
    open(short, "wb").write(buf[:4])

    for p in (torn, flip, magic, short):
        with pytest.raises(WalCorrupt) as ei:
            wal.read_segment(p)
        assert p in str(ei.value)
    # the intact segment still reads: corruption detection, not refusal
    wal.read_segment(good)


def test_wal_writer_chain_rebase_adopt(tmp_path):
    d = str(tmp_path / "m.wal")
    w = WalWriter(d, rank=0, geom={"capacity": 64}, generation=3)
    rows = np.ones((2, 4), np.float32)
    for i in range(3):
        w.append(np.array([i, i + 1], np.int32), {"VVg": rows},
                 epoch=0, step_lo=i * 4, step_hi=(i + 1) * 4)
    assert [s for s, _ in wal.chain_segments(d, 0, 3)] == [0, 1, 2]
    # an empty non-boundary window writes nothing; a boundary marker does
    assert w.append(np.array([], np.int32), {}, 0, 12, 12) is None
    assert w.append(np.array([], np.int32), {}, 0, 12, 12,
                    boundary=True) is not None
    # rebase to generation 5: keep_generations=2 retires chains < 4
    w.rebase(5, epoch=1)
    assert (w.generation, w.seq, w.base_epoch) == (5, 0, 1)
    assert wal.chain_generations(d, 0) == []  # gen-3 chain retired
    w.append(np.array([1], np.int32), {"VVg": rows[:1]}, 1, 0, 4)
    w.append(np.array([2], np.int32), {"VVg": rows[:1]}, 1, 4, 8)
    # adopt after a replay that verified only seq 0: the dead tail goes
    w2 = WalWriter(d, rank=0, geom={"capacity": 64})
    w2.adopt(5, next_seq=1, base_epoch=1)
    assert [s for s, _ in wal.chain_segments(d, 0, 5)] == [0]
    assert (w2.generation, w2.seq, w2.base_epoch) == (5, 1, 1)


# ------------------------------------------------- store hooks + replay

def test_store_wal_rows_roundtrip_fused_and_flat():
    """wal_touched_rows -> apply_wal_rows is byte-exact for both state
    layouts: a fresh same-seed store replayed to equals the source."""
    keys = np.arange(2, 40, dtype=np.uint64)
    for kw in (dict(V_dim=4), dict(V_dim=0),
               dict(V_dim=4, slot_dtype="bf16")):
        src = _mk_store(**kw)
        _train_store(src, keys)
        slots = np.unique(src.lookup(keys))
        slots = slots[(slots >= 0) & (slots < src.state.capacity)]
        rows = src.wal_touched_rows(slots)
        dst = _mk_store(**kw)  # same seed -> identical init state
        dst.apply_wal_rows(slots, rows)
        if kw.get("V_dim"):
            a = np.asarray(src.state.VVg)
            b = np.asarray(dst.state.VVg)
            assert a.tobytes() == b.tobytes()
        else:
            for col in ("w", "z", "sqrt_g", "cnt", "v_live"):
                assert np.asarray(getattr(src.state, col)).tobytes() == \
                    np.asarray(getattr(dst.state, col)).tobytes(), col


def test_replay_applies_chain_and_stops_typed(tmp_path):
    """A 3-segment chain replays to the head; a missing middle segment
    stops at the verified prefix typed 'gap'; a torn tail stops 'torn';
    a geometry mismatch stops 'geometry'. Nothing past a stop is ever
    applied."""
    keys = np.arange(2, 40, dtype=np.uint64)
    src = _mk_store()
    d = str(tmp_path / "m.wal")
    w = WalWriter(d, rank=0, geom=src.wal_geometry(), generation=1)
    snaps = []
    for i in range(3):
        _train_store(src, keys, rounds=1, seed=i)
        slots = np.unique(src.lookup(keys))
        slots = slots[(slots >= 0) & (slots < src.state.capacity)]
        w.append(slots, src.wal_touched_rows(slots), epoch=0,
                 step_lo=i * FLUSH, step_hi=(i + 1) * FLUSH)
        snaps.append(np.asarray(src.state.VVg).copy())

    dst = _mk_store()
    res = wal.replay(dst, d, 0, 1, base_epoch=-1)
    assert (res.segments, res.batches, res.stopped) == (3, 3 * FLUSH, "")
    assert (res.epoch, res.step, res.boundary) == (0, 3 * FLUSH, False)
    assert np.asarray(dst.state.VVg).tobytes() == snaps[2].tobytes()

    # missing middle -> gap: only seq 0 applies
    miss = str(tmp_path / "miss.wal")
    os.makedirs(miss)
    for seq, p in wal.chain_segments(d, 0, 1):
        if seq != 1:
            os.link(p, os.path.join(miss, os.path.basename(p)))
    dst = _mk_store()
    res = wal.replay(dst, miss, 0, 1, base_epoch=-1)
    assert (res.segments, res.stopped) == (1, "gap")
    assert res.step == FLUSH
    assert np.asarray(dst.state.VVg).tobytes() == snaps[0].tobytes()

    # torn tail -> torn: segments 0..1 apply, the half-written 2 not
    torn = str(tmp_path / "torn.wal")
    os.makedirs(torn)
    for seq, p in wal.chain_segments(d, 0, 1):
        q = os.path.join(torn, os.path.basename(p))
        buf = open(p, "rb").read()
        open(q, "wb").write(buf[:len(buf) // 2] if seq == 2 else buf)
    dst = _mk_store()
    res = wal.replay(dst, torn, 0, 1, base_epoch=-1)
    assert (res.segments, res.stopped) == (2, "torn")
    assert np.asarray(dst.state.VVg).tobytes() == snaps[1].tobytes()

    # a differently-shaped table refuses the whole chain typed
    dst = _mk_store(hash_capacity=128)
    res = wal.replay(dst, d, 0, 1, base_epoch=-1)
    assert (res.segments, res.stopped) == (0, "geometry")


# ----------------------------------------------------- learner gating

def test_wal_init_gates_typed(rcv1_path, tmp_path):
    model = str(tmp_path / "m")

    def init(extra):
        ln = SGDLearner()
        ln.init([tuple(kv.split("=", 1)) for kv in
                 train_args(rcv1_path, model, extra=extra)])
        return ln

    with pytest.raises(ValueError, match="requires model_out"):
        ln = SGDLearner()
        args = [kv for kv in train_args(rcv1_path, model,
                                        extra=("wal_flush_batches=4",))
                if not kv.startswith("model_out=")]
        ln.init([tuple(kv.split("=", 1)) for kv in args] +
                [("model_out", "")])
    with pytest.raises(ValueError, match="hashed store"):
        ln = SGDLearner()
        args = [kv for kv in train_args(rcv1_path, model,
                                        extra=("wal_flush_batches=4",))
                if not kv.startswith("hash_capacity=")]
        ln.init([tuple(kv.split("=", 1)) for kv in args])
    with pytest.raises(ValueError, match="evict_occupancy"):
        init(("wal_flush_batches=4", "evict_occupancy=0.5"))
    with pytest.raises(ValueError, match="cold_tier_rows"):
        init(("wal_flush_batches=4", "V_dim=4", "cold_tier_rows=64"))

    # defaults-off: no WAL, no replicator, resume is the classic path
    ln = init(())
    assert ln._wal is None and ln._replica is None
    ln.stop()
    # on: the writer exists and the device replay cache is forced off
    ln = init(("wal_flush_batches=4",))
    assert ln._wal is not None and ln.param.device_cache_mb == 0
    ln.stop()


def test_trajectory_invariance_wal_on_off(rcv1_path, tmp_path):
    """The WAL observes the dispatch path, it must not perturb it: the
    final model of a WAL-on run is byte-identical to the WAL-off run —
    for the flat AND the fused (V_dim>0) layouts.
    (device_cache_mb=0 on both legs: WAL-on forces it off.)"""
    for tag, extra in (("flat", ()), ("fused", ("V_dim=8",))):
        off = str(tmp_path / f"off_{tag}")
        on = str(tmp_path / f"on_{tag}")
        base = ("device_cache_mb=0",) + extra
        assert main(train_args(rcv1_path, off, extra=base)) == 0
        assert main(train_args(
            rcv1_path, on,
            extra=base + ("ckpt_interval=1", f"wal_flush_batches={FLUSH}",
                          "auto_resume=1"))) == 0
        _assert_same_arrays(_npz_arrays(off + "_part-0"),
                            _npz_arrays(on + "_part-0"))
        # and the WAL-on run actually logged: a live chain exists
        assert wal.chain_generations(wal.wal_dir(on), 0)


# ------------------------------------------------ armed fault points

def test_fault_wal_append_err_retains_window(tmp_path):
    """Armed ``wal.append:err``: the append raises the typed
    FaultInjected, the writer's chain position does NOT advance, and
    the retried append (fault cleared) lands at the same seq — the
    learner-side contract that a failed flush retains its window."""
    d = str(tmp_path / "m.wal")
    w = WalWriter(d, 0, {"capacity": 64})
    faultinject.configure("wal.append:err@1")
    with pytest.raises(faultinject.FaultInjected):
        w.append(np.array([1], np.int32),
                 {"VVg": np.ones((1, 4), np.float32)}, 0, 0, 4)
    assert faultinject.stats() == {"wal.append": 1}
    assert w.seq == 0 and wal.chain_segments(d, 0, 0) == []
    faultinject.configure("")
    w.append(np.array([1], np.int32),
             {"VVg": np.ones((1, 4), np.float32)}, 0, 0, 8)
    assert [s for s, _ in wal.chain_segments(d, 0, 0)] == [0]


def test_fault_wal_append_truncate_is_rejected_at_replay(tmp_path):
    """Armed ``wal.append:truncate``: the torn segment lands at its
    FINAL name (the crash-mid-write shape) and replay's CRCs reject it
    typed — applying nothing from it."""
    st = _mk_store()
    d = str(tmp_path / "m.wal")
    w = WalWriter(d, 0, st.wal_geometry())
    faultinject.configure("wal.append:truncate@1")
    p = w.append(np.array([1, 2], np.int32),
                 st.wal_touched_rows(np.array([1, 2], np.int32)),
                 0, 0, 4)
    faultinject.configure("")
    assert p is not None and os.path.exists(p)
    with pytest.raises(WalCorrupt):
        wal.read_segment(p)
    res = wal.replay(_mk_store(), d, 0, 0, base_epoch=-1)
    assert (res.segments, res.stopped) == (0, "torn")


def test_fault_wal_replay_truncate_stops_at_prefix(tmp_path):
    """Armed ``wal.replay:truncate`` on the SECOND read: replay applies
    segment 0, stops typed at the injected half-length view of segment
    1 — the verified prefix, not a crash."""
    st = _mk_store()
    keys = np.arange(2, 20, dtype=np.uint64)
    d = str(tmp_path / "m.wal")
    w = WalWriter(d, 0, st.wal_geometry())
    for i in range(2):
        _train_store(st, keys, rounds=1, seed=i)
        slots = np.unique(st.lookup(keys))
        slots = slots[(slots >= 0) & (slots < st.state.capacity)]
        w.append(slots, st.wal_touched_rows(slots), 0,
                 i * FLUSH, (i + 1) * FLUSH)
    faultinject.configure("wal.replay:truncate@1:1")
    res = wal.replay(_mk_store(), d, 0, 0, base_epoch=-1)
    assert faultinject.stats() == {"wal.replay": 1}
    assert (res.segments, res.batches, res.stopped) == (1, FLUSH, "torn")


def test_fault_replica_push_err_then_scrub_repairs(tmp_path):
    """Armed ``replica.push:err``: the async push fails counted, the
    peer stays incomplete; the anti-entropy scrub (fault cleared)
    detects and re-pushes — and a ``truncate``-torn .dfwal at the peer
    is caught by the scrub's CRC verification."""
    root = tmp_path / "local"
    peer = tmp_path / "peer"
    root.mkdir(), peer.mkdir()
    model = str(root / "m")
    # a family: one WAL segment + the .meta sidecar
    w = WalWriter(wal.wal_dir(model), 0, {"capacity": 64})
    seg = w.append(np.array([1], np.int32),
                   {"VVg": np.ones((1, 4), np.float32)}, 0, 0, 4)
    with open(model + ".meta", "w") as f:
        f.write(json.dumps({"last_epoch": 0}))

    from difacto_tpu.obs import counter
    fail_c = counter("replica_push_failures_total", "")
    before = fail_c.value()
    r = Replicator([str(peer)], k=1, rank=0, root=str(root))
    try:
        faultinject.configure("replica.push:err@1")
        r.push([seg, model + ".meta"], generation=1, epoch=0)
        assert r.flush(timeout=30)
        assert faultinject.stats()["replica.push"] >= 1
        assert fail_c.value() >= before + 2
        assert not os.path.exists(peer / "m.wal" /
                                  os.path.basename(seg))
        # scrub with the fault cleared repairs both files
        faultinject.configure("")
        assert r.scrub(model) == 2
        assert open(peer / "m.meta").read() == \
            open(model + ".meta").read()
        wal.read_segment(str(peer / "m.wal" / os.path.basename(seg)))
        # a torn peer segment (the truncate kind) is detected + repaired
        faultinject.configure("replica.push:truncate@1")
        replicate.push_file(seg, str(peer), str(root))
        faultinject.configure("")
        with pytest.raises(WalCorrupt):
            wal.read_segment(str(peer / "m.wal" /
                                 os.path.basename(seg)))
        assert r.scrub(model) == 1
        wal.read_segment(str(peer / "m.wal" / os.path.basename(seg)))
        assert r.scrub(model) == 0  # converged: nothing left to repair
    finally:
        r.close()


def test_fault_replica_fetch_err_tries_next_peer(rcv1_path, tmp_path):
    """Armed ``replica.fetch:err``: a fetch failure is typed and
    counted, never a crash; and a peer whose family is incomplete fails
    that peer only — the ladder's fetch moves to the next peer and
    restores the full family from it."""
    model = str(tmp_path / "src" / "m")
    os.makedirs(tmp_path / "src")
    assert main(train_args(rcv1_path, model,
                           extra=("ckpt_interval=1",))) == 0
    # equal generations tie-break by path DESCENDING: z_bad ranks first
    pbad = tmp_path / "z_bad_peer"
    pgood = tmp_path / "a_good_peer"
    pbad.mkdir(), pgood.mkdir()
    fam = replicate.family_files(model)
    assert fam
    for peer in (pbad, pgood):
        for f in fam:
            replicate.push_file(f, str(peer), str(tmp_path / "src"))

    # every fetch fails typed -> None, counted, no exception escapes
    faultinject.configure("replica.fetch:err@1")
    lost = str(tmp_path / "lost" / "m")
    os.makedirs(tmp_path / "lost")
    assert replicate.fetch_family(lost, [str(pbad), str(pgood)]) is None
    assert faultinject.stats()["replica.fetch"] >= 1
    faultinject.configure("")

    # the first-ranked peer's newest checkpoint is unreadable (a
    # directory squats its name): its fetch fails typed mid-family and
    # the next peer serves the full restore
    os.remove(pbad / "m_iter-2_part-0")
    os.mkdir(pbad / "m_iter-2_part-0")
    used = replicate.fetch_family(lost, [str(pbad), str(pgood)])
    assert used == str(pgood)
    for f in fam:
        rel = os.path.relpath(f, str(tmp_path / "src"))
        assert open(os.path.join(tmp_path / "lost", rel), "rb").read() \
            == open(f, "rb").read()


# ------------------------------------------- pruning regression (bugfix)

def test_prune_checkpoints_protect_exempts_epochs(tmp_path):
    model = str(tmp_path / "m")
    for e in range(4):
        for suf in ("", mft.MANIFEST_SUFFIX):
            with open(f"{model}_iter-{e}_part-0{suf}", "w") as f:
                f.write("x")
    removed = mft.prune_checkpoints(model, keep=1, protect={1})
    left = sorted(f for f in os.listdir(tmp_path)
                  if not f.endswith(".json"))
    # epochs 0 and 2 retired; 1 survives protected, 3 by keep=1 — and
    # protected epochs do not consume keep slots
    assert left == ["m_iter-1_part-0", "m_iter-3_part-0"]
    assert sorted(removed) == [f"{model}_iter-0_part-0",
                               f"{model}_iter-2_part-0"]


def test_ckpt_keep_never_retires_live_wal_base(rcv1_path, tmp_path):
    """Regression (ISSUE 20 bugfix): at each interval save the prune
    runs BEFORE the chain rebases onto the new generation, so with
    ``ckpt_keep=1`` the un-protected pruner would retire the epoch the
    live chain is still rooted at — orphaning every delta if the
    process died between prune and rebase. The base epoch must survive
    its own save and be retired only by the NEXT one."""
    model = str(tmp_path / "m")
    ln = SGDLearner()
    ln.init([tuple(kv.split("=", 1)) for kv in train_args(
        rcv1_path, model,
        extra=("ckpt_interval=1", "ckpt_keep=1",
               f"wal_flush_batches={FLUSH}"))])
    try:
        ln._save_checkpoint(0)
        assert ln._wal.base_epoch == 0
        ln._save_checkpoint(1)
        # epoch 0 was the live base when save(1) pruned: still here
        assert os.path.exists(f"{model}_iter-0_part-0")
        assert ln._wal.base_epoch == 1
        ln._save_checkpoint(2)
        # now rooted at 1; epoch 0 released and retired, 1 protected
        assert not os.path.exists(f"{model}_iter-0_part-0")
        assert os.path.exists(f"{model}_iter-1_part-0")
        assert os.path.exists(f"{model}_iter-2_part-0")
    finally:
        ln.stop()


# ------------------------------------------------- replication + ladder

def test_replicator_push_lag_and_protected_epochs(tmp_path):
    root, peer = tmp_path / "r", tmp_path / "p"
    root.mkdir(), peer.mkdir()
    f1 = str(root / "a.bin")
    open(f1, "wb").write(os.urandom(1 << 12))
    from difacto_tpu.obs import gauge
    lag = gauge("replica_lag_generations", "")
    r = Replicator([str(peer)], k=1, rank=0, root=str(root))
    try:
        r.push([f1], generation=3, epoch=7)
        assert r.flush(timeout=30)
        assert r.protected_epochs() == set()  # drained -> released
        assert open(peer / "a.bin", "rb").read() == \
            open(f1, "rb").read()
        assert lag.value(peer="p") == 0  # caught up after the drain
    finally:
        r.close()


def test_recovery_ladder_wal_rung_mid_window(rcv1_path, tmp_path):
    """The bench's crash shape, in-process: full WAL-on run, then the
    last epoch's checkpoint + final model vanish and the newest delta
    segment is dropped (died mid-window). A fresh learner climbs
    local -> wal, lands on the surviving verified prefix and stamps the
    recovery record."""
    import glob as _glob
    model = str(tmp_path / "m")
    args = train_args(rcv1_path, model,
                      extra=("ckpt_interval=1", "auto_resume=1",
                             f"wal_flush_batches={FLUSH}"))
    assert main(args) == 0
    for f in (_glob.glob(model + "_iter-2_*")
              + _glob.glob(model + "_part-*")):
        os.remove(f)
    d = wal.wal_dir(model)
    gen = wal.chain_generations(d, 0)[0]
    chain = wal.chain_segments(d, 0, gen)
    assert len(chain) >= 2
    os.remove(chain[-1][1])  # the mid-window segment that never sealed

    ln = SGDLearner()
    ln.init([tuple(kv.split("=", 1)) for kv in args])
    try:
        resumed = ln._try_resume()
        stamp = json.load(open(model + ".recovery.json"))
        assert stamp["rungs"] == ["local", "wal"]
        assert stamp["wal_replay_batches"] > 0
        assert resumed == stamp["resumed_epoch"]
        # mid-epoch head: the re-entered epoch fast-forwards the
        # batches replay already applied
        assert ln._wal_skip == stamp["skip_batches"] > 0
        assert stamp["skip_batches"] <= 2 * FLUSH
    finally:
        ln.stop()


def test_recovery_ladder_peer_rung_disk_loss(rcv1_path, tmp_path):
    """Disk loss, in-process: the whole local family (checkpoints, WAL
    chain, meta) is deleted; a fresh learner with ``replica_peers``
    restores from the peer and resumes — rung 'peer'."""
    import glob as _glob
    peer = tmp_path / "peer"
    peer.mkdir()
    model = str(tmp_path / "m")
    args = train_args(rcv1_path, model,
                      extra=("ckpt_interval=1", "auto_resume=1",
                             f"wal_flush_batches={FLUSH}",
                             f"replica_peers={peer}"))
    assert main(args) == 0
    ref = _npz_arrays(model + "_iter-2_part-0")
    import shutil
    shutil.rmtree(wal.wal_dir(model))
    for f in _glob.glob(model + "_iter-*") + _glob.glob(model + "_part-*") \
            + _glob.glob(model + ".meta") + _glob.glob(model + ".recovery*"):
        os.remove(f)

    ln = SGDLearner()
    ln.init([tuple(kv.split("=", 1)) for kv in args])
    try:
        resumed = ln._try_resume()
        stamp = json.load(open(model + ".recovery.json"))
        assert "peer" in stamp["rungs"]
        assert resumed == 2  # the peer held every interval generation
        _assert_same_arrays(ref, _npz_arrays(model + "_iter-2_part-0"))
    finally:
        ln.stop()


# --------------------------------------- the SIGKILL + disk-loss leg

def test_sigkill_mid_window_disk_loss_recovers_bounded(rcv1_path,
                                                       tmp_path):
    """Acceptance leg: SIGKILL mid-delta-window (armed ``wal.append:
    kill`` — the 5th append is epoch 1's second window at step 8), then
    FULL local disk loss (every model file and the WAL chain deleted).
    The relaunch restores the family from the peer replica, replays the
    delta chain on top of the fetched base, fast-forwards the replayed
    prefix and finishes — with at most one flush window of work re-lost
    and a final model byte-identical to the unkilled reference run."""
    peer = tmp_path / "peer"
    peer.mkdir()
    model = str(tmp_path / "m")
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    args = [sys.executable, "-m", "difacto_tpu"] + train_args(
        rcv1_path, model,
        extra=("ckpt_interval=1", "auto_resume=1",
               f"wal_flush_batches={FLUSH}", f"replica_peers={peer}"))
    with deadline(240):
        # appends 1-3 are epoch 0 (incl. boundary); 4 is epoch 1 step
        # 4; the 5th (epoch 1, step 8) dies before any bytes land
        env["DIFACTO_FAULTS"] = "wal.append:kill@1:4"
        p1 = subprocess.run(args, cwd=str(REPO), env=env,
                            capture_output=True, text=True, timeout=200)
        assert p1.returncode == -signal.SIGKILL, p1.stderr[-2000:]

        # total disk loss: the model family AND its delta log are gone
        import glob as _glob
        import shutil
        shutil.rmtree(wal.wal_dir(model))
        for f in _glob.glob(model + "*"):
            os.remove(f)

        env.pop("DIFACTO_FAULTS")
        p2 = subprocess.run(args, cwd=str(REPO), env=env,
                            capture_output=True, text=True, timeout=200)
        assert p2.returncode == 0, p2.stderr[-2000:]

    stamp = json.load(open(model + ".recovery.json"))
    assert "peer" in stamp["rungs"] and "wal" in stamp["rungs"]
    # bounded RPO: the kill hit step 8 of epoch 1 with the step-4
    # window sealed + replicated — exactly one flush window re-lost
    assert stamp["head"] == {"epoch": 1, "step": FLUSH,
                             "boundary": False}
    assert 0 < stamp["skip_batches"] <= FLUSH

    # byte-identical continuation: the recovered run's final model ==
    # an unkilled run of the identical config
    ref_peer = tmp_path / "ref_peer"
    ref_peer.mkdir()
    ref = str(tmp_path / "ref")
    assert main(train_args(
        rcv1_path, ref,
        extra=("ckpt_interval=1", "auto_resume=1",
               f"wal_flush_batches={FLUSH}",
               f"replica_peers={ref_peer}"))) == 0
    _assert_same_arrays(_npz_arrays(ref + "_part-0"),
                        _npz_arrays(model + "_part-0"))


# ----------------------------------------------------- obs digest

def test_obs_report_durability_digest(capsys):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    snap = {"counters": {
        "wal_bytes_total": {"": 4096.0},
        "wal_replay_batches": {"": 12.0},
        "wal_replay_dropped_total": {"reason=torn": 1.0},
        "recovery_rung_total": {"rung=local": 1.0, "rung=wal": 1.0},
    }, "gauges": {"replica_lag_generations": {"peer=p1": 2.0}}}
    obs_report.report_durability(snap)
    out = capsys.readouterr().out
    assert "durability (WAL + replicas + recovery ladder)" in out
    for needle in ("wal_bytes_total", "wal_replay_dropped_total{reason=torn}",
                   "recovery_rung_total{rung=wal}",
                   "replica_lag_generations{peer=p1}"):
        assert needle in out, needle
