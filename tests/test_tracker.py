"""Control-plane tests: WorkloadPool (assignment, dead-node reset, straggler
re-issue — src/reader/workload_pool.h), Reporter, and the prefetcher."""

import time

import numpy as np
import pytest

from difacto_tpu.data.prefetch import prefetch
from difacto_tpu.tracker import WorkloadPool, WorkloadPoolParam
from difacto_tpu.utils.reporter import Reporter


def test_pool_assign_finish():
    pool = WorkloadPool()
    pool.add(3)
    assert pool.num_remains() == 3
    parts = [pool.get(node=1), pool.get(node=1), pool.get(node=2)]
    assert sorted(parts) == [0, 1, 2]
    assert pool.get(node=3) == -2  # exhausted
    pool.finish(1)  # both of node 1's parts
    assert pool.num_remains() == 1
    assert pool.num_finished == 2
    pool.finish(2)
    assert pool.num_remains() == 0


def test_pool_dead_node_reassign():
    """Reset re-queues a dead node's in-flight parts (Set(del=false))."""
    pool = WorkloadPool()
    pool.add(2)
    p = pool.get(node=7)
    pool.reset(node=7)
    assert pool.num_remains() == 2
    # the part is available again, another node can take it
    got = {pool.get(node=8), pool.get(node=8)}
    assert p in got


def test_pool_straggler_reissue():
    pool = WorkloadPool(WorkloadPoolParam(straggler_timeout=0.01))
    pool.add(12)
    # 10 fast completions establish the mean
    for i in range(10):
        pool.get(node=1)
        pool.finish(1)
    slow = pool.get(node=2)
    # pretend the slow part has been running far past the threshold
    requeued = pool.remove_stragglers(now=time.monotonic() + 3600)
    assert requeued == [slow]
    assert pool.get(node=3) == slow  # re-issued to another node


def test_pool_straggler_needs_history():
    pool = WorkloadPool(WorkloadPoolParam(straggler_timeout=0.01))
    pool.add(2)
    pool.get(node=1)
    assert pool.remove_stragglers(now=time.monotonic() + 3600) == []


def test_reporter_throttle():
    rep = Reporter(every=50)
    got = []
    rep.set_monitor(lambda node, p: got.append(p))
    for i in range(120):
        rep.report(i)
    assert got == [49, 99]  # every 50th report


def test_prefetch_order_and_errors():
    assert list(prefetch(iter(range(100)), depth=2)) == list(range(100))

    def bad():
        yield 1
        raise ValueError("boom")

    with pytest.raises(ValueError):
        list(prefetch(bad()))


def test_prefetch_overlaps(rcv1_path):
    """The prefetched SGD epoch produces the identical trajectory."""
    from difacto_tpu.learners import Learner
    args = [("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"), ("l1", "1"),
            ("lr", "1"), ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "3"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0")]
    learner = Learner.create("sgd")
    learner.init(list(args))
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    np.testing.assert_allclose(
        seen, [69.314718, 69.314718, 67.151912], atol=5e-5)
