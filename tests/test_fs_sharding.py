"""Mesh-sharded parameter table (ISSUE 12): key-range fs-sharding of the
slot store for train AND serve, on the 8-device virtual CPU mesh.

Covers the tentpole's acceptance legs:

- fs=1 degenerate-mesh trajectories are BYTE-identical to the unsharded
  path (the sharded program lowering must be free at fs=1);
- an fs>1 table trains end-to-end and round-trips through per-key-range
  shard checkpoints (one npz + manifest per shard, array-free stub as
  the generation commit marker), including the corrupt-one-shard
  walk-back;
- task=serve loads and queries an fs-sharded store with scores
  byte-identical to the single-device path, whatever layout the
  checkpoint was saved in;
- make_mesh's multi-host host-complete fs constraint fails typed;
- the capacity-scaling report (bench --multichip /
  __graft_entry__.dryrun_multichip) emits per-fs legs with constant
  per-device bytes.
"""

import os

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from difacto_tpu.parallel import (fs_shard_bounds, make_mesh,
                                  validate_fs_capacity)
from difacto_tpu.store.local import (CheckpointCorrupt, SlotStore,
                                     fs_shard_path)
from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam, col_V,
                                              scal_cols, state_bytes)


def _run(rcv1_path, **over):
    args = [("data_in", rcv1_path), ("V_dim", "2"), ("V_threshold", "2"),
            ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
            ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "3"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", "4096")]
    args += [(k, str(v)) for k, v in over.items()]
    learner = Learner.create("sgd")
    assert learner.init(args) == []
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    return learner, seen


def _state_cols(store):
    w, z, sg, cnt, live = (np.asarray(c) for c in
                           scal_cols(store.param, store.state))
    return w, z, sg, cnt, live, np.asarray(col_V(store.param, store.state))


# --------------------------------------------------------------- parity

def test_fs1_degenerate_mesh_trajectory_byte_equality(rcv1_path):
    """The sharded program path at fs=1 (mesh_force) must be bit-for-bit
    the unsharded path: same per-epoch losses, same final table bytes."""
    ln0, seen0 = _run(rcv1_path)
    ln1, seen1 = _run(rcv1_path, mesh_force=1)
    assert ln0.mesh is None and ln1.mesh is not None
    assert seen0 == seen1          # float equality, not allclose
    for a, b in zip(_state_cols(ln0.store), _state_cols(ln1.store)):
        np.testing.assert_array_equal(a, b)


def test_fs_sharded_training_matches_unsharded(rcv1_path):
    """fs=4 hashed training reproduces the unsharded trajectory (the
    cross-shard gather/scatter collectives are numerically
    transparent), and the table stays in its key-range layout."""
    from jax.sharding import PartitionSpec as P
    ln0, seen0 = _run(rcv1_path)
    ln4, seen4 = _run(rcv1_path, mesh_fs=4)
    np.testing.assert_allclose(seen4, seen0, rtol=1e-5)
    assert ln4.store.fs_count == 4
    assert ln4.store.state.VVg.sharding.spec[0] == "fs" \
        or ln4.store.state.VVg.sharding.spec == P("fs", None)


# ------------------------------------------------------------ make_mesh

def test_make_mesh_multihost_fs_constraint_errors(monkeypatch):
    """The fs axis must stay intra-host (host-complete table) and a
    multi-host mesh must use every device — both fail typed."""
    import jax
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "local_device_count", lambda: 4)
    # ok: fs divides the local device count, every device used
    mesh = make_mesh(dp=2, fs=4)
    assert mesh.shape == {"dp": 2, "fs": 4}
    with pytest.raises(ValueError, match="host-complete"):
        make_mesh(dp=1, fs=8)       # fs spans two hosts
    with pytest.raises(ValueError, match="use every device"):
        make_mesh(dp=2, fs=2)       # 4 of 8 global devices
    with pytest.raises(ValueError, match="power of two"):
        make_mesh(dp=1, fs=3)


def test_hash_capacity_must_divide_fs():
    param = SGDUpdaterParam(V_dim=2, hash_capacity=1002)
    with pytest.raises(ValueError, match="divisible"):
        SlotStore(param, mesh=make_mesh(dp=1, fs=4))
    validate_fs_capacity(1024, 4)   # no raise
    assert fs_shard_bounds(1024, 4) == [(0, 256), (256, 512),
                                        (512, 768), (768, 1024)]


# ----------------------------------------------------- shard checkpoints

def _filled_store(mesh, cap=2048, V_dim=2):
    param = SGDUpdaterParam(V_dim=V_dim, hash_capacity=cap, l1=0.0,
                            V_threshold=0)
    s = SlotStore(param, mesh=mesh)
    rng = np.random.RandomState(7)
    keys = rng.randint(1, 1 << 62, 300).astype(np.uint64)
    s.push(keys, 1, np.ones(len(keys), np.float32))  # counts
    s.push(keys, 3, rng.randn(len(keys)).astype(np.float32),
           rng.randn(len(keys), V_dim).astype(np.float32),
           np.ones(len(keys), np.float32))
    return s


def test_sharded_checkpoint_roundtrip(tmp_path):
    """fs=4 save writes one member per key range + an array-free stub,
    every manifest verifies, and the table round-trips byte-identically
    into sharded AND unsharded stores."""
    from difacto_tpu.utils import manifest as mft
    mesh = make_mesh(dp=1, fs=4)
    s = _filled_store(mesh)
    path = str(tmp_path / "model")
    n = s.save(path, save_aux=True)
    assert n > 0
    for i in range(4):
        sp = fs_shard_path(path, i, 4)
        assert os.path.exists(sp)
        man = mft.verify(sp, require_manifest=True)
        assert man["fs_shard"] == i and man["fs_count"] == 4
    stub_man = mft.verify(path, require_manifest=True)
    assert stub_man["fs_count"] == 4 and stub_man["rows"] == n
    # shard members are not walk-back entry points; the stub is
    assert mft.generation_paths(path) == [path]

    s_sharded = SlotStore(s.param, mesh=mesh)
    assert s_sharded.load(path) == n
    s_flat = SlotStore(s.param)
    assert s_flat.load(path) == n
    for a, b, c in zip(_state_cols(s), _state_cols(s_sharded),
                       _state_cols(s_flat)):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    # the sharded load landed fs-sharded
    assert s_sharded.state.VVg.sharding.spec[0] == "fs"


def test_sharded_checkpoint_aux_roundtrip_resumes(tmp_path):
    """save_aux=True round-trips the optimizer state (z/sqrt_g/Vg) so a
    sharded interval checkpoint can resume the exact trajectory."""
    mesh = make_mesh(dp=1, fs=2)
    s = _filled_store(mesh)
    path = str(tmp_path / "aux")
    s.save(path, save_aux=True)
    s2 = SlotStore(s.param, mesh=mesh)
    s2.load(path, weights_only=False)
    _, z1, sg1, _, _, _ = _state_cols(s)
    _, z2, sg2, _, _, _ = _state_cols(s2)
    assert z1.any() and sg1.any()
    np.testing.assert_array_equal(z1, z2)
    np.testing.assert_array_equal(sg1, sg2)


def test_corrupt_one_shard_fails_typed_and_walks_back(tmp_path):
    """A bit flip inside ONE shard member: store.load raises the typed
    CheckpointCorrupt BEFORE any state commits, and the serve open path
    walks the family back to the previous verified generation."""
    from difacto_tpu.serve.model import open_serving_store
    mesh = make_mesh(dp=1, fs=4)
    s = _filled_store(mesh)
    path = str(tmp_path / "model")
    s.save(path)                                   # generation 1 (good)
    s.push(np.array([123456789], np.uint64), 3,
           np.ones(1, np.float32), np.ones((1, 2), np.float32),
           np.ones(1, np.float32))
    s.save(path + "_iter-1")                       # generation 2
    sp = fs_shard_path(path + "_iter-1", 2, 4)
    with open(sp, "r+b") as f:
        data = f.read()
        f.seek(data.find(b"w.npy") + 200)
        f.write(b"\xff\xff\xff")
    fresh = SlotStore(s.param, mesh=mesh)
    with pytest.raises(CheckpointCorrupt):
        fresh.load(path + "_iter-1")
    # serve startup walks back to generation 1 instead of dying
    store, meta, _ = open_serving_store(path + "_iter-1",
                                        [("serve_mesh_fs", "2")])
    assert meta["path"] == path
    assert store.fs_count == 2


def test_two_host_sim_sharded_saves_roundtrip(tmp_path):
    """Multi-host × fs>1 per-shard saves (PR 12 leftover, ISSUE 13
    satellite): every rank writes its OWN ``<model>_part-<rank>``
    sharded family (the table is host-complete — fs stays intra-host,
    dp replicates it across hosts, parallel/mesh.py), so ANY rank's
    family restores the full table into any mesh. Simulated with two
    stores holding the identical dp-replicated state:

    - both ranks' families verify independently (members + stub);
    - rank 1's family loads byte-identically to rank 0's, into fs=2,
      fs=4 AND fs=1 (unsharded) stores;
    - a corrupt shard member in rank 0's family fails typed, and the
      resume walk order (learners/sgd._try_resume: own rank first,
      then every rank) lands on rank 1's intact family."""
    import jax

    from difacto_tpu.utils import manifest as mft
    mesh = make_mesh(dp=1, fs=2)
    s0 = _filled_store(mesh)
    s1 = SlotStore(s0.param, mesh=mesh)
    # rank 1 holds the same dp-replicated state
    s1.state = jax.tree_util.tree_map(lambda x: x, s0.state)
    base = str(tmp_path / "model_iter-0_part-")
    n0 = s0.save(base + "0", save_aux=True, epoch=0)
    n1 = s1.save(base + "1", save_aux=True, epoch=0)
    assert n0 == n1 > 0
    for rank in (0, 1):
        for i in range(2):
            man = mft.verify(fs_shard_path(base + str(rank), i, 2),
                             require_manifest=True)
            assert man["fs_count"] == 2 and man["fs_shard"] == i
        assert mft.verify(base + str(rank),
                          require_manifest=True)["fs_count"] == 2

    loads = []
    for fs, m in ((2, mesh), (4, make_mesh(dp=1, fs=4)), (1, None)):
        fresh = SlotStore(s0.param, mesh=m)
        assert fresh.load(base + "1", weights_only=False) == n0
        loads.append(fresh)
    for a, b, c in zip(_state_cols(s0), _state_cols(loads[0]),
                       _state_cols(loads[2])):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)

    # torn rank-0 family: bit-flip one shard member, walk to rank 1
    sp = fs_shard_path(base + "0", 1, 2)
    with open(sp, "r+b") as f:
        data = f.read()
        f.seek(data.find(b"w.npy") + 150)
        f.write(b"\xff\xff\xff")
    resumed = None
    fresh = SlotStore(s0.param, mesh=mesh)
    for rank in (0, 1):        # the _try_resume walk order
        try:
            fresh.load(base + str(rank), require_manifest=True)
            resumed = rank
            break
        except (FileNotFoundError, OSError):
            continue
        except CheckpointCorrupt:
            continue
    assert resumed == 1
    for a, b in zip(_state_cols(s0), _state_cols(fresh)):
        np.testing.assert_array_equal(a, b)


def test_missing_shard_member_is_corrupt(tmp_path):
    mesh = make_mesh(dp=1, fs=2)
    s = _filled_store(mesh)
    path = str(tmp_path / "model")
    s.save(path)
    os.remove(fs_shard_path(path, 1, 2))
    os.remove(fs_shard_path(path, 1, 2) + ".manifest.json")
    with pytest.raises(CheckpointCorrupt, match="missing"):
        SlotStore(s.param, mesh=mesh).load(path)


# ---------------------------------------------------------------- serve

def test_serve_fs_sharded_scores_byte_identical(rcv1_path, tmp_path):
    """Train (fs-sharded), save (per-shard), then serve the model at
    serve_mesh_fs in {1, 2, 4}: scores are byte-identical across serve
    layouts — the end-to-end 'trains AND serves' acceptance leg."""
    from difacto_tpu.data.reader import Reader
    from difacto_tpu.serve.executor import PredictExecutor
    from difacto_tpu.serve.model import open_serving_store
    model = str(tmp_path / "model")
    ln, _ = _run(rcv1_path, mesh_fs=2, model_out=model)
    assert os.path.exists(model + "_part-0_fs-0-of-2")

    blk = next(iter(Reader(rcv1_path, "libsvm", 0, 1)))
    scores = {}
    for fs in (1, 2, 4):
        store, meta, _ = open_serving_store(
            model, [("serve_mesh_fs", str(fs))])
        assert store.fs_count == fs and store.read_only
        ex = PredictExecutor(store)
        scores[fs] = ex.predict(blk)[0]
        assert ex.stats()["dispatches"] == 1
    assert scores[1].any()
    np.testing.assert_array_equal(scores[1], scores[2])
    np.testing.assert_array_equal(scores[1], scores[4])


def test_hot_reload_geometry_check_covers_fs(tmp_path):
    """An in-place store swap must keep the fs degree (the compiled
    programs bake the layout); run_serve threads serve_mesh_fs through
    the reloader kwargs so reloads keep the mesh."""
    from difacto_tpu.serve.executor import PredictExecutor
    from difacto_tpu.serve.model import open_serving_store
    from difacto_tpu.serve.reload import ModelReloader
    mesh = make_mesh(dp=1, fs=2)
    s = _filled_store(mesh)
    path = str(tmp_path / "model")
    s.save(path)
    store, _, _ = open_serving_store(path, [("serve_mesh_fs", "2")])
    ex = PredictExecutor(store)
    flat, _, _ = open_serving_store(path, [])
    with pytest.raises(ValueError, match="fs=2"):
        ex.swap_store(flat)
    # a reload with the same kwargs rebuilds the same mesh and succeeds
    rl = ModelReloader(ex, path, kwargs=[("serve_mesh_fs", "2")])
    s.save(path)    # bump generation
    res = rl.reload()
    assert res["ok"], res
    assert ex.store.fs_count == 2


def test_run_serve_threads_mesh_into_reloader(rcv1_path, tmp_path):
    """Wire-level leg: task=serve with serve_mesh_fs=2 scores over TCP
    from per-shard checkpoint files, and a `#reload` rebuilds the SAME
    fs-sharded mesh (run_serve passes the store kwargs to the
    ModelReloader — a reload that silently de-sharded the table was the
    exact regression this test pins)."""
    import threading
    import time
    from difacto_tpu.serve import ServeClient, run_serve
    model = str(tmp_path / "model")
    ln, _ = _run(rcv1_path, mesh_fs=2, model_out=model)
    ready = str(tmp_path / "ready")
    t = threading.Thread(target=run_serve, args=([
        ("model_in", model), ("serve_mesh_fs", "2"),
        ("serve_ready_file", ready), ("serve_max_seconds", "8"),
        ("serve_batch_size", "100"), ("serve_max_delay_ms", "50")],),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 15
    while not os.path.exists(ready):
        assert time.monotonic() < deadline, "server never became ready"
        time.sleep(0.05)
    host, port = open(ready).read().split()
    lines = [ln_.encode() for ln_
             in open(rcv1_path).read().splitlines()[:20]]
    with ServeClient(host, int(port)) as c:
        out = c.score_lines(lines)
        res = c.reload()
    assert len(out) == 20 and not any(o.startswith(b"!") for o in out)
    assert res["ok"], res
    t.join(timeout=30)


# ------------------------------------------------- stats + capacity legs

def test_shard_stats_and_gauges():
    mesh = make_mesh(dp=1, fs=4)
    s = _filled_store(mesh)
    stats = s.shard_stats()
    assert [st["shard"] for st in stats] == [0, 1, 2, 3]
    w = _state_cols(s)[0]
    assert sum(st["rows"] for st in stats) == int((w != 0).sum()) > 0
    per_dev = state_bytes(s.param, s.state.capacity) // 4
    assert all(st["table_bytes"] == per_dev for st in stats)
    published = s.publish_shard_stats()
    assert published == stats
    from difacto_tpu.obs import REGISTRY
    snap = REGISTRY.snapshot()["gauges"].get("store_shard_rows", {})
    assert snap, "store_shard_rows gauge not published"
    assert sum(snap.values()) == sum(st["rows"] for st in stats)


def test_capacity_scaling_report_legs():
    from difacto_tpu.parallel.capacity import capacity_scaling_report
    rep = capacity_scaling_report(fs_values=[1, 2], base_capacity=512,
                                  V_dim=2, batch=64, nnz_per_row=4,
                                  steps=2)
    assert [leg["fs"] for leg in rep["legs"]] == [1, 2]
    l1, l2 = rep["legs"]
    assert l2["hash_capacity"] == 2 * l1["hash_capacity"]
    assert l2["table_bytes_per_device"] == l1["table_bytes_per_device"]
    assert rep["capacity_scaling"] == 2.0
    assert rep["scaling_efficiency"] == 1.0
    assert all(leg["examples_per_sec"] > 0 for leg in rep["legs"])
