"""Device-resident batch replay cache (learners/sgd.py _DeviceBatchCache).

Round-4 addition: on tunneled/remote chips the host->device link runs at
~5-10 MB/s, so steady-state epochs were transfer-bound. The cache stages
each packed batch once and replays it from device memory. These tests pin
its contract: exact replay equivalence with shuffle off, correct gating
(neg_sampling, dictionary store), budget fallback, and permutation-only
shuffle on replay.
"""

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from difacto_tpu.learners.sgd import (K_TRAINING, K_VALIDATION,
                                      _DeviceBatchCache)


def run_hashed(rcv1_path, epochs=6, setup=None, **over):
    """``setup(learner)`` runs between init and run — e.g. to pre-seed a
    byte-budget cache."""
    args = [("data_in", rcv1_path), ("data_format", "libsvm"),
            ("loss", "fm"), ("V_dim", "2"), ("V_threshold", "0"),
            ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
            ("batch_size", "25"), ("shuffle", "0"),
            ("max_num_epochs", str(epochs)), ("num_jobs_per_epoch", "1"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", str(1 << 14))]
    args += [(k, str(v)) for k, v in over.items()]
    learner = Learner.create("sgd")
    remain = learner.init(args)
    assert remain == []
    if setup is not None:
        setup(learner)
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    return np.array(seen), learner


def test_replay_identical_no_shuffle(rcv1_path):
    """Replayed epochs reproduce the streamed trajectory exactly (shuffle
    off => identical batches in identical order), and the cache actually
    engaged (ready after epoch 0, entries staged)."""
    ref, _ = run_hashed(rcv1_path, device_cache_mb=0)
    got, learner = run_hashed(rcv1_path, device_cache_mb=256)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    cache = learner._dev_caches[K_TRAINING]
    assert cache.ready and cache.alive
    assert sum(len(v) for v in cache.entries.values()) == 4  # 100 rows / 25


def test_replay_counts_pushed_once(rcv1_path):
    """The epoch-0 feature-count push must not repeat on replay: final
    cnt equals one epoch's occurrence counts either way."""
    _, base = run_hashed(rcv1_path, device_cache_mb=0, epochs=3)
    _, cached = run_hashed(rcv1_path, device_cache_mb=256, epochs=3)
    from difacto_tpu.updaters.sgd_updater import scal_cols
    np.testing.assert_allclose(
        np.asarray(scal_cols(cached.store.param, cached.store.state)[3]),
        np.asarray(scal_cols(base.store.param, base.store.state)[3]))


def test_validation_replay(rcv1_path):
    """data_val epochs ride the cache too and stay correct (loss is a pure
    function of the model, so cached vs streamed val loss is identical)."""
    ref, _ = run_hashed(rcv1_path, device_cache_mb=0, data_val=rcv1_path)
    got, learner = run_hashed(rcv1_path, device_cache_mb=256,
                              data_val=rcv1_path)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    assert learner._dev_caches[K_VALIDATION].ready


def test_neg_sampling_disables_cache(rcv1_path):
    """neg_sampling < 1 must resample every epoch — no train cache."""
    _, learner = run_hashed(rcv1_path, neg_sampling=0.9, epochs=2)
    assert learner._get_cache(K_TRAINING) is None


def run_dict(rcv1_path, epochs=6, extra_callback=None, **over):
    """Dictionary-store (no hash_capacity) run."""
    args = [("data_in", rcv1_path), ("data_format", "libsvm"),
            ("loss", "logit"), ("lr", "1"), ("l1", "1"), ("l2", "1"),
            ("batch_size", "25"), ("shuffle", "0"),
            ("max_num_epochs", str(epochs)), ("num_jobs_per_epoch", "1"),
            ("report_interval", "0"), ("stop_rel_objv", "0")]
    args += [(k, str(v)) for k, v in over.items()]
    learner = Learner.create("sgd")
    learner.init(args)
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    if extra_callback is not None:
        learner.add_epoch_end_callback(
            lambda e, t, v: extra_callback(learner, e))
    learner.run()
    return np.array(seen), learner


def test_dictionary_store_caches_first_pass_with_repad(rcv1_path):
    """The single-host dictionary store stages on its FIRST pass even
    though the table grows mid-epoch (slot assignment is
    insertion-stable; the replay entry repads the staged OOB slot tails
    to the final capacity — round-5, replacing the second-pass staging
    that paid a whole extra streamed epoch). Replayed epochs 1+
    reproduce the streamed trajectory exactly."""
    ref, _ = run_dict(rcv1_path, device_cache_mb=0, init_capacity=64)
    got, learner = run_dict(rcv1_path, device_cache_mb=256,
                            init_capacity=64)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    cache = learner._dev_caches[K_TRAINING]
    assert cache.ready and cache.stage_after_pass == 0 and cache.repadable
    # init_capacity=64 forces growth DURING the staging pass, so the
    # repad path really ran (stale flag set then cleared at replay)
    assert cache.capacity == learner.store.state.capacity
    assert not cache.stale_pads
    assert sum(len(v) for v in cache.entries.values()) == 4  # 100/25


def test_dictionary_cache_repads_on_capacity_growth(rcv1_path):
    """A capacity change after staging (impossible for fixed data, but
    the guard covers it) repads the staged OOB slot tails instead of
    throwing the cache away — stale pads would fall back in bounds and
    alias real rows; the trajectory must be unchanged either way."""
    ref, _ = run_dict(rcv1_path, device_cache_mb=0, epochs=5)

    def grow_after_epoch(learner, e):
        if e == 3:
            # simulate post-staging growth
            from difacto_tpu.updaters.sgd_updater import grow_state
            learner.store.state = grow_state(
                learner.store.param, learner.store.state,
                learner.store.state.capacity * 2)

    seen, learner = run_dict(rcv1_path, device_cache_mb=256, epochs=5,
                             extra_callback=grow_after_epoch)
    cache = learner._dev_caches[K_TRAINING]
    assert cache.alive and cache.ready  # repadded, NOT invalidated
    assert cache.capacity == learner.store.state.capacity
    np.testing.assert_allclose(seen, ref, rtol=1e-6, atol=1e-6)


def test_shuffle_replay_permutes_batches(rcv1_path):
    """With shuffle on, replayed epochs permute the cached batches — the
    trajectory differs from the unshuffled one but uses the same rows, so
    both converge on the same data (epoch-0 loss identical: the first
    epoch streams through the same shuffle-buffer reader either way)."""
    ref, _ = run_hashed(rcv1_path, device_cache_mb=0, shuffle=10)
    got, learner = run_hashed(rcv1_path, device_cache_mb=256, shuffle=10)
    assert learner._dev_caches[K_TRAINING].ready
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)


def test_cache_budget_overflow_keeps_prefix():
    """Budget overflow keeps the fully-staged part prefix and freezes
    staging (round-4 verdict weak #3: all-or-nothing made a dataset 1.1x
    the budget train ~6x slower than one 0.9x it); the half-staged part
    is dropped and its bytes refunded."""
    c = _DeviceBatchCache(1)  # 1 MB
    c.add(0, "a", 300 << 10)
    c.add(0, "b", 300 << 10)
    c.add(1, "c", 300 << 10)
    assert c.alive and not c.frozen
    c.add(1, "d", 300 << 10)  # would exceed 1 MB: freeze, drop part 1
    assert c.frozen and c.partial
    assert c.parts() == {0} and len(c.entries[0]) == 2
    assert c.used == 600 << 10 and c.shared["used"] == 600 << 10
    c.add(2, "e", 8)          # frozen: no further staging
    assert c.parts() == {0}
    c.finish_pass()
    assert c.ready and c.alive  # the prefix replays; the rest streams
    assert list(c.iter_parts(False, seed=0)) == [(0, "a"), (0, "b")]


def test_cache_budget_overflow_nothing_fits():
    """When not even the first part fits, the cache dies outright and
    every epoch streams."""
    c = _DeviceBatchCache(1)
    c.add(0, "a", 2 << 20)
    assert c.frozen and not c.partial and not c.entries
    c.finish_pass()
    assert not c.ready and not c.alive


def test_partial_cache_mixed_regime_trajectory(rcv1_path):
    """A dataset ~2x the budget: the staged prefix replays, the rest
    streams, and the trajectory equals pure streaming exactly (shuffle
    off). Budget is tuned from a full-cache probe run so the test tracks
    payload-size changes."""
    probe, learner = run_hashed(rcv1_path, device_cache_mb=256, epochs=2,
                                num_jobs_per_epoch=4)
    full = learner._dev_caches[K_TRAINING]
    assert full.ready and not full.frozen and len(full.parts()) == 4
    total = sum(full.part_bytes.values())

    ref, _ = run_hashed(rcv1_path, device_cache_mb=0,
                        num_jobs_per_epoch=4)

    # budget that fits ~half the parts: pre-seed the cache with a byte
    # budget (the MB-granular param can't express sub-MB datasets)
    def seed_cache(learner):
        pool = {"used": 0}
        cache = _DeviceBatchCache(0, shared=pool)
        cache.budget = int(total * 0.55)
        learner._dev_caches = {K_TRAINING: cache}
        learner._dev_cache_pool = pool

    seen, learner2 = run_hashed(rcv1_path, device_cache_mb=256,
                                num_jobs_per_epoch=4, setup=seed_cache)
    cache = learner2._dev_caches[K_TRAINING]
    assert cache.ready and cache.partial
    assert 1 <= len(cache.parts()) <= 3
    # the cached set is a part prefix
    assert cache.parts() == set(range(len(cache.parts())))
    np.testing.assert_allclose(seen, ref, rtol=1e-6, atol=1e-6)


def test_cache_iter_parts_order_and_permutation():
    c = _DeviceBatchCache(64)
    for part in (1, 0):
        for i in range(6):
            c.add(part, (part, i), 8)
    c.finish_pass()
    plain = list(c.iter_parts(False, seed=0))
    assert plain == [(p, (p, i)) for p in (0, 1) for i in range(6)]
    shuf = list(c.iter_parts(True, seed=3))
    assert shuf != plain
    # parts stay in order; within-part items are a permutation
    assert [p for p, _ in shuf] == [p for p, _ in plain]
    assert sorted(shuf) == sorted(plain)
    assert list(c.iter_parts(True, seed=3)) == shuf  # deterministic


def test_panel_replay_chunked_backward(tmp_path):
    """Criteo-format (uniform-width panel) cached replay: epochs 1+ take
    the chunked-run backward (panel_chunk_tokens staged at cache time) and
    reproduce the streamed trajectory; only summation order differs."""
    rng = np.random.RandomState(5)
    path = tmp_path / "criteo.txt"
    with open(path, "w") as f:
        for _ in range(200):
            ints = [str(rng.randint(0, 50)) for _ in range(13)]
            cats = [f"c{rng.randint(0, 400)}" for _ in range(26)]
            f.write("\t".join([str(rng.randint(0, 2))] + ints + cats) + "\n")

    def run(cache_mb):
        args = [("data_in", str(path)), ("data_format", "criteo"),
                ("loss", "fm"), ("V_dim", "4"), ("V_threshold", "0"),
                ("lr", "0.1"), ("l1", "0.01"), ("l2", "0"),
                ("batch_size", "50"), ("shuffle", "0"),
                ("max_num_epochs", "5"), ("num_jobs_per_epoch", "1"),
                ("report_interval", "0"), ("stop_rel_objv", "0"),
                ("hash_capacity", str(1 << 14)),
                ("device_cache_mb", str(cache_mb))]
        learner = Learner.create("sgd")
        learner.init(args)
        seen = []
        learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
        learner.run()
        return np.array(seen), learner

    ref, _ = run(0)
    got, learner = run(256)
    cache = learner._dev_caches[K_TRAINING]
    assert cache.ready
    # the cached payloads really carry the chunked layout (panel path)
    payloads = [pl for items in cache.entries.values() for pl in items]
    assert payloads and all(pl[0] == "panel_chunked" for pl in payloads)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_mesh_replay_matches_streaming(rcv1_path):
    """Single-controller mesh path: staged global (DeviceBatch, slots)
    pairs replay epochs 1+ with no re-staging, reproducing the streamed
    trajectory (shuffle off)."""
    def run(cache_mb):
        args = [("data_in", rcv1_path), ("data_format", "libsvm"),
                ("loss", "fm"), ("V_dim", "2"), ("V_threshold", "0"),
                ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
                ("batch_size", "25"), ("shuffle", "0"),
                ("max_num_epochs", "5"), ("num_jobs_per_epoch", "1"),
                ("report_interval", "0"), ("stop_rel_objv", "0"),
                ("hash_capacity", str(1 << 14)),
                ("mesh_dp", "2"), ("mesh_fs", "4"),
                ("device_cache_mb", str(cache_mb))]
        learner = Learner.create("sgd")
        learner.init(args)
        seen = []
        learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
        learner.run()
        return np.array(seen), learner

    ref, _ = run(0)
    got, learner = run(256)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    cache = learner._dev_caches[K_TRAINING]
    assert cache.ready
    payloads = [pl for items in cache.entries.values() for pl in items]
    assert payloads and all(pl[0] == "devbatch" for pl in payloads)


def test_stream_chunks_matches_unsorted(tmp_path):
    """Producer-side chunked-run layout for STREAMED panel training
    (stream_chunks=1, device_cache_mb=0): same trajectory as the
    unsorted-scatter streamed step — the layout changes the backward's
    schedule, not its math."""
    from conftest import write_uniform_libsvm
    data = write_uniform_libsvm(str(tmp_path / "u.libsvm"), rows=300,
                                width=8, id_space=500)
    base, ln0 = run_hashed(data, epochs=4, device_cache_mb=0,
                           stream_chunks=0)
    chunked, ln1 = run_hashed(data, epochs=4, device_cache_mb=0,
                              stream_chunks=1)
    np.testing.assert_allclose(chunked, base, rtol=2e-5)


def test_stream_chunks_staging_replay(tmp_path):
    """With the cache ON, stream_chunks defers to the staging-time
    DEVICE chunker (host-built chunks would double the staged bytes on
    the slow link); the trajectory matches the host-chunked streamed run
    and the staged payloads still carry the chunked layout."""
    from conftest import write_uniform_libsvm
    data = write_uniform_libsvm(str(tmp_path / "u.libsvm"), rows=300,
                                width=8, id_space=500)
    streamed, _ = run_hashed(data, epochs=5, device_cache_mb=0,
                             stream_chunks=1)
    cached, ln = run_hashed(data, epochs=5, device_cache_mb=256,
                            stream_chunks=1)
    np.testing.assert_allclose(cached, streamed, rtol=2e-5)
    cache = ln._get_cache(K_TRAINING)
    assert cache is not None and cache.ready
    # the staged payloads carry the chunked layout
    for payloads in cache.entries.values():
        for pl in payloads:
            assert pl[0] == "panel_chunked"


def test_stream_chunks_binary_panel(tmp_path):
    """Binary (value-elided) uniform panels ride the cv=None chunk path:
    BatchReader drops all-1.0 value arrays, _panel_arrays keeps uniform
    FULL batches valueless (rows must be a multiple of the bucketed
    batch cap — bucket(128)=128 — or the ragged pad path materializes
    values), and _chunk_host must hand chunk_vals=None through dispatch
    and staging."""
    rng = np.random.RandomState(11)
    path = str(tmp_path / "bin.libsvm")
    with open(path, "w") as f:
        for _ in range(384):  # 3 full batches of 128
            ids = np.sort(rng.choice(500, 8, replace=False))
            f.write(str(rng.randint(0, 2)) + " "
                    + " ".join(f"{j}:1" for j in ids) + "\n")
    base, _ = run_hashed(path, epochs=4, device_cache_mb=0,
                         stream_chunks=0, batch_size=128)
    chunked, ln = run_hashed(path, epochs=4, device_cache_mb=0,
                             stream_chunks=1, batch_size=128)
    np.testing.assert_allclose(chunked, base, rtol=2e-5)
    # prove the cv=None branch actually engaged: a full uniform binary
    # batch prepares as a valueless chunked panel
    from difacto_tpu.data import BatchReader
    blk = next(iter(BatchReader(path, "libsvm", batch_size=128)))
    payload = ln._prepare_hashed(blk, want_counts=True, fill_counts=False,
                                 dim_min=8, job="train", b_cap=128,
                                 stream_chunk=True)
    assert payload[0] == "panel_chunked"
    ci, cl, cv = payload[3]
    assert cv is None and payload[4] is True  # binary


def test_non_repadable_cache_invalidates_on_growth_mid_staging():
    """The invalidate arm still guards non-repadable caches (the mesh
    dictionary path): a capacity change between adds kills the cache."""
    c = _DeviceBatchCache(64)
    c.add(0, "a", 10, capacity=100)
    c.add(0, "b", 10, capacity=200)
    assert not c.alive and not c.entries and c.shared["used"] == 0


def test_stale_non_repadable_cache_invalidates_at_replay(rcv1_path):
    """A staged-vs-live capacity mismatch at the replay entry invalidates
    a NON-repadable cache (hashed here; the mesh dictionary in
    production) and training falls back to streaming with the
    trajectory unchanged."""
    ref, _ = run_hashed(rcv1_path, device_cache_mb=0, epochs=5)

    def setup(learner):
        def corrupt(e, t, v):
            if e == 2:
                learner._dev_caches[K_TRAINING].capacity += 1

        learner.add_epoch_end_callback(corrupt)

    seen, learner = run_hashed(rcv1_path, device_cache_mb=256, epochs=5,
                               setup=setup)
    assert not learner._dev_caches[K_TRAINING].alive
    np.testing.assert_allclose(seen, ref, rtol=1e-6, atol=1e-6)
