"""Round-3 fast-path tests (round-3 verdict #2): the pre-localized rec
cache (data/cached.py), its producer-thread collision dedup
(learners/sgd.py _prepare_from_uniq — the uniq->slot gather that
replaced the per-step device remap, docs/perf_notes.md round-5 "host
dedup"), and the producer pool's failure path (data/producer_pool.py).

The parity tests assert the cache reproduces the LIBSVM trajectory exactly
(same hyperparameters, shuffle off): the cached path must be a faster
encoding of the same computation, not a different one — including under
heavy hash collisions, where both paths resolve aliasing through the
same host-side segment-sum semantics (map_keys_dedup / np.unique).
"""

from collections import defaultdict

import numpy as np
import pytest

from difacto_tpu.data.cached import CachedBatchReader, cache_is_localized
from difacto_tpu.data.converter import Converter
from difacto_tpu.data.producer_pool import OrderedProducerPool
from difacto_tpu.data.rec import read_rec_block_ex
from difacto_tpu.data.reader import expand_uri
from difacto_tpu.learners import Learner


def convert_to_rec(src, out, rec_batch_size=0):
    conv = Converter()
    remain = conv.init([
        ("data_in", src), ("data_format", "libsvm"), ("data_out", out),
        ("data_out_format", "rec"),
        ("rec_batch_size", str(rec_batch_size))])
    assert remain == []
    conv.run()
    return out


def run_trajectory(data_in, data_format, hash_capacity, epochs=6, **over):
    args = [("data_in", data_in), ("data_format", data_format),
            ("loss", "fm"), ("V_dim", "2"), ("V_threshold", "0"),
            ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
            ("batch_size", "25"), ("shuffle", "0"),
            ("max_num_epochs", str(epochs)), ("num_jobs_per_epoch", "1"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", str(hash_capacity))]
    args += list(over.items())
    learner = Learner.create("sgd")
    remain = learner.init(args)
    assert remain == []
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    return np.array(seen), learner


@pytest.fixture(scope="module")
def rcv1_rec(rcv1_path, tmp_path_factory):
    d = tmp_path_factory.mktemp("rec")
    return convert_to_rec(rcv1_path, str(d / "rcv1.rec"))


@pytest.fixture(scope="module")
def rcv1_rec_aligned(rcv1_path, tmp_path_factory):
    d = tmp_path_factory.mktemp("rec_al")
    return convert_to_rec(rcv1_path, str(d / "rcv1.rec"), rec_batch_size=25)


def test_cache_is_localized(rcv1_rec):
    assert cache_is_localized(rcv1_rec)


def test_cached_parity_whole_member(rcv1_rec_aligned, rcv1_path):
    """Batch-aligned members (rec_batch_size=batch_size): each batch maps
    its member's uniq straight to slots on the producer thread."""
    ref, _ = run_trajectory(rcv1_path, "libsvm", 1 << 14)
    got, _ = run_trajectory(rcv1_rec_aligned, "rec", 1 << 14)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_cached_parity_sliced_member(rcv1_rec, rcv1_path):
    """One 100-row member sliced into 25-row batches: exercises the
    per-batch re-compaction (uniq subsetting) added for oversized
    members (round-3 advisor medium)."""
    ref, _ = run_trajectory(rcv1_path, "libsvm", 1 << 14)
    got, _ = run_trajectory(rcv1_rec, "rec", 1 << 14)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_cached_parity_heavy_collisions(rcv1_rec, rcv1_path):
    """Tiny hash_capacity: distinct ids collide into shared slots within
    every batch. The host path merges them in map_keys_dedup; the cached
    path must reach the same trajectory through the producer-thread
    uniq->slot index gather (colliding lanes alias the same slot row, so
    their gradients segment-sum together on device)."""
    ref, learner_ref = run_trajectory(rcv1_path, "libsvm", 61)
    got, learner_got = run_trajectory(rcv1_rec, "rec", 61)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # the final tables agree too (same slots, same aliased weights)
    from difacto_tpu.updaters.sgd_updater import col_w
    np.testing.assert_allclose(
        np.asarray(col_w(learner_got.store.param, learner_got.store.state)),
        np.asarray(col_w(learner_ref.store.param, learner_ref.store.state)),
        rtol=1e-5, atol=1e-6)
    # and collisions actually happened (otherwise this test is vacuous)
    blk, uniq = read_rec_block_ex(
        sorted(expand_uri(rcv1_rec))[0])
    slots = uniq % np.uint64(60) + np.uint64(1)
    assert len(np.unique(slots)) < len(uniq)


def test_cached_reader_shuffle_multiset(rcv1_rec):
    """Shuffle permutes rows (multiset of (label, row-nnz) preserved) and
    varies with the seed."""
    def rowset(seed, shuffle):
        rows = []
        for sub, uniq, _ in CachedBatchReader(rcv1_rec, batch_size=17,
                                              shuffle=shuffle, seed=seed):
            for i in range(sub.size):
                feats = sub.index[sub.offset[i]:sub.offset[i + 1]]
                rows.append((float(sub.label[i]),
                             tuple(np.sort(uniq[feats]).tolist())))
        return rows

    plain = rowset(0, False)
    shuf = rowset(1, True)
    assert plain != shuf                      # order actually changed
    assert sorted(plain) == sorted(shuf)      # same multiset of rows
    assert rowset(1, True) == rowset(1, True)  # deterministic per seed


def test_cached_reader_neg_sampling():
    """Keep-probability arithmetic matches the reference: positives always
    kept, negatives kept iff u <= 1 - neg_sampling."""
    import tempfile

    from difacto_tpu.data.rec import write_rec_block
    from difacto_tpu.data.rowblock import RowBlock

    n = 4000
    rng = np.random.RandomState(3)
    labels = (rng.rand(n) < 0.5).astype(np.float32)
    blk = RowBlock(offset=np.arange(n + 1, dtype=np.int64),
                   label=labels,
                   index=np.arange(n, dtype=np.uint32), value=None)
    with tempfile.TemporaryDirectory() as d:
        write_rec_block(f"{d}/part-0.npz", blk,
                        uniq=np.arange(n, dtype=np.uint64))
        got = []
        for sub, uniq, _ in CachedBatchReader(d, batch_size=512,
                                              neg_sampling=0.3, seed=7):
            got.extend(sub.label.tolist())
    got = np.array(got)
    n_pos, n_neg = int(labels.sum()), int((1 - labels).sum())
    assert int((got > 0).sum()) == n_pos          # all positives kept
    kept_neg = int((got == 0).sum())
    # negatives kept w.p. 0.7: binomial(n_neg, 0.7) within 5 sigma
    mu, sd = 0.7 * n_neg, np.sqrt(0.3 * 0.7 * n_neg)
    assert abs(kept_neg - mu) < 5 * sd


def test_cached_reader_member_sharding(rcv1_rec_aligned):
    """Every member lands in exactly one part; parts cover the cache."""
    whole = [tuple(u.tolist()) for _, u, _ in
             CachedBatchReader(rcv1_rec_aligned, 0, 1, batch_size=25)]
    parts = []
    for p in range(3):
        parts.extend(tuple(u.tolist()) for _, u, _ in
                     CachedBatchReader(rcv1_rec_aligned, p, 3,
                                       batch_size=25))
    assert sorted(parts) == sorted(whole)


def test_convert_default_aligns_to_batch_size(rcv1_path, tmp_path):
    """task=convert with the training config (batch_size present, no
    explicit rec_batch_size) produces batch-aligned members — the
    rec_batch_size footgun closed (round-4 verdict weak #5)."""
    from difacto_tpu.data.rec import read_rec_block_ex, rec_members

    out = str(tmp_path / "auto.rec")
    conv = Converter()
    remain = conv.init([
        ("data_in", rcv1_path), ("data_format", "libsvm"),
        ("data_out", out), ("data_out_format", "rec"),
        ("batch_size", "25")])
    assert remain == []
    conv.run()
    members = rec_members(*expand_uri(out, with_sizes=True))
    rows = [read_rec_block_ex(m)[0].size for m, _ in members]
    assert rows == [25, 25, 25, 25]
    # and training from it reproduces the libsvm trajectory
    ref, _ = run_trajectory(rcv1_path, "libsvm", 1 << 14, epochs=3)
    got, _ = run_trajectory(out, "rec", 1 << 14, epochs=3)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_cached_uri_warns_on_oversized_members(rcv1_rec, caplog):
    """Members that dwarf the training batch trigger the loud warning in
    _cached_uri (still trains correctly — parity tests above — but the
    user is told to re-convert)."""
    import logging

    from difacto_tpu.learners.sgd import K_TRAINING

    learner = Learner.create("sgd")
    learner.init([("data_in", rcv1_rec), ("data_format", "rec"),
                  ("batch_size", "10"), ("hash_capacity", "16384")])
    with caplog.at_level(logging.WARNING, logger="difacto_tpu"):
        assert learner._cached_uri(K_TRAINING) == rcv1_rec
    assert any("re-convert" in r.message for r in caplog.records)


def test_cached_reader_counts(rcv1_rec):
    """need_counts: per-uniq occurrence counts over the batch's rows."""
    for sub, uniq, cnts in CachedBatchReader(rcv1_rec, batch_size=30,
                                             need_counts=True):
        assert cnts is not None and len(cnts) == len(uniq)
        ref = np.bincount(sub.index.astype(np.int64),
                          minlength=len(uniq))
        np.testing.assert_array_equal(cnts, ref)
        # re-compaction: every shipped uniq lane is actually used
        if sub.size < 100:
            assert cnts.min() > 0


def test_producer_pool_retry_resumes():
    """A part that fails mid-iteration is re-queued (pool.reset) and the
    retry resumes after the already-delivered items — every item arrives
    exactly once, in order (producer_pool.py:79-100)."""
    calls = defaultdict(int)

    def make_iter(part):
        calls[part] += 1
        attempt = calls[part]

        def gen():
            for i in range(5):
                if part == 1 and attempt == 1 and i == 3:
                    raise RuntimeError("boom")
                yield (part, i)
        return gen()

    pool = OrderedProducerPool(3, make_iter, n_workers=2, depth=2,
                               max_retries=2)
    items = list(pool)
    assert items == [(p, (p, i)) for p in range(3) for i in range(5)]
    assert calls[1] == 2  # the failing part was retried exactly once


def test_producer_pool_straggler_reissue():
    """A part stuck on a hung producer is re-issued by idle workers via
    WorkloadPool.remove_stragglers (round-3 verdict #4); the generation
    guard keeps delivery exactly-once even though the original attempt
    wakes up afterwards and races the replacement."""
    import threading

    from difacto_tpu.tracker.workload_pool import (WorkloadPool,
                                                   WorkloadPoolParam)

    n_parts, n_items = 12, 3
    release = threading.Event()
    attempts = defaultdict(int)
    lock = threading.Lock()

    def make_iter(part):
        with lock:
            attempts[part] += 1
            att = attempts[part]
        if part == n_parts - 1 and att == 2:
            release.set()  # replacement started: let the hung one wake

        def gen():
            if part == n_parts - 1 and att == 1:
                release.wait(30)  # simulate a hung read
            for i in range(n_items):
                yield (part, i)
        return gen()

    wp = WorkloadPool(WorkloadPoolParam(straggler_timeout=0.2))
    pool = OrderedProducerPool(n_parts, make_iter, n_workers=3, depth=2,
                               pool=wp)
    items = list(pool)
    assert items == [(p, (p, i)) for p in range(n_parts)
                     for i in range(n_items)]
    assert attempts[n_parts - 1] == 2  # the stuck part was re-issued


def test_producer_pool_escalates_after_max_retries():
    """A persistently failing part escalates to the consumer after
    max_retries, after delivering the preceding parts."""
    def make_iter(part):
        def gen():
            if part == 1:
                raise RuntimeError("persistent")
            for i in range(3):
                yield (part, i)
        return gen()

    pool = OrderedProducerPool(2, make_iter, n_workers=2, depth=2,
                               max_retries=1)
    got = []
    with pytest.raises(RuntimeError, match="persistent"):
        for part, item in pool:
            got.append((part, item))
    assert got == [(0, (0, i)) for i in range(3)]


def test_paired_replay_without_counts_matches(tmp_path, monkeypatch):
    """Replay PAIRS dispatch through an executable compiled WITHOUT the
    counts section (replay counts are zeroed; apply_grad's per-row
    activation refresh subsumes the count-side one — learners/sgd.py
    _warm_pair_exec) and must reproduce the streamed trajectory exactly,
    with feature counts still pushed exactly once. The background pair
    compile is forced synchronous so pairing deterministically engages
    from epoch 1 (on CPU the compile otherwise races the tiny epochs and
    the pair path would go untested)."""
    import threading as real_threading

    import difacto_tpu.learners.sgd as sgd_mod

    class _SyncThread:
        def __init__(self, target=None, **kw):
            self._target = target

        def start(self):
            self._target()

    class _ThreadingShim:
        Thread = _SyncThread

        def __getattr__(self, name):
            return getattr(real_threading, name)

    monkeypatch.setattr(sgd_mod, "threading", _ThreadingShim())
    # a UNIFORM-width dataset: the panel layout (and so the chunked pair
    # path) only engages when rows are near-uniform; the ragged rcv1
    # fixture packs COO and never pairs
    rng = np.random.RandomState(5)
    d = tmp_path
    with open(d / "uniform.libsvm", "w") as f:
        for _ in range(200):
            feats = rng.choice(500, 8, replace=False) + 1
            cols = " ".join(f"{int(j)}:1" for j in np.sort(feats))
            f.write(f"{int(rng.randint(0, 2))} {cols}\n")
    rec = convert_to_rec(str(d / "uniform.libsvm"), str(d / "uniform.rec"),
                         rec_batch_size=25)
    ref, base = run_trajectory(rec, "rec", 1 << 14, device_cache_mb="0")
    got, learner = run_trajectory(rec, "rec", 1 << 14, device_cache_mb="256")
    assert getattr(learner, "_paired_dispatches", 0) > 0
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    from difacto_tpu.updaters.sgd_updater import scal_cols
    np.testing.assert_allclose(
        np.asarray(scal_cols(learner.store.param, learner.store.state)[3]),
        np.asarray(scal_cols(base.store.param, base.store.state)[3]))
