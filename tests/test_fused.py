"""Fused sparse-FM kernel + on-device dedup (ISSUE 13, ROADMAP item 3).

Acceptance legs:

- trajectories are BYTE-identical across ``fused_kernel=off|jnp`` (and
  ``pallas`` via interpret mode — the same kernels Mosaic compiles on
  TPU, executed bit-exactly on CPU) at the step level AND through full
  learner runs at fs=1 and fs=4;
- the on-device dedup (ops/fused.dedup_tokens) reproduces the host
  ``np.unique`` + ``pad_slots_oob`` contract exactly, and a streamed
  ``device_dedup=1`` learner run is byte-identical to the host-dedup
  run;
- backend resolution fails typed where the backend cannot exist
  (pallas under a sharded table) and degrades to ``off`` on flat
  tables.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from difacto_tpu.learners import Learner
from difacto_tpu.losses import create
from difacto_tpu.ops import fused
from difacto_tpu.step import make_step_fns
from difacto_tpu.store.local import pad_slots_oob
from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                              make_fns, set_all_live)

from conftest import write_uniform_libsvm


def _table_bits(state_vvg) -> np.ndarray:
    """Bitwise table view: the scal section stores f32 BITS split into
    storage-dtype lanes, so float compares see spurious NaN != NaN —
    byte-identity is the uint view (updaters/sgd_updater.py pack_scal)."""
    v = np.asarray(jax.device_get(state_vvg))
    return v.view(np.uint16) if v.dtype != np.float32 \
        else v.view(np.uint32)


# ---------------------------------------------------------------- dedup

@pytest.mark.parametrize("seed", [0, 7])
def test_dedup_tokens_matches_host_unique(seed):
    rng = np.random.RandomState(seed)
    capacity = 512
    tok = rng.randint(1, 100, 300).astype(np.int32)
    uniq, inverse = np.unique(tok, return_inverse=True)
    u_cap = 128
    want_slots = pad_slots_oob(uniq.astype(np.int32), u_cap, capacity)
    slots, inv, n = jax.jit(
        lambda t: fused.dedup_tokens(t, u_cap, capacity))(jnp.asarray(tok))
    assert int(n) == len(uniq)
    np.testing.assert_array_equal(np.asarray(slots), want_slots)
    np.testing.assert_array_equal(np.asarray(inv), inverse)


def test_dedup_tokens_single_value():
    slots, inv, n = fused.dedup_tokens(
        jnp.full((16,), 5, jnp.int32), 8, 64)
    assert int(n) == 1
    assert np.asarray(slots).tolist() == [5] + list(range(65, 72))
    assert np.asarray(inv).tolist() == [0] * 16


# -------------------------------------------------------------- resolve

def test_resolve_backend_contract():
    assert fused.resolve_backend("off", V_dim=4) == "off"
    assert fused.resolve_backend("auto", V_dim=0) == "off"
    assert fused.resolve_backend("auto", V_dim=4) == "jnp"
    assert fused.resolve_backend("jnp", V_dim=4) == "jnp"
    with pytest.raises(ValueError, match="sharded"):
        fused.resolve_backend("pallas", mesh=object(), V_dim=4)
    with pytest.raises(ValueError, match="unknown fused_kernel"):
        fused.resolve_backend("mosaic", V_dim=4)
    # the knob validates at learner init too (Param enum metadata)
    param = SGDUpdaterParam(V_dim=2, fused_kernel="pallas")
    assert make_fns(param).backend == "pallas"


# ----------------------------------------------------- step trajectories

def _run_steps(fused_kernel, v_dtype, steps=5, vdim=8):
    from bench import make_batches
    param = SGDUpdaterParam(V_dim=vdim, V_threshold=0, lr=0.1, l1=1e-4,
                            l2=1e-4, V_dtype=v_dtype,
                            fused_kernel=fused_kernel)
    fns = make_fns(param)
    loss = create("fm", vdim)
    state = set_all_live(param, init_state(param, 512))
    _, train_step, _ = make_step_fns(fns, loss)
    step = jax.jit(train_step, donate_argnums=0)
    batches = make_batches(2, 32, 5, 128, 512, "zipf", seed=3)
    objs = []
    for i in range(steps):
        b, s = batches[i % 2]
        state, objv, auc = step(state, b, jnp.asarray(s))
        objs.append((float(objv), float(auc)))
    return objs, _table_bits(state.VVg)


@pytest.mark.parametrize("v_dtype", ["bfloat16", "float32"])
def test_trajectory_byte_identical_off_vs_jnp(v_dtype):
    o0, t0 = _run_steps("off", v_dtype)
    o1, t1 = _run_steps("jnp", v_dtype)
    assert o0 == o1                      # float equality, not allclose
    np.testing.assert_array_equal(t0, t1)


def test_trajectory_byte_identical_pallas_interpret():
    """The pallas kernels (interpret mode off-TPU — the same kernel
    bodies Mosaic compiles) reproduce the off-path trajectory
    bit-for-bit: gather, in-kernel FTRL/AdaGrad epilogue, DMA
    scatter-back, OOB pad handling."""
    if not fused.pallas_importable():  # pragma: no cover - jax bundles it
        pytest.skip("no pallas in this jax build")
    o0, t0 = _run_steps("off", "bfloat16", steps=3)
    o2, t2 = _run_steps("pallas", "bfloat16", steps=3)
    assert o0 == o2
    np.testing.assert_array_equal(t0, t2)


def test_pallas_gather_scatter_kernels_match_jnp():
    if not fused.pallas_importable():  # pragma: no cover
        pytest.skip("no pallas in this jax build")
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    slots = jnp.asarray(
        pad_slots_oob(np.array([1, 5, 9, 30, 63], np.int32), 12, 64))
    g_jnp = fused.gather_rows(table, slots, "jnp")
    g_pl = fused.gather_rows(table, slots, "pallas")
    np.testing.assert_array_equal(np.asarray(g_jnp), np.asarray(g_pl))
    rows = jnp.asarray(rng.randn(12, 16).astype(np.float32))
    s_jnp = fused.scatter_rows(table, slots, rows, "jnp")
    s_pl = fused.scatter_rows(table, slots, rows, "pallas")
    np.testing.assert_array_equal(np.asarray(s_jnp), np.asarray(s_pl))


# --------------------------------------------------------- learner runs

def _learner_run(data, **over):
    args = [("data_in", data), ("V_dim", "2"), ("V_threshold", "2"),
            ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
            ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "2"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", "4096")]
    args += [(k, str(v)) for k, v in over.items()]
    ln = Learner.create("sgd")
    assert ln.init(args) == []
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    ln.run()
    return seen, _table_bits(ln.store.state.VVg)


def test_learner_byte_equality_fs1(rcv1_path):
    s0, t0 = _learner_run(rcv1_path, fused_kernel="off")
    s1, t1 = _learner_run(rcv1_path, fused_kernel="jnp")
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


def test_learner_byte_equality_fs4(rcv1_path):
    """fused_kernel=off|jnp stay byte-identical under the fs=4 sharded
    table (the jnp fused path partitions like the composed one and the
    state_constrainer keeps the donated layout)."""
    s0, t0 = _learner_run(rcv1_path, fused_kernel="off", mesh_fs=4)
    s1, t1 = _learner_run(rcv1_path, fused_kernel="jnp", mesh_fs=4)
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


def test_pallas_knob_rejected_on_mesh(rcv1_path):
    ln = Learner.create("sgd")
    with pytest.raises(ValueError, match="sharded"):
        ln.init([("data_in", rcv1_path), ("V_dim", "2"),
                 ("hash_capacity", "4096"), ("mesh_fs", "4"),
                 ("fused_kernel", "pallas")])


# ----------------------------------------------------- device_dedup path

def test_device_dedup_trajectory_byte_identical(tmp_path):
    """Streamed hashed training with device_dedup=1 (raw token lanes,
    in-step sort/dedup) is byte-identical to the host-np.unique path —
    losses AND final table bits — across 3 epochs on panel-shaped
    data."""
    path = str(tmp_path / "u.libsvm")
    write_uniform_libsvm(path, rows=300, width=8, id_space=500)
    common = dict(device_cache_mb=0, producer_mode="thread",
                  max_num_epochs=3, num_jobs_per_epoch=2, batch_size=64)
    s0, t0 = _learner_run(path, **common)
    s1, t1 = _learner_run(path, device_dedup=1, **common)
    assert s0 == s1 and len(s0) == 3
    np.testing.assert_array_equal(t0, t1)


def test_device_dedup_prepare_produces_raw_payload(tmp_path):
    """prepare_hashed(device_dedup=True) ships the raw-panel payload
    past the count push, and falls back to host dedup while counts are
    being filled (epoch 0)."""
    from difacto_tpu.data.pack_stream import ShapeSchedule, prepare_hashed
    from difacto_tpu.data.rowblock import RowBlock
    rng = np.random.RandomState(0)
    width, rows = 6, 40
    blk = RowBlock(
        offset=np.arange(rows + 1, dtype=np.int64) * width,
        label=rng.randint(0, 2, rows).astype(np.float32),
        index=rng.randint(0, 10_000, rows * width).astype(np.uint64),
        value=None)
    shapes = ShapeSchedule()
    raw = prepare_hashed(shapes, 4096, blk, want_counts=False,
                         fill_counts=False, dim_min=8, job="t",
                         device_dedup=True)
    assert raw[0] == "panel_raw"
    kind, i32, f32, binary, b_cap, w, u_cap = raw
    assert w == width
    # trailing meta: [rows, distinct-count]; the u-cap covers the
    # distinct count + the TRASH lane pad cells may add
    assert i32[-2] == rows and i32[-1] <= u_cap - 1
    hosted = prepare_hashed(shapes, 4096, blk, want_counts=True,
                            fill_counts=True, dim_min=8, job="t",
                            device_dedup=True)
    assert hosted[0] in ("panel", "coo")   # count push -> host dedup


def test_device_dedup_skips_cached_regime(tmp_path):
    """With a replay cache active (the default), device_dedup never
    produces raw payloads — staged epochs replay from HBM and the raw
    path's target regime is pure streaming."""
    path = str(tmp_path / "u.libsvm")
    write_uniform_libsvm(path, rows=200, width=8, id_space=400)
    s0, t0 = _learner_run(path, max_num_epochs=2, device_dedup=1,
                          device_cache_mb=256)
    s1, t1 = _learner_run(path, max_num_epochs=2,
                          device_cache_mb=256)
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)
