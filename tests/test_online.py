"""Online continuous learning (ISSUE 17): the serve→log→train→reload
loop — training-log mechanics (append / delayed-label join / horizon
defaults / sealed segments), the tailing trainer and ``task=online``,
trajectory integrity (offline replay reproduces the online checkpoint
byte-identically), golden parity (online-trained model serves
bit-for-bit with task=pred, through the routed fleet and at
serve_mesh_fs=2), the watcher-vs-pruner reload race, the three
``online.*`` fault points, freshness SLO gauges, and the SIGKILL'd-
trainer chaos leg.

Conventions: network/subprocess-bearing tests run under an explicit
SIGALRM deadline (the test_serve.py convention); the end-to-end legs
carry the ``chaos`` marker (in tier-1, selectable with ``-m chaos``;
``make online-chaos`` runs just these).
"""

import contextlib
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from difacto_tpu.__main__ import main
from difacto_tpu.obs import REGISTRY
from difacto_tpu.online import OnlineLog, TailReader, push_reload
from difacto_tpu.online.log import list_segments, read_index, seg_path
from difacto_tpu.utils import faultinject

REPO = pathlib.Path(__file__).resolve().parent.parent


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No injected fault may leak across tests."""
    yield
    faultinject.configure("")


def fixture_rows(rcv1_path):
    with open(rcv1_path, "rb") as f:
        return [l for l in f.read().splitlines() if l.strip()]


def _parse_row(row: bytes):
    from difacto_tpu.data.parsers import get_parser
    return get_parser("libsvm")(row)


def _read_back(path: str):
    """One RowBlock over a sealed segment, via the normal rec reader."""
    from difacto_tpu.data.reader import Reader
    from difacto_tpu.data.rowblock import RowBlock
    blocks = list(Reader(path, "rec", 0, 1))
    return blocks[0] if len(blocks) == 1 else RowBlock.concat(blocks)


def _wait_for(cond, seconds: float, what: str):
    t0 = time.monotonic()
    while time.monotonic() - t0 < seconds:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {seconds}s waiting for {what}")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------- log unit mechanics

def test_log_roundtrip_labels_segments_index(tmp_path):
    """Append + join + seal: rows resolve in arrival order with their
    joined labels, seal as ordinary rec2 members every segment_rows, the
    index records each seal, and the log's bookkeeping files stay
    invisible to rec readers."""
    from difacto_tpu.data.rec import rec_members
    log_dir = str(tmp_path / "olog")
    olog = OnlineLog(log_dir, segment_rows=4, label_delay_s=3600.0)
    src = [_parse_row(b"1 %d:1 %d:2" % (3 + i, 50 + i)) for i in range(8)]
    for i, blk in enumerate(src):
        assert olog.append(blk, row_id=i) == i
        assert olog.label(i, float(i % 2))
    assert list_segments(log_dir) == [0, 1]
    for s in (0, 1):
        blk = _read_back(seg_path(log_dir, s))
        assert blk.size == 4
        assert blk.label.tolist() == [0.0, 1.0, 0.0, 1.0]
        # arrival order preserved: row i's ids are {3+i, 50+i}
        for r in range(4):
            ids = blk.index[blk.offset[r]:blk.offset[r + 1]]
            assert set(int(x) for x in ids) == {3 + 4 * s + r,
                                                50 + 4 * s + r}
    idx = read_index(log_dir)
    assert [(e["seg"], e["rows"]) for e in idx] == [(0, 4), (1, 4)]
    assert all(e["ts"] > 0 for e in idx)
    # log.idx.jsonl and log.end never reach the block readers
    olog.end()
    members = [m for m, _ in rec_members([log_dir])]
    assert len(members) == 2 and all(m.endswith(".rec2") for m in members)
    # a resolved row can no longer be labeled; stats are coherent
    assert not olog.label(0, 1.0)
    st = olog.stats()
    assert st["rows_logged"] == 8 and st["pending"] == 0
    assert st["buffered"] == 0 and st["next_seg"] == 2
    # a restarting writer resumes numbering past the sealed segments
    assert OnlineLog(log_dir).stats()["next_seg"] == 2
    with pytest.raises(ValueError, match="label_default"):
        OnlineLog(str(tmp_path / "x"), label_default="bogus")


def test_label_horizon_default_negative_vs_drop(tmp_path):
    """An unlabeled row past the label_delay_s horizon resolves to the
    configured default: label 0.0 under ``negative``, excluded from the
    log under ``drop``."""
    before = REGISTRY.value("online_label_defaults_total")
    neg = OnlineLog(str(tmp_path / "neg"), segment_rows=2,
                    label_delay_s=0.05, label_default="negative")
    neg.append(_parse_row(b"1 3:1"), row_id=0)
    neg.append(_parse_row(b"1 4:1"), row_id=1)
    assert list_segments(neg.log_dir) == []      # still inside the horizon
    time.sleep(0.1)
    neg.poll()                                   # expiry without traffic
    assert list_segments(neg.log_dir) == [0]
    blk = _read_back(seg_path(neg.log_dir, 0))
    assert blk.size == 2 and blk.label.tolist() == [0.0, 0.0]
    assert REGISTRY.value("online_label_defaults_total") - before == 2

    drop = OnlineLog(str(tmp_path / "drop"), segment_rows=2,
                     label_delay_s=0.05, label_default="drop")
    drop.append(_parse_row(b"1 3:1"), row_id=0)
    drop.append(_parse_row(b"1 4:1"), row_id=1)
    time.sleep(0.1)
    drop.flush()
    assert list_segments(drop.log_dir) == []
    assert drop.stats()["rows_dropped"] == 2
    # a labeled row behind the dropped pair still makes it out
    drop.append(_parse_row(b"1 5:1"), row_id=2)
    drop.label(2, 1.0)
    drop.flush()
    blk = _read_back(seg_path(drop.log_dir, 0))
    assert blk.size == 1 and blk.label.tolist() == [1.0]


def test_tail_reader_replay_end_stop_and_deadline(tmp_path):
    """TailReader terminates on each of its four exits: replay gap,
    log.end (written after the final seal — the hand-off is race-free),
    stop event, and max_seconds."""
    log_dir = str(tmp_path / "olog")
    olog = OnlineLog(log_dir, segment_rows=1, label_delay_s=3600.0)
    for i in range(2):
        olog.append(_parse_row(b"1 3:1"), row_id=i)
        olog.label(i, 1.0)
    assert list_segments(log_dir) == [0, 1]
    # replay: drain the finished prefix, stop at the gap
    got = list(TailReader(log_dir, replay=True))
    assert got == [(0, seg_path(log_dir, 0)), (1, seg_path(log_dir, 1))]
    with deadline(60):
        # live tail: a reader blocked on seg 2 sees the seal, then ends
        out = []

        def tail():
            out.extend(s for s, _ in TailReader(log_dir, poll_s=0.01))

        t = threading.Thread(target=tail)
        t.start()
        time.sleep(0.1)
        olog.append(_parse_row(b"1 4:1"), row_id=2)
        olog.label(2, 0.0)
        olog.end()
        t.join(timeout=30)
        assert not t.is_alive() and out == [0, 1, 2]
    # stop event pre-set: returns without yielding the missing segment
    ev = threading.Event()
    ev.set()
    assert list(TailReader(log_dir, start_seg=99, stop=ev)) == []
    # bounded lifetime
    t0 = time.monotonic()
    assert list(TailReader(str(tmp_path / "empty"), poll_s=0.01,
                           max_seconds=0.05)) == []
    assert time.monotonic() - t0 < 5


# -------------------------------------- trained-loop fixtures (module)

def _online_args(log_dir, model, extra=()):
    # l1=0.1 (not the golden suite's l1=1): one online pass over each
    # row must leave real weights behind, not prune the store empty
    return ["task=online", f"online_log_dir={log_dir}",
            f"model_out={model}", "lr=1", "l1=0.1", "l2=1",
            "batch_size=100", "report_interval=0", *extra]


@pytest.fixture(scope="module")
def online_log(rcv1_path, tmp_path_factory):
    """A finished 4-segment training log over the 100 rcv1 fixture rows,
    every row joined with its true label (huge horizon: resolve-on-label,
    so the sealed stream is exactly the labeled source rows in order)."""
    d = tmp_path_factory.mktemp("online_log")
    log_dir = str(d / "olog")
    olog = OnlineLog(log_dir, segment_rows=25, label_delay_s=3600.0)
    for i, row in enumerate(fixture_rows(rcv1_path)):
        olog.append(_parse_row(row), row_id=i)
        olog.label(i, float(row.split()[0]))
    olog.end()
    assert list_segments(log_dir) == [0, 1, 2, 3]
    return log_dir


@pytest.fixture(scope="module")
def online_model(online_log, tmp_path_factory):
    """task=online over the finished log: tail drains the 4 segments,
    the tail-commit writes the _iter-3 generation, the final save the
    undecorated model."""
    d = tmp_path_factory.mktemp("online_model")
    model = str(d / "model")
    assert main(_online_args(online_log, model,
                             ("online_ckpt_interval_s=0",))) == 0
    assert os.path.exists(model + "_part-0")
    assert os.path.exists(model + "_iter-3_part-0")
    assert os.path.exists(model + "_iter-3_part-0.manifest.json")
    return model


# ---------------------------------------------------- acceptance legs

def test_replay_reproduces_online_checkpoint_bytes(online_log,
                                                   online_model,
                                                   tmp_path):
    """Trajectory integrity: replaying the sealed log offline
    (online_replay=1) issues the identical segment passes over the
    identical bytes — the final checkpoint is byte-identical to the
    online one, array for array."""
    model2 = str(tmp_path / "replay")
    assert main(_online_args(online_log, model2,
                             ("online_replay=1",
                              "online_ckpt_interval_s=0"))) == 0
    with np.load(online_model + "_part-0") as a, \
            np.load(model2 + "_part-0") as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            assert a[k].tobytes() == b[k].tobytes(), \
                f"array {k!r} differs between online and replay"


def test_online_model_golden_pred_fleet_and_fs2(online_model, rcv1_path,
                                                tmp_path):
    """Golden parity: the online-trained model scores the fixture rows
    byte-identically via task=pred, through a routed 2-replica fleet
    (fs=1), and on a single fs=2-sharded replica."""
    from difacto_tpu.serve import (RouterServer, ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    pred_out = str(tmp_path / "pred")
    assert main(["task=pred", f"model_in={online_model}",
                 f"data_val={rcv1_path}", f"pred_out={pred_out}"]) == 0
    with open(pred_out + "_part-0", "rb") as f:
        probs = [l.split(b"\t")[1] for l in f.read().splitlines()]
    assert len(probs) == 100 and len(set(probs)) > 1

    with deadline(240):
        srvs = []
        for _ in range(2):
            store, _, _ = open_serving_store(online_model)
            srvs.append(ServeServer(store, batch_size=100,
                                    max_delay_ms=50.0).start())
        try:
            router = RouterServer([(s.host, s.port) for s in srvs]).start()
        except OSError as e:  # pragma: no cover - locked-down CI box
            for s in srvs:
                s.close()
            pytest.skip(f"cannot bind the router port: {e}")
        try:
            with ServeClient(router.host, router.port) as c:
                resp = c.score_lines(rows)
            st = router.stats_snapshot()
            assert sum(b["rows"] for b in st["backends"]) >= 100, st
        finally:
            router.close()
            for s in srvs:
                s.close()
        assert resp == probs

        store2, _, _ = open_serving_store(online_model,
                                          [("serve_mesh_fs", "2")])
        assert store2.fs_count == 2
        srv = ServeServer(store2, batch_size=100,
                          max_delay_ms=200.0).start()
        try:
            with ServeClient(srv.host, srv.port) as c:
                resp2 = c.score_lines(rows)
        finally:
            srv.close()
        assert resp2 == probs


def test_reload_typed_walkback_on_pruned_generation(online_log, rcv1_path,
                                                    tmp_path):
    """Watcher-vs-pruner race: a replica reloading a generation that
    rank-0 pruning just removed gets the typed walk-back ({'ok': false},
    reload_failures counted) and KEEPS SERVING the incumbent model; the
    next surviving generation catches it up. push_reload carries the
    same contract per endpoint and never raises."""
    from difacto_tpu.serve import ServeClient, ServeServer, \
        open_serving_store
    from difacto_tpu.serve.reload import ModelReloader
    from difacto_tpu.utils import manifest as mft
    rows = fixture_rows(rcv1_path)
    # a 4-generation family: commit after every segment
    model = str(tmp_path / "fam")
    assert main(_online_args(online_log, model,
                             ("online_ckpt_interval_s=0.001",))) == 0
    for e in range(4):
        assert os.path.exists(f"{model}_iter-{e}_part-0"), e

    with deadline(120):
        store, _, _ = open_serving_store(f"{model}_iter-3")
        srv = ServeServer(store, batch_size=50, max_delay_ms=5.0).start()
        srv.reloader = ModelReloader(srv.executor, f"{model}_iter-3",
                                     server=srv)
        try:
            gen0 = srv.executor.stats()["model_generation"]
            # rank-0 pruning retires the two oldest generations while
            # this replica is about to load one of them
            removed = mft.prune_checkpoints(model, 2)
            assert any("_iter-0" in p for p in removed), removed
            res = srv.reloader.reload(f"{model}_iter-0")
            assert res["ok"] is False and res["error"], res
            assert srv.reloader.reload_failures == 1
            # never crashed, old model still serving at its generation
            with ServeClient(srv.host, srv.port) as c:
                got = c.predict(rows[:5])
            assert all(g is not None for g in got)
            assert srv.executor.stats()["model_generation"] == gen0
            # the loop's push: one dead endpoint, one live replica with a
            # surviving generation — best-effort, typed, no exception
            dead = _free_port()
            out = push_reload([("127.0.0.1", dead),
                               (srv.host, srv.port)], f"{model}_iter-2")
            assert out == {"ok": 1, "failed": 1}
            assert srv.executor.stats()["model_generation"] == gen0 + 1
            # pushing the pruned generation is the typed failure path
            out = push_reload([(srv.host, srv.port)], f"{model}_iter-1")
            assert out == {"ok": 0, "failed": 1}
            assert srv.executor.stats()["model_generation"] == gen0 + 1
        finally:
            srv.close()


# ------------------------------------------------ fault points (chaos)

@pytest.mark.chaos
def test_fault_log_append_row_still_served(online_model, rcv1_path,
                                           tmp_path):
    """``online.log.append:err@1``: every log append fails — the rows
    are all still answered (the serve path never fails because the
    training log did), the drops are counted, nothing is logged."""
    from difacto_tpu.serve import ServeClient, ServeServer, \
        open_serving_store
    rows = fixture_rows(rcv1_path)[:10]
    olog = OnlineLog(str(tmp_path / "olog"), segment_rows=4,
                     label_delay_s=0.05)
    drops0 = REGISTRY.value("online_log_drops_total")
    fired0 = REGISTRY.value("faults_fired_total",
                            point="online.log.append", kind="err")
    with deadline(60):
        store, _, _ = open_serving_store(online_model)
        srv = ServeServer(store, batch_size=16, max_delay_ms=2.0,
                          online_log=olog).start()
        try:
            faultinject.configure("online.log.append:err@1")
            with ServeClient(srv.host, srv.port) as c:
                got = c.predict(rows)
            assert all(g is not None for g in got)
            fired = faultinject.stats()   # read before disarm resets it
        finally:
            faultinject.configure("")
            srv.close()
    assert olog.stats()["rows_logged"] == 0
    assert REGISTRY.value("online_log_drops_total") - drops0 == 10
    assert fired.get("online.log.append", 0) >= 10
    assert REGISTRY.value("faults_fired_total",
                          point="online.log.append",
                          kind="err") - fired0 >= 10


@pytest.mark.chaos
def test_fault_seal_retains_buffer_then_recovers(tmp_path):
    """``online.seal:err@1``: a failing seal keeps the resolved buffer
    in memory (rows are never lost) and the next advance after disarm
    commits every row into the segment it always belonged to."""
    olog = OnlineLog(str(tmp_path / "olog"), segment_rows=2,
                     label_delay_s=3600.0)
    fails0 = REGISTRY.value("online_seal_failures_total")
    faultinject.configure("online.seal:err@1")
    olog.append(_parse_row(b"1 3:1"), row_id=0)
    olog.label(0, 1.0)
    olog.append(_parse_row(b"0 4:1"), row_id=1)
    olog.label(1, 0.0)
    # the seal fired and failed; nothing on disk, both rows retained
    assert faultinject.stats().get("online.seal", 0) >= 1, \
        faultinject.stats()
    assert list_segments(olog.log_dir) == []
    assert olog.stats()["buffered"] == 2
    assert REGISTRY.value("online_seal_failures_total") - fails0 >= 1
    faultinject.configure("")
    olog.flush()
    assert list_segments(olog.log_dir) == [0]
    blk = _read_back(seg_path(olog.log_dir, 0))
    assert blk.size == 2 and blk.label.tolist() == [1.0, 0.0]


@pytest.mark.chaos
def test_fault_label_join_typed_err_connection_survives(online_model,
                                                        tmp_path):
    """``online.label_join:err@1``: the join failure surfaces as a typed
    ``!err`` reply to the reporting client; the connection stays up and
    the next (disarmed) label joins normally."""
    from difacto_tpu.serve import ServeServer, open_serving_store
    olog = OnlineLog(str(tmp_path / "olog"), segment_rows=8,
                     label_delay_s=3600.0)
    with deadline(60):
        store, _, _ = open_serving_store(online_model)
        srv = ServeServer(store, batch_size=8, max_delay_ms=2.0,
                          online_log=olog).start()
        sock = socket.create_connection((srv.host, srv.port), timeout=10)
        try:
            rf = sock.makefile("rb")
            sock.sendall(b"#score 7 1 5:1 9:2\n")
            line = rf.readline()
            assert line and not line.startswith(b"!"), line
            assert olog.stats()["rows_logged"] == 1
            faultinject.configure("online.label_join:err@1")
            sock.sendall(b"#label 7 1\n")
            err = rf.readline()
            assert err.startswith(b"!err"), err
            fired = faultinject.stats()   # read before disarm resets it
            faultinject.configure("")
            sock.sendall(b"#label 7 1\n")
            assert json.loads(rf.readline()) == {"ok": True}
            # the row resolved on join; a duplicate label is a typed miss
            sock.sendall(b"#label 7 0\n")
            assert json.loads(rf.readline()) == {"ok": False}
        finally:
            sock.close()
            srv.close()
    assert fired.get("online.label_join", 0) >= 1, fired


# ------------------------------------------- end-to-end loop (chaos)

@pytest.mark.chaos
def test_inprocess_loop_feedback_freshness_and_reports(online_model,
                                                       rcv1_path,
                                                       tmp_path, capsys):
    """The loop in one process: feedback loadgen (#score/#label) against
    a logging replica, the tailing trainer pushing generations back to
    it — labels join, the served generation advances, the freshness SLO
    trio rides #metrics and the trainer's metrics JSONL renders through
    tools/obs_report.py."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen_feedback
    from obs_report import load_last_snapshot, report_gauges

    from difacto_tpu.online import OnlineParam, OnlineTrainer
    from difacto_tpu.serve import ServeClient, ServeServer, \
        open_serving_store
    from difacto_tpu.serve.reload import ModelReloader
    rows = fixture_rows(rcv1_path)
    log_dir = str(tmp_path / "olog")
    model = str(tmp_path / "model")
    metrics = str(tmp_path / "trainer.metrics.jsonl")
    olog = OnlineLog(log_dir, segment_rows=32, label_delay_s=0.4)
    joined0 = REGISTRY.value("online_labels_joined_total")
    pushes0 = REGISTRY.value("online_reload_pushes_total")
    with deadline(300):
        store, _, _ = open_serving_store(online_model)
        srv = ServeServer(store, batch_size=64, max_delay_ms=2.0,
                          online_log=olog).start()
        srv.reloader = ModelReloader(srv.executor, model, server=srv)
        gen0 = srv.executor.stats()["model_generation"]
        op = OnlineParam(online_log_dir=log_dir,
                         online_ckpt_interval_s=0.3,
                         online_endpoints=f"{srv.host}:{srv.port}")
        tr = OnlineTrainer(op, [
            ("model_out", model), ("lr", "1"), ("l1", "0.1"), ("l2", "1"),
            ("batch_size", "100"), ("report_interval", "0"),
            ("metrics_path", metrics), ("metrics_interval_s", "0.2")])
        res = {}
        tt = threading.Thread(
            target=lambda: res.update(trained=tr.run()))
        tt.start()
        try:
            rep = run_loadgen_feedback(srv.host, srv.port, rows, qps=120,
                                       duration_s=3.0, label_rate=1.0,
                                       label_delay_s=0.3)
            time.sleep(0.6)          # let the last horizons expire
            olog.end()
            tt.join(timeout=180)
            assert not tt.is_alive(), "trainer never drained the log"
            mt = ""
            with ServeClient(srv.host, srv.port) as c:
                mt = c.metrics()
            gen1 = srv.executor.stats()["model_generation"]
        finally:
            if tt.is_alive():  # pragma: no cover - deadline blew
                tr.stop()
                tt.join(timeout=60)
            srv.close()
    assert rep["err"] == 0 and rep["label_errs"] == 0, rep
    assert rep["labels_sent"] > 0 and rep["labels_acked"] > 0, rep
    assert REGISTRY.value("online_labels_joined_total") - joined0 > 0
    assert olog.stats()["rows_logged"] == rep["ok"], (olog.stats(), rep)
    # the trainer drained the whole log and pushed generations back
    assert res["trained"] == max(list_segments(log_dir))
    assert tr.generations() >= 1
    assert REGISTRY.value("online_reload_pushes_total") - pushes0 >= 1
    assert gen1 > gen0, "no generation ever reached the serving replica"
    # freshness SLO trio: on the replica's #metrics ...
    for name in ("train_behind_serve_s", "online_rows_behind",
                 "serve_generation_age_s"):
        assert name in mt, f"{name} missing from #metrics"
    # ... and in the trainer's JSONL, rendered by the obs report
    snap = load_last_snapshot(metrics)
    assert "train_behind_serve_s" in snap.get("gauges", {}), snap.keys()
    report_gauges(snap)
    out = capsys.readouterr().out
    assert "== gauges (at last flush) ==" in out
    assert "train_behind_serve_s" in out


@pytest.mark.chaos
def test_chaos_online_loop_survives_trainer_sigkill(online_model,
                                                    rcv1_path, tmp_path):
    """Acceptance: steady loadgen through the router against a
    2-replica logging fleet while the subprocess trainer tails the log
    and pushes generations; SIGKILL the trainer mid-generation — zero
    client-visible !err, the fleet keeps serving, and after relaunch
    (auto_resume walk-back) the served model_generation advances past
    the pre-kill value."""
    sys.path.insert(0, str(REPO / "tools"))
    from loadgen import run_loadgen

    from difacto_tpu.serve import (RouterServer, ServeClient, ServeServer,
                                   open_serving_store)
    from difacto_tpu.serve.reload import ModelReloader
    rows = fixture_rows(rcv1_path)
    log_dir = str(tmp_path / "olog")
    model = str(tmp_path / "model")
    # one shared in-process log (CLI replicas would use per-replica
    # dirs); short horizon: rows resolve to the negative default fast
    olog = OnlineLog(log_dir, segment_rows=128, label_delay_s=0.2)
    proc = proc2 = None
    with deadline(570):
        srvs = []
        for _ in range(2):
            store, _, _ = open_serving_store(online_model)
            srv = ServeServer(store, batch_size=64, max_delay_ms=2.0,
                              online_log=olog).start()
            srv.reloader = ModelReloader(srv.executor, model, server=srv)
            srvs.append(srv)
        try:
            router = RouterServer([(s.host, s.port)
                                   for s in srvs]).start()
        except OSError as e:  # pragma: no cover - locked-down CI box
            for s in srvs:
                s.close()
            pytest.skip(f"cannot bind the router port: {e}")
        eps = ",".join(f"{s.host}:{s.port}" for s in srvs)
        cmd = [sys.executable, "-m", "difacto_tpu", "task=online",
               f"online_log_dir={log_dir}", f"model_out={model}",
               "lr=1", "l1=0.1", "l2=1", "batch_size=100",
               "report_interval=0", "auto_resume=1",
               "online_ckpt_interval_s=0.5", f"online_endpoints={eps}"]
        env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
        reps = []
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                reps.append(run_loadgen(router.host, router.port, rows,
                                        qps=60, duration_s=2.0))

        def gen(i):
            return srvs[i].executor.stats()["model_generation"]

        t = threading.Thread(target=pump)
        t.start()
        try:
            gen0 = gen(0)
            proc = subprocess.Popen(cmd, env=env, cwd=str(REPO),
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)
            _wait_for(lambda: gen(0) > gen0, 240,
                      "the first pushed generation")
            pre_kill = gen(0)
            proc.kill()                       # SIGKILL, mid-generation
            assert proc.wait(timeout=60) == -signal.SIGKILL
            # the fleet keeps serving with the trainer dead
            with ServeClient(router.host, router.port) as c:
                got = c.predict(rows[:10])
            assert all(g is not None for g in got)
            # relaunch: auto_resume walks back to the last verified
            # generation and re-tails from the next segment
            proc2 = subprocess.Popen(cmd, env=env, cwd=str(REPO),
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.DEVNULL)
            _wait_for(lambda: gen(0) > pre_kill, 240,
                      "a generation advance after the relaunch")
        finally:
            stop.set()
            t.join()
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        try:
            olog.end()
            rc = proc2.wait(timeout=240)
            assert rc == 0, f"relaunched trainer exited {rc}"
            # the push reached BOTH replicas
            assert gen(1) > gen0
            # the headline: the kill+relaunch cost the clients NOTHING
            assert sum(r["err"] for r in reps) == 0, reps
            assert sum(r["ok"] for r in reps) > 0, reps
        finally:
            if proc2 is not None and proc2.poll() is None:
                proc2.kill()
                proc2.wait()
            router.close()
            for s in srvs:
                s.close()
