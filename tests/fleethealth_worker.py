"""Subprocess worker for the fleethealth concurrent-writer test.

Loads serve/fleethealth.py straight from its file path — NOT through the
difacto_tpu package — so each writer process costs a few milliseconds,
not a jax import. The module is deliberately dependency-free (stdlib
only) precisely so other tools can do the same.

Usage: fleethealth_worker.py <fleethealth.py> <blacklist> <tag> <n>
"""

import importlib.util
import sys


def load_module(path):
    spec = importlib.util.spec_from_file_location("fleethealth", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    module_path, bl_path, tag, n = sys.argv[1:5]
    fh = load_module(module_path).FleetHealth(
        bl_path, down_s=60.0, max_bytes=1 << 30)
    for k in range(int(n)):
        # alternate down/clear over a small endpoint set: maximal
        # contention on the same file, interleaved with the other writer
        if k % 2 == 0:
            fh.mark_down(f"host-{tag}", 1000 + k % 7)
        else:
            fh.mark_up(f"host-{tag}", 1000 + k % 7)
    print("done")
