"""Subprocess worker for the fleethealth concurrent-writer test.

Loads serve/fleethealth.py straight from its file path — NOT through the
difacto_tpu package — so each writer process costs a few milliseconds,
not a jax import. The module is deliberately dependency-free (stdlib
only) precisely so other tools can do the same.

Usage: fleethealth_worker.py <fleethealth.py> <blacklist> <tag> <n>
           [max_bytes]

``max_bytes`` (default: effectively unbounded) arms the in-place
compaction path: a small value makes every writer compact the shared
file many times while its peers append — the race the N-router-group
test drives.
"""

import importlib.util
import sys


def load_module(path):
    spec = importlib.util.spec_from_file_location("fleethealth", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    module_path, bl_path, tag, n = sys.argv[1:5]
    max_bytes = int(sys.argv[5]) if len(sys.argv) > 5 else 1 << 30
    fh = load_module(module_path).FleetHealth(
        bl_path, down_s=60.0, max_bytes=max_bytes)
    for k in range(int(n)):
        # alternate down/clear over a small endpoint set: maximal
        # contention on the same file, interleaved with the other writer
        if k % 2 == 0:
            fh.mark_down(f"host-{tag}", 1000 + k % 7)
        else:
            fh.mark_up(f"host-{tag}", 1000 + k % 7)
    print("done")
