"""Multi-host BCD (reference: distributed block coordinate descent across
workers+servers, src/bcd/bcd_learner.cc:51-93): two launch.py processes
each hold half the rows, union their feature dictionaries and group stats
over DCN, allreduce per-block (g, h) partials, and must REPRODUCE the
single-process golden diag-Newton trajectory — data-parallel summation
changes fp order, not math."""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

from tests.test_bcd import OBJV_DIAG_NEWTON
import pytest  # noqa: F401  (guard mark below)

from conftest import two_process_launch

pytestmark = two_process_launch

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_two_process_bcd_matches_golden(rcv1_path, tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", "7991", "--",
         sys.executable, str(REPO / "tests" / "bcd_worker.py"),
         str(tmp_path), rcv1_path],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    trajs = []
    for r in (0, 1):
        with open(tmp_path / f"traj-{r}.json") as f:
            trajs.append(json.load(f))
    np.testing.assert_allclose(trajs[0], trajs[1], rtol=1e-7)
    np.testing.assert_allclose(trajs[0], OBJV_DIAG_NEWTON, rtol=1e-4)
