"""SGD learner end-to-end tests.

The first test is the reference's executable baseline: the exact 20-epoch
objective trajectory of l1-regularized logistic regression (FTRL) on the
rcv1-100 fixture (tests/cpp/sgd_learner_test.cc:9-49, golden values from
tests/matlab/sgd_test.m), matched to the reference's own 5e-5 tolerance.
"""

import numpy as np
import pytest

from difacto_tpu.learners import Learner

GOLDEN = [
    69.314718, 69.314718, 67.151912, 61.414778, 56.244989, 53.218700,
    51.248737, 49.846688, 48.650164, 47.698351, 46.924038, 46.388223,
    45.970721, 45.499307, 45.102245, 44.798413, 44.565211, 44.386417,
    44.240657, 44.109764,
]


def make_learner(rcv1_path, **over):
    args = [("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"), ("l1", "1"),
            ("lr", "1"), ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "20"), ("shuffle", "0"),
            ("report_interval", "0"),
            # epoch-1 loss equals epoch-0 bitwise (w stays 0 after one FTRL
            # step on this data), so any positive stop_rel_objv stops at
            # epoch 1; disable to exercise the full trajectory
            ("stop_rel_objv", "0")]
    args += list(over.items())
    learner = Learner.create("sgd")
    remain = learner.init(args)
    assert remain == []
    return learner


def test_sgd_golden_trajectory(rcv1_path):
    learner = make_learner(rcv1_path)
    seen = []
    learner.add_epoch_end_callback(
        lambda epoch, train, val: seen.append(train.loss))
    learner.run()
    assert len(seen) == 20
    err = np.abs(np.array(seen) - np.array(GOLDEN))
    assert err.max() < 5e-5, (seen, GOLDEN)  # the reference's own tolerance


def test_sgd_with_embeddings_learns(rcv1_path):
    """FM path (V_dim=2): objective decreases and embeddings activate."""
    learner = make_learner(rcv1_path, V_dim="2", V_threshold="2", lr="0.1",
                           l1="0.1", l2="0", max_num_epochs="10")
    seen = []
    learner.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    learner.run()
    assert seen[-1] < seen[0] * 0.9
    # some embeddings became live (the flag lives in the fused scal lanes)
    from difacto_tpu.updaters.sgd_updater import scal_cols
    live = scal_cols(learner.store.param, learner.store.state)[4]
    assert int(np.asarray(live).sum()) > 0
    penalty, nnz = learner.store.evaluate()
    assert nnz > 0


def test_sgd_save_load_dump(rcv1_path, tmp_path):
    model = str(tmp_path / "model")
    learner = make_learner(rcv1_path, max_num_epochs="5",
                           model_out=model, has_aux="true")
    learner.run()
    w_before = np.asarray(learner.store.state.w).copy()
    keys_before = learner.store._keys.copy()
    slots_before = learner.store._slots.copy()

    # resume into a fresh learner: trajectory continues from saved state
    l2 = make_learner(rcv1_path, max_num_epochs="5", model_in=model)
    n = l2.store.load(l2._model_name(model, -1))
    assert n > 0
    new_slots = l2.store.lookup(keys_before[w_before[slots_before] != 0])
    old_w = w_before[slots_before][w_before[slots_before] != 0]
    new_w = np.asarray(l2.store.state.w)[new_slots]
    np.testing.assert_allclose(new_w, old_w, atol=1e-7)

    # dump TSV
    out = str(tmp_path / "dump.tsv")
    n_dumped = l2.store.dump(out, dump_aux=True)
    lines = open(out).read().strip().splitlines()
    assert len(lines) == n_dumped > 0
    cols = lines[0].split("\t")
    assert len(cols) == 5  # id, size, w, sqrt_g, z
    assert int(cols[1]) == 1


def test_sgd_validation_and_early_stop(rcv1_path):
    learner = make_learner(rcv1_path, data_val=rcv1_path,
                           max_num_epochs="30", stop_rel_objv="0.01")
    epochs = []
    learner.add_epoch_end_callback(lambda e, t, v: epochs.append((e, v.auc)))
    learner.run()
    assert len(epochs) < 30          # early stop triggered
    assert epochs[-1][1] > 0         # validation ran and produced AUC


def test_sgd_prediction_task(rcv1_path, tmp_path):
    model = str(tmp_path / "m")
    learner = make_learner(rcv1_path, max_num_epochs="5", model_out=model)
    learner.run()
    pred_out = str(tmp_path / "pred")
    pl = make_learner(rcv1_path, task="2", model_in=model,
                      data_val=rcv1_path, pred_out=pred_out)
    pl.run()
    lines = open(pred_out + "_part-0").read().strip().splitlines()
    assert len(lines) == 100
    lab, prob = lines[0].split("\t")
    assert 0.0 <= float(prob) <= 1.0


def test_default_reporting_matches_silent_path(rcv1_path, capsys,
                                               monkeypatch):
    """The DEFAULT config (report_interval=1: live part-boundary rows ON —
    every other test runs report_interval=0) trains the identical
    trajectory: the _row_due merge/row machinery is display-only. Time is
    stubbed inside the learner module so EVERY part boundary is due (the
    maximal-row case), and parts > 1 exercise the boundary bookkeeping
    and the cross-part pending carry that the throttle introduced."""
    import time as real_time

    import difacto_tpu.learners.sgd as sgd_mod

    def run(**over):
        learner = make_learner(rcv1_path, num_jobs_per_epoch="4",
                               max_num_epochs="6", **over)
        seen = []
        learner.add_epoch_end_callback(
            lambda e, t, v: seen.append((t.loss, t.auc, t.nnz_w)))
        learner.run()
        return seen

    silent = run()  # helper default: report_interval=0

    class _JumpyTime:
        """time shim for the sgd module only: monotonic() advances 10 s
        per call so every part boundary clears report_interval."""
        def __init__(self):
            self._now = 0.0

        def monotonic(self):
            self._now += 10.0
            return self._now

        def __getattr__(self, name):
            return getattr(real_time, name)

    monkeypatch.setattr(sgd_mod, "time", _JumpyTime())
    capsys.readouterr()
    live = run(report_interval="1")
    rows = [ln for ln in capsys.readouterr().out.splitlines() if "|" in ln]

    assert live == silent
    # the live path really reported: one row per part per train epoch
    # (every boundary due under the stubbed clock) plus the epoch tails
    assert len(rows) >= 6
    """pad_v_rows: the lane-padded [V | pad | Vg | pad] layout is bitwise
    equivalent to the compact one, auto-disables over the memory budget,
    and re-lays-out on growth across the threshold."""
    import jax.numpy as jnp
    from difacto_tpu.losses import FMParams
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  grow_state, init_state,
                                                  make_fns, row_layout,
                                                  set_all_live, v_half)

    # budget gate: small table pads, huge table falls back to compact
    p = SGDUpdaterParam(V_dim=16, V_threshold=0, pad_v_rows_max_mb=1)
    assert v_half(p, 1024) == 64
    assert v_half(p, 1 << 20) == 16
    assert v_half(SGDUpdaterParam(V_dim=16, pad_v_rows=False), 1024) == 16
    assert v_half(SGDUpdaterParam(V_dim=64), 1024) == 64  # already aligned

    rng = np.random.RandomState(3)
    C, U, k = 256, 32, 16
    slots = np.sort(rng.permutation(C - 1)[:U] + 1).astype(np.int32)
    gw = rng.randn(U).astype(np.float32)
    gV = rng.randn(U, k).astype(np.float32) * 0.1

    def run(pad):
        par = SGDUpdaterParam(V_dim=k, V_threshold=0, lr=0.1, l1=0.01,
                              pad_v_rows=pad)
        fns = make_fns(par)
        st = set_all_live(par, init_state(par, C))
        for _ in range(3):
            st = fns.apply_grad(st, jnp.asarray(slots), jnp.asarray(gw),
                                jnp.asarray(gV), jnp.ones(U))
        w, V, vm = fns.get_rows(st, jnp.asarray(slots))
        return np.asarray(w), np.asarray(V), np.asarray(fns.evaluate(st))

    wp, Vp, ep = run(True)
    wc, Vc, ec = run(False)
    np.testing.assert_array_equal(wp, wc)
    np.testing.assert_array_equal(Vp, Vc)
    np.testing.assert_array_equal(ep, ec)

    # growth across the budget threshold re-lays-out old rows
    par = SGDUpdaterParam(V_dim=k, V_threshold=0, lr=0.1, l1=0.01,
                          pad_v_rows_max_mb=1)
    fns = make_fns(par)
    st = set_all_live(par, init_state(par, 1024))
    assert st.VVg.shape[1] == 128  # scal lanes ride the existing pad
    st = fns.apply_grad(st, jnp.asarray(slots), jnp.asarray(gw),
                        jnp.asarray(gV), jnp.ones(U))
    _, V_before, _ = fns.get_rows(st, jnp.asarray(slots))
    from difacto_tpu.updaters.sgd_updater import col_Vg, scal_cols
    Vg_before = np.asarray(col_Vg(par, st))[:1024]
    scal_before = [np.asarray(c)[:1024] for c in scal_cols(par, st)]
    grown = grow_state(par, st, 1 << 20)
    # compact halves after crossing the cap; the row is re-laid to the
    # tile-aligned fused width (scal section behind the halves). The
    # WIDTH is 128 on both sides here while h moves 64 -> 16 — the
    # geometry change a width-equality guard would miss (advisor
    # round-5 finding: Vg silently zeroed on growth)
    assert grown.VVg.shape[1] == row_layout(par, 1 << 20)[2] == 128
    assert row_layout(par, 1024)[1] != row_layout(par, 1 << 20)[1]
    np.testing.assert_array_equal(np.asarray(col_Vg(par, grown))[:1024],
                                  Vg_before)
    for got, want in zip(scal_cols(par, grown), scal_before):
        np.testing.assert_array_equal(np.asarray(got)[:1024], want)
    _, V_after, _ = fns.get_rows(grown, jnp.asarray(slots))
    np.testing.assert_array_equal(np.asarray(V_before),
                                  np.asarray(V_after))
