"""CLI task tests: train / pred / dump / convert round trips on the fixture,
mirroring the reference's main.cc dispatch (src/main.cc:66-90)."""

import os

import numpy as np
import pytest

from difacto_tpu.__main__ import main


def test_cli_train_pred_dump(rcv1_path, tmp_path, capsys):
    model = str(tmp_path / "model")
    conf = tmp_path / "train.conf"
    conf.write_text(
        f"data_in = {rcv1_path}\n"
        "# comment line\n"
        "lr = 1\nl1 = 1\nl2 = 1\n"
        "batch_size = 100\nmax_num_epochs = 3\nshuffle = 0\n"
        "num_jobs_per_epoch = 1\nreport_interval = 0\n"
        f"model_out = {model}\n")
    assert main([str(conf)]) == 0
    assert os.path.exists(model + "_part-0")

    pred_out = str(tmp_path / "pred")
    assert main([str(conf), "task=pred", f"model_in={model}",
                 f"data_val={rcv1_path}", f"pred_out={pred_out}"]) == 0
    assert len(open(pred_out + "_part-0").readlines()) == 100

    dump_out = str(tmp_path / "dump.tsv")
    assert main(["task=dump", f"model_in={model}_part-0",
                 f"name_dump={dump_out}", "need_reverse=true"]) == 0
    lines = open(dump_out).read().strip().splitlines()
    assert lines
    # need_reverse=true: ids are back in the original (small) libsvm space
    ids = [int(l.split("\t")[0]) for l in lines]
    assert max(ids) < 1 << 17


def test_cli_convert_roundtrip(rcv1_path, tmp_path):
    rec_dir = str(tmp_path / "cache.rec")
    assert main(["task=convert", f"data_in={rcv1_path}",
                 "data_format=libsvm", f"data_out={rec_dir}",
                 "data_out_format=rec"]) == 0
    back = str(tmp_path / "back.libsvm")
    assert main(["task=convert", f"data_in={rec_dir}", "data_format=rec",
                 f"data_out={back}", "data_out_format=libsvm"]) == 0

    from difacto_tpu.data import Reader
    a = [b for b in Reader(rcv1_path, "libsvm")]
    b = [b for b in Reader(back, "libsvm")]
    na, nb = sum(x.size for x in a), sum(x.size for x in b)
    assert na == nb == 100
    ia = np.concatenate([x.index for x in a])
    ib = np.concatenate([x.index for x in b])
    np.testing.assert_array_equal(ia, ib)
    va = np.concatenate([x.values_or_ones() for x in a])
    vb = np.concatenate([x.values_or_ones() for x in b])
    np.testing.assert_allclose(va, vb, rtol=1e-5)


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("conf,overrides", [
    ("local.conf", ["report_interval=0"]),
    ("fm.conf", ["report_interval=0"]),
    ("lbfgs.conf", []),   # report_interval is an sgd-family knob
    ("bcd.conf", []),
])
def test_cli_example_confs_train(conf, overrides, monkeypatch, caplog):
    # every runnable example conf trains end-to-end through the CLI
    # (epochs capped; fixture paths inside the confs are repo-relative);
    # a key falling through the whole chain only WARNS in main(), so the
    # key-rot guard here is the absence of that warning
    monkeypatch.chdir(REPO)
    with caplog.at_level("WARNING", logger="difacto_tpu"):
        assert main([os.path.join(REPO, "examples", conf),
                     "max_num_epochs=2"] + overrides) == 0
    rot = [r.message for r in caplog.records
           if "unknown config key" in r.getMessage()]
    assert not rot, f"unconsumed keys in examples/{conf}: {rot}"


@pytest.mark.parametrize("conf,shrink", [
    # shrink the tables (last occurrence wins) so the guard doesn't
    # allocate the confs' production-size state just to check keys
    ("criteo_hashed.conf", ["hash_capacity=4096", "V_dim=2"]),
    ("criteo_dict.conf", ["V_dim=2"]),
])
def test_cli_example_conf_templates_parse(conf, shrink):
    # the criteo confs are templates (data_in commented out): guard them
    # against key rot — every key must be consumed by the learner chain
    # (an unknown key would survive init as a leftover). Their 2x4 mesh
    # builds on the 8 virtual devices the conftest provides. The kwargs
    # go through the same DifactoParam consumption main() applies.
    from difacto_tpu.__main__ import DifactoParam
    from difacto_tpu.config import parse_cli_args
    from difacto_tpu.learners import Learner
    kwargs = parse_cli_args(
        [os.path.join(REPO, "examples", conf)] + shrink)
    param, remain = DifactoParam.init_allow_unknown(kwargs)
    remain = Learner.create(param.learner).init(remain)
    assert not remain, f"unknown keys in examples/{conf}: {remain}"


def test_cli_bad_task(tmp_path):
    with pytest.raises(ValueError):
        main(["task=nonsense"])


def test_cli_usage():
    assert main([]) == 1
