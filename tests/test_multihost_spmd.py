"""Multi-host x mesh synchronized stepping (round-1 verdict item 4).

Two processes launched through launch.py, each with 4 virtual CPU devices,
train over a global (dp=2, fs=4) mesh with the hashed store. The per-step
global batch is the union of both hosts' local batches, so the trajectory
must match a single-host run over the same data with the same
hash_capacity (reference analog: ps-lite rendezvous + synchronized
barriers, src/store/kvstore_dist.h:61-70)."""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from conftest import two_process_launch

pytestmark = two_process_launch

REPO = pathlib.Path(__file__).resolve().parent.parent
EPOCHS = 4


def _single_host_reference(rcv1_path, data_val, **overrides):
    from difacto_tpu.learners import Learner
    conf = {"data_in": rcv1_path, "V_dim": "2", "V_threshold": "2",
            "lr": "0.1", "l1": "0.1", "l2": "0",
            "batch_size": "100", "max_num_epochs": str(EPOCHS),
            "shuffle": "0", "report_interval": "0",
            "stop_rel_objv": "0", "stop_val_auc": "-2",
            "num_jobs_per_epoch": "1",
            "hash_capacity": str(1 << 20)}
    if data_val:
        conf["data_val"] = data_val
    conf.update({k: str(v) for k, v in overrides.items()})
    ln = Learner.create("sgd")
    ln.init(list(conf.items()))
    seen, seen_val = [], []
    ln.add_epoch_end_callback(
        lambda e, t, v: (seen.append(t.loss), seen_val.append(v.loss)))
    ln.run()
    return seen, seen_val


def test_two_process_mesh_matches_single_host(rcv1_path, tmp_path):
    # validation file of 300 rows: eval Reader chunks (256MB => whole file)
    # exceed b_cap=bucket(100)=128, so the SPMD eval path must slice them
    # into batch_size windows (advisor round-2 medium finding)
    val_path = str(tmp_path / "val300.libsvm")
    text = open(rcv1_path).read()
    with open(val_path, "w") as f:
        f.write(text * 3)

    trajs = _launch_two(tmp_path, rcv1_path, EPOCHS, 7921,
                        data_val=val_path)
    # both ranks observed the identical global trajectory
    np.testing.assert_allclose(trajs[0]["train"], trajs[1]["train"],
                               rtol=0, atol=0)
    np.testing.assert_allclose(trajs[0]["val"], trajs[1]["val"],
                               rtol=0, atol=0)
    assert len(trajs[0]["train"]) == EPOCHS

    # and it matches the single-host run over the same data: each host read
    # half the file (byte-range parts), the per-step union batch = the
    # single host's 100-row batch. Validation loss is a pure sum over rows,
    # so it is chunking-invariant and must match too.
    ref, ref_val = _single_host_reference(rcv1_path, val_path)
    np.testing.assert_allclose(trajs[0]["train"], ref, rtol=2e-4)
    np.testing.assert_allclose(trajs[0]["val"], ref_val, rtol=2e-4)

    # per-rank checkpoints were written by both hosts
    assert (tmp_path / "model_part-0").exists()
    assert (tmp_path / "model_part-1").exists()


def _launch_two(tmp_path, data, epochs, port, extra=(), data_val=""):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", str(port), "--",
         sys.executable, str(REPO / "tests" / "spmd_worker.py"),
         str(tmp_path), data, str(epochs), data_val, *extra],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    trajs = []
    for rank in range(2):
        with open(tmp_path / f"traj-{rank}.json") as f:
            trajs.append(json.load(f))
    return trajs


def test_two_process_dictionary_matches_single_host(rcv1_path, tmp_path):
    """Exact-id dictionary store over two hosts (round-4 missing #1: the
    reference keys its distributed model by exact 64-bit feature id,
    src/sgd/sgd_updater.h:141-176 — no two features ever alias). The
    control plane ships raw ids; every host inserts the identical sorted
    union, so replica dictionaries stay bit-identical. V_dim=0 makes the
    trajectory slot-numbering-invariant, so the 2-process run must match
    a single-host dictionary run."""
    trajs = _launch_two(tmp_path, rcv1_path, EPOCHS, 7927,
                        extra=["hash_capacity=0", "V_dim=0"])
    np.testing.assert_allclose(trajs[0]["train"], trajs[1]["train"],
                               rtol=0, atol=0)
    # replica-dictionary invariants: identical id->slot maps and capacity
    assert trajs[0]["num_features"] == trajs[1]["num_features"] > 0
    assert trajs[0]["capacity"] == trajs[1]["capacity"]
    # passes after the first ship int32 slots instead of uint64 ids
    # (half the control bytes); both ranks took that branch
    assert trajs[0]["slot_steps"] > 0 and trajs[1]["slot_steps"] > 0

    ref, _ = _single_host_reference(rcv1_path, "", hash_capacity=0,
                                    V_dim=0)
    np.testing.assert_allclose(trajs[0]["train"], ref, rtol=2e-4)


def test_two_process_dictionary_growth_and_embeddings(rcv1_path, tmp_path):
    """Dictionary SPMD with embeddings and a small init_capacity: the
    table must grow by doubling mid-epoch-0 through the DEFERRED growth
    path (exchange() computes OOB padding against the capacity the
    dispatch thread will have; grow_to applies it in step order). Ranks
    must stay bit-identical and the objective must fall. The rcv1
    fixture has 2775 distinct features, so init_capacity=1024 forces
    1024 -> 4096."""
    trajs = _launch_two(tmp_path, rcv1_path, 3, 7929,
                        extra=["hash_capacity=0", "init_capacity=1024"])
    np.testing.assert_allclose(trajs[0]["train"], trajs[1]["train"],
                               rtol=0, atol=0)
    assert trajs[0]["num_features"] == trajs[1]["num_features"] == 2775
    assert trajs[0]["capacity"] == trajs[1]["capacity"] == 4096
    losses = trajs[0]["train"]
    assert losses[-1] < losses[0]


def test_two_process_mesh_panel_path(tmp_path):
    """Uniform-width data engages the SPMD panel + chunked-run step
    (round-5: the synchronized schedule previously always built COO
    batches and took the unsorted backward). Both ranks must agree on
    the global panel decision, observe the identical trajectory, and
    match a single-host run over the same data."""
    from conftest import write_uniform_libsvm
    data = write_uniform_libsvm(tmp_path / "uniform.libsvm", rows=100)

    trajs = _launch_two(tmp_path, data, 3, 7925)
    assert trajs[0]["panel_steps"] > 0 and trajs[1]["panel_steps"] > 0
    np.testing.assert_allclose(trajs[0]["train"], trajs[1]["train"],
                               rtol=0, atol=0)

    from difacto_tpu.learners import Learner
    ln = Learner.create("sgd")
    ln.init([("data_in", data), ("V_dim", "2"), ("V_threshold", "2"),
             ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
             ("batch_size", "100"), ("max_num_epochs", "3"),
             ("shuffle", "0"), ("report_interval", "0"),
             ("stop_rel_objv", "0"), ("stop_val_auc", "-2"),
             ("num_jobs_per_epoch", "1"), ("hash_capacity", str(1 << 20))])
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    ln.run()
    np.testing.assert_allclose(trajs[0]["train"], seen, rtol=2e-4)
