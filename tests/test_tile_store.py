"""DataStore / TileCache tests (src/data/data_store.h, tile_store.h analogs)."""

import numpy as np
import pytest

from difacto_tpu.data.tile_store import DataStore, TileCache


def test_datastore_store_fetch_range():
    ds = DataStore()
    ds.store("x", np.arange(10, dtype=np.int32))
    np.testing.assert_array_equal(ds.fetch("x"), np.arange(10))
    np.testing.assert_array_equal(ds.fetch("x", 1, 3), [1, 2])  # the
    # reference's doc example (data_store.h:66-74)
    assert ds.size("x") == 10
    ds.remove("x")
    with pytest.raises(KeyError):
        ds.fetch("x")


def test_datastore_spill_roundtrip(tmp_path):
    ds = DataStore(max_mem_bytes=100, spill_dir=str(tmp_path))
    a = np.arange(20, dtype=np.float32)  # 80 bytes
    b = np.arange(10, dtype=np.float32)  # 40 bytes -> a spills
    ds.store("a", a)
    ds.store("b", b)
    assert ds._spilled  # something went to disk
    np.testing.assert_array_equal(ds.fetch("a"), a)  # reload transparent
    np.testing.assert_array_equal(ds.fetch("b"), b)


def test_datastore_requires_spill_dir():
    with pytest.raises(ValueError):
        DataStore(max_mem_bytes=10)


def test_tile_cache_lru():
    built = []

    def build(r, c):
        built.append((r, c))
        return (r, c)

    tc = TileCache(build, max_items=2)
    assert tc.fetch(0, 0) == (0, 0)
    assert tc.fetch(0, 1) == (0, 1)
    assert tc.fetch(0, 0) == (0, 0)  # hit
    assert tc.hits == 1
    tc.fetch(0, 2)                   # evicts (0, 1)
    tc.fetch(0, 1)                   # rebuild
    assert built.count((0, 1)) == 2
    assert len(tc) == 2


def test_bcd_with_bounded_tile_cache(rcv1_path):
    """BCD converges identically with an LRU-bounded tile cache."""
    from difacto_tpu.learners import Learner

    def run(cache_items):
        learner = Learner.create("bcd")
        learner.init([("data_in", rcv1_path), ("l1", ".1"), ("lr", ".05"),
                      ("block_ratio", "1"), ("tail_feature_filter", "0"),
                      ("max_num_epochs", "3"), ("random_block", "0"),
                      ("tile_cache_items", str(cache_items))])
        seen = []
        learner.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
        learner.run()
        return seen, learner

    ref, unlimited = run(0)
    bounded, learner = run(1)  # forces rebuilds across blocks
    np.testing.assert_allclose(bounded, ref, rtol=1e-6)
    # the bounded cache must rebuild evicted tiles; unlimited builds once
    assert learner._tile_cache.misses > unlimited._tile_cache.misses
    assert len(learner._tile_cache) == 1
