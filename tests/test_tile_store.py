"""DataStore / TileCache tests (src/data/data_store.h, tile_store.h analogs)."""

import numpy as np
import pytest

from difacto_tpu.data.tile_store import DataStore, TileCache


def test_datastore_store_fetch_range():
    ds = DataStore()
    ds.store("x", np.arange(10, dtype=np.int32))
    np.testing.assert_array_equal(ds.fetch("x"), np.arange(10))
    np.testing.assert_array_equal(ds.fetch("x", 1, 3), [1, 2])  # the
    # reference's doc example (data_store.h:66-74)
    assert ds.size("x") == 10
    ds.remove("x")
    with pytest.raises(KeyError):
        ds.fetch("x")


def test_datastore_spill_roundtrip(tmp_path):
    ds = DataStore(max_mem_bytes=100, spill_dir=str(tmp_path))
    a = np.arange(20, dtype=np.float32)  # 80 bytes
    b = np.arange(10, dtype=np.float32)  # 40 bytes -> a spills
    ds.store("a", a)
    ds.store("b", b)
    assert ds._spilled  # something went to disk
    np.testing.assert_array_equal(ds.fetch("a"), a)  # reload transparent
    np.testing.assert_array_equal(ds.fetch("b"), b)


def test_datastore_requires_spill_dir():
    with pytest.raises(ValueError):
        DataStore(max_mem_bytes=10)


def test_tile_cache_lru():
    built = []

    def build(r, c):
        built.append((r, c))
        return (r, c)

    tc = TileCache(build, max_items=2)
    assert tc.fetch(0, 0) == (0, 0)
    assert tc.fetch(0, 1) == (0, 1)
    assert tc.fetch(0, 0) == (0, 0)  # hit
    assert tc.hits == 1
    tc.fetch(0, 2)                   # evicts (0, 1)
    tc.fetch(0, 1)                   # rebuild
    assert built.count((0, 1)) == 2
    assert len(tc) == 2


def test_bcd_with_bounded_tile_cache(rcv1_path):
    """BCD converges identically with an LRU-bounded tile cache."""
    from difacto_tpu.learners import Learner

    def run(cache_items):
        learner = Learner.create("bcd")
        learner.init([("data_in", rcv1_path), ("l1", ".1"), ("lr", ".05"),
                      ("block_ratio", "1"), ("tail_feature_filter", "0"),
                      ("max_num_epochs", "3"), ("random_block", "0"),
                      ("tile_cache_items", str(cache_items))])
        seen = []
        learner.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
        learner.run()
        return seen, learner

    ref, unlimited = run(0)
    bounded, learner = run(1)  # forces rebuilds across blocks
    np.testing.assert_allclose(bounded, ref, rtol=1e-6)
    # the bounded cache must rebuild evicted tiles; unlimited builds once
    assert learner._tile_cache.misses > unlimited._tile_cache.misses
    assert len(learner._tile_cache) == 1


def test_tile_builder_shared():
    """data/tile_builder.py (the shared TileBuilder, tile_builder.h:17-183):
    dictionary accumulation across tiles, tail filter, colmaps."""
    import numpy as np
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.data.tile_builder import TileBuilder

    def blk(ids, label=1.0):
        return RowBlock(offset=np.array([0, len(ids)], dtype=np.int64),
                        label=np.array([label], dtype=np.float32),
                        index=np.array(ids, dtype=np.uint64))

    tb = TileBuilder()
    tb.add(blk([5, 7, 9]))
    tb.add(blk([7, 11]))
    tb.add(blk([5, 13]), is_train=False)  # val ids never enter the dict
    assert tb.nrows_train == 2 and tb.nrows_val == 1
    # dictionary is the union of TRAIN ids with summed counts; compact
    # stores ids byte-reversed (Localizer's uniform-keyspace trick), so
    # map back before comparing
    from difacto_tpu.base import reverse_bytes
    fwd = {int(reverse_bytes(np.uint64(x))): i
           for i, x in enumerate(tb.ids)}
    assert set(fwd) == {5, 7, 9, 11}
    assert tb.cnts[fwd[7]] == 2 and tb.cnts[fwd[5]] == 1

    # tail filter keeps count > 1 only
    kept = tb.filter_tail(1)
    assert [int(reverse_bytes(np.uint64(x))) for x in kept] == [7]
    # colmaps: tile 0's uniq [5,7,9] -> only 7 maps; val tile's 5 filtered
    cm0 = tb.colmap(0)
    assert (cm0 >= 0).sum() == 1
    cm2 = tb.colmap(2)
    assert (cm2 >= 0).sum() == 0  # val tile held {5, 13}, both filtered
