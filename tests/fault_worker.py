"""Fault-injection worker for tests/test_fault.py, run through
launch.py --max-restarts 1 with 2 processes.

On the first attempt (DIFACTO_RESTART=0), rank 1 kills itself in the
MIDDLE of epoch 1, simulating a dead host; two injection modes cover both
execution regimes:

- ``allgather`` (device cache off): dies at its 4th DCN allgather — after
  epoch 1's training batch but before the epoch's termination round. The
  survivor's heartbeat watchdog must abort its blocked control-plane
  collective.
- ``step`` (device cache on): dies entering its 2nd train step — the
  first REPLAYED step (epochs 1+ run from the device cache with no DCN
  handshakes at all). The survivor blocks inside the collective-bearing
  jitted step; the replay-wide watchdog guard must abort it.
- ``window`` (bounded-delay τ>0, ISSUE 16): trains at batch_size=10 so
  every epoch runs 5 windowed steps per host, and rank 1 dies at its
  7th clock post — MID-WINDOW in epoch 1, while the survivor's exchange
  pipeline may be up to τ steps ahead and its wait_clock barriers
  target rank 1's now-never-coming clock keys. The guarded waits /
  collectives must abort via the heartbeat watchdog, and the relaunched
  single process REJOINS AT THE CURRENT CLOCK (fault.restart_attempt
  namespaces the clock keys) and finishes the run windowed.

Either way the launcher evicts a host and relaunches a single process
that auto-resumes from the epoch-0 checkpoint and finishes the run over
ALL the data (byte-range re-sharding).

Usage: fault_worker.py <out_dir> <data_path> [epochs] [mode]
"""
import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from difacto_tpu.parallel.multihost import initialize  # noqa: E402

initialize()

attempt = os.environ.get("DIFACTO_RESTART", "0")
rank = jax.process_index()

out_dir, data = sys.argv[1], sys.argv[2]
epochs = int(sys.argv[3]) if len(sys.argv) > 3 else 4
mode = sys.argv[4] if len(sys.argv) > 4 else "allgather"


def _die():
    print(f"rank {rank}: simulating host death", flush=True)
    # die by signal, like a real dead host (OOM-kill / machine loss); the
    # launcher only restarts on signal death or a peer-dead exit code — a
    # plain rc=1 is a config error, not a fault
    import signal
    os.kill(os.getpid(), signal.SIGKILL)


if rank == 1 and attempt == "0" and mode == "allgather":
    import difacto_tpu.parallel.multihost as mh
    _orig, _calls = mh.control_allgather_np, {"n": 0}

    def _dying_allgather(arr):
        _calls["n"] += 1
        if _calls["n"] == 4:  # epoch 1, after its train batch: mid-epoch
            _die()
        return _orig(arr)

    mh.control_allgather_np = _dying_allgather

if rank == 1 and attempt == "0" and mode == "window":
    import difacto_tpu.parallel.multihost as mh
    _orig_post, _posts = mh.post_clock, {"n": 0}

    def _dying_post(gen, t):
        _posts["n"] += 1
        if _posts["n"] == 7:  # 5 steps/epoch: the 2nd step of epoch 1,
            _die()            # mid-τ-window after the epoch-0 ckpt
        return _orig_post(gen, t)

    mh.post_clock = _dying_post

from difacto_tpu.learners import Learner  # noqa: E402

nprocs = jax.process_count()
ln = Learner.create("sgd")
ln.init([("data_in", data), ("V_dim", "2"), ("V_threshold", "2"),
         ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
         # window mode: 5 windowed steps per host per epoch, so the
         # τ=2 wait_clock barriers genuinely engage before the kill
         ("batch_size", "10" if mode == "window" else "100"),
         ("max_num_epochs", str(epochs)),
         ("shuffle", "0"), ("report_interval", "0"),
         ("stop_rel_objv", "0"), ("stop_val_auc", "-2"),
         ("num_jobs_per_epoch", "1"),
         ("hash_capacity", str(1 << 20)),
         ("mesh_dp", str(nprocs)), ("mesh_fs", "4"),
         ("ckpt_interval", "1"), ("auto_resume", "1"),
         ("device_cache_mb", "0" if mode == "allgather" else "2048"),
         ("model_out", os.path.join(out_dir, "model"))])

if rank == 1 and attempt == "0" and mode == "step":
    from difacto_tpu.learners.sgd import K_TRAINING
    _orig_step, _calls = ln._train_step, {"n": 0}

    def _dying_step(*a, **kw):
        _calls["n"] += 1
        if _calls["n"] == 2:  # the first REPLAYED step (epoch 1)
            # this mode exists to exercise the replay-wide watchdog
            # guard: fail LOUDLY (non-recovery rc) if batch geometry
            # drift means this is not actually a replayed step
            cache = ln._dev_caches.get(K_TRAINING)
            if cache is None or not cache.ready:
                print("fault_worker: step-mode kill fired during a "
                      "STREAMED step — replay path not covered; fix the "
                      "kill trigger", flush=True)
                os._exit(3)
            _die()
        return _orig_step(*a, **kw)

    ln._train_step = _dying_step

seen = []
ln.add_epoch_end_callback(lambda e, t, v: seen.append((e, t.loss)))

from difacto_tpu.parallel.fault import HostFailure, exit_code_for  # noqa

try:
    ln.run()
except HostFailure as e:
    print(f"rank {rank}: {e}", flush=True)
    sys.exit(exit_code_for(e.dead))

with open(os.path.join(out_dir, f"traj-{rank}.json"), "w") as f:
    json.dump({"epochs": seen, "attempt": int(attempt),
               "nprocs": nprocs}, f)
print(f"rank {rank} done (attempt {attempt}): {seen}")
