"""rec2 zero-copy format + vectorized parser suite (ISSUE 7).

Covers the four contracts the streamed fast path rests on:

- **parser parity**: the bulk-numpy ``parse_libsvm``/``parse_criteo``
  are byte-identical to the per-line loop references
  (``parse_*_ref``) on the rcv1 fixture and on edge-case corpora
  (exponents, signs, implicit values, CRLF, 20-digit ids), including
  the mixed implicit/explicit value regression;
- **golden parity**: text-parsed, rec(v1 .npz)-read, and rec2-mmap'd
  RowBlocks are byte-identical per part;
- **robustness**: truncations and bit flips at random offsets raise a
  typed :class:`RecCorrupt` or read back exactly (flips in dead
  padding) — never a crash or a silent wrong array; the ``rec.read``
  fault-injection point fires through the same contract;
- **determinism**: thread-, process-, and rec2-streamed learner
  trajectories are equal, and streamed == replay on the same parts
  (extends the PR 1 determinism tests).
"""

import contextlib
import os
import signal

import numpy as np
import pytest

from difacto_tpu.data.parsers import (parse_criteo, parse_criteo_ref,
                                      parse_libsvm, parse_libsvm_ref)
from difacto_tpu.data.rec2 import (RecCorrupt, read_rec2, write_rec2)
from difacto_tpu.data.rowblock import RowBlock


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def assert_blocks_equal(a: RowBlock, b: RowBlock, what: str = "") -> None:
    """Byte-identical comparison: same arrays, same dtypes, same
    value/weight elision."""
    np.testing.assert_array_equal(a.offset, b.offset, err_msg=what)
    assert a.label.dtype == b.label.dtype
    np.testing.assert_array_equal(a.label, b.label, err_msg=what)
    assert a.index.dtype == b.index.dtype
    np.testing.assert_array_equal(a.index, b.index, err_msg=what)
    assert (a.value is None) == (b.value is None), what
    if a.value is not None:
        assert a.value.dtype == b.value.dtype
        np.testing.assert_array_equal(a.value, b.value, err_msg=what)
    assert (a.weight is None) == (b.weight is None), what
    if a.weight is not None:
        np.testing.assert_array_equal(a.weight, b.weight, err_msg=what)


# ------------------------------------------------------------- parsers
def test_parse_libsvm_mixed_implicit_explicit():
    """Regression (ISSUE 7 satellite): a chunk mixing implicit-value
    (``idx``) and explicit-value (``idx:val``) tokens must parse the
    implicit entries as value 1.0 — independent of which form the
    chunk's FIRST token took."""
    cases = [
        # explicit first: implicit 2 and 7 must still be 1.0
        (b"1 3:0.5 7\n0 2 4:2.0\n", {3: 0.5, 7: 1.0, 2: 1.0, 4: 2.0}),
        # implicit first: explicit values must not inherit 1.0
        (b"1 7 3:0.5\n0 4:2.0 2\n", {7: 1.0, 3: 0.5, 4: 2.0, 2: 1.0}),
        (b"-1 5 6 7:0.25\n", {5: 1.0, 6: 1.0, 7: 0.25}),
    ]
    for chunk, want in cases:
        for parser in (parse_libsvm, parse_libsvm_ref):
            blk = parser(chunk)
            assert blk.value is not None, parser.__name__
            got = dict(zip(blk.index.tolist(), blk.value.tolist()))
            assert got == want, (parser.__name__, chunk)
        assert_blocks_equal(parse_libsvm(chunk), parse_libsvm_ref(chunk),
                            f"mixed tokens {chunk!r}")
    # native parser (falls back to the python one when the .so is absent)
    from difacto_tpu.data.native_parsers import parse_libsvm_native
    chunk = b"1 3:0.5 7\n0 2 4:2.0\n"
    assert_blocks_equal(parse_libsvm_native(chunk), parse_libsvm_ref(chunk),
                        "native mixed tokens")


def test_parse_libsvm_all_implicit_elides_value():
    """All-implicit (binary) chunks elide the value array entirely."""
    for parser in (parse_libsvm, parse_libsvm_ref):
        blk = parser(b"1 3 7 9\n0 2\n")
        assert blk.value is None, parser.__name__
        np.testing.assert_array_equal(blk.index, [3, 7, 9, 2])
        np.testing.assert_array_equal(blk.offset, [0, 3, 4])


def test_parse_libsvm_vectorized_matches_reference_fixture(rcv1_path):
    with open(rcv1_path, "rb") as f:
        chunk = f.read()
    assert_blocks_equal(parse_libsvm(chunk), parse_libsvm_ref(chunk),
                        "rcv1 fixture")


def test_parse_libsvm_vectorized_edge_cases():
    cases = [
        b"",
        b"\n\n",
        b"1\n",                                   # label-only row
        b"1 2:3\r\n0 4:5e-3\r\n",                 # CRLF + exponent
        b"+1 10:+.5 11:-0.25 12:2.\n",            # signs, bare dot forms
        b"-1 1:1e2 2:1E-2 3:0.3e+1\n",            # exponent spellings
        b"0 18446744073709551615:1\n",            # uint64 max id
        b"1 3:0.033906222568727 4:1.7976e30\n",   # long mantissa, huge val
        b"  1   2:3  \n\t0\t4:5\t\n",             # leading/extra whitespace
        b"1 2:3",                                 # no trailing newline
        b"0.5 7:0.125\n-0.5 8:12345.6789\n",      # fractional labels
    ]
    for chunk in cases:
        assert_blocks_equal(parse_libsvm(chunk), parse_libsvm_ref(chunk),
                            f"case {chunk!r}")


def test_parse_libsvm_vectorized_random_corpus():
    """Fuzz parity: random valid libsvm text, vectorized == reference."""
    rng = np.random.RandomState(11)
    lines = []
    for _ in range(300):
        n = rng.randint(0, 6)
        toks = [f"{rng.choice([-1, 0, 1])}"]
        for _ in range(n):
            idx = rng.randint(0, 1 << 62)
            if rng.rand() < 0.3:
                toks.append(str(idx))          # implicit value
            elif rng.rand() < 0.5:
                toks.append(f"{idx}:{rng.rand():.9g}")
            else:
                toks.append(f"{idx}:{rng.randn() * 10 ** rng.randint(-8, 9):.12g}")
        lines.append(" ".join(toks))
    chunk = ("\n".join(lines) + "\n").encode()
    assert_blocks_equal(parse_libsvm(chunk), parse_libsvm_ref(chunk),
                        "random corpus")


def test_parse_libsvm_malformed_raises():
    for bad in (b"1 3:\n", b"1 :5\n", b"1 a:5\n", b"1 3:4:5\n",
                b"x 3:5\n", b"1 3:zz\n"):
        with pytest.raises(ValueError):
            parse_libsvm(bad)
        with pytest.raises(ValueError):
            parse_libsvm_ref(bad)


def _criteo_lines(rng, n):
    lines = []
    for _ in range(n):
        fields = [str(rng.randint(0, 2))]
        for _ in range(13):  # integer features, some empty
            fields.append("" if rng.rand() < 0.3
                          else str(rng.randint(0, 10000)))
        for _ in range(26):  # categorical hex-ish features, some empty
            fields.append("" if rng.rand() < 0.3
                          else "%08x" % rng.randint(0, 1 << 31))
        lines.append("\t".join(fields))
    return lines


def test_parse_criteo_vectorized_matches_reference():
    rng = np.random.RandomState(5)
    chunk = ("\n".join(_criteo_lines(rng, 200)) + "\n").encode()
    assert_blocks_equal(parse_criteo(chunk), parse_criteo_ref(chunk),
                        "criteo train")
    # test-mode (no leading label column)
    test_chunk = ("\n".join(l.split("\t", 1)[1]
                            for l in _criteo_lines(rng, 50)) + "\n").encode()
    assert_blocks_equal(parse_criteo(test_chunk, is_train=False),
                        parse_criteo_ref(test_chunk, is_train=False),
                        "criteo test-mode")
    # CRLF + missing trailing newline
    crlf = ("\r\n".join(_criteo_lines(rng, 20))).encode()
    assert_blocks_equal(parse_criteo(crlf), parse_criteo_ref(crlf),
                        "criteo crlf")
    assert_blocks_equal(parse_criteo(b""), parse_criteo_ref(b""), "empty")


# ------------------------------------------------------ rec2 round trip
def _sample_arrays(rng):
    n, nnz = 57, 411
    off = np.zeros(n + 1, np.int64)
    off[1:] = np.sort(rng.randint(0, nnz, n))
    off[-1] = nnz
    return {
        "offset": off,
        "label": rng.rand(n).astype(np.float32),
        "index": rng.randint(0, 1 << 62, nnz).astype(np.uint64),
        "value": rng.randn(nnz).astype(np.float32),
        "weight": rng.rand(n).astype(np.float32),
        "uniq": np.sort(rng.randint(0, 1 << 62, 97).astype(np.uint64)),
    }


def test_rec2_roundtrip_and_zero_copy(tmp_path):
    rng = np.random.RandomState(3)
    arrays = _sample_arrays(rng)
    path = str(tmp_path / "blk.rec2")
    write_rec2(path, arrays)
    got = read_rec2(path)
    assert set(got) == set(arrays)
    for k, a in arrays.items():
        assert got[k].dtype == a.dtype, k
        np.testing.assert_array_equal(got[k], a, err_msg=k)
        # zero-copy: the arrays view the mmap, they don't own their bytes
        assert not got[k].flags["OWNDATA"], k
    # page alignment of every section (the mmap/memcpy contract)
    import mmap as _mmap
    from difacto_tpu.data import rec2 as _r2
    with open(path, "rb") as f:
        raw = f.read()
    n_sections = _r2._HEAD.unpack_from(raw, 0)[2]
    for i in range(n_sections):
        _, _, off, _ = _r2._SECT.unpack_from(raw,
                                             _r2._HEAD.size + i * 32)
        assert off % _r2.PAGE == 0


def test_rec2_rejects_unknown_section(tmp_path):
    with pytest.raises(ValueError):
        write_rec2(str(tmp_path / "x.rec2"),
                   {"bogus": np.zeros(3, np.int64)})


def test_rec2_reader_dispatch(tmp_path):
    """rec.py reads .rec2 and .npz members transparently from one dir."""
    from difacto_tpu.data.rec import (read_rec_block_ex, rec_members,
                                      write_rec_block)
    rng = np.random.RandomState(9)
    a = _sample_arrays(rng)
    blk = RowBlock(offset=a["offset"], label=a["label"], index=a["index"],
                   value=a["value"], weight=a["weight"])
    d = tmp_path / "cache.rec"
    d.mkdir()
    write_rec_block(str(d / "part-00000.rec2"), blk)
    write_rec_block(str(d / "part-00001.npz"), blk)
    (d / "stray.tmp").write_bytes(b"junk")  # must be ignored
    members = rec_members([str(d)])
    assert sorted(os.path.basename(m) for m, _ in members) == \
        ["part-00000.rec2", "part-00001.npz"]
    b2, u2 = read_rec_block_ex(str(d / "part-00000.rec2"))
    b1, u1 = read_rec_block_ex(str(d / "part-00001.npz"))
    assert u1 is None and u2 is None
    assert_blocks_equal(b1, b2, "npz vs rec2 member")


# ------------------------------------------------------- golden parity
def test_golden_parity_text_rec_rec2(rcv1_path, tmp_path):
    """Text-parsed, rec(v1 .npz)-read and rec2-mmap'd RowBlocks are
    byte-identical per part (ISSUE 7 satellite). Localization is OFF so
    members carry the raw text-parsed arrays verbatim."""
    from difacto_tpu.data import Reader
    from difacto_tpu.data.converter import Converter
    from difacto_tpu.data.rec import iter_rec_blocks, rec_members

    def convert(encoding: str, out: str):
        conv = Converter()
        conv.init([("data_in", rcv1_path), ("data_format", "libsvm"),
                   ("data_out", out), ("data_out_format", "rec"),
                   ("rec_encoding", encoding), ("rec_localize", "0"),
                   ("rec_batch_size", "32"), ("convert_procs", "1")])
        conv.run()
        return conv

    c2 = convert("rec2", str(tmp_path / "v2.rec"))
    convert("npz", str(tmp_path / "v1.rec"))
    assert c2.stats["eps"] > 0 and c2.stats["rows"] == 100

    text_blocks = list(Reader(rcv1_path, "libsvm"))
    text_rows = RowBlock.concat(text_blocks)
    for enc, out in (("rec2", "v2.rec"), ("npz", "v1.rec")):
        members = rec_members([str(tmp_path / out)])
        suffix = ".rec2" if enc == "rec2" else ".npz"
        assert all(m.endswith(suffix) for m, _ in members), enc
        blocks = list(iter_rec_blocks([str(tmp_path / out)], 0, 1))
        got = RowBlock.concat(blocks)
        assert [b.size for b in blocks] == [32, 32, 32, 4], enc
        assert_blocks_equal(got, text_rows, f"{enc} vs text")


def test_parallel_convert_matches_serial(rcv1_path, tmp_path):
    """convert_procs=2 produces the same row multiset and stats as the
    serial path (members differ only in naming/boundaries)."""
    from difacto_tpu.data.converter import Converter
    from difacto_tpu.data.rec import iter_rec_blocks

    def convert(procs: int, out: str):
        conv = Converter()
        conv.init([("data_in", rcv1_path), ("data_format", "libsvm"),
                   ("data_out", out), ("data_out_format", "rec"),
                   ("rec_localize", "0"), ("rec_batch_size", "32"),
                   ("convert_procs", str(procs))])
        conv.run()
        return conv

    with deadline(120):
        c1 = convert(1, str(tmp_path / "serial.rec"))
        c2 = convert(2, str(tmp_path / "par.rec"))
    assert c1.stats["rows"] == c2.stats["rows"] == 100
    assert c2.stats["procs"] == 2 and c2.stats["members"] >= 2
    assert c2.stats["eps"] > 0 and c2.stats["parse_s"] >= 0

    def row_multiset(out):
        rows = set()
        for blk in iter_rec_blocks([out], 0, 1):
            for r in range(blk.size):
                s, e = int(blk.offset[r]), int(blk.offset[r + 1])
                val = (blk.value[s:e].tobytes() if blk.value is not None
                       else b"")
                rows.add((float(blk.label[r]),
                          blk.index[s:e].tobytes(), val))
        return rows

    assert row_multiset(str(tmp_path / "serial.rec")) == \
        row_multiset(str(tmp_path / "par.rec"))


# --------------------------------------------------------- robustness
def test_rec2_truncation_always_typed(tmp_path):
    """EVERY strict truncation raises RecCorrupt — never a crash, never
    a silent short read."""
    rng = np.random.RandomState(21)
    path = str(tmp_path / "t.rec2")
    write_rec2(path, _sample_arrays(rng))
    full = open(path, "rb").read()
    cuts = sorted({0, 1, 7, 8, len(full) // 2, len(full) - 1}
                  | {int(x) for x in rng.randint(0, len(full), 40)})
    for cut in cuts:
        with open(path, "wb") as f:
            f.write(full[:cut])
        with pytest.raises(RecCorrupt):
            read_rec2(path)
    # the un-truncated file still reads
    with open(path, "wb") as f:
        f.write(full)
    assert read_rec2(path)


def test_rec2_bitflip_never_silent_wrong(tmp_path):
    """Bit flips at random offsets either raise RecCorrupt or leave the
    decoded arrays exactly equal (flips in dead padding) — a flipped
    data/header/table byte can never surface as silently wrong arrays."""
    rng = np.random.RandomState(22)
    arrays = _sample_arrays(rng)
    path = str(tmp_path / "b.rec2")
    write_rec2(path, arrays)
    full = bytearray(open(path, "rb").read())
    flips = 0
    for off in rng.randint(0, len(full), 120):
        bit = 1 << rng.randint(0, 8)
        mut = bytearray(full)
        mut[off] ^= bit
        with open(path, "wb") as f:
            f.write(mut)
        try:
            got = read_rec2(path)
        except RecCorrupt:
            flips += 1
            continue
        for k, a in arrays.items():
            np.testing.assert_array_equal(
                got[k], a, err_msg=f"silent corruption at byte {off}")
    assert flips > 0  # the CRCs actually caught real flips


def test_rec2_faultinject_read_point(tmp_path):
    """The rec.read chaos point: ``truncate`` must surface as a typed
    RecCorrupt (CRC rejection of the half-length view), ``err`` as the
    injected OSError — and both must actually fire."""
    from difacto_tpu.utils import faultinject
    rng = np.random.RandomState(23)
    path = str(tmp_path / "f.rec2")
    write_rec2(path, _sample_arrays(rng))
    try:
        faultinject.configure("rec.read:truncate@1")
        with pytest.raises(RecCorrupt):
            read_rec2(path)
        assert faultinject.stats()["rec.read"] == 1
        faultinject.configure("rec.read:err@1")
        with pytest.raises(OSError):
            read_rec2(path)
        assert faultinject.stats()["rec.read"] == 1
    finally:
        faultinject.configure("")
    assert read_rec2(path)  # disarmed: reads fine again


# -------------------------------------------------------- determinism
def _run_learner(data_in, data_format, producer_mode="thread",
                 cache_mb=0, n_jobs=2):
    from difacto_tpu.learners import Learner
    ln = Learner.create("sgd")
    ln.init([("data_in", data_in), ("data_format", data_format),
             ("V_dim", "0"), ("l2", "1"), ("l1", "1"), ("lr", "1"),
             ("num_jobs_per_epoch", str(n_jobs)), ("batch_size", "50"),
             ("max_num_epochs", "2"), ("shuffle", "0"),
             ("report_interval", "0"), ("stop_rel_objv", "0"),
             ("device_cache_mb", str(cache_mb)),
             ("producer_mode", producer_mode),
             ("hash_capacity", "4096"), ("num_producers", "1")])
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append((t.nrows, t.loss)))
    ln.run()
    return seen


def test_trajectories_thread_process_rec2_and_replay(rcv1_path, tmp_path):
    """ISSUE 7 acceptance: thread-, process-, and rec2-streamed
    trajectories are equal, and streamed == replay on the same parts."""
    from difacto_tpu.data.converter import Converter
    conv = Converter()
    conv.init([("data_in", rcv1_path), ("data_format", "libsvm"),
               ("data_out", str(tmp_path / "rcv1.rec")),
               ("data_out_format", "rec"), ("rec_batch_size", "50"),
               ("convert_procs", "1")])
    conv.run()
    rec_uri = str(tmp_path / "rcv1.rec")

    with deadline(600):
        rec2_thread = _run_learner(rec_uri, "rec")
        rec2_process = _run_learner(rec_uri, "rec",
                                    producer_mode="process")
        rec2_replay = _run_learner(rec_uri, "rec", cache_mb=512)
        # single part: text and rec2 see identical 50-row batches in
        # identical order (two parts would split text by byte range but
        # rec by member, shifting batch boundaries)
        text_1 = _run_learner(rcv1_path, "libsvm", n_jobs=1)
        rec2_1 = _run_learner(rec_uri, "rec", n_jobs=1)

    # same transport, same parts: byte-identical trajectories
    assert rec2_thread == rec2_process
    assert rec2_thread == rec2_replay  # streamed == replay
    # text-streamed vs rec2-streamed on the same batches: identical
    assert text_1 == rec2_1
