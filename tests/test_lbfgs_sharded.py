"""Mesh-sharded L-BFGS (round-3 verdict #6): the flat [w, V...] vector —
and with it every gradient, direction and s/y history vector — is sharded
over an 8-device fs mesh; the 6m+1 Gram inner products become XLA psums
(the reference allreduced them across servers via SendJobAndWait,
src/common/learner_utils.h:21-51, src/lbfgs/lbfgs_updater.h:84-121).

The golden trajectories must be REPRODUCED, not approximated: sharding a
reduction changes the machine, not the math (fp summation order may differ
at 1e-7; the goldens tolerate 1e-5).
"""

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from tests.test_lbfgs import OBJV_BASIC, OBJV_WITHV


def run_sharded(rcv1_path, **over):
    args = {"data_in": rcv1_path, "m": "5", "V_dim": "0", "l2": "0",
            "init_alpha": "1", "tail_feature_filter": "0",
            "max_num_epochs": "19", "mesh_fs": "8"}
    args.update({k: str(v) for k, v in over.items()})
    learner = Learner.create("lbfgs")
    remain = learner.init(list(args.items()))
    assert remain == []
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    return learner, np.array(seen)


def _assert_actually_sharded(learner, n_dev=8):
    w = learner.weights
    devs = {s.device for s in w.addressable_shards}
    assert len(devs) == n_dev
    for s in w.addressable_shards:
        assert s.data.shape[0] == w.shape[0] // n_dev


def test_lbfgs_sharded_basic_golden(rcv1_path):
    learner, seen = run_sharded(rcv1_path)
    _assert_actually_sharded(learner)
    err = np.abs(seen - np.array(OBJV_BASIC))
    assert err.max() < 1e-5, list(zip(seen, OBJV_BASIC))


def test_lbfgs_sharded_fm_golden(rcv1_path):
    """The FM (V_dim=5) trajectory with the deterministic initializer,
    sharded (tests/cpp/lbfgs_learner_test.cc:88-146; tolerance rationale in
    tests/test_lbfgs.py test_lbfgs_withv_golden)."""
    args = {"data_in": rcv1_path, "m": "5", "V_dim": "5", "l2": "0.1",
            "V_l2": "0.01", "V_threshold": "0", "rho": "0.5",
            "init_alpha": "1", "tail_feature_filter": "0",
            "max_num_epochs": str(len(OBJV_WITHV)), "mesh_fs": "8"}
    learner = Learner.create("lbfgs")
    assert learner.init(list(args.items())) == []

    def initializer(lens, weights):
        # (lbfgs_learner_test.cc:128-140): V[j] = (j - V_dim/2) * .01
        n = 0
        for l in lens:
            for i in range(l):
                if i > 0:
                    weights[n] = (i - (l - 1) / 2) * 0.01
                n += 1
        return weights

    learner.set_weight_initializer(initializer)
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    _assert_actually_sharded(learner)
    err = np.abs(np.array(seen) - np.array(OBJV_WITHV))
    assert err.max() < 2e-4, list(zip(seen, OBJV_WITHV))


def test_lbfgs_sharded_ckpt_roundtrip(rcv1_path, tmp_path):
    """Sharded save -> load -> identical weights and re-sharded layout."""
    learner, _ = run_sharded(rcv1_path, max_num_epochs="3",
                             model_out=str(tmp_path / "m"))
    w0 = np.asarray(learner.weights)
    other = Learner.create("lbfgs")
    other.init([("data_in", rcv1_path), ("m", "5"), ("V_dim", "0"),
                ("l2", "0"), ("mesh_fs", "8")])
    other.load(str(tmp_path / "m"))
    _assert_actually_sharded(other)
    np.testing.assert_allclose(np.asarray(other.weights)[:other.N],
                               w0[:learner.N])
