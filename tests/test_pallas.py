"""Pallas kernel correctness on CPU interpret mode vs jnp references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from difacto_tpu.ops.pallas_kernels import gather_rows, scatter_add_rows


@pytest.mark.parametrize("n,w", [(16, 128), (8, 256), (32, 8)])
def test_gather_rows_matches_take(n, w):
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(64, w).astype(np.float32))
    idx = jnp.asarray(rng.permutation(64)[:n].astype(np.int32))
    got = gather_rows(table, idx, interpret=True)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(table)[np.asarray(idx)])


@pytest.mark.parametrize("n,w", [(16, 128), (32, 8)])
def test_scatter_add_rows_matches_at_add(n, w):
    rng = np.random.RandomState(1)
    table_np = rng.randn(64, w).astype(np.float32)
    idx_np = rng.permutation(64)[:n].astype(np.int32)  # unique
    upd_np = rng.randn(n, w).astype(np.float32)
    want = table_np.copy()
    want[idx_np] += upd_np
    got = scatter_add_rows(jnp.asarray(table_np), jnp.asarray(idx_np),
                           jnp.asarray(upd_np), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_gather_then_scatter_roundtrip():
    """Pull rows, modify, push back — the store hot-path shape."""
    rng = np.random.RandomState(2)
    table = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    idx = jnp.asarray(np.array([3, 7, 1, 30, 12, 25, 0, 31],
                               dtype=np.int32))
    rows = gather_rows(table, idx, interpret=True)
    delta = -0.1 * rows
    out = scatter_add_rows(table, idx, delta, interpret=True)
    np.testing.assert_allclose(np.asarray(out)[np.asarray(idx)],
                               np.asarray(rows) * 0.9, rtol=1e-5)
