"""Shared endpoint health (serve/fleethealth.py): the fleet-wide
blacklist file under the conditions that would corrupt a naive design —
concurrent writers from separate processes, stale entries, and the
client-side contract that a blacklisted endpoint is skipped on the FIRST
connect (no timeout paid) while the timed re-probe still clears it.

Part of the fleet suite (``chaos`` marker, tier-1): ``make fleet-chaos``
selects these together with the rolling-restart/router chaos tests.
"""

import contextlib
import json
import pathlib
import signal
import socket
import subprocess
import sys
import time

import pytest

from difacto_tpu.serve.fleethealth import FleetHealth, open_blacklist

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.chaos


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def test_fleethealth_concurrent_writers_two_processes(tmp_path):
    """Two separate PROCESSES hammer the same blacklist file with
    interleaved down/clear marks; the advisory-locked O_APPEND protocol
    must leave every line intact — exact count, all parseable — not a
    torn or interleaved log."""
    bl = str(tmp_path / "blacklist")
    module = str(REPO / "difacto_tpu" / "serve" / "fleethealth.py")
    worker = str(REPO / "tests" / "fleethealth_worker.py")
    n = 200
    with deadline(120):
        procs = [subprocess.Popen(
            [sys.executable, worker, module, bl, tag, str(n)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            for tag in ("a", "b")]
        for p in procs:
            out, err = p.communicate(timeout=90)
            assert p.returncode == 0, err.decode()[-2000:]
    lines = open(bl, "rb").read().splitlines()
    assert len(lines) == 2 * n, f"expected {2*n} marks, got {len(lines)}"
    for ln in lines:
        rec = json.loads(ln)   # every line parses — no torn appends
        assert rec["op"] in ("down", "clear") and ":" in rec["ep"]
    # the fold sees both writers' endpoints
    fh = FleetHealth(bl, down_s=60.0)
    downs = fh.down_endpoints()
    assert any(ep.startswith("host-a") for ep in downs)
    assert any(ep.startswith("host-b") for ep in downs)


def test_fleethealth_stale_entries_reprobe_and_clear(tmp_path):
    """A down mark only suppresses for ``down_s`` (the timed re-probe
    window), and an explicit clear lifts it immediately — plus a fresh
    reader handle sees both transitions through the file."""
    bl = str(tmp_path / "blacklist")
    fh = FleetHealth(bl, down_s=0.3)
    fh.mark_down("h", 9000)
    assert fh.is_down("h", 9000)
    # (<= down_s + 1ms: the mark's wall timestamp is rounded to 1ms)
    assert 0.0 < fh.down_remaining("h", 9000) <= 0.301
    # a second handle (another process's view) folds the same state
    assert FleetHealth(bl, down_s=0.3).is_down("h", 9000)
    time.sleep(0.35)
    assert not fh.is_down("h", 9000), \
        "stale down mark outlived its re-probe window"
    # a successful probe clears fleet-wide, ahead of the window
    fh.mark_down("h", 9000)
    assert fh.is_down("h", 9000)
    fh.mark_up("h", 9000)
    assert not fh.is_down("h", 9000)
    assert not FleetHealth(bl, down_s=0.3).is_down("h", 9000)
    # unrelated endpoints never blur
    fh.mark_down("other", 9001)
    assert not fh.is_down("h", 9000) and fh.is_down("other", 9001)


def test_fleethealth_missing_and_torn_files_degrade_clean(tmp_path):
    """Shared health is an optimization, never a dependency: a missing
    file reads as nothing-down, and garbage/torn lines are skipped
    while intact marks still fold."""
    fh = FleetHealth(str(tmp_path / "never_written"))
    assert fh.down_endpoints() == {}
    bl = str(tmp_path / "torn")
    good = FleetHealth(bl, down_s=60.0)
    good.mark_down("h", 1)
    with open(bl, "ab") as f:
        f.write(b'{"ts": 1, "op"')   # a writer died mid-append
    good.mark_down("h2", 2)
    downs = FleetHealth(bl, down_s=60.0).down_endpoints()
    assert set(downs) == {"h:1", "h2:2"}
    # open_blacklist coerces paths and passes handles through
    assert open_blacklist(None) is None
    assert open_blacklist(good) is good
    assert isinstance(open_blacklist(bl), FleetHealth)


def test_fleethealth_compaction_bounds_file(tmp_path):
    """Past ``max_bytes`` the appender folds the log in place: the file
    stays bounded and only live down marks survive."""
    bl = str(tmp_path / "blacklist")
    fh = FleetHealth(bl, down_s=60.0, max_bytes=2048)
    for k in range(200):
        fh.mark_down("h", 7000 + (k % 3))
        fh.mark_up("h", 7000 + (k % 3))
    fh.mark_down("live", 8000)
    size = pathlib.Path(bl).stat().st_size
    assert size < 2048 + 512, f"compaction never ran: {size} bytes"
    assert FleetHealth(bl, down_s=60.0).is_down("live", 8000)


def _dead_endpoint():
    """A (host, port) that refuses connections: bind, record, close."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    host, port = s.getsockname()[:2]
    s.close()
    return host, port


def test_fleethealth_client_skips_blacklisted_on_first_connect(tmp_path):
    """A ServeClient seeded with a blacklisted endpoint never dials it:
    zero connect failures, zero failovers — the whole point of sharing
    the discovery — and one client's ejection seeds the next client."""
    from difacto_tpu.serve import ServeClient, ServeServer
    from difacto_tpu.serve.executor import PredictExecutor  # noqa: F401
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  set_all_live)

    param = SGDUpdaterParam(V_dim=4, l1_shrk=False, hash_capacity=4096)
    store = SlotStore(param, read_only=True)
    store.state = set_all_live(param, store.state)
    with deadline(120):
        try:
            srv = ServeServer(store, batch_size=8,
                              max_delay_ms=1.0).start()
        except OSError as e:  # pragma: no cover - loaded CI box
            pytest.skip(f"cannot bind a serving port: {e}")
        dead = _dead_endpoint()
        bl = str(tmp_path / "blacklist")
        try:
            # client A discovers the dead endpoint the hard way: its
            # ejection (eject_after=1: one connect failure is enough —
            # the client fails over and never revisits, so a higher
            # threshold would never trip here) lands in the shared file
            with ServeClient(endpoints=[dead, (srv.host, srv.port)],
                             retries=3, eject_after=1, backoff_s=0.01,
                             blacklist=bl) as ca:
                assert ca.failovers >= 1
                eh = ca.endpoints_health()
                assert eh[0]["ejected"] and eh[0]["ejections"] >= 1
                assert ca.predict([b"0 5:1 17:1"])[0] is not None
                assert eh[1]["host"] == srv.host
            assert FleetHealth(bl, down_s=5.0).is_down(*dead)
            # client B is seeded: FIRST connect skips the dead endpoint
            # entirely — no dial, no failure, no failover
            with ServeClient(endpoints=[dead, (srv.host, srv.port)],
                             retries=1, blacklist=bl) as cb:
                assert cb.failovers == 0
                assert (cb.host, cb.port) == (srv.host, srv.port)
                eh = cb.endpoints_health()
                assert eh[0]["ejected"] and eh[0]["fails"] == 0
                got = cb.predict([b"0 5:1 17:1", b"0 3:2"])
                assert all(g is not None for g in got)
                # the live endpoint carried every row
                assert cb.endpoints_health()[1]["rows"] >= 2
        finally:
            srv.close()


def test_fleethealth_three_writers_compaction_races_reader(tmp_path):
    """The N-router-group condition (ISSUE 18): THREE separate writer
    processes append through in-place compaction (max_bytes small
    enough that every writer compacts the shared file repeatedly) while
    this process's reader hammers the fold the whole time. The reader
    never errors, every surviving line parses, and each writer's FINAL
    per-endpoint state survives the compaction races — no mark lost."""
    import threading

    bl = str(tmp_path / "blacklist")
    module = str(REPO / "difacto_tpu" / "serve" / "fleethealth.py")
    worker = str(REPO / "tests" / "fleethealth_worker.py")
    n = 300
    stop = threading.Event()
    reader_errs: list = []
    reads = [0]
    reader = FleetHealth(bl, down_s=3600.0)

    def hammer():
        while not stop.is_set():
            try:
                downs = reader.down_endpoints()
                if not isinstance(downs, dict):
                    reader_errs.append(f"bad fold: {downs!r}")
            except Exception as e:  # noqa: BLE001 - the assertion
                reader_errs.append(repr(e))
            reads[0] += 1

    th = threading.Thread(target=hammer)
    with deadline(120):
        th.start()
        try:
            procs = [subprocess.Popen(
                [sys.executable, worker, module, bl, tag, str(n),
                 "4096"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE)
                for tag in ("a", "b", "c")]
            for p in procs:
                out, err = p.communicate(timeout=90)
                assert p.returncode == 0, err.decode()[-2000:]
        finally:
            stop.set()
            th.join()
    assert not reader_errs, reader_errs[:5]
    assert reads[0] > 0
    # every line of the (compacted) survivor parses; the torn-tail
    # healing newline may leave blank lines, which every fold skips
    for ln in open(bl, "rb").read().splitlines():
        if not ln.strip():
            continue
        rec = json.loads(ln)
        assert rec["op"] in ("down", "clear") and ":" in rec["ep"]
    # no mark lost: each writer's last op per endpoint is deterministic
    # (its own append order), so the fold must show exactly the
    # endpoints whose final mark was a down
    downs = FleetHealth(bl, down_s=3600.0).down_endpoints()
    for tag in ("a", "b", "c"):
        expect_down = set()
        for j in range(7):
            last_k = max(k for k in range(n) if k % 7 == j)
            if last_k % 2 == 0:   # worker: even k marks down
                expect_down.add(f"host-{tag}:{1000 + j}")
        got = {ep for ep in downs if ep.startswith(f"host-{tag}:")}
        assert got == expect_down, (tag, got, expect_down)


def test_fleethealth_long_lived_client_sees_marks_after_connect(tmp_path):
    """The seed-once bugfix (ISSUE 18 satellite): a client constructed
    BEFORE any mark exists still absorbs marks written afterwards — the
    next endpoint selection re-folds on the file's (mtime, size) change
    and routes around the marked endpoint without burning a dial, a
    failure, or a failover on it."""
    from difacto_tpu.serve import ServeClient, ServeServer
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import (SGDUpdaterParam,
                                                  set_all_live)

    param = SGDUpdaterParam(V_dim=4, l1_shrk=False, hash_capacity=4096)
    store = SlotStore(param, read_only=True)
    store.state = set_all_live(param, store.state)
    with deadline(120):
        try:
            srv_a = ServeServer(store, batch_size=8,
                                max_delay_ms=1.0).start()
            srv_b = ServeServer(store, batch_size=8,
                                max_delay_ms=1.0).start()
        except OSError as e:  # pragma: no cover - loaded CI box
            pytest.skip(f"cannot bind a serving port: {e}")
        bl = str(tmp_path / "blacklist")
        try:
            # constructed against an EMPTY blacklist, connected to A
            with ServeClient(endpoints=[(srv_a.host, srv_a.port),
                                        (srv_b.host, srv_b.port)],
                             retries=1, blacklist=bl) as c:
                assert c.predict([b"0 5:1 17:1"])[0] is not None
                assert (c.host, c.port) == (srv_a.host, srv_a.port)
                # between bursts the connection is down (idle drop /
                # server rotation); meanwhile A dies and ANOTHER client
                # publishes the discovery
                c.close()
                a_ep = (srv_a.host, srv_a.port)
                srv_a.close()
                FleetHealth(bl, down_s=30.0).mark_down(*a_ep)
                # the reconnect re-folds the moved file and side-steps
                # A before dialing: no dial, no failure, no failover
                assert c.predict([b"0 5:1 17:1"])[0] is not None
                assert c.failovers == 0, c.endpoints_health()
                eh = c.endpoints_health()
                assert eh[0]["fails"] == 0 and eh[0]["ejected"], eh
                assert (c.host, c.port) == (srv_b.host, srv_b.port)
        finally:
            srv_a.close()
            srv_b.close()
