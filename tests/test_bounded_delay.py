"""Bounded-delay (τ) windowed training parity (ISSUE 16).

The τ=0 acceptance gate: the windowed exchange schedule must reproduce
the synchronous SPMD trajectory BYTE-IDENTICALLY — τ only deepens the
staging pipeline and adds a clock-vector barrier; device steps stay
collective-synchronous on the global mesh, so no gradient ever moves.
Covered twice:

- single-process fast path: ``bounded_delay > 0`` with a mesh engages
  the same windowed SPMD schedule (clock posts take their
  single-process early returns), so τ in {1, 4} must match the plain
  synchronous run bit for bit — no launcher needed;
- two-process sim (behind ``two_process_launch``): launch.py
  ``--bounded-delay 4`` plumbs τ through the cluster env
  (DIFACTO_BOUNDED_DELAY) and the windowed 2-host run must match the
  τ=0 2-host run and the single-host reference.
"""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

from conftest import two_process_launch

REPO = pathlib.Path(__file__).resolve().parent.parent
EPOCHS = 3


def _train_inprocess(rcv1_path, tmp_path, tau, tag):
    from difacto_tpu.learners import Learner
    conf = {"data_in": rcv1_path, "V_dim": "2", "V_threshold": "2",
            "lr": "0.1", "l1": "0.1", "l2": "0",
            "batch_size": "100", "max_num_epochs": str(EPOCHS),
            "shuffle": "0", "report_interval": "0",
            "stop_rel_objv": "0", "stop_val_auc": "-2",
            "num_jobs_per_epoch": "1", "hash_capacity": str(1 << 20),
            "mesh_dp": "2", "mesh_fs": "4",
            # a single host streams the WHOLE file: rcv1's ~96 nnz/row
            # batches exceed the bucket(batch*64) auto cap
            "nnz_cap": "16384",
            "model_out": str(tmp_path / f"model_{tag}"),
            "bounded_delay": str(tau)}
    ln = Learner.create("sgd")
    ln.init(list(conf.items()))
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    ln.run()
    return seen


def test_windowed_schedule_tau0_byte_identical(rcv1_path, tmp_path):
    """Single-process fast path: τ>0 engages the windowed SPMD schedule
    and must reproduce the plain synchronous trajectory exactly."""
    ref = _train_inprocess(rcv1_path, tmp_path, 0, "t0")
    assert len(ref) == EPOCHS
    for tau in (1, 4):
        got = _train_inprocess(rcv1_path, tmp_path, tau, f"t{tau}")
        np.testing.assert_allclose(got, ref, rtol=0, atol=0)


def test_windowed_schedule_staleness_metrics(rcv1_path, tmp_path):
    """τ>0 runs account their window: the staleness gauge, the exchange
    wait counter and the push-delay histogram all exist in the global
    registry (docs/observability.md catalog)."""
    from difacto_tpu.obs import REGISTRY
    _train_inprocess(rcv1_path, tmp_path, 2, "metrics")
    snap = REGISTRY.snapshot()
    assert "train_staleness_batches" in snap.get("gauges", {})
    # single process never blocks on a peer clock, but the counter is
    # registered the moment the window opens
    assert "exchange_wait_seconds_total" in snap.get("counters", {})
    assert REGISTRY.value("exchange_wait_seconds_total") == 0.0
    hist = snap.get("hists", {}).get("push_delay_batches")
    assert hist, "push_delay_batches histogram missing"
    # one observation per dispatched windowed step
    data = REGISTRY.histogram("push_delay_batches").data()
    assert data["count"] >= EPOCHS  # at least one step per epoch


@two_process_launch
def test_two_process_bounded_delay_matches_sync(rcv1_path, tmp_path):
    """launch.py --bounded-delay 4 (cluster-env τ plumbing) must yield
    the τ=0 two-process trajectory byte-for-byte on both ranks."""
    sync = _launch_two(tmp_path / "sync", rcv1_path, 7951)
    wind = _launch_two(tmp_path / "wind", rcv1_path, 7955,
                       launch_extra=["--bounded-delay", "4"])
    for rank in range(2):
        np.testing.assert_allclose(wind[rank]["train"],
                                   sync[rank]["train"], rtol=0, atol=0)
    np.testing.assert_allclose(wind[0]["train"], wind[1]["train"],
                               rtol=0, atol=0)
    assert len(wind[0]["train"]) == EPOCHS


def _launch_two(out_dir, data, port, launch_extra=()):
    out_dir.mkdir(parents=True, exist_ok=True)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", str(port), *launch_extra, "--",
         sys.executable, str(REPO / "tests" / "spmd_worker.py"),
         str(out_dir), data, str(EPOCHS), ""],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    trajs = []
    for rank in range(2):
        with open(out_dir / f"traj-{rank}.json") as f:
            trajs.append(json.load(f))
    return trajs
