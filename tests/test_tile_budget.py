"""Bounded learner memory (round-3 verdict #7): a device-tile cache
smaller than the tile set must still reproduce the golden results, with
evicted tiles rebuilt on demand; and the TileCache byte budget itself.
"""

import numpy as np
import pytest

from difacto_tpu.data.tile_store import TileCache
from difacto_tpu.learners import Learner
from tests.test_lbfgs import OBJV_BASIC


def test_tilecache_byte_budget_evicts_and_rebuilds():
    builds = []

    def build(r, c):
        builds.append((r, c))
        return np.zeros(1024, np.uint8)  # 1 KB per tile

    c = TileCache(build, max_bytes=3 << 10)
    for i in range(5):
        c.fetch(0, i)
    assert len(c) == 3 and c.nbytes == 3 << 10
    c.fetch(0, 2)                    # hit (recent)
    assert c.hits == 1
    c.fetch(0, 0)                    # evicted -> rebuilt
    assert builds.count((0, 0)) == 2


def test_tilecache_none_tiles_counted_free():
    c = TileCache(lambda r, f: None, max_bytes=1 << 10)
    for i in range(8):
        c.fetch(0, i)
    assert len(c) == 8 and c.nbytes == 0


def test_lbfgs_golden_with_tiny_tile_cache(rcv1_path):
    """Many small tiles (tiny chunk size), cache budget far below the
    tile set: the 19-epoch golden trajectory must be bit-comparable and
    rebuild-on-miss must actually fire."""
    learner = Learner.create("lbfgs")
    remain = learner.init([
        ("data_in", rcv1_path), ("m", "5"), ("V_dim", "0"), ("l2", "0"),
        ("init_alpha", "1"), ("tail_feature_filter", "0"),
        ("max_num_epochs", "19"),
        ("data_chunk_size", "0.003"),   # ~3 KB text chunks -> many tiles
        ("tile_cache_mb", "1")])
    assert remain == []
    seen = []
    learner.add_epoch_end_callback(lambda e, prog: seen.append(prog.objv))
    learner.run()
    err = np.abs(np.array(seen) - np.array(OBJV_BASIC))
    assert err.max() < 1e-5, list(zip(seen, OBJV_BASIC))
    assert learner._n_tiles["train"] > 1
    cache = learner._tile_cache
    assert cache is not None
    # every epoch re-fetches every tile; with an over-budget set the
    # misses must exceed the tile count (rebuilds happened) unless the
    # tiny fixture happens to fit — guard on actual eviction instead
    if cache.nbytes >= (1 << 20):
        assert cache.misses > learner._n_tiles["train"]


def test_bcd_golden_with_bounded_cache(rcv1_path):
    """BCD's golden optimum with a 1-item slice cache (maximal eviction
    pressure): identical optimum, rebuilds on demand
    (tests/cpp/bcd_learner_test.cc:40-65 value)."""
    learner = Learner.create("bcd")
    learner.init([
        ("data_in", rcv1_path), ("l1", ".1"), ("lr", ".8"),
        ("block_ratio", "1"), ("tail_feature_filter", "0"),
        ("max_num_epochs", "50"),
        ("tile_cache_items", "1"), ("tile_cache_mb", "1")])
    progs = []
    learner.add_epoch_end_callback(lambda e, p: progs.append(p))
    learner.run()
    assert abs(progs[-1].objv - 15.884923) / progs[-1].objv < 1e-3
    assert learner._tile_cache.misses > len(learner._tile_cache)
