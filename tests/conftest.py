"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host-platform virtual devices (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# the environment pins JAX_PLATFORMS=axon at interpreter startup and the env
# var is not re-read; config.update is the reliable override
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest


@pytest.fixture(scope="session")
def rcv1_path() -> str:
    """First 100 rows of the public rcv1.binary dataset (libsvm format) —
    the same fixture the reference's golden tests use (tests/README.md)."""
    return str(pathlib.Path(__file__).parent / "data" / "rcv1_100.libsvm")
