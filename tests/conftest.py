"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host-platform virtual devices (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# the environment pins JAX_PLATFORMS=axon at interpreter startup and the env
# var is not re-read; config.update is the reliable override
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

# Known-environment guards (ISSUE 12 satellite): device-count / platform
# dependent suites degrade to explicit SKIPS on boxes that cannot run
# them, instead of joining the failure set and masking real regressions.
#
# Two-process jax.distributed runs (launch.py -n 2 workers) need a second
# CPU core: on a 1-core container the pair starves and
# multihost_utils.process_allgather fails inside the worker rather than
# testing anything. Sharding tests that only need the 8-device VIRTUAL
# mesh (this file's XLA flag) are unaffected and must not use this mark.
two_process_launch = pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="two-process jax.distributed run needs >= 2 CPU cores "
           "(1-core boxes fail in process_allgather, a known "
           "environment limit, not a code regression)")

# jax.shard_map moved between jax releases (jax.experimental.shard_map
# in this image's build); suites written against the top-level name skip
# until the learner migrates.
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no top-level jax.shard_map")


def pytest_configure(config):
    # registered here (no pytest.ini): `slow` gates tier-2-only tests
    # out of the tier-1 `-m 'not slow'` run; `chaos` tags the
    # fault-injection resilience suite (tests/test_chaos.py) — IN
    # tier-1, selectable alone with `-m chaos`
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection resilience suite (runs in tier-1)")


@pytest.fixture(scope="session")
def rcv1_path() -> str:
    """First 100 rows of the public rcv1.binary dataset (libsvm format) —
    the same fixture the reference's golden tests use (tests/README.md)."""
    return str(pathlib.Path(__file__).parent / "data" / "rcv1_100.libsvm")


def write_uniform_libsvm(path, rows: int = 200, width: int = 8,
                         id_space: int = 300, seed: int = 7) -> str:
    """Uniform-width libsvm data: every row has exactly ``width`` valued
    features, so the panel layout (ops/batch.py panel_width) engages and
    mesh/SPMD tests exercise the panel + chunked-run step instead of COO."""
    import numpy as np
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(rows):
            ids = np.sort(rng.choice(id_space, width, replace=False))
            vals = rng.rand(width)
            f.write(str(rng.randint(0, 2)) + " " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(ids, vals)) + "\n")
    return str(path)
