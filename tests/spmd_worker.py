"""Multi-host SPMD worker: run by tests/test_multihost_spmd.py through
launch.py with 2 processes, each holding 4 virtual CPU devices, training
over a global (dp=2, fs=4) mesh. Dumps the per-epoch loss trajectory as
JSON so the parent can compare ranks against the single-host reference.

Usage: spmd_worker.py <out_dir> <data_path> [epochs] [data_val] [k=v ...]

Trailing ``k=v`` pairs override the base config — e.g. ``hash_capacity=0``
switches to the exact-id dictionary store, whose replica dictionaries stay
host-consistent through the id-exchange control plane (learners/sgd.py
exchange()).
"""
import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from difacto_tpu.parallel.multihost import initialize  # noqa: E402

initialize()

from difacto_tpu.learners import Learner  # noqa: E402

out_dir, data = sys.argv[1], sys.argv[2]
epochs = int(sys.argv[3]) if len(sys.argv) > 3 else 4
data_val = sys.argv[4] if len(sys.argv) > 4 else ""

conf = {"data_in": data, "V_dim": "2", "V_threshold": "2",
        "lr": "0.1", "l1": "0.1", "l2": "0",
        "batch_size": "100", "max_num_epochs": str(epochs),
        "shuffle": "0", "report_interval": "0",
        "stop_rel_objv": "0", "stop_val_auc": "-2",
        "num_jobs_per_epoch": "1",
        "hash_capacity": str(1 << 20),
        "mesh_dp": "2", "mesh_fs": "4",
        "model_out": os.path.join(out_dir, "model")}
if data_val:
    # exercises the SPMD eval path: Reader chunks larger than b_cap must be
    # sliced into batch_size row windows (advisor round-2 medium finding)
    conf["data_val"] = data_val
for kv in sys.argv[5:]:
    k, v = kv.split("=", 1)
    conf[k] = v
args = list(conf.items())
ln = Learner.create("sgd")
ln.init(args)
seen, seen_val = [], []
ln.add_epoch_end_callback(
    lambda e, t, v: (seen.append(t.loss), seen_val.append(v.loss)))
ln.run()

rank = jax.process_index()
with open(os.path.join(out_dir, f"traj-{rank}.json"), "w") as f:
    json.dump({"train": seen, "val": seen_val,
               "panel_steps": getattr(ln, "_spmd_panel_steps", 0),
               # dictionary passes after the first exchange int32 slots
               # instead of uint64 ids (half the DCN control bytes)
               "slot_steps": getattr(ln, "_spmd_slot_steps", 0),
               # dictionary-replica invariants: every rank must hold the
               # identical id->slot map and table capacity
               "num_features": ln.store.num_features,
               "capacity": int(ln.store.state.capacity)}, f)
print(f"rank {rank} done: {seen}")
