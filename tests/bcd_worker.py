"""Multi-host BCD worker for tests/test_multihost_bcd.py (run through
launch.py): each process holds its byte range's row tiles; per-block
(g, h) partials meet in the DCN allreduce and every host applies the
identical diag-Newton update. Writes its per-epoch objective trajectory."""
import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from difacto_tpu.parallel.multihost import initialize  # noqa: E402

initialize()

from difacto_tpu.learners import Learner  # noqa: E402

out_dir, data = sys.argv[1], sys.argv[2]
rank = jax.process_index()

ln = Learner.create("bcd")
ln.init([("data_in", data), ("l1", ".1"), ("lr", ".05"),
         ("block_ratio", "0.001"), ("tail_feature_filter", "0"),
         ("max_num_epochs", "10")])
seen = []
ln.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
ln.run()

with open(os.path.join(out_dir, f"traj-{rank}.json"), "w") as f:
    json.dump(seen, f)
print(f"rank {rank} done")
