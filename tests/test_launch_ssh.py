"""launch.py --launcher ssh (the run_ssh.sh / dmlc-tracker ssh path,
/root/reference/run_ssh.sh:1, reference launch.py:32-78) — exercised with
a fake ssh shim that runs the remote command locally, so the test needs no
real cluster: hostfile parsing, per-rank env on the remote command line,
coordinator = first host, and eviction of the failed host on restart."""

import json
import os
import pathlib
import stat
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

SHIM = """#!/bin/sh
# fake ssh: $1 = host, $2 = remote command; run it locally, recording the
# target host for the test
echo "$1" >> "$SHIM_LOG"
exec sh -c "$2"
"""


def _write_shim(tmp_path):
    shim = tmp_path / "fake_ssh"
    shim.write_text(SHIM)
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return shim


def test_ssh_launcher_env_and_hosts(tmp_path):
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("# comment\nhostA extra tokens\nhostB\n")
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import json, os, sys\n"
        "out = sys.argv[1]\n"
        "rank = os.environ['DIFACTO_RANK']\n"
        "with open(f'{out}/r{rank}.json', 'w') as f:\n"
        "    json.dump({k: v for k, v in os.environ.items()\n"
        "               if k.startswith('DIFACTO')}, f)\n")
    env = dict(os.environ, SHIM_LOG=str(tmp_path / "shim.log"))
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "--launcher", "ssh",
         "-H", str(hostfile), "--ssh-cmd", str(shim), "--port", "7961",
         "--", sys.executable, str(worker), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    # one process per hostfile entry, ssh'd to the right hosts
    assert sorted((tmp_path / "shim.log").read_text().split()) == \
        ["hostA", "hostB"]
    envs = {}
    for r in (0, 1):
        with open(tmp_path / f"r{r}.json") as f:
            envs[r] = json.load(f)
    assert envs[0]["DIFACTO_NPROCS"] == "2"
    assert envs[1]["DIFACTO_RANK"] == "1"
    # rendezvous coordinator is the FIRST host for every rank
    assert envs[0]["DIFACTO_COORDINATOR"].startswith("hostA:")
    assert envs[1]["DIFACTO_COORDINATOR"] == envs[0]["DIFACTO_COORDINATOR"]


def test_ssh_launcher_evicts_failed_host(tmp_path):
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("hostA\nhostB\n")
    worker = tmp_path / "worker.py"
    # attempt 0: rank 0 (hostA) dies by signal; attempt 1 must run on
    # hostB alone and succeed
    worker.write_text(
        "import os, signal, sys\n"
        "out = sys.argv[1]\n"
        "rank = os.environ['DIFACTO_RANK']\n"
        "attempt = os.environ['DIFACTO_RESTART']\n"
        "open(f'{out}/a{attempt}-r{rank}-'\n"
        "     f'{os.environ[\"DIFACTO_COORDINATOR\"].split(\":\")[0]}',\n"
        "     'w').close()\n"
        "if attempt == '0' and rank == '0':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n")
    env = dict(os.environ, SHIM_LOG=str(tmp_path / "shim.log"))
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "--launcher", "ssh",
         "-H", str(hostfile), "--ssh-cmd", str(shim), "--port", "7971",
         "--max-restarts", "1",
         "--", sys.executable, str(worker), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "evicting hostA" in proc.stderr
    # attempt 1 ran a single process on hostB, with hostB the coordinator
    marks = sorted(p.name for p in tmp_path.glob("a1-*"))
    assert marks == ["a1-r0-hostB"]
