"""difacto-lint fixture suite (docs/static_analysis.md).

Three layers, all tier-1:

- **per-rule fixtures** — for every local rule one true-positive
  snippet that must be flagged EXACTLY once, plus negative and
  suppressed twins that must be clean;
- **cross-rule fixtures** — tiny synthetic packages exercising each
  registry-drift rule's drifted and in-sync shapes;
- **the machinery** — JSON output schema, baseline add/expire,
  suppression pragma placement, exit codes, parse errors — and the
  the-tree-is-clean gate: the analyzer over this very repo must report
  zero unsuppressed, non-baselined findings.

Everything runs the analyzer in-process (no subprocesses): the whole
suite is a few hundred milliseconds.
"""

import json
import pathlib
import textwrap

import pytest

from difacto_tpu.analysis import core
from difacto_tpu.analysis.cli import main as lint_main

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def lint_src(tmp_path, src, rules, filename="mod.py"):
    """Run selected rules over one source snippet; return active
    findings."""
    (tmp_path / filename).write_text(textwrap.dedent(src))
    project = core.Project(tmp_path, [filename])
    res = core.run_project(project, rules)
    return res.active


# ---------------------------------------------------------------------------
# local-rule fixtures: (rule, true-positive, negative). The suppressed
# twin is generated from the true positive by pragma-tagging every line.

LOCAL_FIXTURES = [
    ("thread-daemon", """
        import threading
        def f():
            t = threading.Thread(target=print)
            t.start()
     """, """
        import threading
        def f():
            t = threading.Thread(target=print, daemon=True)
            t.start()
        def g():
            t = threading.Thread(target=print)
            t.start()
            t.join()
     """),
    ("lock-release", """
        import threading
        lock = threading.Lock()
        def f():
            lock.acquire()
            print("critical")
            lock.release()  # an exception above leaks the lock
     """, """
        import threading
        lock = threading.Lock()
        def f():
            lock.acquire()
            try:
                print("critical")
            finally:
                lock.release()
        def g():
            with lock:
                print("critical")
        def h():
            if not lock.acquire(timeout=1):
                return
            try:
                print("critical")
            finally:
                lock.release()
     """),
    ("resource-close", """
        import socket
        def f():
            s = socket.socket()
            s.connect(("h", 1))
     """, """
        import socket
        def ok_with():
            with socket.socket() as s:
                s.connect(("h", 1))
        def ok_finally():
            s = socket.socket()
            try:
                s.connect(("h", 1))
            finally:
                s.close()
        def ok_escapes():
            s = socket.socket()
            return s
        def ok_handed_off(pool):
            s = socket.socket()
            pool.add(s)
     """),
    ("wall-clock", """
        import time
        def f():
            t0 = time.time()
            return time.monotonic() - t0
     """, """
        import time
        def f():
            t0 = time.monotonic()
            return time.monotonic() - t0
     """),
    ("broad-except", """
        def f():
            try:
                g()
            except Exception:
                pass
     """, """
        import logging
        log = logging.getLogger(__name__)
        def ok_logs():
            try:
                g()
            except Exception as e:
                log.warning("g failed: %s", e)
        def ok_reraises():
            try:
                g()
            except Exception:
                raise RuntimeError("context")
        def ok_captures():
            errs = []
            try:
                g()
            except BaseException as e:
                errs.append(e)
        def ok_narrow():
            try:
                g()
            except ValueError:
                pass
     """),
    ("jax-donate", """
        import jax
        def run(step, x):
            step2 = jax.jit(step, donate_argnums=(0,))
            y = step2(x)
            return x
     """, """
        import jax
        def run(step, x):
            step2 = jax.jit(step, donate_argnums=(0,))
            x = step2(x)
            return x
     """),
    ("jax-jit-capture", """
        import jax
        class Model:
            def make(self):
                @jax.jit
                def inner(a):
                    return a * self.scale
                return inner
     """, """
        import jax
        class Model:
            def make(self):
                scale = self.scale
                @jax.jit
                def inner(a, s):
                    return a * s
                return inner
     """),
    ("jax-host-call", """
        import jax
        import numpy as np
        @jax.jit
        def f(a):
            return np.sum(a)
     """, """
        import jax
        import jax.numpy as jnp
        import numpy as np
        @jax.jit
        def f(a):
            return jnp.sum(a.astype(np.float32))
        def host(a):
            return np.sum(a)
     """),
    ("cond-wait-while", """
        import threading
        cond = threading.Condition()
        def f(ready):
            with cond:
                if not ready:
                    cond.wait()
     """, """
        import threading
        cond = threading.Condition()
        def ok_while(ready):
            with cond:
                while not ready():
                    cond.wait()
        def ok_wait_for(ready):
            with cond:
                cond.wait_for(ready)
     """),
    ("jax-dtype64", """
        import jax
        import numpy as np
        @jax.jit
        def f(a):
            return a * np.float64(2.0)
     """, """
        import jax
        import jax.numpy as jnp
        import numpy as np
        @jax.jit
        def ok_f32(a):
            return a * jnp.float32(2.0)
        def host_exact(xs):
            # host-side float64 accumulation is deliberate (parsers,
            # DCN wires) — never flagged outside jit targets
            return np.float64(2.0) * np.sum(xs)
     """),
]


@pytest.mark.parametrize("rule,bad,good",
                         LOCAL_FIXTURES,
                         ids=[r for r, _, _ in LOCAL_FIXTURES])
def test_local_rule_true_positive_fires_exactly_once(tmp_path, rule, bad,
                                                     good):
    found = lint_src(tmp_path, bad, [rule])
    assert len(found) == 1, \
        f"{rule}: expected exactly 1 finding, got {found}"
    assert found[0].rule == rule
    assert found[0].line > 0 and found[0].message


@pytest.mark.parametrize("rule,bad,good",
                         LOCAL_FIXTURES,
                         ids=[r for r, _, _ in LOCAL_FIXTURES])
def test_local_rule_negative_fixture_is_clean(tmp_path, rule, bad, good):
    assert lint_src(tmp_path, good, [rule]) == []


@pytest.mark.parametrize("rule,bad,good",
                         LOCAL_FIXTURES,
                         ids=[r for r, _, _ in LOCAL_FIXTURES])
def test_local_rule_suppression_pragma_silences(tmp_path, rule, bad, good):
    tagged = "\n".join(
        line + f"  # lint: ok({rule})" if line.strip() else line
        for line in textwrap.dedent(bad).splitlines())
    (tmp_path / "mod.py").write_text(tagged)
    res = core.run_project(core.Project(tmp_path, ["mod.py"]), [rule])
    assert res.active == []
    assert sum(f.suppressed for f in res.findings) == 1


def test_standalone_pragma_covers_next_code_line(tmp_path):
    src = ("import time\n"
           "# lint: ok(wall-clock) timestamp-of-record\n"
           "STAMP = time.time()\n")
    (tmp_path / "mod.py").write_text(src)
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["wall-clock"])
    assert res.active == [] and len(res.findings) == 1


def test_jit_method_self_argument_flagged(tmp_path):
    found = lint_src(tmp_path, """
        import jax
        class Model:
            @jax.jit
            def step(self, x):
                return x
     """, ["jax-jit-capture"])
    assert len(found) == 1 and "traced" in found[0].message


def test_parse_error_is_a_finding(tmp_path):
    found = lint_src(tmp_path, "def broken(:\n", ["wall-clock"])
    assert [f.rule for f in found] == ["parse-error"]


# ---------------------------------------------------------------------------
# cross-rule fixtures: tiny synthetic projects


_PROJ_SEQ = [0]


def make_project(tmp_path, files, **kw):
    _PROJ_SEQ[0] += 1
    root = tmp_path / f"proj{_PROJ_SEQ[0]}"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    lint = sorted({rel.split("/")[0] for rel in files if rel.endswith(".py")
                   and not rel.startswith(("tests/", "docs/"))})
    return core.Project(root, lint, **kw)


def test_fault_registry_drift_and_sync(tmp_path):
    proj = make_project(tmp_path, {
        "pkg/mod.py": """
            from utils import faultinject
            def work():
                faultinject.fire("my.point")
        """,
    })
    rules = ["fault-registry"]
    found = core.run_project(proj, rules).active
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "never armed" in msgs and "undocumented" in msgs

    proj = make_project(tmp_path, {
        "pkg/mod.py": """
            from utils import faultinject
            def work():
                faultinject.fire("my.point")
        """,
        "tests/test_mod.py": 'FAULTS = "my.point:err@1"\n',
        "docs/chaos.md": "fault points: `my.point` tears the thing\n",
    })
    assert core.run_project(proj, rules).active == []


def test_fault_registry_rejects_unknown_kind(tmp_path):
    proj = make_project(tmp_path, {
        "pkg/mod.py": """
            from utils import faultinject
            faultinject.fire("my.point")
        """,
        "tests/test_mod.py": 'FAULTS = "my.point:explode@1"\n',
        "docs/chaos.md": "`my.point`\n",
    })
    found = core.run_project(proj, ["fault-registry"]).active
    assert len(found) == 1 and "explode" in found[0].message


def test_metric_registry_type_conflict_and_missing_doc(tmp_path):
    proj = make_project(tmp_path, {
        "pkg/a.py": 'from obs import counter\n'
                    'c = counter("my_widgets_total", "desc")\n',
        "pkg/b.py": 'from obs import gauge\n'
                    'g = gauge("my_widgets_total", "desc")\n',
        "docs/observability.md": "catalog: `my_widgets_total`\n",
    })
    found = core.run_project(proj, ["metric-registry"]).active
    assert len(found) == 1
    assert "one name must keep one type" in found[0].message

    proj = make_project(tmp_path, {
        "pkg/a.py": 'from obs import counter\n'
                    'c = counter("my_widgets_total", "desc")\n',
        "docs/observability.md": "catalog has nothing\n",
    })
    found = core.run_project(proj, ["metric-registry"]).active
    assert len(found) == 1 and "missing from" in found[0].message


def test_control_registry_two_way_match(tmp_path):
    files = {
        "srv/server.py": 'HANDLED = ("#stats", "#orphan")\n',
        "cli/client.py": 'SENT = ("#stats", "#lost")\n',
        "docs/wire.md": "`#stats` `#orphan` `#lost`\n",
    }
    proj = make_project(
        tmp_path, files,
        handler_files=("srv/server.py",), sender_files=("cli/client.py",))
    found = core.run_project(proj, ["control-registry"]).active
    by_msg = {f.message.split('"')[1]: f.message for f in found}
    assert set(by_msg) == {"#orphan", "#lost"}
    assert "ever sends" in by_msg["#orphan"]
    assert "never handles" in by_msg["#lost"]

    files["srv/server.py"] = 'HANDLED = ("#stats",)\n'
    files["cli/client.py"] = 'SENT = ("#stats",)\n'
    proj = make_project(
        tmp_path, files,
        handler_files=("srv/server.py",), sender_files=("cli/client.py",))
    assert core.run_project(proj, ["control-registry"]).active == []


def test_control_registry_requires_docs_entry(tmp_path):
    proj = make_project(
        tmp_path,
        {"srv/server.py": 'H = "#stats"\n',
         "cli/client.py": 'S = "#stats"\n',
         "docs/wire.md": "nothing here\n"},
        handler_files=("srv/server.py",), sender_files=("cli/client.py",))
    found = core.run_project(proj, ["control-registry"]).active
    assert len(found) == 1 and "undocumented" in found[0].message


def test_config_registry_undeclared_knob_and_env(tmp_path):
    proj = make_project(tmp_path, {
        "pkg/mod.py": """
            import os
            from config import Param
            class FooParam(Param):
                declared_knob: int = 1
            def read(kwargs):
                a = next(v for k, v in kwargs if k == "declared_knob")
                b = next(v for k, v in kwargs if k == "mystery_knob")
                return a, b, os.environ.get("DIFACTO_SECRET")
        """,
        "docs/conf.md": "knobs: declared_knob\n",
    })
    found = core.run_project(proj, ["config-registry"]).active
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "mystery_knob" in msgs and "DIFACTO_SECRET" in msgs

    proj = make_project(tmp_path, {
        "pkg/mod.py": """
            import os
            from config import Param
            class FooParam(Param):
                declared_knob: int = 1
            def read(kwargs):
                a = next(v for k, v in kwargs if k == "declared_knob")
                return a, os.environ.get("DIFACTO_SECRET")
        """,
        "docs/conf.md": "knobs: declared_knob, DIFACTO_SECRET\n",
    })
    assert core.run_project(proj, ["config-registry"]).active == []


# ---------------------------------------------------------------------------
# interprocedural concurrency rules (analysis/concurrency.py)


def test_lock_order_cycle_detected_single_file(tmp_path):
    found = lint_src(tmp_path, """
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def fwd():
            with A:
                with B:
                    pass
        def rev():
            with B:
                with A:
                    pass
     """, ["lock-order"])
    assert len(found) == 1
    msg = found[0].message
    assert "lock-order cycle" in msg
    # BOTH witness paths ride the finding
    assert "fwd" in msg and "rev" in msg


def test_lock_order_consistent_order_is_clean(tmp_path):
    assert lint_src(tmp_path, """
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:
                    pass
        def g():
            with A:
                with B:
                    pass
     """, ["lock-order"]) == []


def test_lock_order_suppression(tmp_path):
    src = textwrap.dedent("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def fwd():
            with A:  # lint: ok(lock-order) fixture
                with B:
                    pass
        def rev():
            with B:
                with A:
                    pass
    """)
    (tmp_path / "mod.py").write_text(src)
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["lock-order"])
    assert res.active == []
    assert sum(f.suppressed for f in res.findings) == 1


def test_lock_order_interprocedural_deadlock_package(tmp_path):
    """The synthetic two-lock deadlock: m1 takes A then calls into m2
    which takes B; m2 also takes B then calls back into m1 for A. The
    cycle spans modules — only the call graph can see it."""
    proj = make_project(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/m1.py": """
            import threading
            from pkg import m2
            A = threading.Lock()
            def outer():
                with A:
                    m2.take_b()
            def take_a():
                with A:
                    pass
        """,
        "pkg/m2.py": """
            import threading
            from pkg import m1
            B = threading.Lock()
            def take_b():
                with B:
                    pass
            def rev():
                with B:
                    m1.take_a()
        """,
    })
    found = core.run_project(proj, ["lock-order"]).active
    assert len(found) == 1
    msg = found[0].message
    assert "m1.py::A" in msg and "m2.py::B" in msg
    assert "outer" in msg and "rev" in msg  # one witness per direction


def test_lock_order_thread_target_does_not_propagate(tmp_path):
    """Held locks stop at a Thread(target=...) hand-off: the target
    runs on another thread, so A-held-while-spawning does not order A
    before anything the spawned thread takes."""
    assert lint_src(tmp_path, """
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def take_b():
            with B:
                pass
        def take_a():
            with A:
                pass
        def spawn():
            with A:
                t = threading.Thread(target=take_b, daemon=True)
                t.start()
        def rev():
            with B:
                take_a()
     """, ["lock-order"]) == []


def test_lock_blocking_direct_and_negative(tmp_path):
    found = lint_src(tmp_path, """
        import threading
        L = threading.Lock()
        def f(conn):
            with L:
                conn.sendall(b"x")
     """, ["lock-blocking"])
    assert len(found) == 1 and "sendall" in found[0].message

    assert lint_src(tmp_path, """
        import threading
        import queue
        L = threading.Lock()
        q = queue.Queue()
        def ok_outside(conn):
            with L:
                pass
            conn.sendall(b"x")
        def ok_timed():
            with L:
                return q.get(timeout=0.1)
        def ok_nowait():
            with L:
                q.put_nowait(1)
     """, ["lock-blocking"]) == []


def test_lock_blocking_queue_without_timeout(tmp_path):
    found = lint_src(tmp_path, """
        import threading
        import queue
        L = threading.Lock()
        q = queue.Queue()
        def f():
            with L:
                return q.get()
     """, ["lock-blocking"])
    assert len(found) == 1
    assert "queue.get() without timeout" in found[0].message


def test_lock_blocking_interprocedural(tmp_path):
    found = lint_src(tmp_path, """
        import threading
        import time
        L = threading.Lock()
        def helper():
            time.sleep(0.1)
        def f():
            with L:
                helper()
     """, ["lock-blocking"])
    assert len(found) == 1
    msg = found[0].message
    assert "time.sleep" in msg and "helper" in msg


def test_lock_blocking_suppression(tmp_path):
    src = textwrap.dedent("""
        import threading
        L = threading.Lock()
        def f(conn):
            with L:
                conn.sendall(b"x")  # lint: ok(lock-blocking) fixture
    """)
    (tmp_path / "mod.py").write_text(src)
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["lock-blocking"])
    assert res.active == []
    assert sum(f.suppressed for f in res.findings) == 1


# ---------------------------------------------------------------------------
# --changed-only incremental mode


def test_changed_only_limits_local_rules_not_cross(tmp_path, capsys):
    """Local rules narrow to changed files; the concurrency rules still
    see the whole tree (a cycle in an UNCHANGED file must still fail)."""
    import subprocess
    root = tmp_path / "repo"
    root.mkdir()

    def git(*args):
        subprocess.run(
            ["git", "-C", str(root), "-c", "user.email=t@t",
             "-c", "user.name=t", *args],
            check=True, capture_output=True)

    (root / "a.py").write_text(textwrap.dedent("""
        import threading
        import time
        T = time.time()
        A = threading.Lock()
        B = threading.Lock()
        def fwd():
            with A:
                with B:
                    pass
        def rev():
            with B:
                with A:
                    pass
    """))
    (root / "b.py").write_text("import time\nU = time.monotonic()\n")
    git("init", "-q")
    git("add", ".")
    git("commit", "-q", "-m", "seed")
    (root / "b.py").write_text("import time\nU = time.time()\n")

    args = ["--root", str(root), ".", "--rules", "wall-clock,lock-order",
            "--format", "json"]
    rc = lint_main(args)
    full = json.loads(capsys.readouterr().out)
    assert rc == 1 and full["counts"]["active"] == 3  # 2 wall + 1 cycle

    rc = lint_main(args + ["--changed-only"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    by_rule = {}
    for f in doc["findings"]:
        by_rule.setdefault(f["rule"], []).append(f["path"])
    # a.py's wall-clock finding is pre-existing -> skipped; b.py's is
    # new -> reported; the cycle lives in unchanged a.py -> reported
    assert by_rule == {"wall-clock": ["b.py"], "lock-order": ["a.py"]}


# ---------------------------------------------------------------------------
# locktrace: the runtime lock sentinel (utils/locktrace.py)


def test_locktrace_records_and_roundtrips_across_threads(tmp_path,
                                                         monkeypatch):
    import threading

    from difacto_tpu.utils import locktrace

    monkeypatch.setenv("DIFACTO_LOCKTRACE", "1")
    locktrace.reset()
    a = locktrace.mutex()
    b = locktrace.mutex()

    def nest():
        with a:
            with b:
                pass

    t = threading.Thread(target=nest, daemon=True)
    t.start()
    t.join()
    nest()  # the main thread takes the same order

    edges = locktrace.edges()
    assert len(edges) == 1
    ((src, dst), count), = edges.items()
    assert count == 2  # one edge per thread, same sites
    assert src != dst
    assert all(s.startswith("tests/test_lint.py:") for s in (src, dst))
    assert locktrace.sites()[src] == "Lock"

    out = tmp_path / "locks.json"
    locktrace.dump(out)
    data = locktrace.load(out)
    assert data["edges"] == edges
    assert data["sites"][dst] == "Lock"
    locktrace.reset()
    assert locktrace.edges() == {}


def test_locktrace_release_order_and_disabled(monkeypatch):
    import threading

    from difacto_tpu.utils import locktrace

    monkeypatch.delenv("DIFACTO_LOCKTRACE", raising=False)
    raw = locktrace.mutex()
    assert isinstance(raw, type(threading.Lock()))

    monkeypatch.setenv("DIFACTO_LOCKTRACE", "1")
    locktrace.reset()
    a = locktrace.mutex()
    b = locktrace.mutex()
    # hand-over-hand: a release between acquires drops the edge source
    a.acquire()
    a.release()
    b.acquire()
    b.release()
    assert locktrace.edges() == {}
    with a:
        with b:
            assert b.locked()
    assert len(locktrace.edges()) == 1


def test_locktrace_dynamic_edges_subset_of_static_graph(monkeypatch):
    """The tier-1 gate: every acquisition-order edge a real execution
    records must already exist in the static lock-order graph (the
    static model over-approximates; a dynamic edge it missed is a
    callgraph blind spot to fix), and the static graph of this tree is
    cycle-free with an empty baseline."""
    import numpy as np

    from difacto_tpu.analysis.cli import DEFAULT_PATHS
    from difacto_tpu.analysis.concurrency import get_model
    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.serve.batcher import MicroBatcher, ServeStats
    from difacto_tpu.utils import locktrace

    monkeypatch.setenv("DIFACTO_LOCKTRACE", "1")
    locktrace.reset()
    blk = RowBlock(offset=np.array([0, 1], dtype=np.int64),
                   label=np.zeros(1, dtype=np.float32),
                   index=np.zeros(1, dtype=np.uint32),
                   value=None, weight=None)
    stats = ServeStats()
    bat = MicroBatcher(lambda x: np.zeros(x.size, np.float32),
                       batch_size=2, queue_cap=1, stats=stats)
    try:
        assert bat.submit(blk) is not None
        # second row overflows queue_cap=1: the shed counters tick
        # UNDER the batcher admission lock — a real nested acquisition
        assert bat.submit(blk) is None
        stats.record_latency(0.001)
        stats.snapshot()
    finally:
        bat.close()

    edges = locktrace.edges()
    assert edges, "the scenario must actually nest traced locks"

    project = core.Project(
        REPO_ROOT, [p for p in DEFAULT_PATHS if (REPO_ROOT / p).exists()])
    model = get_model(project)
    assert model.cycles == [], \
        f"static lock-order graph has cycles: {model.cycles}"
    site2lock = {f"{li.path}:{li.line}": lid
                 for lid, li in model.locks.items()}
    for a, b in edges:
        assert a in site2lock, \
            f"dynamic lock site {a} unknown to the static model"
        assert b in site2lock, \
            f"dynamic lock site {b} unknown to the static model"
        edge = (site2lock[a], site2lock[b])
        assert edge in model.edges, \
            f"observed edge {edge} missing from the static graph — " \
            f"callgraph blind spot"


# ---------------------------------------------------------------------------
# lockmap: merged static + dynamic graph (tools/lockmap.py)


def test_lockmap_merges_static_and_dynamic(tmp_path, monkeypatch):
    import importlib.util

    from difacto_tpu.utils import locktrace

    # tools/ is not a package: load lockmap by path
    spec = importlib.util.spec_from_file_location(
        "difacto_lockmap", REPO_ROOT / "tools" / "lockmap.py")
    lockmap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lockmap)

    monkeypatch.setenv("DIFACTO_LOCKTRACE", "1")
    locktrace.reset()
    import numpy as np

    from difacto_tpu.data.rowblock import RowBlock
    from difacto_tpu.serve.batcher import MicroBatcher, ServeStats
    blk = RowBlock(offset=np.array([0, 1], dtype=np.int64),
                   label=np.zeros(1, dtype=np.float32),
                   index=np.zeros(1, dtype=np.uint32),
                   value=None, weight=None)
    bat = MicroBatcher(lambda x: np.zeros(x.size, np.float32),
                       batch_size=2, queue_cap=1, stats=ServeStats())
    try:
        bat.submit(blk)
        bat.submit(blk)
    finally:
        bat.close()
    dump = tmp_path / "trace.json"
    locktrace.dump(dump)

    graph = lockmap.build(REPO_ROOT, dump)
    assert graph["cycles"] == []
    assert graph["dynamic_only"] == []
    assert graph["confirmed"], "dynamic edges must confirm static ones"
    dot = lockmap.to_dot(graph)
    assert "digraph lockmap" in dot and "confirmed" in dot
    doc = lockmap.to_json(graph)
    assert doc["dynamic_edges"] and doc["locks"]


# ---------------------------------------------------------------------------
# machinery: output formats, baseline, exit codes


def _bad_tree(tmp_path):
    (tmp_path / "mod.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n")
    return tmp_path


def test_json_output_schema(tmp_path, capsys):
    _bad_tree(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "mod.py", "--format", "json",
                    "--rules", "wall-clock"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == core.JSON_VERSION
    assert set(doc["counts"]) == {"files", "total", "active", "suppressed",
                                  "baselined", "expired_baseline"}
    assert doc["counts"] == {"files": 1, "total": 1, "active": 1,
                             "suppressed": 0, "baselined": 0,
                             "expired_baseline": 0}
    (finding,) = doc["findings"]
    assert set(finding) >= {"rule", "path", "line", "message",
                            "fingerprint", "suppressed", "baselined"}
    assert finding["rule"] == "wall-clock" and finding["path"] == "mod.py"
    assert isinstance(doc["expired_baseline"], list)


def test_github_format_annotations(tmp_path, capsys):
    _bad_tree(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "mod.py", "--format", "github",
                    "--rules", "wall-clock"])
    out = capsys.readouterr().out
    assert rc == 1
    assert out.startswith("::error file=mod.py,line=4,")
    assert "wall-clock" in out


def test_baseline_add_then_expire(tmp_path, capsys):
    _bad_tree(tmp_path)
    baseline = tmp_path / ".lint-baseline.json"
    args = ["--root", str(tmp_path), "mod.py", "--rules", "wall-clock"]

    # findings fail the run until intentionally baselined
    assert lint_main(args) == 1
    assert lint_main(args + ["--write-baseline"]) == 0
    data = json.loads(baseline.read_text())
    assert data["version"] == core.BASELINE_VERSION
    assert len(data["findings"]) == 1
    capsys.readouterr()

    # grandfathered: same finding no longer fails, reported as baselined
    rc = lint_main(args + ["--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["counts"]["baselined"] == 1 and doc["counts"]["active"] == 0

    # a NEW finding is not masked by the old baseline entry
    (tmp_path / "mod.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n"
        "\ndef g():\n    return time.time()\n")
    assert lint_main(args) == 1
    capsys.readouterr()

    # the flagged line was fixed: entry expires, run stays green and
    # says so (regenerate with make lint-baseline)
    (tmp_path / "mod.py").write_text(
        "import time\n\ndef f():\n    return time.monotonic()\n")
    rc = lint_main(args + ["--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["counts"]["active"] == 0
    assert len(doc["expired_baseline"]) == 1
    assert lint_main(args + ["--write-baseline"]) == 0
    assert json.loads(baseline.read_text())["findings"] == {}


def test_fingerprints_survive_line_drift(tmp_path):
    (tmp_path / "mod.py").write_text("import time\nt = time.time()\n")
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["wall-clock"])
    fp0 = res.findings[0].fingerprint()
    (tmp_path / "mod.py").write_text(
        "import time\n\n# a new comment above\n\nt = time.time()\n")
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["wall-clock"])
    assert res.findings[0].fingerprint() == fp0


def test_unknown_rule_is_usage_error(tmp_path, capsys):
    _bad_tree(tmp_path)
    assert lint_main(["--root", str(tmp_path), "mod.py",
                      "--rules", "no-such-rule"]) == 2


def test_list_rules_names_every_registered_rule(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in core.all_rules():
        assert rid in out


# ---------------------------------------------------------------------------
# the gate: this tree is clean


def test_the_tree_is_clean(capsys):
    """`make lint` on the repo: zero unsuppressed, non-baselined
    findings. If this fails, run `python tools/lint.py` and either fix
    the finding, annotate it with a reasoned `# lint: ok(rule)`, or —
    for intentional grandfathering only — `make lint-baseline`."""
    rc = lint_main(["--root", str(REPO_ROOT), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0, f"tree has lint findings: {doc['findings']}"
    assert doc["counts"]["active"] == 0
    # the suite itself keeps the analyzer honest: suppressions in the
    # tree must stay EXACTLY this number — bump deliberately when
    # adding one, prune when a fix removes one. Inventory (the v4
    # sweep re-justified every entry): 24 data-race (stop flags,
    # monotonic #stats counters, atomic reference swaps, single-owner
    # instances, pre-spawn publication, the write-once profiler handle
    # in obs/trace.start_device, the ISSUE 18 client blacklist-refold
    # fields and the router group's write-once accept-thread handle),
    # 6 wall-clock (cross-process file
    # timestamps x3, JSONL record stamps, trace-id entropy, run-dir
    # stamp), 2 lock-release (locktrace forwarding wrapper),
    # 1 lock-blocking (native build serialization), 17 jax-recompile
    # (pack/staging-time sticky caps the provenance model cannot chase
    # through payload tuples / the device cache — incl. the ISSUE 13
    # panel_raw device-dedup dispatch; warm-replay keys; probe-tool
    # per-variant compiles; the capacity-scaling sweep's
    # one-compile-per-fs-rung loop in parallel/capacity.py and the
    # kernel bench's one-compile-per-backend loop in bench.py — those
    # loops ARE the benchmark matrices), 4 jax-host-sync
    # (timing-harness completion fences in probe tools). The v5 scrub
    # added ZERO suppressions: its one real finding (the bench --mesh
    # leg jitted an unpinned donated-state program) was FIXED by
    # threading mesh -> state_shardings through build_step, and the
    # three shard rules run clean on the tree.
    assert doc["counts"]["suppressed"] == 54
    import collections
    per_rule = collections.Counter(
        f["rule"] for f in doc["findings"] if f["suppressed"])
    assert dict(per_rule) == {
        "data-race": 24,
        "jax-recompile": 17,
        "wall-clock": 6,
        "jax-host-sync": 4,
        "lock-release": 2,
        "lock-blocking": 1,
    }


# ---------------------------------------------------------------------------
# thread-edge reference forms (analysis/callgraph.py _resolve_ref):
# partial / lambda / local-alias targets must produce thread roots


THREAD_FORMS = [
    ("partial", """
        import threading
        import functools
        def work():
            pass
        def spawn():
            t = threading.Thread(target=functools.partial(work, 1),
                                 daemon=True)
            t.start()
     """),
    ("lambda", """
        import threading
        def work():
            pass
        def spawn():
            t = threading.Thread(target=lambda: work(), daemon=True)
            t.start()
     """),
    ("alias", """
        import threading
        class W:
            def _loop(self):
                pass
            def spawn(self):
                run = self._loop
                t = threading.Thread(target=run, daemon=True)
                t.start()
     """),
]


@pytest.mark.parametrize("form,src", THREAD_FORMS,
                         ids=[f for f, _ in THREAD_FORMS])
def test_thread_target_forms_become_roots(tmp_path, form, src):
    """Regression for the callgraph thread-edge blind spot: every
    hand-off form resolves to a thread ROOT the race pass can see."""
    import textwrap as _tw

    from difacto_tpu.analysis.races import get_race_model
    (tmp_path / "mod.py").write_text(_tw.dedent(src))
    project = core.Project(tmp_path, ["mod.py"])
    model = get_race_model(project)
    target = "mod.py::W._loop" if form == "alias" else "mod.py::work"
    assert target in model.roots, \
        f"{form}: {target} missing from roots {sorted(model.roots)}"


def test_thread_edge_partial_does_not_propagate_locks(tmp_path):
    """A partial-wrapped thread target still breaks held-set
    propagation: no lock-order cycle through the spawn."""
    assert lint_src(tmp_path, """
        import threading
        import functools
        A = threading.Lock()
        B = threading.Lock()
        def take_b():
            with B:
                pass
        def take_a():
            with A:
                pass
        def spawn():
            with A:
                t = threading.Thread(target=functools.partial(take_b),
                                     daemon=True)
                t.start()
        def rev():
            with B:
                take_a()
     """, ["lock-order"]) == []


# ---------------------------------------------------------------------------
# data-race rule (analysis/races.py)


RACE_TP = """
    import threading
    class Worker:
        def __init__(self):
            self.n = 0
        def start(self):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()
        def _loop(self):
            self.n += 1
        def read(self):
            return self.n
"""


def test_data_race_two_root_true_positive_with_both_witnesses(tmp_path):
    found = lint_src(tmp_path, RACE_TP, ["data-race"])
    assert len(found) == 1
    msg = found[0].message
    assert "Worker.n" in msg
    # the two-site witness: the conflicting write and read, with roots
    # and held locks for each side
    assert "write at" in msg and "read at" in msg
    assert "_loop" in msg and "read" in msg
    assert "locks: none" in msg


def test_data_race_guarded_negative_infers_guardedby(tmp_path):
    src = """
        import threading
        class Worker:
            def __init__(self):
                self.n = 0
                self.mu = threading.Lock()
            def start(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
            def _loop(self):
                with self.mu:
                    self.n += 1
            def read(self):
                with self.mu:
                    return self.n
    """
    assert lint_src(tmp_path, src, ["data-race"]) == []
    from difacto_tpu.analysis.races import get_race_model
    import textwrap as _tw
    (tmp_path / "g.py").write_text(_tw.dedent(src))
    model = get_race_model(core.Project(tmp_path, ["g.py"]))
    assert model.guarded_by.get("g.py::Worker.n") == \
        ("g.py::Worker.mu",)


def test_data_race_init_before_publish_negative(tmp_path):
    # cfg is written only in __init__ (and the spawn happens later):
    # published-then-immutable state is not a race however many
    # threads read it
    assert lint_src(tmp_path, """
        import threading
        class Worker:
            def __init__(self):
                self.cfg = {"rate": 1.0}
            def start(self):
                for _ in range(4):
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()
            def _loop(self):
                return self.cfg
     """, ["data-race"]) == []


def test_data_race_suppressed_twin(tmp_path):
    src = RACE_TP.replace(
        "self.n += 1",
        "self.n += 1  # lint: ok(data-race) fixture: benign counter")
    (tmp_path / "mod.py").write_text(textwrap.dedent(src))
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["data-race"])
    assert res.active == []
    assert sum(f.suppressed for f in res.findings) == 1


def test_data_race_multi_instance_root_races_with_itself(tmp_path):
    # one spawn site in a loop -> the root can run as two instances:
    # its unguarded writes race even with no second root
    found = lint_src(tmp_path, """
        import threading
        class Worker:
            def __init__(self):
                self.n = 0
            def start(self):
                while True:
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()
            def _loop(self):
                self.n += 1
     """, ["data-race"])
    assert len(found) == 1 and "Worker.n" in found[0].message


def test_data_race_join_hatch_clears_loadgen_pattern(tmp_path):
    # worker threads write closure counters; the binder reads them only
    # AFTER joining every worker — sequenced, not racing
    assert lint_src(tmp_path, """
        import threading
        def run():
            n_ok = 0
            def recv():
                nonlocal n_ok
                n_ok += 1
            t = threading.Thread(target=recv)
            t.start()
            t.join()
            return n_ok
     """, ["data-race"]) == []


def test_data_race_unspawned_closure_cell_is_confined(tmp_path):
    # a closure cell is per call frame: without a thread hand-off of
    # the nested function it cannot be shared, however many roots
    # reach the binder
    assert lint_src(tmp_path, """
        import threading
        def outer():
            k = 0
            def bump():
                nonlocal k
                k += 1
            bump()
            return k
        def root_a():
            outer()
        def root_b():
            outer()
        def spawn():
            threading.Thread(target=root_a, daemon=True).start()
            threading.Thread(target=root_b, daemon=True).start()
     """, ["data-race"]) == []


def test_data_race_global_written_from_thread(tmp_path):
    found = lint_src(tmp_path, """
        import threading
        COUNT = 0
        def work():
            global COUNT
            COUNT += 1
        def main():
            threading.Thread(target=work, daemon=True).start()
            return COUNT
     """, ["data-race"])
    assert len(found) == 1 and "COUNT" in found[0].message


# ---------------------------------------------------------------------------
# racetrace: the runtime shared-state sentinel (utils/shared.py)


def test_shared_attr_disabled_is_inert(monkeypatch):
    from difacto_tpu.utils import shared
    monkeypatch.delenv("DIFACTO_RACETRACE", raising=False)
    assert shared.attr() is None


def test_shared_tracer_eraser_state_machine(tmp_path, monkeypatch):
    import threading

    from difacto_tpu.utils import locktrace, shared

    monkeypatch.setenv("DIFACTO_RACETRACE", "1")
    shared.reset()
    locktrace.reset()

    class Box:
        val = shared.attr()
        ro = shared.attr()

        def __init__(self):
            self.mu = locktrace.mutex()
            self.val = 0          # exclusive phase (construction)
            self.ro = "config"

    b = Box()
    fid = "tests/test_lint.py::" \
          "test_shared_tracer_eraser_state_machine.<locals>.Box.val"
    b.val = 1                     # still exclusive: same thread
    st = shared.fields()[fid]
    assert st["state"] == "exclusive" and st["lockset"] is None

    def other():
        with b.mu:
            b.val += 1            # second thread: shared -> modified

    t = threading.Thread(target=other, daemon=True)
    t.start()
    t.join()
    st = shared.fields()[fid]
    assert st["state"] == "shared-modified"
    assert st["threads"] == 2
    # the candidate lockset is what the second thread held
    assert len(st["lockset"]) == 1

    with b.mu:
        _ = b.val                 # intersects to the same lock
    assert shared.fields()[fid]["lockset"] == st["lockset"]
    _ = b.val                     # unlocked read empties the lockset
    st = shared.fields()[fid]
    assert st["lockset"] == []
    assert fid in shared.alarms()

    # the read-only field never left exclusive (one thread)
    rid = fid.replace(".val", ".ro")
    assert shared.fields()[rid]["state"] == "exclusive"

    out = tmp_path / "races.json"
    shared.dump(out)
    loaded = shared.load(out)
    assert loaded[fid]["state"] == "shared-modified"
    assert loaded[fid]["lockset"] == []
    shared.reset()
    assert shared.fields() == {}


def test_racetrace_gate_dynamic_fields_statically_known_safe(tmp_path):
    """The tier-1 RACETRACE gate: drive the serve admission path in a
    subprocess with DIFACTO_RACETRACE=1 and assert every field observed
    in a shared state is statically KNOWN-SAFE (consistently locked,
    read-only after publish, or suppressed with a rationale), and every
    dynamic Eraser ALARM is a suppressed field. Anything else is a
    thread-root or shared-state-index blind spot — fix the model, never
    ignore the observation."""
    import os
    import subprocess
    import sys

    from difacto_tpu.analysis.cli import DEFAULT_PATHS
    from difacto_tpu.analysis.races import get_race_model
    from difacto_tpu.utils import shared

    dump = tmp_path / "racetrace.json"
    scenario = textwrap.dedent("""
        import time
        import numpy as np
        from difacto_tpu.serve.batcher import MicroBatcher, ServeStats
        from difacto_tpu.data.rowblock import RowBlock
        blk = RowBlock(offset=np.array([0, 1], dtype=np.int64),
                       label=np.zeros(1, dtype=np.float32),
                       index=np.zeros(1, dtype=np.uint32),
                       value=None, weight=None)
        stats = ServeStats()
        bat = MicroBatcher(lambda x: np.zeros(x.size, np.float32),
                           batch_size=2, queue_cap=1, stats=stats)
        bat.start()
        fut = bat.submit(blk)
        assert fut is not None
        fut.result(10)
        bat.submit(blk)
        stats.record_latency(0.001)
        stats.snapshot()
        time.sleep(0.3)
        bat.close()
    """)
    env = dict(os.environ,
               DIFACTO_RACETRACE="1",
               DIFACTO_RACETRACE_OUT=str(dump),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", scenario],
                       cwd=REPO_ROOT, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    observed = shared.load(dump)
    multi = {f: rec for f, rec in observed.items()
             if rec["state"] != "exclusive"}
    assert multi, "the scenario must actually share traced fields"

    project = core.Project(
        REPO_ROOT, [p for p in DEFAULT_PATHS if (REPO_ROOT / p).exists()])
    model = get_race_model(project)
    safe = model.known_safe()
    for fid, rec in sorted(multi.items()):
        assert fid in model.fields, \
            f"dynamically shared field {fid} unknown to the static index"
        assert fid in safe, \
            f"dynamically shared field {fid} is not statically " \
            f"guarded/read-only/suppressed — blind spot"
        if rec["state"] == "shared-modified" and rec["lockset"] == []:
            assert fid in model.suppressed_fields, \
                f"dynamic race ALARM on {fid} without a reasoned " \
                f"suppression"


# ---------------------------------------------------------------------------
# satellite machinery: timing report, sarif, lockmap GuardedBy


def test_json_report_carries_pass_timings(tmp_path, capsys):
    _bad_tree(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "mod.py", "--format", "json",
                    "--rules", "wall-clock,data-race"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["lint_seconds"] >= 0
    assert set(doc["rule_seconds"]) == {"wall-clock", "data-race"}
    assert all(v >= 0 for v in doc["rule_seconds"].values())


def test_sarif_output_schema(tmp_path, capsys):
    _bad_tree(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "mod.py",
                    "--format", "sarif", "--rules", "wall-clock"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "difacto-lint"
    (result,) = run["results"]
    assert result["ruleId"] == "wall-clock"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "mod.py"
    assert loc["region"]["startLine"] == 4
    assert result["partialFingerprints"]["difactoLint/v1"]
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rules == {"wall-clock"}

    # suppressions do not reach code scanning
    (tmp_path / "mod.py").write_text(
        "import time\n\ndef f():\n    return time.monotonic()\n")
    rc = lint_main(["--root", str(tmp_path), "mod.py",
                    "--format", "sarif", "--rules", "wall-clock"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["runs"][0]["results"] == []


def _load_lockmap():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "difacto_lockmap", REPO_ROOT / "tools" / "lockmap.py")
    lockmap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lockmap)
    return lockmap


def test_lockmap_check_fails_on_dynamic_only_edge(tmp_path, capsys):
    """--check must exit 1 when a real run recorded an edge the static
    model cannot reproduce (a callgraph blind spot)."""
    lockmap = _load_lockmap()
    graph = lockmap.build(REPO_ROOT)
    # fabricate a dump with a REVERSED static edge: its sites are known
    # locks, but the static graph is acyclic so the reverse direction
    # cannot be a static edge
    (src, dst), _e = sorted(graph["static_edges"].items())[0]
    lock2site = {lid: f"{li.path}:{li.line}"
                 for lid, li in graph["locks"].items()}
    dump = tmp_path / "trace.json"
    dump.write_text(json.dumps({
        "version": 1,
        "sites": {lock2site[src]: "Lock", lock2site[dst]: "Lock"},
        "edges": [{"src": lock2site[dst], "dst": lock2site[src],
                   "count": 1}],
    }))
    rc = lockmap.main(["--root", str(REPO_ROOT),
                       "--dynamic", str(dump), "--check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DYNAMIC-ONLY" in out

    graph2 = lockmap.build(REPO_ROOT, dump)
    assert graph2["dynamic_only"] == [(dst, src)]


def test_lockmap_outputs_carry_guardedby(tmp_path):
    lockmap = _load_lockmap()
    graph = lockmap.build(REPO_ROOT)
    assert graph["guarded_by"], "the tree has inferred GuardedBy facts"
    # every guard names a known lock, inverted into the guards index
    for fid, locks in graph["guarded_by"].items():
        for lk in locks:
            assert lk in graph["locks"]
            assert fid in graph["guards"][lk]
    dot = lockmap.to_dot(graph)
    assert "guards: " in dot
    doc = lockmap.to_json(graph)
    assert doc["guarded_by"] == graph["guarded_by"]
    assert "difacto_tpu/serve/batcher.py::MicroBatcher._rows_queued" \
        in doc["guarded_by"]


def test_standalone_pragma_skips_comment_run(tmp_path):
    src = ("import time\n"
           "# lint: ok(wall-clock) timestamp-of-record\n"
           "# rationale continues on a second comment line\n"
           "STAMP = time.time()\n")
    (tmp_path / "mod.py").write_text(src)
    res = core.run_project(core.Project(tmp_path, ["mod.py"]),
                           ["wall-clock"])
    assert res.active == [] and len(res.findings) == 1


# ---------------------------------------------------------------------------
# jaxflow cross rules (analysis/jaxflow.py, difacto-lint v4): fixture
# twins — true positive exactly once, negative, suppressed — for each
# of jax-recompile / jax-host-sync / jax-donate-flow. The jax-dtype64
# local rule rides the LOCAL_FIXTURES table above. Deeper model tests
# (bounded provenance, hot-set closure, the JAXTRACE runtime gate)
# live in tests/test_jaxflow.py.


RECOMPILE_TP = """
    import jax
    def f(x, n):
        return x
    g = jax.jit(f, static_argnums=(1,))
    def hot(xs):
        for x in xs:
            g(x, len(x))
"""


def test_jax_recompile_unbounded_static_true_positive(tmp_path):
    found = lint_src(tmp_path, RECOMPILE_TP, ["jax-recompile"])
    assert len(found) == 1, found
    assert "len(...)" in found[0].message
    assert "bounded" in found[0].message


def test_jax_recompile_capped_static_is_clean(tmp_path):
    assert lint_src(tmp_path, """
        import jax
        from difacto_tpu.data.pack_stream import ShapeSchedule
        def f(x, n):
            return x
        g = jax.jit(f, static_argnums=(1,))
        CAP = 64
        def hot(xs, shapes):
            for x in xs:
                g(x, shapes.cap("b", len(x)))
                g(x, CAP)
    """, ["jax-recompile"]) == []


def test_jax_recompile_suppressed_twin(tmp_path):
    src = RECOMPILE_TP.replace(
        "g(x, len(x))",
        "g(x, len(x))  # lint: ok(jax-recompile) probe harness")
    res = lint_src(tmp_path, src, ["jax-recompile"])
    assert res == []


def test_jax_recompile_pjit_site_true_positive(tmp_path):
    """pjit-named creation sites (jax pjit / jaxtrace.pjit with
    shardings) are jit sites with the same identity — an unbounded
    static through a sharded program is still a finding (ISSUE 12:
    sharded train/serve programs must not dodge the gates)."""
    found = lint_src(tmp_path, """
        from difacto_tpu.utils import jaxtrace
        def f(x, n):
            return x
        g = jaxtrace.pjit(f, static_argnums=(1,), in_shardings=None,
                          out_shardings=None)
        def hot(xs):
            for x in xs:
                g(x, len(x))
    """, ["jax-recompile"])
    assert len(found) == 1, found
    assert "len(...)" in found[0].message


def test_jax_recompile_pjit_bounded_is_clean(tmp_path):
    assert lint_src(tmp_path, """
        from difacto_tpu.utils import jaxtrace
        def f(x, n):
            return x
        g = jaxtrace.pjit(f, static_argnums=(1,), donate_argnums=(0,))
        CAP = 128
        def hot(xs):
            for x in xs:
                g(x, CAP)
    """, ["jax-recompile"]) == []


def test_jax_recompile_jit_in_loop_and_immediate_invoke(tmp_path):
    found = lint_src(tmp_path, """
        import jax
        def f(x):
            return x
        def worst(xs):
            for x in xs:
                step = jax.jit(f)
                step(x)
        def also_bad(x):
            return jax.jit(f)(x)
    """, ["jax-recompile"])
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2, found
    assert "inside a loop" in msgs
    assert "invoked in one expression" in msgs


HOST_SYNC_TP = """
    import jax
    import numpy as np
    def f(x):
        return x
    step = jax.jit(f)
    def run(xs):
        out = 0.0
        for x in xs:
            y = step(x)
            out += float(y)
        return out
"""


def test_jax_host_sync_true_positive(tmp_path):
    found = lint_src(tmp_path, HOST_SYNC_TP, ["jax-host-sync"])
    assert len(found) == 1, found
    assert "float" in found[0].message
    assert "sync" in found[0].message


def test_jax_host_sync_declared_fetch_is_clean(tmp_path):
    assert lint_src(tmp_path, """
        import jax
        from difacto_tpu.utils import jaxtrace
        def f(x):
            return x
        step = jax.jit(f)
        def run(xs):
            out = 0.0
            for x in xs:
                y = step(x)
                out += float(jaxtrace.fetch(y, point="harness"))
            return out
    """, ["jax-host-sync"]) == []


def test_jax_host_sync_cold_path_is_clean(tmp_path):
    # the same coercion OUTSIDE the hot set (no loop, no _loop) is not
    # a finding: a one-off fetch at epoch end is normal
    assert lint_src(tmp_path, """
        import jax
        def f(x):
            return x
        step = jax.jit(f)
        def once(x):
            return float(step(x))
    """, ["jax-host-sync"]) == []


def test_jax_host_sync_interprocedural_through_helper(tmp_path):
    # the coercion lives in a helper the hot loop calls with a device
    # value — reachability + param taint must cross the edge
    found = lint_src(tmp_path, """
        import jax
        def f(x):
            return x
        step = jax.jit(f)
        def report(y):
            return float(y)
        def run(xs):
            out = 0.0
            for x in xs:
                y = step(x)
                out += report(y)
            return out
    """, ["jax-host-sync"])
    assert len(found) == 1, found
    assert "report" in found[0].message


def test_jax_host_sync_suppressed_twin(tmp_path):
    src = HOST_SYNC_TP.replace(
        "out += float(y)",
        "out += float(y)  # lint: ok(jax-host-sync) harness fence")
    assert lint_src(tmp_path, src, ["jax-host-sync"]) == []


DONATE_FLOW_TP = """
    import jax
    def g(x):
        return x + 1
    f = jax.jit(g, donate_argnums=(0,))
    def inner(buf):
        return f(buf)
    def outer(b):
        r = inner(b)
        return b
"""


def test_jax_donate_flow_cross_edge_read_true_positive(tmp_path):
    found = lint_src(tmp_path, DONATE_FLOW_TP, ["jax-donate-flow"])
    assert len(found) == 1, found
    assert "donated" in found[0].message or "donates" in found[0].message
    assert "inner" in found[0].message


def test_jax_donate_flow_rebind_is_clean(tmp_path):
    assert lint_src(tmp_path, """
        import jax
        def g(x):
            return x + 1
        f = jax.jit(g, donate_argnums=(0,))
        def inner(buf):
            return f(buf)
        def outer(b):
            b = inner(b)
            return b
    """, ["jax-donate-flow"]) == []


def test_jax_donate_flow_suppressed_twin(tmp_path):
    src = DONATE_FLOW_TP.replace(
        "        return b\n",
        "        # lint: ok(jax-donate-flow) fixture rationale\n"
        "        return b\n")
    assert lint_src(tmp_path, src, ["jax-donate-flow"]) == []


def test_jax_donate_flow_static_and_range_conflicts(tmp_path):
    found = lint_src(tmp_path, """
        import jax
        def g(x, n):
            return x
        f1 = jax.jit(g, donate_argnums=(1,), static_argnums=(1,))
        f2 = jax.jit(g, donate_argnums=(5,))
    """, ["jax-donate-flow"])
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2, found
    assert "also static_argnums" in msgs
    assert "point past" in msgs


def test_jax_donate_flow_aliased_positions(tmp_path):
    found = lint_src(tmp_path, """
        import jax
        def g(x, y):
            return x + y
        f = jax.jit(g, donate_argnums=(0,))
        def run(a):
            return f(a, a)
    """, ["jax-donate-flow"])
    assert len(found) == 1, found
    assert "non-donated" in found[0].message


# ---------------------------------------------------------------------------
# shardflow cross rules (analysis/shardflow.py, difacto-lint v5):
# fixture twins — true positive exactly once, negative, suppressed —
# for each of jax-shard-break / jax-shard-replicate / jax-shard-pallas.
# The model-level views (pin verdicts, hlomap merge, the HLOSCAN
# tier-1 gate) live in tests/test_hloscan.py.


SHARD_PIN_TP = """
    import jax
    from difacto_tpu.parallel import sharding_tree, state_sharding

    def train(state, batch):
        return state

    def build(mesh, state):
        shardings = sharding_tree(state, state_sharding(mesh))
        step = jax.jit(train, donate_argnums=0)
        return step, shardings
"""


def test_jax_shard_break_unpinned_donating_program(tmp_path):
    found = lint_src(tmp_path, SHARD_PIN_TP, ["jax-shard-break"])
    assert len(found) == 1, found
    assert "train" in found[0].message
    assert "pins its output layout" in found[0].message


def test_jax_shard_break_pinned_programs_are_clean(tmp_path):
    # the two sanctioned pin shapes: out_shardings= on the jit call,
    # and a target threaded through a pinning builder (the
    # `_, train_step, _ = make_step(..., state_shardings=...)` idiom)
    assert lint_src(tmp_path, """
        import jax
        from difacto_tpu.parallel import sharding_tree, state_sharding
        from difacto_tpu.step import state_constrainer

        def train(state, batch):
            return state

        def make_step(fns, state_shardings=None):
            constrain = state_constrainer(state_shardings)
            def step(state, batch):
                return constrain(state)
            return None, step, None

        def build(mesh, state, fns):
            shardings = sharding_tree(state, state_sharding(mesh))
            step = jax.jit(train, donate_argnums=0,
                           out_shardings=shardings)
            _, train_step, _ = make_step(fns, state_shardings=shardings)
            pinned = jax.jit(train_step, donate_argnums=0)
            return step, pinned
    """, ["jax-shard-break"]) == []


def test_jax_shard_break_pin_suppressed_twin(tmp_path):
    src = SHARD_PIN_TP.replace(
        "step = jax.jit(train, donate_argnums=0)",
        "step = jax.jit(train, donate_argnums=0)"
        "  # lint: ok(jax-shard-break) single-device fixture")
    assert lint_src(tmp_path, src, ["jax-shard-break"]) == []


AXIS_BREAK_TP = """
    import jax.numpy as jnp

    def grow(state, extra):
        return jnp.concatenate([state.w, extra])
"""


def test_jax_shard_break_axis_breaker_true_positive(tmp_path):
    found = lint_src(tmp_path, AXIS_BREAK_TP, ["jax-shard-break"])
    assert len(found) == 1, found
    assert "jnp.concatenate" in found[0].message
    assert "capacity axis" in found[0].message


def test_jax_shard_break_reshape_and_boolean_mask(tmp_path):
    found = lint_src(tmp_path, """
        def pack(state):
            return state.w.reshape(-1)

        def live_rows(table):
            return table[table != 0]
    """, ["jax-shard-break"])
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2, found
    assert "reshape" in msgs
    assert "boolean mask" in msgs


def test_jax_shard_break_gather_on_table_is_clean(tmp_path):
    # the sanctioned access pattern: gather rows by a padded slot
    # vector; axis-breakers over NON-table arrays are fine
    assert lint_src(tmp_path, """
        import jax.numpy as jnp

        def gather(state, slots):
            rows = state.w[slots]
            order = jnp.argsort(slots)
            return rows, order
    """, ["jax-shard-break"]) == []


def test_jax_shard_break_axis_suppressed_twin(tmp_path):
    src = AXIS_BREAK_TP.replace(
        "return jnp.concatenate([state.w, extra])",
        "return jnp.concatenate([state.w, extra])"
        "  # lint: ok(jax-shard-break) host-side checkpoint merge")
    assert lint_src(tmp_path, src, ["jax-shard-break"]) == []


SHARD_REPLICATE_TP = """
    import jax
    from difacto_tpu.parallel import state_sharding

    def publish(mesh, state):
        spec = state_sharding(mesh)
        full = jax.device_put(state.w)
        return full, spec
"""


def test_jax_shard_replicate_true_positive(tmp_path):
    found = lint_src(tmp_path, SHARD_REPLICATE_TP,
                     ["jax-shard-replicate"])
    assert len(found) == 1, found
    assert "device_put with no sharding" in found[0].message


def test_jax_shard_replicate_placed_and_non_table_clean(tmp_path):
    assert lint_src(tmp_path, """
        import jax
        import numpy as np
        from difacto_tpu.parallel import state_sharding

        def publish(mesh, state, rows):
            spec = state_sharding(mesh)
            placed = jax.device_put(state.w, spec)
            host = np.asarray(rows)
            return placed, host
    """, ["jax-shard-replicate"]) == []


def test_jax_shard_replicate_donated_from_replicated_copy(tmp_path):
    # rule (b): the donated argument of an fs-scoped program fed from
    # a replicating coercion at the exact call edge
    found = lint_src(tmp_path, """
        import jax
        from difacto_tpu.parallel import (replicated, sharding_tree,
                                          state_sharding)

        def train(state, batch):
            return state

        def run(mesh, state, batch):
            shardings = sharding_tree(state, state_sharding(mesh))
            step = jax.jit(train, donate_argnums=0,
                           out_shardings=shardings)
            fresh = jax.device_put(state, replicated(mesh))
            return step(fresh, batch)
    """, ["jax-shard-replicate"])
    assert len(found) == 1, found
    assert "donated argument 0" in found[0].message
    assert "replicated" in found[0].message


def test_jax_shard_replicate_suppressed_twin(tmp_path):
    src = SHARD_REPLICATE_TP.replace(
        "full = jax.device_put(state.w)",
        "full = jax.device_put(state.w)"
        "  # lint: ok(jax-shard-replicate) export path, mesh-free")
    assert lint_src(tmp_path, src, ["jax-shard-replicate"]) == []


SHARD_PALLAS_TP = """
    from jax.experimental import pallas as pl

    def _kernel_body(ref, out):
        pass

    def _pallas_gather(table, slots):
        return pl.pallas_call(_kernel_body)(table, slots)

    def gather(table, slots, backend="jnp"):
        if backend == "pallas":
            return _pallas_gather(table, slots)
        return table[slots]

    def hot(table, slots):
        return gather(table, slots, backend="pallas")
"""


def test_jax_shard_pallas_unresolved_literal_true_positive(tmp_path):
    found = lint_src(tmp_path, SHARD_PALLAS_TP, ["jax-shard-pallas"])
    assert len(found) == 1, found
    assert "gather" in found[0].message
    assert "resolve_backend" in found[0].message


def test_jax_shard_pallas_resolved_and_default_clean(tmp_path):
    # the three safe shapes: a backend bound from resolve_backend, the
    # parameter left to its non-pallas default, and a non-pallas literal
    assert lint_src(tmp_path, """
        from jax.experimental import pallas as pl
        from difacto_tpu.ops.fused import resolve_backend

        def _kernel_body(ref, out):
            pass

        def _pallas_gather(table, slots):
            return pl.pallas_call(_kernel_body)(table, slots)

        def gather(table, slots, backend="jnp"):
            if backend == "pallas":
                return _pallas_gather(table, slots)
            return table[slots]

        def hot(table, slots, mesh):
            backend = resolve_backend("auto", mesh=mesh)
            return gather(table, slots, backend=backend)

        def cold(table, slots):
            return gather(table, slots)

        def explicit(table, slots):
            return gather(table, slots, backend="jnp")
    """, ["jax-shard-pallas"]) == []


def test_jax_shard_pallas_suppressed_twin(tmp_path):
    src = SHARD_PALLAS_TP.replace(
        'return gather(table, slots, backend="pallas")',
        'return gather(table, slots, backend="pallas")'
        "  # lint: ok(jax-shard-pallas) interpret-mode parity harness")
    assert lint_src(tmp_path, src, ["jax-shard-pallas"]) == []
