"""launch.py cluster modes (mpi/sge/yarn — the remaining dmlc-tracker
launchers, reference launch.py:32-78, run_yarn.sh:3) — exercised with fake
mpirun/qsub/yarn shims that run the submitted tasks locally, so the tests
need no real scheduler: rank mapping from the MPI env, SGE array-task
ranks, O_EXCL rank claiming for rankless YARN containers, shared-dir
rendezvous (rank 0 = coordinator), and rc-file collection."""

import json
import os
import pathlib
import stat
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# fake mpirun: run -np N copies of the command locally with the OpenMPI
# rank env set (what a real mpirun does on its allocation)
FAKE_MPIRUN = """#!/bin/sh
np=0
while [ $# -gt 0 ]; do
  case "$1" in
    -np) np="$2"; shift 2 ;;
    *) break ;;
  esac
done
i=0
while [ $i -lt $np ]; do
  OMPI_COMM_WORLD_RANK=$i "$@" &
  i=$((i+1))
done
wait
"""

# fake qsub: run the array job's tasks locally ($SGE_TASK_ID is 1-based),
# return immediately after spawning (qsub is submit-and-exit)
FAKE_QSUB = """#!/bin/sh
script="$1"
n=$(sed -n 's/^#\\$ -t 1-\\([0-9]*\\)$/\\1/p' "$script")
i=1
while [ $i -le $n ]; do
  SGE_TASK_ID=$i sh "$script" &
  i=$((i+1))
done
exit 0
"""

# fake yarn distributed-shell client: spawn -num_containers copies of
# -shell_command with NO rank information (containers claim ranks)
FAKE_YARN = """#!/bin/sh
n=1; cmd=""
while [ $# -gt 0 ]; do
  case "$1" in
    -num_containers) n="$2"; shift 2 ;;
    -shell_command) cmd="$2"; shift 2 ;;
    *) shift ;;
  esac
done
i=0
while [ $i -lt $n ]; do
  sh -c "$cmd" &
  i=$((i+1))
done
exit 0
"""


def _shim(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    p.chmod(p.stat().st_mode | stat.S_IEXEC)
    return p


def _worker(tmp_path):
    w = tmp_path / "worker.py"
    w.write_text(
        "import json, os, sys\n"
        "out = sys.argv[1]\n"
        "rank = os.environ['DIFACTO_RANK']\n"
        "with open(f'{out}/r{rank}.json', 'w') as f:\n"
        "    json.dump({k: v for k, v in os.environ.items()\n"
        "               if k.startswith('DIFACTO')}, f)\n")
    return w


def _run_dir(rdv):
    """The per-submission run-* subdir (stale-state isolation)."""
    runs = sorted(rdv.glob("run-*"))
    assert len(runs) == 1, runs
    return runs[0]


def _run(tmp_path, launcher, extra):
    worker = _worker(tmp_path)
    rdv = tmp_path / "rdv"
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "--launcher", launcher,
         "-n", "3", "--rendezvous-dir", str(rdv), "--local-python",
         "--rendezvous-timeout", "60", "--port", "7971"] + extra
        + ["--", sys.executable, str(worker), str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    envs = {}
    for r in range(3):
        with open(tmp_path / f"r{r}.json") as f:
            envs[r] = json.load(f)
    for r in range(3):
        assert envs[r]["DIFACTO_NPROCS"] == "3"
        assert envs[r]["DIFACTO_RANK"] == str(r)
        # every task resolved the SAME coordinator (rank 0's host)
        assert envs[r]["DIFACTO_COORDINATOR"] == \
            envs[0]["DIFACTO_COORDINATOR"]
        # and the shims exported the heartbeat mesh env (fast abort on
        # container death even without launcher-side restarts)
        assert envs[r]["DIFACTO_HB_PEERS"].count(",") == 2
    # the shims recorded their exit codes
    run = _run_dir(rdv)
    for r in range(3):
        assert (run / f"rc-{r}").read_text() == "0"
    return envs


def test_mpi_launcher(tmp_path):
    shim = _shim(tmp_path, "fake_mpirun", FAKE_MPIRUN)
    _run(tmp_path, "mpi", ["--mpirun-cmd", str(shim)])


def test_sge_launcher(tmp_path):
    shim = _shim(tmp_path, "fake_qsub", FAKE_QSUB)
    _run(tmp_path, "sge", ["--qsub-cmd", str(shim)])
    # the generated array-job script carries the task range
    job = (_run_dir(tmp_path / "rdv") / "job.sh").read_text()
    assert "#$ -t 1-3" in job and "SGE_TASK_ID" in job


def test_yarn_launcher_claims_ranks(tmp_path):
    shim = _shim(tmp_path, "fake_yarn", FAKE_YARN)
    _run(tmp_path, "yarn", ["--yarn-cmd", str(shim)])
    # rankless containers each claimed a distinct rank file
    claims = sorted(p.name
                    for p in _run_dir(tmp_path / "rdv").glob("claim-*"))
    assert claims == ["claim-0", "claim-1", "claim-2"]


def test_cluster_rejects_max_restarts(tmp_path):
    # resubmission is the scheduler's job in cluster modes: asking for
    # launcher-side restarts must fail fast, not silently not-restart
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "--launcher", "mpi",
         "-n", "2", "--rendezvous-dir", str(tmp_path / "rdv"),
         "--max-restarts", "1", "--", "true"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "max-restarts" in proc.stderr


def test_cluster_failure_rc_propagates(tmp_path):
    shim = _shim(tmp_path, "fake_mpirun", FAKE_MPIRUN)
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(7)\n")
    rdv = tmp_path / "rdv"
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "--launcher", "mpi",
         "-n", "2", "--rendezvous-dir", str(rdv), "--local-python",
         "--mpirun-cmd", str(shim), "--rendezvous-timeout", "60",
         "--", sys.executable, str(bad)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 7
