"""Mesh-sharded BCD (round-3 verdict: distributed BCD): tile rows shard
over an 8-device dp mesh — each device holds its row slice of every
tile's pred/labels/mask and the per-block COO entries landing in it; the
per-block (g, h) contraction is per-device segment-sums + a psum, the
TPU analog of the reference's workers computing partial block gradients
that the servers sum (src/bcd/bcd_learner.cc:236-263,
src/bcd/bcd_updater.h:139-159).

The golden trajectory must be REPRODUCED, not approximated: sharding
a reduction changes the machine, not the math (fp order at ~1e-7; the
goldens tolerate 1e-5)."""

import numpy as np

from difacto_tpu.learners import Learner
from tests.test_bcd import OBJV_DIAG_NEWTON
import pytest  # noqa: F401  (guard mark below)

from conftest import requires_shard_map

pytestmark = requires_shard_map


def run_sharded(rcv1_path, **over):
    args = {"data_in": rcv1_path, "l1": ".1", "lr": ".05",
            "block_ratio": "0.001", "tail_feature_filter": "0",
            "max_num_epochs": "10", "mesh_dp": "8"}
    args.update({k: str(v) for k, v in over.items()})
    learner = Learner.create("bcd")
    remain = learner.init(list(args.items()))
    assert remain == []
    seen = []
    learner.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
    learner.run()
    return learner, np.array(seen)


def test_bcd_sharded_golden(rcv1_path):
    learner, seen = run_sharded(rcv1_path)
    np.testing.assert_allclose(seen, OBJV_DIAG_NEWTON, rtol=1e-4)
    # the row arrays are ACTUALLY sharded over all 8 devices
    pred = learner.tiles[0]["pred"]
    devs = {s.device for s in pred.addressable_shards}
    assert len(devs) == 8
    for s in pred.addressable_shards:
        assert s.data.shape[0] == pred.shape[0] // 8


def test_bcd_sharded_multi_block_optimum(rcv1_path):
    """block_ratio=1 (multiple blocks) converges to the same optimum on
    the mesh (bcd_learner_test.cc:40-65 family)."""
    learner, seen = run_sharded(rcv1_path, block_ratio="1",
                                max_num_epochs="60", random_block="0")
    # single-device reference with identical config
    ref_learner, ref_seen = run_sharded(
        rcv1_path, block_ratio="1", max_num_epochs="60", random_block="0",
        mesh_dp="1")
    np.testing.assert_allclose(seen[-1], ref_seen[-1], rtol=1e-4)
    np.testing.assert_allclose(learner.w, ref_learner.w,
                               rtol=1e-3, atol=1e-5)
