"""Tiered table capacity (ISSUE 19): quantized slots, sketch-based
admission, host-RAM cold tier.

Acceptance legs:

- DEFAULTS ARE BYTE-IDENTICAL: ``slot_dtype=fp32`` + ``admit_min_count=0``
  + cold tier off reproduces the knob-free learner run bit-for-bit, at
  fs=1 AND fs=4 — the new subsystem costs nothing when off;
- quantized trajectories are byte-identical across
  ``fused_kernel=off|jnp`` (and pallas interpret where available) — the
  dequant/requant epilogue is part of the portable row contract;
- sketch admission is deterministic across the thread and process
  producer transports (same (seed, epoch, part) mix on both);
- a quantized (and tiered) checkpoint round-trips through the
  verified-manifest path and serves/predicts within tolerance of the
  fp32 model;
- the cold tier's promote/demote churn is byte-exact, and the armed
  ``store.demote`` / ``store.promote`` faults degrade without losing a
  row (chaos marker).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from difacto_tpu.capacity import AdmissionFilter, ColdTier, CountMinSketch
from difacto_tpu.capacity.sketch import make_admission
from difacto_tpu.learners import Learner
from difacto_tpu.ops import fused
from difacto_tpu.store.local import (K_FEACOUNT, K_GRADIENT, SlotStore)
from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam
from difacto_tpu.utils import faultinject as fi

from conftest import write_uniform_libsvm


def _table_bits(state_vvg) -> np.ndarray:
    v = np.asarray(jax.device_get(state_vvg))
    if v.dtype == np.float32:
        return v.view(np.uint32)
    if v.dtype == np.int8:
        return v.view(np.uint8)
    return v.view(np.uint16)


def _mk_store(**kw) -> SlotStore:
    base = dict(hash_capacity=64, V_dim=4, V_threshold=0, lr=0.1,
                V_lr=0.1)
    base.update(kw)
    p, rest = SGDUpdaterParam.init_allow_unknown(
        [(k, str(v)) for k, v in base.items()])
    assert rest == []
    return SlotStore(p)


def _train_store(st: SlotStore, keys: np.ndarray, rounds: int = 3,
                 seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        k = np.sort(rng.choice(keys, size=min(8, len(keys)),
                               replace=False))
        st.push(k, K_FEACOUNT, np.ones(len(k), np.float32))
        st.pull(k)
        g = rng.standard_normal(len(k)).astype(np.float32) * 0.1
        gV = rng.standard_normal(
            (len(k), st.param.V_dim)).astype(np.float32) * 0.01
        st.push(k, K_GRADIENT, g, gV, np.ones(len(k), bool))


# ----------------------------------------------------------------- sketch

def test_count_min_never_undercounts():
    cms = CountMinSketch(width=1 << 10, depth=2, seed=3)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, 500, 4000)
    cms.add(tok)
    true = np.bincount(tok, minlength=500)
    est = cms.estimate(np.arange(500))
    assert np.all(est >= true)


def test_count_min_deterministic_across_instances():
    tok = np.arange(100) % 13
    a = CountMinSketch(seed=9)
    b = CountMinSketch(seed=9)
    np.testing.assert_array_equal(a.add(tok), b.add(tok))
    np.testing.assert_array_equal(a.counts, b.counts)


def test_admission_filter_sentinel_and_threshold():
    f = AdmissionFilter(hash_capacity=100, min_count=3, seed=1)
    tok = np.array([7, 7, 7, 8], dtype=np.int32)
    out = f.filter(tok)
    # the whole batch is counted before the estimate readback, so all
    # three 7s see est=3 and admit; the lone 8 (est=1) remaps to the
    # OOB sentinel (=capacity)
    assert out.tolist() == [7, 7, 7, 100]
    # second pass: 8 reaches estimate 2 — still below min_count=3
    out2 = f.filter(tok)
    assert out2.tolist() == [7, 7, 7, 100]
    # third pass crosses the threshold for 8
    out3 = f.filter(tok)
    assert out3.tolist() == [7, 7, 7, 8]


def test_make_admission_off_and_mix():
    assert make_admission(64, 0, seed=1, epoch=0, part=0) is None
    a = make_admission(64, 2, seed=1, epoch=0, part=3)
    b = make_admission(64, 2, seed=1, epoch=0, part=3)
    c = make_admission(64, 2, seed=1, epoch=1, part=3)
    tok = (np.arange(50) % 7).astype(np.int32)
    np.testing.assert_array_equal(a.sketch.add(tok), b.sketch.add(tok))
    assert not np.array_equal(a.sketch._mult, c.sketch._mult)


# ------------------------------------------------------------- quantizer

@pytest.mark.parametrize("slot_dtype", ["int8", "fp8"])
def test_requant_idempotent(slot_dtype):
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32) * 0.03)
    codes, scale = fused.quant_half(x, slot_dtype)
    deq = fused.dequant_half(codes, scale, slot_dtype)
    codes2, scale2 = fused.quant_half(deq, slot_dtype)
    np.testing.assert_array_equal(np.asarray(codes), np.asarray(codes2))
    np.testing.assert_array_equal(np.asarray(scale), np.asarray(scale2))


# ------------------------------------------------------------- cold tier

def test_tier_route_sorted_unique_with_pads():
    st = _mk_store(cold_tier_rows=32)
    tier = st.tier
    assert isinstance(tier, ColdTier)
    slots = np.array([1, 5, 40, 50, 64, 65], dtype=np.int64)  # 64+ = pads
    routed, order, perm = tier.route(slots)
    assert np.all(np.diff(routed) > 0)           # strictly sorted
    d = tier.D
    assert int((routed >= d).sum()) == 2         # the two pads stay OOB
    np.testing.assert_array_equal(routed[perm], routed[perm])
    # perm maps input position -> routed position of that same slot
    for p, s in enumerate(slots[:4]):
        assert routed[perm[p]] == tier._resident[s]


def test_tier_promote_demote_churn_byte_exact():
    # D = 32 device rows: every 16-key batch fits, but the 48 distinct
    # slots touched below force trained rows through demote + promote
    st = _mk_store(hash_capacity=256, slot_dtype="int8",
                   cold_tier_rows=224, seed=5)
    keys = np.arange(1, 400, 3, dtype=np.int64)
    _train_store(st, keys[:16], rounds=3, seed=1)
    w0, V0, _ = st.pull(np.sort(keys[:16]))
    # force churn: touch many other keys so the trained rows demote and
    # re-promote through the host tier repeatedly
    for i in range(4):
        st.pull(np.sort(keys[16 + 8 * i:24 + 8 * i]))
    w1, V1, _ = st.pull(np.sort(keys[:16]))
    np.testing.assert_array_equal(w0, w1)
    np.testing.assert_array_equal(V0, V1)


@pytest.mark.parametrize("slot_dtype", ["fp32", "int8"])
def test_tiered_checkpoint_round_trip(tmp_path, slot_dtype):
    keys = np.array([3, 11, 57, 999933, 12345, 777, 42, 5150, 90210,
                     1234567, 88, 4096], dtype=np.int64)
    st = _mk_store(slot_dtype=slot_dtype, cold_tier_rows=32, seed=7)
    _train_store(st, keys, rounds=6, seed=1)
    w0, V0, _ = st.pull(np.sort(keys))
    path = str(tmp_path / "m")
    st.save(path)
    st2 = _mk_store(slot_dtype=slot_dtype, cold_tier_rows=32, seed=7)
    st2.load(path)
    w1, V1, _ = st2.pull(np.sort(keys))
    # logical f32 arrays requantize through build_rows on load; with the
    # per-row scales round-tripping exactly this is byte-exact
    np.testing.assert_array_equal(w0, w1)
    np.testing.assert_array_equal(V0, V1)


def test_quantized_checkpoint_loads_untiered_and_stamps(tmp_path):
    """A tiered int8 save is a plain LOGICAL checkpoint: an untiered
    store of the full hash_capacity loads it and serves the same rows,
    and the stamps route a serving load to the same representation."""
    keys = np.arange(2, 40, 3, dtype=np.int64)
    st = _mk_store(slot_dtype="int8", cold_tier_rows=32, seed=7)
    _train_store(st, keys, rounds=4, seed=2)
    w0, V0, _ = st.pull(np.sort(keys))
    path = str(tmp_path / "m")
    st.save(path)

    from difacto_tpu.serve.model import model_meta, open_serving_store
    meta = model_meta(path)
    assert meta["slot_dtype"] == "int8"
    flat = _mk_store(slot_dtype="int8", cold_tier_rows=0, seed=7)
    flat.load(path)
    w1, V1, _ = flat.pull(np.sort(keys))
    np.testing.assert_array_equal(w0, w1)
    np.testing.assert_array_equal(V0, V1)

    store, meta2, _ = open_serving_store(path)
    # serving adopts the quantized representation but NEVER the tier
    assert store.param.slot_dtype == "int8"
    assert store.param.cold_tier_rows == 0 and store.tier is None
    w2, _, _ = store.pull(np.sort(keys))
    np.testing.assert_array_equal(w0, w2)


def test_occupancy_eviction_without_tier():
    st = _mk_store(hash_capacity=32, evict_occupancy=0.5, seed=3)
    keys = np.arange(1, 200, 7, dtype=np.int64)
    _train_store(st, keys, rounds=4, seed=3)
    n = st.maybe_evict()
    assert n > 0
    # occupancy dropped to <= 0.9 * threshold
    stn = st._state_np(st.state, keys=("w", "cnt", "v_live"))
    occ = (stn["w"] != 0) | (stn["cnt"] != 0) | np.asarray(
        stn["v_live"], bool)
    occ[0] = False
    assert occ.sum() <= 0.9 * 0.5 * 31 + 1
    # idempotent below threshold
    assert st.maybe_evict() == 0


def test_occupancy_eviction_with_tier_keeps_rows_addressable():
    st = _mk_store(hash_capacity=64, cold_tier_rows=32,
                   evict_occupancy=0.4, seed=3)
    keys = np.arange(1, 150, 5, dtype=np.int64)
    _train_store(st, keys, rounds=4, seed=4)
    w0, V0, _ = st.pull(np.sort(keys))
    n = st.maybe_evict()
    assert n > 0
    # under a tier, eviction demotes: every row still fully serves
    w1, V1, _ = st.pull(np.sort(keys))
    np.testing.assert_array_equal(w0, w1)
    np.testing.assert_array_equal(V0, V1)


def test_capacity_stats_multiplier():
    st = _mk_store(hash_capacity=256, slot_dtype="int8",
                   cold_tier_rows=128)
    s = st.capacity_stats()
    assert s["logical_rows"] == 256 and s["device_rows"] == 128
    assert s["capacity_multiplier"] >= 8.0
    base = _mk_store(hash_capacity=256).capacity_stats()
    assert base["capacity_multiplier"] == 1.0


def test_tier_requires_fused_layout_and_no_mesh():
    with pytest.raises(ValueError, match="V_dim"):
        _mk_store(V_dim=0, cold_tier_rows=16)
    with pytest.raises(ValueError, match="cold_tier_rows"):
        _mk_store(hash_capacity=64, cold_tier_rows=63)


# ------------------------------------------------------ learner parity

def _learner_run(data, **over):
    args = [("data_in", data), ("V_dim", "2"), ("V_threshold", "2"),
            ("lr", "0.1"), ("l1", "0.1"), ("l2", "0"),
            ("num_jobs_per_epoch", "1"), ("batch_size", "100"),
            ("max_num_epochs", "2"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("hash_capacity", "4096")]
    args += [(k, str(v)) for k, v in over.items()]
    ln = Learner.create("sgd")
    assert ln.init(args) == []
    seen = []
    ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
    ln.run()
    return seen, _table_bits(ln.store.state.VVg)


def test_defaults_byte_identical_fs1(rcv1_path):
    """Explicitly passing every capacity knob at its default reproduces
    the knob-free run bit-for-bit: the subsystem is invisible when off."""
    s0, t0 = _learner_run(rcv1_path)
    s1, t1 = _learner_run(rcv1_path, slot_dtype="fp32",
                          admit_min_count=0, evict_occupancy=0,
                          cold_tier_rows=0)
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


def test_defaults_byte_identical_fs4(rcv1_path):
    s0, t0 = _learner_run(rcv1_path, mesh_fs=4)
    s1, t1 = _learner_run(rcv1_path, mesh_fs=4, slot_dtype="fp32",
                          admit_min_count=0, evict_occupancy=0,
                          cold_tier_rows=0)
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


@pytest.mark.parametrize("backends", [("off", "jnp")])
def test_quantized_trajectory_across_backends(rcv1_path, backends):
    """int8 slot storage keeps the off|jnp fused backends byte-identical
    — the dequant/requant epilogue is part of the shared row contract."""
    s0, t0 = _learner_run(rcv1_path, slot_dtype="int8",
                          fused_kernel=backends[0])
    s1, t1 = _learner_run(rcv1_path, slot_dtype="int8",
                          fused_kernel=backends[1])
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


def test_quantized_trajectory_pallas_interpret(rcv1_path):
    if not fused.pallas_importable():  # pragma: no cover
        pytest.skip("no pallas in this jax build")
    s0, t0 = _learner_run(rcv1_path, slot_dtype="int8",
                          fused_kernel="off")
    s2, t2 = _learner_run(rcv1_path, slot_dtype="int8",
                          fused_kernel="pallas")
    assert s0 == s2
    np.testing.assert_array_equal(t0, t2)


def test_quantized_trajectory_fs4(rcv1_path):
    s0, t0 = _learner_run(rcv1_path, slot_dtype="int8", mesh_fs=4,
                          fused_kernel="off")
    s1, t1 = _learner_run(rcv1_path, slot_dtype="int8", mesh_fs=4,
                          fused_kernel="jnp")
    assert s0 == s1
    np.testing.assert_array_equal(t0, t1)


def test_admission_thread_vs_process_deterministic(tmp_path):
    """The (seed, epoch, part) -> sketch mix is shared by both producer
    transports, so an admission-gated streamed run lands on the same
    admitted set — and the same table bits — thread or process."""
    path = str(tmp_path / "u.libsvm")
    write_uniform_libsvm(path, rows=300, width=8, id_space=500)
    common = dict(device_cache_mb=0, admit_min_count=2,
                  max_num_epochs=3, num_jobs_per_epoch=2, batch_size=64)
    s0, t0 = _learner_run(path, producer_mode="thread", **common)
    s1, t1 = _learner_run(path, producer_mode="process", **common)
    assert s0 == s1 and len(s0) == 3
    np.testing.assert_array_equal(t0, t1)


def test_admission_changes_the_admitted_set(tmp_path):
    path = str(tmp_path / "u.libsvm")
    write_uniform_libsvm(path, rows=200, width=8, id_space=400)
    _, t0 = _learner_run(path, device_cache_mb=0,
                         producer_mode="thread")
    _, t1 = _learner_run(path, device_cache_mb=0,
                         producer_mode="thread", admit_min_count=4)
    assert not np.array_equal(t0, t1)


def test_tiered_learner_run_matches_untiered(tmp_path):
    """A cold-tier learner run converges to the same model as the
    untiered run of the same data: residency is pure placement. The
    tier gates the device staging fast paths (stream-chunk, on-device
    dedup), so fp32 summation order shifts — close, not bit-equal."""
    path = str(tmp_path / "u.libsvm")
    write_uniform_libsvm(path, rows=200, width=8, id_space=300)
    common = dict(device_cache_mb=0, producer_mode="thread",
                  hash_capacity=1024, V_threshold=0)
    ln_args = [("data_in", path), ("V_dim", "2"), ("lr", "0.1"),
               ("l1", "0.1"), ("l2", "0"), ("num_jobs_per_epoch", "1"),
               ("batch_size", "100"), ("max_num_epochs", "2"),
               ("shuffle", "0"), ("report_interval", "0"),
               ("stop_rel_objv", "0")]

    def run(cold):
        ln = Learner.create("sgd")
        args = ln_args + [(k, str(v)) for k, v in common.items()]
        args += [("cold_tier_rows", str(cold))]
        assert ln.init(args) == []
        seen = []
        ln.add_epoch_end_callback(lambda e, t, v: seen.append(t.loss))
        ln.run()
        return seen, ln.store

    s0, st0 = run(0)
    s1, st1 = run(512)
    np.testing.assert_allclose(s0, s1, rtol=1e-5)
    keys = np.arange(0, 300, dtype=np.int64)
    w0, _, _ = st0.pull(keys)
    w1, _, _ = st1.pull(keys)
    # V is excluded: the tier draws its own virgin-init stream for the
    # tail, so per-slot V starts (and stays) on a different random walk
    np.testing.assert_allclose(w0, w1, rtol=1e-3, atol=1e-6)


# --------------------------------------------------------- pred parity

def test_quantized_checkpoint_pred_parity(rcv1_path, tmp_path):
    """task=pred from an int8 checkpoint tracks the fp32 golden
    predictions within quantization tolerance — the CLI round trip the
    serving path takes (model_meta slot_dtype stamp -> re-quantized
    weights-only load)."""
    from difacto_tpu.__main__ import main

    def train_pred(slot_dtype):
        model = str(tmp_path / f"m_{slot_dtype}")
        pred = str(tmp_path / f"p_{slot_dtype}")
        assert main([f"data_in={rcv1_path}", "lr=1", "l1=1", "l2=1",
                     "V_dim=2", "V_threshold=2", "batch_size=100",
                     "max_num_epochs=3", "shuffle=0",
                     "num_jobs_per_epoch=1", "report_interval=0",
                     f"slot_dtype={slot_dtype}",
                     f"model_out={model}"]) == 0
        assert main(["task=pred", f"model_in={model}", "V_dim=2",
                     f"slot_dtype={slot_dtype}",
                     f"data_val={rcv1_path}", "report_interval=0",
                     f"pred_out={pred}"]) == 0
        return np.array([float(l.split()[-1]) for l in
                         open(pred + "_part-0").read().splitlines()])

    golden = train_pred("fp32")
    quant = train_pred("int8")
    assert len(golden) == len(quant) == 100
    # same sign structure and close scores: quantization noise only
    assert np.mean(np.abs(golden - quant)) < 0.05
    assert np.corrcoef(golden, quant)[0, 1] > 0.98


# --------------------------------------------------------------- chaos

@pytest.mark.chaos
def test_chaos_demote_fault_keeps_victims_serving():
    """Armed ``store.demote:err@1``: every demotion batch is refused —
    victims stay resident and keep serving their exact values, new cold
    keys degrade to OOB zeros for the batch, nothing tears."""
    st = _mk_store(hash_capacity=256, slot_dtype="int8",
                   cold_tier_rows=224, seed=9)
    big = np.arange(1, 400, 3, dtype=np.int64)
    _train_store(st, big[:20], rounds=2, seed=4)
    wpre, Vpre, _ = st.pull(np.sort(big[:20]))
    res_pre = st.tier._resident.copy()
    fi.configure("store.demote:err@1")
    try:
        st.pull(np.sort(big[20:50]))
        assert fi.stats().get("store.demote", 0) > 0
    finally:
        fi.configure("")
    np.testing.assert_array_equal(res_pre, st.tier._resident)
    wpost, Vpost, _ = st.pull(np.sort(big[:20]))
    np.testing.assert_array_equal(wpre, wpost)
    np.testing.assert_array_equal(Vpre, Vpost)


@pytest.mark.chaos
def test_chaos_promote_fault_degrades_batch_only():
    """Armed ``store.promote:err@1``: the promote is refused before the
    scatter — the missing slots read zeros through the OOB lanes for
    this batch, and the store keeps serving its trained rows."""
    st = _mk_store(hash_capacity=256, slot_dtype="fp32",
                   cold_tier_rows=224, seed=9)
    big = np.arange(1, 400, 3, dtype=np.int64)
    _train_store(st, big[:10], rounds=2, seed=5)
    fi.configure("store.promote:err@1")
    try:
        w, V, _ = st.pull(np.sort(big[60:80]))
        assert fi.stats().get("store.promote", 0) > 0
    finally:
        fi.configure("")
    assert np.all(w == 0)
    w2, V2, _ = st.pull(np.sort(big[:10]))
    assert V2 is not None and np.any(V2 != 0)
