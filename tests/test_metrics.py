"""BinClassMetric parity tests (src/loss/bin_class_metric.h):
AUC (area*n with <0.5 flip), Accuracy (majority flip), LogLoss, LogitObjv —
raw sums, never divided by n.
"""

import numpy as np

from difacto_tpu.losses.metrics import (accuracy_times_n, auc_times_n,
                                        logit_objv_np, logloss, rmse_stub)


def brute_auc(label, pred):
    pos = pred[label > 0]
    neg = pred[label <= 0]
    if len(pos) == 0 or len(neg) == 0:
        return 1.0
    wins = sum((p > q) + 0.5 * (p == q) for p in pos for q in neg)
    a = wins / (len(pos) * len(neg))
    return (1 - a if a < 0.5 else a) * len(label)


def test_auc_matches_brute_force():
    rng = np.random.RandomState(0)
    for _ in range(10):
        n = rng.randint(3, 40)
        label = rng.choice([0.0, 1.0], n)
        pred = rng.randn(n).astype(np.float32)
        got = auc_times_n(label, pred)
        # ties are counted differently by rank-sum vs 0.5-credit; avoid ties
        assert abs(got - brute_auc(label, pred)) < 1e-4


def test_auc_degenerate():
    assert auc_times_n(np.ones(5), np.random.randn(5)) == 1.0
    assert auc_times_n(np.zeros(5), np.random.randn(5)) == 1.0
    assert auc_times_n(np.zeros(0), np.zeros(0)) == 0.0


def test_accuracy_majority_flip():
    label = np.array([1, 1, 0, 0], dtype=np.float32)
    pred = np.array([1.0, 1.0, -1.0, 1.0])
    # 3 correct at threshold 0 -> returns 3 (majority side)
    assert accuracy_times_n(label, pred, 0.0) == 3
    # all wrong -> flipped to n (bin_class_metric.h:66)
    assert accuracy_times_n(label, -pred - 0.1, 0.0) >= 2


def test_logloss_finite_at_extremes():
    label = np.array([0.0, 1.0])
    pred = np.array([100.0, -100.0], dtype=np.float32)  # maximally wrong
    v = logloss(label, pred)
    assert np.isfinite(v) and v > 40


def test_logit_objv():
    label = np.array([1.0, 0.0])
    pred = np.array([0.0, 0.0], dtype=np.float32)
    assert abs(logit_objv_np(label, pred) - 2 * np.log(2)) < 1e-6


def test_rmse_stub_sums_raw_diff():
    # the reference's "RMSE" sums raw differences (bin_class_metric.h:94-102)
    assert rmse_stub(np.array([3.0, 1.0]), np.array([1.0, 1.0])) == 2.0
