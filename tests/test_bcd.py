"""BCD learner tests vs the reference goldens (tests/cpp/bcd_learner_test.cc).
"""

import numpy as np
import pytest

from difacto_tpu.learners import Learner
from difacto_tpu.learners.bcd import fea_group_stats, partition_feature

OBJV_DIAG_NEWTON = [
    34.877064, 33.885559, 29.572740, 27.458964, 25.317689, 23.917098,
    22.855843, 22.099876, 21.552682, 21.137216,
]


def run_bcd(rcv1_path, **over):
    args = [("data_in", rcv1_path), ("l1", ".1"), ("lr", ".05"),
            ("block_ratio", "0.001"), ("tail_feature_filter", "0"),
            ("max_num_epochs", "10")]
    d = dict(args)
    d.update({k: str(v) for k, v in over.items()})
    learner = Learner.create("bcd")
    remain = learner.init(list(d.items()))
    assert remain == []
    return learner


def test_partition_feature_single_group():
    ranges = partition_feature(0, [(0, 4)])
    assert len(ranges) == 4
    # contiguous ascending cover of the keyspace
    for i in range(1, 4):
        assert ranges[i - 1][1] >= ranges[i][0] - 1
        assert ranges[i - 1][0] < ranges[i][0]


def test_partition_feature_rejects_bad_bits():
    with pytest.raises(ValueError):
        partition_feature(3, [(0, 1)])


def test_fea_group_stats_sampling():
    from difacto_tpu.data.rowblock import RowBlock
    # 20 rows, 1 feature each; skip=10 samples rows 0 and 10
    blk = RowBlock(offset=np.arange(21, dtype=np.int64),
                   label=np.ones(20, dtype=np.float32),
                   index=np.zeros(20, dtype=np.uint64))
    v = fea_group_stats([blk], 0)
    assert v[0] == 2      # sampled nnz
    assert v[1] == 2      # sampled rows
    assert v[2] == 20     # total rows


def test_bcd_diag_newton_golden(rcv1_path):
    """tests/cpp/bcd_learner_test.cc:9-38: single block (block_ratio=.001),
    relative tolerance 1e-5."""
    learner = run_bcd(rcv1_path)
    seen = []
    learner.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
    learner.run()
    assert len(seen) == 10
    rel = np.abs(np.array(seen) - np.array(OBJV_DIAG_NEWTON)) \
        / np.array(seen)
    assert rel.max() < 1e-5, list(zip(seen, OBJV_DIAG_NEWTON))


@pytest.mark.parametrize("block_ratio", [0.4, 1, 10])
def test_bcd_convergence(rcv1_path, block_ratio):
    """tests/cpp/bcd_learner_test.cc:40-66: converges to the same optimum
    objv 15.884923 (nnz 47) for any block partition."""
    learner = run_bcd(rcv1_path, lr=".8", block_ratio=str(block_ratio),
                      max_num_epochs="50")
    last = {}
    learner.add_epoch_end_callback(lambda e, p: last.update(p=p))
    learner.run()
    assert abs(last["p"].objv - 15.884923) / last["p"].objv < 1e-3
    assert last["p"].nnz_w == 47


def test_bcd_save_load(rcv1_path, tmp_path):
    m = str(tmp_path / "bcd_model")
    learner = run_bcd(rcv1_path, max_num_epochs="5", model_out=m)
    learner.run()
    l2 = run_bcd(rcv1_path, max_num_epochs="1", model_in=m)
    seen = []
    l2.add_epoch_end_callback(lambda e, p: seen.append(p.objv))
    l2.run()
    # warm-started epoch continues below the cold epoch-0 objective
    assert seen[0] < OBJV_DIAG_NEWTON[0]
