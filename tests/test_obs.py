"""Unified observability subsystem (ISSUE 4): registry exactness under
concurrency, snapshot merge algebra, cross-process counter equality
(thread vs process producer transports), Chrome-trace validity, the
Prometheus ``#metrics`` serve endpoint, the JSONL flusher + obs_report
renderer, and the bounded-overhead guard for the always-on registry.

Every multiprocess/network test runs under the suite's SIGALRM deadline
convention (test_producer_process.py).
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from difacto_tpu.obs import (REGISTRY, MetricsFlusher, Registry,
                             hist_quantiles, merge_into, merged_snapshot,
                             render_prometheus, trace)


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ------------------------------------------------------------- registry

def test_counter_concurrent_writer_exactness():
    """8 threads x 20k increments land exactly: the per-thread cells are
    single-writer, so no increment can be lost to a data race."""
    reg = Registry(enabled=True)
    c = reg.counter("x_total").labels(worker="w")

    def work():
        for _ in range(20_000):
            c.inc()

    with deadline(60):
        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert reg.value("x_total", worker="w") == 160_000
    # labeled series are independent
    assert reg.value("x_total", worker="other") == 0


def test_histogram_merge_associativity():
    """Histogram snapshots merge exactly and associatively:
    (a + b) + c == a + (b + c) == one registry observing everything."""
    rng = np.random.RandomState(3)
    samples = rng.lognormal(mean=-5, sigma=2, size=900)
    regs = [Registry(enabled=True) for _ in range(3)]
    all_in_one = Registry(enabled=True)
    for i, v in enumerate(samples):
        regs[i % 3].histogram("lat_seconds").observe(float(v))
        all_in_one.histogram("lat_seconds").observe(float(v))
    a, b, c = (r.snapshot() for r in regs)

    left = merge_into(merge_into({}, a), b)
    left = merge_into(left, c)
    right = merge_into(merge_into({}, b), c)
    right = merge_into(right, a)
    key = ()
    hl = left["hists"]["lat_seconds"][key]
    hr = right["hists"]["lat_seconds"][key]
    ho = all_in_one.snapshot()["hists"]["lat_seconds"][key]
    assert hl["counts"] == hr["counts"] == ho["counts"]
    assert hl["count"] == len(samples)
    np.testing.assert_allclose(hl["sum"], ho["sum"], rtol=1e-9)
    np.testing.assert_allclose(hl["sum"], hr["sum"], rtol=1e-9)
    # quantiles derive from the merged buckets and bracket the truth
    q = hist_quantiles(hl)
    exact = np.percentile(samples, 50)
    bounds = hl["bounds"]
    i = next(j for j, bnd in enumerate(bounds) if q[0.5] <= bnd)
    lo = bounds[i - 1] if i else 0.0
    assert lo <= exact <= bounds[min(i + 1, len(bounds) - 1)] * 1.0001


def test_gauge_and_noop_registry():
    reg = Registry(enabled=True)
    reg.gauge("depth").set(7)
    reg.gauge("depth").inc(3)
    assert reg.value("depth") == 10
    off = Registry(enabled=False)
    off.counter("a").inc()
    off.histogram("b").observe(1.0)
    off.gauge("c").set(5)
    snap = off.snapshot()
    assert not snap["counters"] and not snap["hists"] and not snap["gauges"]


# ----------------------------------------------- cross-process equality

def counted_items(part):
    """Module-level (spawn pickles by reference): every yielded item
    counts rows + bytes into the WORKER's process-global registry."""
    from difacto_tpu.obs import REGISTRY as R
    rows = R.counter("obs_test_rows_total")
    byts = R.counter("obs_test_bytes_total")
    for j in range(4):
        a = np.full(16, part * 10 + j, dtype=np.int64)
        rows.inc()
        byts.inc(a.nbytes)
        yield (part, j, a)


def test_cross_process_snapshot_equality():
    """The exactness contract of obs/proc.py: a process-transport run
    reports IDENTICAL row/byte counters to a thread-transport run of the
    same parts — cross-process totals are exact, not sampled."""
    from difacto_tpu.data.producer_pool import (OrderedProducerPool,
                                                ProcessProducerPool)
    with deadline(120):
        # thread transport: counted_items runs in-process, so the global
        # registry delta is the thread-side truth
        before_rows = REGISTRY.value("obs_test_rows_total")
        before_bytes = REGISTRY.value("obs_test_bytes_total")
        t_items = list(OrderedProducerPool(5, counted_items, n_workers=2))
        t_rows = REGISTRY.value("obs_test_rows_total") - before_rows
        t_bytes = REGISTRY.value("obs_test_bytes_total") - before_bytes

        # process transport: workers count into their own registries; the
        # pool ships snapshots into this fresh target registry
        reg = Registry(enabled=True)
        p_pool = ProcessProducerPool(5, counted_items, n_workers=2,
                                     slot_bytes=1 << 20, obs_registry=reg)
        p_items = list(p_pool)
    assert len(t_items) == len(p_items) == 20
    assert t_rows == 20 and t_bytes == 20 * 16 * 8
    assert reg.value("obs_test_rows_total") == t_rows
    assert reg.value("obs_test_bytes_total") == t_bytes
    # the worker-side ring-wait stage crossed the boundary too
    assert reg.value("stage_seconds_total", stage="ring_wait") >= 0.0


# ----------------------------------------------------------------- trace

def test_chrome_trace_json_valid(tmp_path):
    """Emitted span files are valid Chrome trace JSON: an object with a
    traceEvents list of complete ("X") events carrying name/ts/dur/
    pid/tid, with nesting recorded through parent span ids."""
    trace.drain_events()  # isolate from any ambient events
    trace.start()
    try:
        with trace.span("outer", part=3):
            with trace.span("inner"):
                time.sleep(0.002)
        path = str(tmp_path / "trace.json")
        assert trace.save(path) == path
    finally:
        trace.stop()
        trace.drain_events()
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert len(evs) == 2
    by_name = {e["name"]: e for e in evs}
    for e in evs:
        assert e["ph"] == "X"
        for k in ("ts", "dur", "pid", "tid", "name", "args"):
            assert k in e
    inner, outer = by_name["inner"], by_name["outer"]
    assert inner["args"]["parent"] == outer["args"]["span_id"]
    assert inner["dur"] >= 2000  # the 2ms sleep, in microseconds
    # inner nests inside outer on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1


# ------------------------------------------------------------ exporters

def test_prometheus_render_and_flusher(tmp_path):
    reg = Registry(enabled=True)
    reg.counter("reqs_total", "requests").labels(code="200").inc(5)
    reg.gauge("depth").set(3)
    h = reg.histogram("lat_seconds")
    for v in (0.001, 0.002, 0.004, 0.2):
        h.observe(v)
    txt = render_prometheus(reg.snapshot())
    assert "# TYPE difacto_reqs_total counter" in txt
    assert 'difacto_reqs_total{code="200"} 5' in txt
    assert "difacto_depth 3" in txt
    assert 'difacto_lat_seconds_bucket{le="+Inf"} 4' in txt
    assert 'quantile="0.99"' in txt and "_sum" in txt and "_count" in txt

    log_path = str(tmp_path / "m.jsonl")
    fl = MetricsFlusher(log_path, interval_s=999.0, registries=[reg])
    fl.flush()
    reg.counter("reqs_total").labels(code="200").inc()
    fl.close()  # final flush
    lines = [json.loads(l) for l in open(log_path)]
    assert len(lines) == 2
    assert lines[-1]["metrics"]["counters"]["reqs_total"]["code=200"] == 6

    # obs_report renders the log (and must not crash on real shapes)
    out = subprocess.run(
        [sys.executable, "tools/obs_report.py", "--metrics", log_path],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "lat_seconds" in out.stdout


def test_flusher_rotation_caps_file(tmp_path):
    """Satellite (ISSUE 5): ``max_mb`` rolls the JSONL to ``.1`` before
    a flush would breach the cap — a weeks-long serve process holds at
    most ~2x max_mb of metrics log — and obs_report still reads the
    history through the roll."""
    reg = Registry(enabled=True)
    reg.counter("reqs_total", "requests").inc()
    log_path = str(tmp_path / "m.jsonl")
    # measure one real snapshot line, then cap at ~2.5 lines per file
    probe = str(tmp_path / "probe.jsonl")
    MetricsFlusher(probe, interval_s=999.0, registries=[reg]).flush()
    cap_mb = (os.path.getsize(probe) * 2.5) / (1 << 20)
    fl = MetricsFlusher(log_path, interval_s=999.0, registries=[reg],
                        max_mb=cap_mb)
    for i in range(12):
        reg.counter("reqs_total").inc()
        fl.flush()
    fl.close()   # never started; close() just final-flushes
    cap_bytes = cap_mb * (1 << 20)
    assert os.path.exists(log_path + ".1"), "never rotated"
    assert os.path.getsize(log_path) <= cap_bytes
    assert os.path.getsize(log_path + ".1") <= cap_bytes
    # the reader walks .1 then the live file: newest snapshot wins and
    # nothing crashes on the roll boundary
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import obs_report
    snap = obs_report.load_last_snapshot(log_path)
    assert snap["counters"]["reqs_total"][""] == 13
    # live file empty right after a roll: history still resolves
    empty = str(tmp_path / "e.jsonl")
    os.replace(log_path, empty + ".1")
    open(empty, "w").close()
    assert obs_report.load_last_snapshot(empty)[
        "counters"]["reqs_total"][""] == 13


# -------------------------------------------------------- serve #metrics

def test_serve_metrics_endpoint():
    """Acceptance: ``#metrics`` on a live task=serve returns Prometheus
    text with the serve latency histogram quantiles, queue depth, shed
    count and model_generation — while ``#stats`` keeps its JSON wire
    format (backward compatible keys)."""
    from difacto_tpu.serve import ServeClient, ServeServer
    from difacto_tpu.store.local import SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    param = SGDUpdaterParam(V_dim=0, l1_shrk=False, hash_capacity=1 << 10)
    store = SlotStore(param, read_only=True)
    with deadline(120):
        srv = ServeServer(store, batch_size=8, max_delay_ms=1.0,
                          queue_cap=64).start()
        try:
            with ServeClient(srv.host, srv.port) as c:
                rows = [b"0 %d:1 %d:1" % (i, i + 7) for i in range(30)]
                scores = c.predict(rows)
                assert all(s is not None for s in scores)
                srv.stats.record_shed(2)  # a shed must surface in both
                txt = c.metrics()
                st = c.stats()
        finally:
            srv.close()
    # Prometheus surface
    assert "# TYPE difacto_serve_latency_seconds histogram" in txt
    assert 'difacto_serve_latency_seconds_quantile{quantile="0.5"}' in txt
    assert 'quantile="0.99"' in txt
    assert "difacto_serve_queue_depth" in txt
    assert "difacto_serve_shed_total 2" in txt
    assert "difacto_serve_model_generation 1" in txt
    assert "difacto_serve_requests_total 30" in txt
    # #stats wire format unchanged, and consistent with the registry
    for k in ("requests", "responses", "shed", "errors", "qps", "batches",
              "batch_occupancy", "queue_depth", "queue_depth_max",
              "p50_ms", "p99_ms", "model_generation"):
        assert k in st, k
    assert st["requests"] == 30 and st["shed"] == 2


# ------------------------------------------------------- overhead guard

def _synthetic_step_loop(reg, steps: int = 200) -> float:
    """A small training-step stand-in: real numpy work plus the per-step
    metric traffic the instrumented hot paths actually issue."""
    c = reg.counter("guard_seconds_total").labels(stage="step")
    rows = reg.counter("guard_rows_total")
    h = reg.histogram("guard_step_seconds")
    x = np.random.RandomState(0).rand(192, 192).astype(np.float32)
    t0 = time.perf_counter()
    acc = 0.0
    for _ in range(steps):
        y = x @ x
        acc += float(y[0, 0])
        c.inc(1e-3)
        rows.inc(256)
        h.observe(1e-3)
    assert acc != 0
    return time.perf_counter() - t0


def test_metrics_overhead_bounded():
    """Acceptance guard: the enabled registry on a synthetic step loop
    stays within noise of the DIFACTO_OBS=off no-op registry — cheap
    enough to leave on by default. Best-of-3 each to damp scheduler
    noise; the bound is generous (50% + 50ms) so only a real hot-path
    regression (a lock on the inc path, an allocation per observe)
    trips it."""
    on = Registry(enabled=True)
    off = Registry(enabled=False)
    assert off.counter("guard_seconds_total") is not None
    with deadline(120):
        _synthetic_step_loop(on, steps=20)   # warm both paths
        _synthetic_step_loop(off, steps=20)
        t_on = min(_synthetic_step_loop(on) for _ in range(3))
        t_off = min(_synthetic_step_loop(off) for _ in range(3))
    assert t_on <= t_off * 1.5 + 0.05, (t_on, t_off)


# ------------------------------------------------- learner stage source

def test_learner_stage_stats_from_registry(rcv1_path):
    """The streamed stage decomposition bench.py reports is sourced from
    the learner's obs registry (stage_seconds_total), including the
    parse/pack split, and the metrics_path knob writes a renderable
    JSONL log."""
    import tempfile

    from difacto_tpu.learners import Learner
    with deadline(300), tempfile.TemporaryDirectory() as d:
        mpath = os.path.join(d, "m.jsonl")
        ln = Learner.create("sgd")
        ln.init([("data_in", rcv1_path), ("V_dim", "0"), ("l2", "1"),
                 ("l1", "0"), ("lr", "1"), ("num_jobs_per_epoch", "2"),
                 ("batch_size", "50"), ("max_num_epochs", "1"),
                 ("shuffle", "0"), ("report_interval", "0"),
                 ("stop_rel_objv", "0"), ("device_cache_mb", "0"),
                 ("hash_capacity", "4096"), ("producer_mode", "thread"),
                 ("metrics_path", mpath), ("metrics_interval_s", "999")])
        ln.run()
        st = ln.stage_stats()
        # the registry split parse from pack (the old private timer
        # lumped them) and accounted the device steps
        assert st["parse_s"] > 0 and st["step_s"] > 0
        assert set(st) >= {"parse_s", "pack_s", "ring_wait_s",
                           "transfer_s", "step_s", "producer_mode"}
        snap = ln.obs.snapshot()
        assert snap["counters"]["train_rows_total"][()] == 100
        assert snap["hists"]["train_step_seconds"][()]["count"] > 0
        # the final flush landed and carries the same stage counters
        lines = [json.loads(l) for l in open(mpath)]
        stages = lines[-1]["metrics"]["counters"]["stage_seconds_total"]
        assert any("parse" in k for k in stages)
