"""Native libsvm parser: byte-for-byte equivalence with the Python parser
and a throughput sanity check."""

import numpy as np
import pytest

from difacto_tpu.data.parsers import parse_libsvm
from difacto_tpu.data.native_parsers import parse_libsvm_native
from difacto_tpu.native import get_lib

needs_native = pytest.mark.skipif(get_lib() is None,
                                  reason="native toolchain unavailable")


@needs_native
def test_native_matches_python_on_fixture(rcv1_path):
    chunk = open(rcv1_path, "rb").read()
    a = parse_libsvm(chunk)
    b = parse_libsvm_native(chunk)
    assert a.size == b.size == 100
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.index, b.index)
    np.testing.assert_allclose(a.values_or_ones(), b.values_or_ones(),
                               rtol=1e-6)


@needs_native
def test_native_edge_cases():
    # empty chunk, blank lines, no-feature rows, binary values, \r\n
    cases = [
        b"",
        b"\n\n\n",
        b"1\n0\n",                       # label-only rows
        b"1 5:1 7:1\n0 2:1\n",           # all-ones -> value elided
        b"-1 3:0.5 9:2.25\r\n+1 1:1e-3\r\n",
        b"0.5 18446744073709551615:4\n",  # uint64 max feature id
    ]
    for chunk in cases:
        a = parse_libsvm(chunk)
        b = parse_libsvm_native(chunk)
        assert a.size == b.size, chunk
        np.testing.assert_array_equal(a.offset, b.offset)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.index, b.index)
        np.testing.assert_allclose(a.values_or_ones(), b.values_or_ones(),
                                   rtol=1e-6, err_msg=str(chunk))
    # binary elision: all values 1 -> value is None
    assert parse_libsvm_native(b"1 5:1 7:1\n").value is None
    assert parse_libsvm_native(b"1 5:2\n").value is not None


@needs_native
def test_native_rejects_malformed():
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 nocolon\n")
    # empty value must not swallow the next line's label (strtof skips \n)
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 5:\n0 3:1\n")
    # negative index must not wrap to a huge uint64
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 -5:2\n")
    # exotic whitespace after ':' must not swallow the next line either
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 5:\x0c\n0 3:1\n")
    # id one past uint64 max must error, not clamp
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 18446744073709551616:1\n")


@needs_native
def test_native_is_faster(rcv1_path):
    import time
    chunk = open(rcv1_path, "rb").read() * 50  # ~5000 rows

    def best_of(f, n=3):
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            f(chunk)
            times.append(time.perf_counter() - t0)
        return min(times)

    py = best_of(parse_libsvm)
    native = best_of(parse_libsvm_native)
    # typically 10-30x faster; generous bound to stay robust under CI load
    assert native < py * 0.8, (native, py)


@needs_native
def test_reader_uses_native(rcv1_path):
    """End to end: the Reader path produces the same 100 rows."""
    from difacto_tpu.data import Reader
    blocks = list(Reader(rcv1_path, "libsvm"))
    assert sum(b.size for b in blocks) == 100


@needs_native
def test_murmur64a_native_matches_python():
    """The C++ and pure-Python MurmurHash64A must agree bit for bit —
    hosts with and without the toolchain must build the same feature
    space (parsers.py _hash64 docstring contract)."""
    import ctypes
    from difacto_tpu.data.parsers import _hash64
    lib = get_lib()
    for s in [b"", b"a", b"ab", b"criteo", b"x" * 7, b"y" * 8, b"z" * 9,
              b"longer_categorical_value" * 3, bytes(range(256))]:
        assert _hash64(s) == lib.difacto_murmur64a(s, len(s), 0), s


def _criteo_chunk(nrows, with_empties=True, seed=0):
    rng = np.random.RandomState(seed)
    lines = []
    for _ in range(nrows):
        ints = [str(rng.randint(0, 1000))
                if (not with_empties or rng.rand() > 0.2) else ""
                for _ in range(13)]
        cats = [f"c{rng.randint(0, 9999):x}"
                if (not with_empties or rng.rand() > 0.1) else ""
                for _ in range(26)]
        lines.append(f"{rng.randint(0, 2)}\t" + "\t".join(ints + cats))
    return ("\n".join(lines) + "\n").encode()


@needs_native
def test_criteo_native_matches_python():
    from difacto_tpu.data.parsers import parse_criteo
    from difacto_tpu.data.native_parsers import parse_criteo_native
    chunk = _criteo_chunk(300)
    a = parse_criteo(chunk)
    b = parse_criteo_native(chunk)
    assert a.size == b.size == 300
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.index, b.index)


@needs_native
def test_criteo_native_test_mode_and_crlf():
    """is_train=False (label-less rows; regression: buffer sizing) and
    CRLF blank lines (regression: phantom rows) match the Python parser."""
    from difacto_tpu.data.parsers import parse_criteo
    from difacto_tpu.data.native_parsers import parse_criteo_native
    # fully-populated label-less rows — the worst case for nnz sizing
    rng = np.random.RandomState(1)
    lines = ["\t".join(str(rng.randint(0, 99)) for _ in range(39))
             for _ in range(8)]
    chunk = ("\n".join(lines) + "\n").encode()
    a = parse_criteo(chunk, is_train=False)
    b = parse_criteo_native(chunk, is_train=False)
    assert a.size == b.size == 8
    np.testing.assert_array_equal(a.index, b.index)
    assert (b.label == 0).all()

    crlf = b"1\ta\tb\r\n\r\n0\tc\r\n"
    a = parse_criteo(crlf)
    b = parse_criteo_native(crlf)
    assert a.size == b.size == 2  # the blank CRLF line is not a row
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.index, b.index)


@needs_native
def test_adfea_native_matches_python():
    from difacto_tpu.data.native_parsers import parse_adfea_native
    from difacto_tpu.data.parsers import parse_adfea
    rng = np.random.RandomState(3)
    lines = []
    for i in range(200):
        feats = " ".join(f"{rng.randint(0, 1 << 40)}:{rng.randint(0, 9000)}"
                         for _ in range(rng.randint(1, 12)))
        lines.append(f"{i} {rng.randint(1, 5)} {rng.randint(0, 2)} {feats}")
    chunk = ("\n".join(lines) + "\n").encode()
    a = parse_adfea(chunk)
    b = parse_adfea_native(chunk)
    assert a.size == b.size == 200
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.index, b.index)
    assert a.value is None and b.value is None

    # space-only separators (single line, the max_rows sizing edge) and
    # tab separators
    flat = (" ".join(lines[:50])).encode()
    a, b = parse_adfea(flat), parse_adfea_native(flat)
    assert a.size == b.size == 50
    np.testing.assert_array_equal(a.index, b.index)
    tabbed = chunk.replace(b" ", b"\t")
    a, b = parse_adfea(tabbed), parse_adfea_native(tabbed)
    np.testing.assert_array_equal(a.offset, b.offset)
