"""Native libsvm parser: byte-for-byte equivalence with the Python parser
and a throughput sanity check."""

import numpy as np
import pytest

from difacto_tpu.data.parsers import parse_libsvm
from difacto_tpu.data.native_parsers import parse_libsvm_native
from difacto_tpu.native import get_lib

needs_native = pytest.mark.skipif(get_lib() is None,
                                  reason="native toolchain unavailable")


@needs_native
def test_native_matches_python_on_fixture(rcv1_path):
    chunk = open(rcv1_path, "rb").read()
    a = parse_libsvm(chunk)
    b = parse_libsvm_native(chunk)
    assert a.size == b.size == 100
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.index, b.index)
    np.testing.assert_allclose(a.values_or_ones(), b.values_or_ones(),
                               rtol=1e-6)


@needs_native
def test_native_edge_cases():
    # empty chunk, blank lines, no-feature rows, binary values, \r\n
    cases = [
        b"",
        b"\n\n\n",
        b"1\n0\n",                       # label-only rows
        b"1 5:1 7:1\n0 2:1\n",           # all-ones -> value elided
        b"-1 3:0.5 9:2.25\r\n+1 1:1e-3\r\n",
        b"0.5 18446744073709551615:4\n",  # uint64 max feature id
    ]
    for chunk in cases:
        a = parse_libsvm(chunk)
        b = parse_libsvm_native(chunk)
        assert a.size == b.size, chunk
        np.testing.assert_array_equal(a.offset, b.offset)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.index, b.index)
        np.testing.assert_allclose(a.values_or_ones(), b.values_or_ones(),
                                   rtol=1e-6, err_msg=str(chunk))
    # binary elision: all values 1 -> value is None
    assert parse_libsvm_native(b"1 5:1 7:1\n").value is None
    assert parse_libsvm_native(b"1 5:2\n").value is not None


@needs_native
def test_native_rejects_malformed():
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 nocolon\n")
    # empty value must not swallow the next line's label (strtof skips \n)
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 5:\n0 3:1\n")
    # negative index must not wrap to a huge uint64
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 -5:2\n")
    # exotic whitespace after ':' must not swallow the next line either
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 5:\x0c\n0 3:1\n")
    # id one past uint64 max must error, not clamp
    with pytest.raises(ValueError):
        parse_libsvm_native(b"1 18446744073709551616:1\n")


@needs_native
def test_native_is_faster(rcv1_path):
    import time
    chunk = open(rcv1_path, "rb").read() * 50  # ~5000 rows

    def best_of(f, n=3):
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            f(chunk)
            times.append(time.perf_counter() - t0)
        return min(times)

    py = best_of(parse_libsvm)
    native = best_of(parse_libsvm_native)
    # typically 10-30x faster; generous bound to stay robust under CI load
    assert native < py * 0.8, (native, py)


@needs_native
def test_reader_uses_native(rcv1_path):
    """End to end: the Reader path produces the same 100 rows."""
    from difacto_tpu.data import Reader
    blocks = list(Reader(rcv1_path, "libsvm"))
    assert sum(b.size for b in blocks) == 100
