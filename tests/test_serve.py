"""Online serving subsystem (ISSUE 2): read-only weights-only stores,
the bucketed predict executor, micro-batching TCP serving, overload
shedding, and the pred<->serve golden contract.

Every network-bearing test runs under an explicit SIGALRM deadline (the
test_producer_process.py convention): a wedged server or a lost response
must fail the suite loudly, not eat the tier-1 timeout.
"""

import contextlib
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from difacto_tpu.__main__ import main


@contextlib.contextmanager
def deadline(seconds: int):
    def on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def trained_model(rcv1_path, tmp_path_factory):
    """A small trained sgd model (dictionary store) + its task=pred
    output on the same 100 fixture rows."""
    d = tmp_path_factory.mktemp("serve_model")
    model = str(d / "model")
    args = [f"data_in={rcv1_path}", "lr=1", "l1=1", "l2=1",
            "batch_size=100", "max_num_epochs=3", "shuffle=0",
            "num_jobs_per_epoch=1", "report_interval=0",
            f"model_out={model}"]
    assert main(args) == 0
    pred_out = str(d / "pred")
    assert main(args + ["task=pred", f"model_in={model}",
                        f"data_val={rcv1_path}",
                        f"pred_out={pred_out}"]) == 0
    with open(pred_out + "_part-0", "rb") as f:
        pred_lines = f.read().splitlines()
    assert len(pred_lines) == 100
    return {"model": model, "pred_lines": pred_lines}


def fixture_rows(rcv1_path):
    with open(rcv1_path, "rb") as f:
        return [l for l in f.read().splitlines() if l.strip()]


# ----------------------------------------------------- read-only store

def test_read_only_store_weights_only(trained_model):
    """Satellite: weights-only / read-only load — push raises cleanly,
    lookups never insert, aux is never materialized, and the served
    weights equal the fully-loaded ones."""
    from difacto_tpu.serve import open_serving_store
    from difacto_tpu.store.local import K_GRADIENT, SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    store, meta, _ = open_serving_store(trained_model["model"])
    assert meta["learner"] == "sgd" and store.read_only
    n_before = store.num_features
    with pytest.raises(RuntimeError, match="read-only store"):
        store.push(np.array([1, 2, 3], np.uint64), K_GRADIENT,
                   np.zeros(3, np.float32))
    # unknown ids resolve to TRASH without growing the dictionary
    slots = store.map_keys(np.array([1 << 60, 2 << 60], np.uint64))
    assert (slots == 0).all()
    assert store.num_features == n_before

    # weights match a full (aux-bearing) load of the same checkpoint
    full = SlotStore(SGDUpdaterParam(V_dim=meta["V_dim"]))
    full.load(meta["path"])
    keys = full._keys[:16]
    w_ro, _, _ = store.pull(keys)
    w_full, _, _ = full.pull(keys)
    np.testing.assert_array_equal(w_ro, w_full)


def test_weights_only_skips_aux(tmp_path):
    """An aux checkpoint loaded weights-only serves the same weights and
    never copies z/sqrt_g into the assembled state."""
    from difacto_tpu.store.local import K_GRADIENT, SlotStore
    from difacto_tpu.updaters.sgd_updater import SGDUpdaterParam

    param = SGDUpdaterParam(V_dim=0, l1=0.0, lr=1.0, hash_capacity=256)
    st = SlotStore(param)
    keys = np.arange(1, 40, dtype=np.uint64)
    st.push(keys, K_GRADIENT, np.linspace(-1, 1, 39).astype(np.float32))
    path = str(tmp_path / "ck")
    st.save(path, save_aux=True)

    ro = SlotStore(param, read_only=True)
    ro.load(path)  # defaults to weights_only on a read-only store
    w_ro, _, _ = ro.pull(keys)
    w_tr, _, _ = st.pull(keys)
    np.testing.assert_array_equal(w_ro, w_tr)
    # aux columns of the read-only state are all zero (never loaded)
    from difacto_tpu.updaters.sgd_updater import scal_cols
    _, z, sg, _, _ = scal_cols(param, ro.state)
    assert float(np.abs(np.asarray(z)).sum()) == 0.0
    assert float(np.abs(np.asarray(sg)).sum()) == 0.0


# ------------------------------------------------------- routed errors

def test_pred_routed_error_names_learner(tmp_path):
    """Satellite: the task=pred learner error names the learner that
    produced model_in (from the checkpoint meta) and points at
    task=serve."""
    model = str(tmp_path / "lbfgs_model.npz")
    np.savez(model, feaids=np.arange(5, dtype=np.uint64),
             lens=np.ones(5, np.int64),
             weights=np.ones(5, np.float32),
             V_dim=np.array(4), learner=np.array("lbfgs"))
    with pytest.raises(ValueError) as ei:
        main(["task=pred", "learner=lbfgs", f"model_in={model}"])
    msg = str(ei.value)
    assert "learner='lbfgs'" in msg and "produced by" in msg
    assert "task=serve" in msg


def test_serve_rejects_non_sgd_model(tmp_path):
    from difacto_tpu.serve import open_serving_store
    model = str(tmp_path / "bcd_model.npz")
    np.savez(model, feaids=np.arange(3, dtype=np.uint64),
             w=np.ones(3, np.float32), learner=np.array("bcd"))
    with pytest.raises(ValueError, match="learner='bcd'"):
        open_serving_store(model)


# ------------------------------------------------------------ serving

def test_serve_smoke_and_clean_shutdown(trained_model, rcv1_path):
    """Tier-1 smoke (satellite): ephemeral port, score 100 rows, stats
    flow, and a clean shutdown that leaves no threads or sockets."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(120):
        threads_before = set(threading.enumerate())
        store, _, _ = open_serving_store(trained_model["model"])
        # batch_size=100 + generous delay: each pipelined 100-row round
        # forms ONE deterministic micro-batch, so the steady-state
        # assertion below is about bucket caching, not arrival timing
        srv = ServeServer(store, batch_size=100,
                          max_delay_ms=200.0).start()
        port = srv.port
        try:
            with ServeClient(srv.host, port) as c:
                resp = c.predict(rows)
                assert len(resp) == 100
                assert all(r is not None and 0.0 < r < 1.0 for r in resp)
                # steady state: scoring the same traffic again compiles
                # nothing new — every dispatch is a bucket hit
                st0 = c.stats()
                c.predict(rows)
                st1 = c.stats()
        finally:
            srv.close()
            srv.close()  # idempotent
        assert st1["buckets_compiled"] == st0["buckets_compiled"]
        assert st1["bucket_hits"] > st0["bucket_hits"]
        assert st1["responses"] == 200 and st1["shed"] == 0
        assert st1["p50_ms"] > 0 and st1["p99_ms"] >= st1["p50_ms"]
        # no serving threads survive close()
        leftover = [t for t in threading.enumerate()
                    if t not in threads_before and t.is_alive()]
        assert not leftover, f"threads leaked: {leftover}"
        # the listening socket is really gone
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", port), timeout=0.5)


def test_serve_matches_pred_bit_for_bit(trained_model, rcv1_path):
    """Golden satellite + acceptance: serve responses are byte-identical
    to the task=pred output for the same rows (both ride the same
    bucketed predict executor and the same %g formatting)."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(120):
        store, _, _ = open_serving_store(trained_model["model"])
        # batch_size=100 + generous delay: the pipelined client's 100
        # rows form ONE micro-batch, the same batch task=pred scored
        srv = ServeServer(store, batch_size=100,
                          max_delay_ms=200.0).start()
        try:
            with ServeClient(srv.host, srv.port) as c:
                resp = c.score_lines(rows)
        finally:
            srv.close()
    pred_probs = [l.split(b"\t")[1] for l in trained_model["pred_lines"]]
    assert resp == pred_probs


def test_serve_cli_task(trained_model, rcv1_path, tmp_path):
    """task=serve end-to-end through the CLI: ready-file handshake,
    scoring over TCP, bounded lifetime exit."""
    rows = fixture_rows(rcv1_path)
    ready = str(tmp_path / "ready")
    rc = {}

    def run():
        rc["exit"] = main([
            "task=serve", f"model_in={trained_model['model']}",
            "serve_max_seconds=8", f"serve_ready_file={ready}",
            "serve_batch_size=64"])

    with deadline(120):
        t = threading.Thread(target=run)
        t.start()
        while not os.path.exists(ready):
            time.sleep(0.02)
            assert t.is_alive(), "serve CLI exited before listening"
        host, port = open(ready).read().split()
        from difacto_tpu.serve import ServeClient
        with ServeClient(host, int(port)) as c:
            got = c.predict(rows[:10])
            assert all(g is not None for g in got)
            st = c.stats()
            assert st["responses"] == 10
        t.join(timeout=60)  # serve_max_seconds bounds the lifetime
        assert not t.is_alive() and rc["exit"] == 0


def test_overload_sheds_and_stays_bounded(trained_model, rcv1_path):
    """Satellite: open-loop loadgen at ~2x sustainable QPS — the bounded
    admission queue sheds (non-zero shed count), depth never exceeds the
    cap, and every request is answered (no deadline-missed hang; the
    SIGALRM deadline is the hang detector)."""
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from loadgen import run_loadgen

    from difacto_tpu.serve import ServeServer, open_serving_store
    rows = fixture_rows(rcv1_path)
    with deadline(180):
        store, _, _ = open_serving_store(trained_model["model"])
        srv = ServeServer(store, batch_size=64, max_delay_ms=2.0,
                          queue_cap=128)
        # throttle the executor so "sustainable" is known and small:
        # <= 64 rows per >= 40 ms batch ~= 1.6k rows/s ceiling
        real = srv.batcher.predict_fn

        def slow_predict(blk):
            time.sleep(0.04)
            return real(blk)

        srv.batcher.predict_fn = slow_predict
        srv.start()
        try:
            # warm the shape buckets off the measured window
            run_loadgen(srv.host, srv.port, rows, qps=200, duration_s=0.5)
            rep = run_loadgen(srv.host, srv.port, rows, qps=3200,
                              duration_s=2.0)
            snap = srv.stats_snapshot()
        finally:
            srv.close()
    assert rep["shed"] > 0, rep
    assert rep["ok"] > 0, rep
    # every sent request was answered — shed fast, never dropped silently
    assert rep["ok"] + rep["shed"] + rep["err"] == rep["sent"], rep
    # admission stays bounded at the configured cap
    assert snap["queue_depth_max"] <= 128, snap
    assert snap["shed"] == rep["shed"]


def test_parse_endpoints_grammar():
    """One endpoint-list grammar for client/loadgen/takeover
    (config.parse_endpoints)."""
    from difacto_tpu.config import parse_endpoints

    assert parse_endpoints("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_endpoints(" a:1 , b:2 ") == [("a", 1), ("b", 2)]
    assert parse_endpoints([("h", 3), "i:4"]) == [("h", 3), ("i", 4)]
    with pytest.raises(ValueError, match="bad endpoint"):
        parse_endpoints("noport")
    with pytest.raises(ValueError, match="empty endpoint"):
        parse_endpoints("")


def _free_port() -> int:
    """A port that was just free — nothing listens on it afterwards, so
    connecting yields ECONNREFUSED (the dead-replica stand-in)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_client_multi_endpoint_failover(trained_model, rcv1_path):
    """ISSUE 5 client leg, unit level: a dead first endpoint is skipped
    at connect; killing the active replica mid-call fails the unanswered
    tail over to the next one; per-endpoint health tracks ejection."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(120):
        store, _, _ = open_serving_store(trained_model["model"])
        srv1 = ServeServer(store, batch_size=32, max_delay_ms=2.0).start()
        srv2 = ServeServer(store, batch_size=32, max_delay_ms=2.0).start()
        dead = _free_port()
        try:
            with ServeClient(endpoints=[("127.0.0.1", dead),
                                        (srv1.host, srv1.port),
                                        (srv2.host, srv2.port)],
                             retries=2, eject_after=1,
                             reprobe_s=30.0) as c:
                # constructor already failed over past the dead replica
                assert c.port == srv1.port
                assert c.failovers >= 1
                got = c.predict(rows[:5])
                assert all(g is not None for g in got)
                eh = c.endpoints_health()
                assert eh[0]["fails"] >= 1 and eh[0]["ejected"]
                assert eh[1]["active"] and not eh[1]["ejected"]
                # kill the active replica: the tail fails over to srv2
                srv1.close()
                got = c.predict(rows[:10])
                assert all(g is not None for g in got)
                assert c.port == srv2.port
        finally:
            srv1.close()
            srv2.close()


def test_client_ejection_and_timed_reprobe(trained_model):
    """An ejected endpoint comes back after reprobe_s — the first use
    after the window is the probe, not a permanent blacklist."""
    from difacto_tpu.serve import (ServeClient, ServeServer,
                                   open_serving_store)
    with deadline(60):
        store, _, _ = open_serving_store(trained_model["model"])
        srv = ServeServer(store, batch_size=8, max_delay_ms=1.0).start()
        dead = _free_port()
        try:
            with ServeClient(endpoints=[("127.0.0.1", dead),
                                        (srv.host, srv.port)],
                             retries=1, eject_after=1,
                             reprobe_s=0.2) as c:
                assert c.endpoints_health()[0]["ejected"]
                time.sleep(0.25)
                assert not c.endpoints_health()[0]["ejected"]
                # single endpoint + retries=0 keeps fail-fast semantics
                with pytest.raises(OSError):
                    ServeClient("127.0.0.1", dead, retries=0)
        finally:
            srv.close()


def test_no_serve_threads_leak_overall():
    """Whatever ran before this test, no serve threads may survive."""
    names = [t.name for t in threading.enumerate()
             if t.name.startswith("serve-")]
    assert not names, names


def test_router_affinity_matches_pred_bit_for_bit(trained_model,
                                                  rcv1_path):
    """Affinity routing is cache placement, never correctness (ISSUE
    18): the same 100 rows routed ``balance=affinity`` across TWO
    replicas come back byte-identical to the task=pred golden — the
    per-owner partition + positional splice preserves request order and
    every replica serves the full model — and the affinity hit/miss
    counters and hit-rate gauge are live on the router."""
    from difacto_tpu.serve import (RouterServer, ServeClient,
                                   ServeServer, open_serving_store)
    rows = fixture_rows(rcv1_path)
    with deadline(120):
        store_a, _, _ = open_serving_store(trained_model["model"])
        store_b, _, _ = open_serving_store(trained_model["model"])
        try:
            srv_a = ServeServer(store_a, batch_size=100,
                                max_delay_ms=50.0).start()
        except OSError as e:  # pragma: no cover - loaded CI box
            pytest.skip(f"cannot bind a serving port: {e}")
        srv_b = ServeServer(store_b, batch_size=100,
                            max_delay_ms=50.0).start()
        router = None
        try:
            try:
                router = RouterServer(
                    [(srv_a.host, srv_a.port), (srv_b.host, srv_b.port)],
                    balance="affinity").start()
            except OSError as e:  # pragma: no cover
                pytest.skip(f"cannot bind the router port: {e}")
            with ServeClient(router.host, router.port) as c:
                resp = c.score_lines(rows)
                st = c.stats()
                text = c.metrics()
        finally:
            if router is not None:
                router.close()
            srv_a.close()
            srv_b.close()
    pred_probs = [l.split(b"\t")[1] for l in trained_model["pred_lines"]]
    assert resp == pred_probs
    # with every owner live and untried, every forward is an affinity
    # hit; both replicas carried rows (the ring actually partitions)
    assert st["balance"] == "affinity", st
    assert st["affinity_hits"] > 0, st
    assert st["affinity_misses"] == 0, st
    assert all(b["rows"] > 0 for b in st["backends"]), st
    assert "router_affinity_hit_rate 1" in text, text[:400]
