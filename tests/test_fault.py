"""Multi-host fault tolerance (round-3 verdict #4).

The dead-host protocol end to end (parallel/fault.py + launch.py
--max-restarts + SGDLearner ckpt_interval/auto_resume): two launch.py
processes train over a global mesh; rank 1 kills itself MID-EPOCH; the
survivor's heartbeat watchdog aborts its blocked DCN collective instead of
hanging; the launcher evicts a host and relaunches; the relaunched run
auto-resumes from the last epoch checkpoint and finishes over all the
data. Reference analog: GetDeadNodes polling + WorkloadPool::Reset part
re-advertisement + model reload (src/tracker/dist_tracker.h:164-186,
src/reader/workload_pool.h:88-105, SURVEY §5.3).

Also: heartbeat monitor unit behavior and straggler re-issue wiring.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from conftest import two_process_launch

REPO = pathlib.Path(__file__).resolve().parent.parent
EPOCHS = 4


@pytest.mark.parametrize("mode,port", [("allgather", 7941), ("step", 7945)])
@two_process_launch
def test_kill_one_host_mid_epoch_recovers(rcv1_path, tmp_path, mode, port):
    """Both execution regimes: ``allgather`` kills rank 1 at a streamed
    epoch's DCN handshake; ``step`` kills it entering the first REPLAYED
    train step (device cache on, no DCN calls) — the survivor must be
    freed by the replay-wide watchdog guard instead."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    env["DIFACTO_HB_TIMEOUT"] = "2"  # overridden timeout: fast test
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", str(port), "--max-restarts", "1",
         "--hb-port", "29990" if mode == "allgather" else "29930",
         "--hb-timeout", "2", "--",
         sys.executable, str(REPO / "tests" / "fault_worker.py"),
         str(tmp_path), rcv1_path, str(EPOCHS), mode],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    # the launcher actually evicted + restarted (attempt 1, single host)
    with open(tmp_path / "traj-0.json") as f:
        traj = json.load(f)
    assert traj["attempt"] == 1
    assert traj["nprocs"] == 1
    # resumed at epoch 1 from the epoch-0 checkpoint and finished the run
    epochs_run = [e for e, _ in traj["epochs"]]
    assert epochs_run == list(range(1, EPOCHS))
    # and it converged: monotone-ish decreasing loss to a sane value
    losses = [l for _, l in traj["epochs"]]
    assert losses[-1] < losses[0]
    # the survivor-side abort path was exercised (watchdog exit 42 or a
    # collective error), i.e. the first attempt really failed
    assert "attempt 0 failed" in proc.stderr


@two_process_launch
def test_kill_one_host_mid_window_recovers(rcv1_path, tmp_path):
    """Bounded-delay chaos arm (ISSUE 16): rank 1 is SIGKILLed
    MID-WINDOW under τ=2 (launch.py --bounded-delay 2, the cluster-env
    plumbing) while the survivor's exchange pipeline may be staged
    ahead. The survivor's guarded wait_clock/allgather must abort via
    the heartbeat watchdog instead of waiting out the 10-minute KV
    timeout on the dead host's clock key; the launcher evicts + re-
    launches; byte-range re-sharding re-issues the dead host's parts;
    and the relaunched process rejoins at a FRESH clock epoch
    (fault.restart_attempt namespacing) and finishes the run windowed,
    resuming from the epoch-0 checkpoint."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    env["DIFACTO_HB_TIMEOUT"] = "2"  # overridden timeout: fast test
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", "7961", "--max-restarts", "1",
         "--bounded-delay", "2",
         "--hb-port", "29940", "--hb-timeout", "2", "--",
         sys.executable, str(REPO / "tests" / "fault_worker.py"),
         str(tmp_path), rcv1_path, str(EPOCHS), "window"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=540)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    with open(tmp_path / "traj-0.json") as f:
        traj = json.load(f)
    assert traj["attempt"] == 1
    assert traj["nprocs"] == 1
    epochs_run = [e for e, _ in traj["epochs"]]
    assert epochs_run == list(range(1, EPOCHS))
    losses = [l for _, l in traj["epochs"]]
    assert losses[-1] < losses[0]
    assert "attempt 0 failed" in proc.stderr
    # the final model was written by the windowed relaunch
    assert (tmp_path / "model_part-0").exists()


def test_heartbeat_detects_dead_peer():
    from difacto_tpu.parallel.fault import (HeartbeatMonitor, HostFailure)
    a = HeartbeatMonitor(0, 2, 29960, interval=0.1, timeout=0.8)
    b = HeartbeatMonitor(1, 2, 29960, interval=0.1, timeout=0.8)
    a.start(), b.start()
    try:
        time.sleep(0.5)
        assert a.dead_peers() == []
        a.check()  # no raise
        b.stop()   # "host 1 dies"
        time.sleep(1.2)
        assert a.dead_peers() == [1]
        with pytest.raises(HostFailure):
            a.check()
        with pytest.raises(HostFailure):
            a.guarded(lambda: None)
    finally:
        a.stop()
        b.stop()


def test_heartbeat_guarded_passthrough():
    from difacto_tpu.parallel.fault import HeartbeatMonitor
    a = HeartbeatMonitor(0, 2, 29970, interval=0.1, timeout=5.0)
    b = HeartbeatMonitor(1, 2, 29970, interval=0.1, timeout=5.0)
    a.start(), b.start()
    try:
        time.sleep(0.4)
        assert a.guarded(lambda x: x + 1, 41) == 42
        assert a._in_collective_since is None  # context cleaned up
    finally:
        a.stop()
        b.stop()


def test_from_env_gating(monkeypatch):
    from difacto_tpu.parallel import fault
    monkeypatch.delenv("DIFACTO_HB_PORT", raising=False)
    assert fault.from_env(0, 2) is None          # env unset
    monkeypatch.setenv("DIFACTO_HB_PORT", "29980")
    assert fault.from_env(0, 1) is None          # single process
    mon = fault.from_env(0, 2)
    try:
        assert mon is not None and mon.timeout == 5.0
    finally:
        mon.stop()
