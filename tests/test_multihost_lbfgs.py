"""Multi-host L-BFGS (reference: distributed vector-free L-BFGS across
workers+servers, src/lbfgs/lbfgs_learner.cc:14-108): two launch.py
processes each read half the data by byte range, union their feature
dictionaries over DCN, sum raw (objv, auc, grad) partials in an
allreduce, and must REPRODUCE the single-process golden trajectory —
data-parallel summation changes fp order, not math (goldens tolerate
1e-4 relative)."""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

from tests.test_lbfgs import OBJV_BASIC
import pytest  # noqa: F401  (guard mark below)

from conftest import two_process_launch

pytestmark = two_process_launch

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_two_process_lbfgs_matches_golden(rcv1_path, tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, str(REPO / "launch.py"), "-n", "2",
         "--port", "7981", "--",
         sys.executable, str(REPO / "tests" / "lbfgs_worker.py"),
         str(tmp_path), rcv1_path],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n" \
                                 f"stderr:\n{proc.stderr}"
    trajs = []
    for r in (0, 1):
        with open(tmp_path / f"traj-{r}.json") as f:
            trajs.append(json.load(f))
    # both hosts observed the identical trajectory (same global math)
    np.testing.assert_allclose(trajs[0], trajs[1], rtol=1e-7)
    # and it is the single-process golden one
    np.testing.assert_allclose(trajs[0], OBJV_BASIC, rtol=1e-4, atol=1e-4)
