"""Remote-storage IO: fsspec URIs behind Reader / checkpoint / convert paths
(the dmlc Stream equivalent — reference reads hdfs:// via dmlc InputSplit,
example/yarn.conf). Tested against fsspec's memory:// filesystem."""

import numpy as np
import pytest

pytest.importorskip("fsspec")

from difacto_tpu.data import Reader
from difacto_tpu.learners import Learner
from difacto_tpu.utils import stream


@pytest.fixture
def memfs():
    import fsspec
    fs = fsspec.filesystem("memory")
    yield fs
    try:
        fs.rm("/", recursive=True)
    except FileNotFoundError:
        pass


def test_stream_helpers_roundtrip(memfs):
    uri = "memory://dir/a.txt"
    with stream.open_stream(uri, "wb") as f:
        f.write(b"hello\nworld\n")
    assert stream.exists(uri) and stream.isfile(uri)
    assert stream.getsize(uri) == 12
    assert stream.isdir("memory://dir")
    assert any(p.endswith("a.txt") for p in stream.listdir("memory://dir"))
    assert any(p.endswith("a.txt") for p in stream.glob("memory://dir/*.txt"))
    with stream.open_stream(uri, "rb") as f:
        assert f.read() == b"hello\nworld\n"


def test_npz_roundtrip_remote(memfs):
    uri = "memory://models/ck.npz"
    a = np.arange(10, dtype=np.float32)
    stream.save_npz(uri, a=a, b=np.array(3))
    with stream.load_npz(uri) as z:
        np.testing.assert_array_equal(z["a"], a)
        assert int(z["b"]) == 3


def test_reader_over_memory_fs(memfs, rcv1_path):
    """Byte-range sharded reading from a remote URI matches local."""
    data = open(rcv1_path, "rb").read()
    with stream.open_stream("memory://data/rcv1.libsvm", "wb") as f:
        f.write(data)
    local = [b for b in Reader(rcv1_path, "libsvm", 0, 2)]
    remote = [b for b in Reader("memory://data/rcv1.libsvm", "libsvm", 0, 2)]
    assert sum(b.size for b in local) == sum(b.size for b in remote)
    np.testing.assert_array_equal(
        np.concatenate([b.label for b in local]),
        np.concatenate([b.label for b in remote]))


def test_train_with_remote_model_out(memfs, rcv1_path):
    """Full train with model_out and pred_out on the remote fs, then load
    the checkpoint back from the URI."""
    with stream.open_stream("memory://in/rcv1.libsvm", "wb") as f:
        f.write(open(rcv1_path, "rb").read())
    args = [("data_in", "memory://in/rcv1.libsvm"), ("V_dim", "0"),
            ("l1", "1"), ("l2", "1"), ("lr", "1"), ("batch_size", "100"),
            ("max_num_epochs", "3"), ("shuffle", "0"),
            ("report_interval", "0"), ("stop_rel_objv", "0"),
            ("num_jobs_per_epoch", "1"),
            ("model_out", "memory://out/model")]
    ln = Learner.create("sgd")
    ln.init(list(args))
    ln.run()
    assert stream.exists("memory://out/model_part-0")

    l2 = Learner.create("sgd")
    l2.init(list(args))
    n = l2.store.load("memory://out/model_part-0")
    assert n > 0
    # slot order differs after load (sorted-key assignment); compare by key
    keys = l2.store._keys.copy()
    np.testing.assert_allclose(l2.store.pull(keys)[0], ln.store.pull(keys)[0])


def test_rec_convert_to_remote(memfs, rcv1_path):
    """task=convert writing the binary cache to a remote URI, then stream
    training from it."""
    from difacto_tpu.data.converter import Converter

    conv = Converter()
    conv.init([("data_in", rcv1_path), ("data_format", "libsvm"),
               ("data_out", "memory://cache/rcv1.rec"),
               ("data_out_format", "rec")])
    conv.run()
    blocks = [b for b in Reader("memory://cache/rcv1.rec", "rec", 0, 1)]
    assert sum(b.size for b in blocks) == 100
