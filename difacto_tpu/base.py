"""Core types and feature-id utilities.

TPU-native re-design of the reference's ``include/difacto/base.h``:

- ``real_t`` -> float32 (``REAL_DTYPE``), ``feaid_t`` -> uint64 (``FEAID_DTYPE``)
  (reference: include/difacto/base.h:16-20).
- ``reverse_bytes`` vectorises the bit-reversal of feature ids
  (include/difacto/base.h:39-51) over numpy uint64 arrays. The reference uses it
  to make the key space uniform so key-range sharding across servers is
  balanced; we use it for exactly the same reason — the slot table is sharded
  by contiguous ranges of the *reversed* id space across the mesh feature axis.
- feature-group id encode/decode (include/difacto/base.h:60-73).

There are no DMLC_ROLE role predicates: the TPU framework is SPMD — a single
controller drives a device mesh, so scheduler/worker/server collapse into
(host controller, data pipeline, sharded arrays).
"""

from __future__ import annotations

import numpy as np

# value dtype used for weights/gradients on host and device
REAL_DTYPE = np.float32
# raw feature-id dtype (uint64, like the reference's feaid_t)
FEAID_DTYPE = np.uint64

# KWArgs in the reference is vector<pair<string,string>>; here: list of tuples.
KWArgs = list


def reverse_bytes(x: np.ndarray | int) -> np.ndarray | int:
    """Reverse the nibbles of uint64 feature ids (vectorised).

    Mirrors ``ReverseBytes`` in include/difacto/base.h:39-51 — a full 64-bit
    byte+nibble reversal that makes ascending dense ids span the uint64 space
    uniformly. Applying it twice is the identity.
    """
    scalar = np.isscalar(x) or (isinstance(x, np.ndarray) and x.ndim == 0)
    x = np.asarray(x, dtype=np.uint64)
    x = (x << np.uint64(32)) | (x >> np.uint64(32))
    x = ((x & np.uint64(0x0000FFFF0000FFFF)) << np.uint64(16)) | \
        ((x & np.uint64(0xFFFF0000FFFF0000)) >> np.uint64(16))
    x = ((x & np.uint64(0x00FF00FF00FF00FF)) << np.uint64(8)) | \
        ((x & np.uint64(0xFF00FF00FF00FF00)) >> np.uint64(8))
    x = ((x & np.uint64(0x0F0F0F0F0F0F0F0F)) << np.uint64(4)) | \
        ((x & np.uint64(0xF0F0F0F0F0F0F0F0)) >> np.uint64(4))
    return x.item() if scalar else x


def encode_fea_grp_id(x, gid: int, nbits: int):
    """Pack a feature-group id into the low bits of a feature id.

    Mirrors ``EncodeFeaGrpID`` (include/difacto/base.h:60-63).
    """
    if not 0 <= gid < (1 << nbits):
        raise ValueError(f"gid {gid} out of range for {nbits} bits")
    x = np.asarray(x, dtype=np.uint64)
    out = (x << np.uint64(nbits)) | np.uint64(gid)
    return out.item() if out.ndim == 0 else out


def decode_fea_grp_id(x, nbits: int):
    """Inverse of :func:`encode_fea_grp_id` (include/difacto/base.h:71-73)."""
    x = np.asarray(x, dtype=np.uint64)
    out = x & np.uint64((1 << nbits) - 1)
    return out.item() if out.ndim == 0 else out
