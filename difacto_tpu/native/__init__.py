"""Native (C++) kernels for the host-side data path.

The TPU compute path is JAX/XLA; the host runtime around it (parsing, IO)
uses C++ where the reference did (dmlc-core's parsers are C++ too). Build is
lazy and cached: first use compiles the shared library with g++ next to this
package; any failure falls back to the pure-Python implementations, so the
framework never hard-requires a toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_difacto_native.so")
_SRC = [os.path.join(_DIR, "libsvm_parser.cc"),
        os.path.join(_DIR, "criteo_parser.cc"),
        os.path.join(_DIR, "adfea_parser.cc")]

_lock = mutex()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # per-pid tmp so concurrent first-use builds in separate processes
    # can't interleave writes; os.replace is atomic
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp] + _SRC
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.info("native build skipped (%s); using Python fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _newest_src_mtime() -> float:
    # a missing source (partial checkout) must not break get_lib's
    # fallback contract — treat it as infinitely new so the build is
    # attempted, fails, and callers fall back to Python
    try:
        return max(os.path.getmtime(s) for s in _SRC)
    except OSError:
        return float("inf")


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if
    unavailable (callers must fall back to Python)."""
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if _tried:
            return None
        _tried = True
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < _newest_src_mtime())
        # the first-use build is serialized on purpose: every caller
        # needs its result anyway, and the compile is bounded by the
        # subprocess timeout=120 (concurrent PROCESS builders are
        # already safe via the per-pid tmp + atomic replace)
        # lint: ok(lock-blocking) intentional bounded build under the init lock
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.info("native load failed (%s); using Python fallbacks", e)
            return None
        lib.difacto_parse_libsvm.restype = ctypes.c_int
        lib.difacto_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.difacto_parse_criteo.restype = ctypes.c_int
        lib.difacto_parse_criteo.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.difacto_parse_adfea.restype = ctypes.c_int
        lib.difacto_parse_adfea.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.difacto_murmur64a.restype = ctypes.c_uint64
        lib.difacto_murmur64a.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64]
        _lib = lib
        return _lib
