// Fast adfea chunk parser (C ABI, bound via ctypes).
//
// Native-path equivalent of the reference's AdfeaParser
// (src/reader/adfea_parser.h:20-91): the format is a whitespace-separated
// token stream "lineid count label idx:gid idx:gid ...". Tokens WITHOUT a
// ':' cycle through (lineid, count, label) — the third starts a new row
// whose label is 1.0 iff it begins with '1'; tokens WITH a ':' append
// feature id EncodeFeaGrpID(idx, gid % 4096, 12) to the current row. The
// Python parser (difacto_tpu/data/parsers.py:parse_adfea) is the semantic
// reference and the fallback.
//
// Contract (single pass, caller allocates worst-case buffers):
//   labels[max_rows], offset[max_rows+1], index[max_nnz]
//   max_rows >= number of non-':' tokens / 3 + 1,
//   max_nnz  >= number of ':' characters.
// Values are always binary (no value array). Returns 0 on success, -1 on
// malformed input (non-numeric idx/gid).

#include <cctype>
#include <cstdint>
#include <cstdlib>

namespace {

inline const char* skip_sep(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n'))
    ++p;
  return p;
}

}  // namespace

extern "C" int difacto_parse_adfea(
    const char* data, int64_t len,
    float* labels, int64_t* offset, uint64_t* index,
    int64_t* out_rows, int64_t* out_nnz) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  int head_pos = 0;  // cycles 0:lineid 1:count 2:label
  offset[0] = 0;

  while (p < end) {
    p = skip_sep(p, end);
    if (p >= end) break;
    const char* tok = p;
    const char* colon = nullptr;
    while (p < end && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n') {
      if (*p == ':') colon = p;
      ++p;
    }
    if (colon) {
      char* next = nullptr;
      uint64_t idx = strtoull(tok, &next, 10);
      if (next != colon) return -1;
      uint64_t gid = strtoull(colon + 1, &next, 10);
      if (next != p) return -1;
      index[nnz++] = (idx << 12) | (gid % 4096);
      if (rows > 0) offset[rows] = nnz;
    } else {
      if (head_pos == 2) {
        head_pos = 0;
        labels[rows] = (*tok == '1') ? 1.0f : 0.0f;
        ++rows;
        offset[rows] = nnz;
      } else {
        ++head_pos;
      }
    }
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}
