// Fast libsvm chunk parser (C ABI, bound via ctypes).
//
// Native-path equivalent of the reference's dmlc::data::LibSVMParser
// (used via src/reader/reader.h:31-32): parse a text chunk
// "label idx:val idx:val ..." per line into CSR arrays. The Python parser
// (difacto_tpu/data/parsers.py:parse_libsvm) is the semantic reference and
// the fallback; this exists because feeding TPU chips from text on the host
// is interpreter-bound (SURVEY §7 hard part (e)).
//
// Contract (single pass, caller allocates worst-case buffers):
//   labels[max_rows], offset[max_rows+1], index[max_nnz], value[max_nnz]
//   max_rows >= number of '\n' + 1, max_nnz >= number of ':' characters.
// Returns 0 on success, -1 on malformed input (missing ':', bad number).
// *out_has_value = 0 when every value == 1.0 (binary elision,
// src/reader/batch_reader.cc:71-73 drops such arrays).

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>

namespace {

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// strtof/strtoull honor LC_NUMERIC; parse with a fixed "C" locale so a
// comma-decimal host locale can't make well-formed files unparseable
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

}  // namespace

extern "C" int difacto_parse_libsvm(
    const char* data, int64_t len,
    float* labels, int64_t* offset, uint64_t* index, float* value,
    int64_t* out_rows, int64_t* out_nnz, int* out_has_value) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  int has_value = 0;
  offset[0] = 0;

  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') { ++p; continue; }  // empty line

    // label
    char* next = nullptr;
    float lab = strtof_l(p, &next, c_locale());
    if (next == p) return -1;
    p = next;
    labels[rows] = lab;

    // features until newline
    for (;;) {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') { if (p < end) ++p; break; }
      if (*p == '-') return -1;  // strtoull would silently wrap negatives
      errno = 0;
      uint64_t idx = strtoull_l(p, &next, 10, c_locale());
      if (next == p) return -1;
      if (errno == ERANGE) return -1;  // id > uint64 max must not clamp
      float val = 1.0f;
      if (next < end && *next == ':') {
        p = next + 1;
        // the value must start right after ':' — strtof skips whitespace
        // (incl. '\n') and would otherwise swallow the next line's label
        if (p >= end || isspace((unsigned char)*p)) return -1;
        val = strtof_l(p, &next, c_locale());
        if (next == p) return -1;
        p = next;
      } else if (next >= end || isspace((unsigned char)*next)) {
        // implicit-value token "idx": value 1.0, same as "idx:1" — a
        // chunk may mix implicit and explicit tokens freely (the value
        // array stays consistent regardless of which form came first)
        p = next;
      } else {
        return -1;  // trailing garbage glued to the index
      }
      index[nnz] = idx;
      value[nnz] = val;
      if (val != 1.0f) has_value = 1;
      ++nnz;
    }
    ++rows;
    offset[rows] = nnz;
  }

  *out_rows = rows;
  *out_nnz = nnz;
  *out_has_value = has_value;
  return 0;
}
