// Fast Criteo CTR chunk parser (C ABI, bound via ctypes).
//
// Native equivalent of the reference's CriteoParser
// (src/reader/criteo_parser.h:25-115): tab-separated
// "<label> <13 int fields> <26 categorical fields>", each non-empty field
// hashed to 64 bits with its column id packed in the low 12 bits
// (EncodeFeaGrpID, include/difacto/base.h:60-63). The reference hashes
// with CityHash64; we use MurmurHash64A (public-domain algorithm,
// implemented from its specification) — any stable uniform 64-bit hash
// preserves the semantics, and the Python fallback
// (difacto_tpu/data/parsers.py) implements the identical function.
//
// Returns 0 on success; rows with fewer fields are padded as empty
// (missing fields contribute no feature), matching the Python parser.

#include <cstdint>
#include <cstring>

namespace {

inline uint64_t murmur64a(const char* key, int len, uint64_t seed) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = seed ^ (static_cast<uint64_t>(len) * m);
  const int nblocks = len / 8;
  for (int i = 0; i < nblocks; ++i) {
    uint64_t k;
    memcpy(&k, key + i * 8, 8);
    k *= m;
    k ^= k >> r;
    k *= m;
    h ^= k;
    h *= m;
  }
  const unsigned char* tail =
      reinterpret_cast<const unsigned char*>(key + nblocks * 8);
  switch (len & 7) {
    case 7: h ^= static_cast<uint64_t>(tail[6]) << 48; [[fallthrough]];
    case 6: h ^= static_cast<uint64_t>(tail[5]) << 40; [[fallthrough]];
    case 5: h ^= static_cast<uint64_t>(tail[4]) << 32; [[fallthrough]];
    case 4: h ^= static_cast<uint64_t>(tail[3]) << 24; [[fallthrough]];
    case 3: h ^= static_cast<uint64_t>(tail[2]) << 16; [[fallthrough]];
    case 2: h ^= static_cast<uint64_t>(tail[1]) << 8;  [[fallthrough]];
    case 1: h ^= static_cast<uint64_t>(tail[0]); h *= m;
  }
  h ^= h >> r;
  h *= m;
  h ^= h >> r;
  return h;
}

constexpr int kNumFields = 39;   // 13 ints + 26 categoricals
constexpr int kGrpBits = 12;

}  // namespace

extern "C" uint64_t difacto_murmur64a(const char* key, int64_t len,
                                      uint64_t seed) {
  return murmur64a(key, static_cast<int>(len), seed);
}

extern "C" int difacto_parse_criteo(
    const char* data, int64_t len, int is_train,
    float* labels, int64_t* offset, uint64_t* index,
    int64_t max_rows, int64_t max_nnz,
    int64_t* out_rows, int64_t* out_nnz) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  offset[0] = 0;

  while (p < end) {
    const char* eol = static_cast<const char*>(
        memchr(p, '\n', end - p));
    if (eol == nullptr) eol = end;
    // skip empty lines, including CRLF blanks ("\r\n"), like the Python
    // fallback's strip
    if (eol == p || (eol == p + 1 && *p == '\r')) { p = eol + 1; continue; }
    if (rows >= max_rows) return -2;  // caller under-sized the buffers

    int field = 0;  // 0 = label (when is_train), then features
    int first_feature_field = is_train ? 1 : 0;
    const char* fs = p;  // field start
    float label = 0.0f;
    for (const char* q = p; ; ++q) {
      if (q == eol || *q == '\t') {
        int flen = static_cast<int>(q - fs);
        if (flen > 0 && fs[flen - 1] == '\r') --flen;
        int fidx = field - first_feature_field;  // feature column id
        if (field < first_feature_field) {
          // label field
          label = 0.0f;
          if (flen > 0) {
            // criteo labels are "0"/"1"; parse leading int, sign aware
            bool neg = fs[0] == '-';
            int64_t v = 0;
            for (int i = neg ? 1 : 0; i < flen; ++i) {
              if (fs[i] < '0' || fs[i] > '9') break;
              v = v * 10 + (fs[i] - '0');
            }
            label = static_cast<float>(neg ? -v : v);
          }
        } else if (fidx < kNumFields && flen > 0) {
          if (nnz >= max_nnz) return -2;  // under-sized buffer
          uint64_t h = murmur64a(fs, flen, 0);
          index[nnz++] = (h << kGrpBits)
              | static_cast<uint64_t>(fidx);
        }
        ++field;
        fs = q + 1;
        if (q == eol) break;
      }
    }
    labels[rows] = label;
    ++rows;
    offset[rows] = nnz;
    p = eol + 1;
  }

  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}
