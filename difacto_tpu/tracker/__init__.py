"""Control plane: workload distribution and failure handling.

The reference's AsyncLocalTracker (src/tracker/async_local_tracker.h) is
superseded by data/producer_pool.OrderedProducerPool, which fills the same
issue/execute/monitor role against the WorkloadPool (round-3 verdict:
fold or delete — folded).
"""

from .workload_pool import WorkloadPool, WorkloadPoolParam

__all__ = ["WorkloadPool", "WorkloadPoolParam"]
