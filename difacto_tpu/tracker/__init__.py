"""Control plane: job tracking, workload distribution, failure handling."""

from .async_tracker import AsyncTracker
from .workload_pool import WorkloadPool, WorkloadPoolParam

__all__ = ["AsyncTracker", "WorkloadPool", "WorkloadPoolParam"]
