"""Async job tracker: single-consumer queue with callbacks and a monitor.

Equivalent of the reference's AsyncLocalTracker<Job, Result>
(src/tracker/async_local_tracker.h:28-151) — the backbone of both the local
Tracker and the worker's in-flight minibatch pipeline. An executor thread
drains the queue; each job's result flows to its ``on_complete`` callback and
to the tracker-wide monitor. ``num_remains`` drives bounded-in-flight
backpressure (the <=2 pipelined minibatches, sgd_learner.cc:310-312).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, List, Optional, Tuple


class AsyncTracker:
    def __init__(self) -> None:
        self._mu = threading.Condition()
        self._pending: deque = deque()
        self._running = 0
        self._executor: Optional[Callable[[Any], Any]] = None
        self._monitor: Optional[Callable[[Any, Any], None]] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    # ------------------------------------------------------------ producer
    def issue(self, job: Any,
              on_complete: Optional[Callable[[Any], None]] = None) -> None:
        if self._thread is None:
            raise RuntimeError("set_executor must be called before issue")
        with self._mu:
            if self._error is not None:
                raise RuntimeError("executor failed") from self._error
            self._pending.append((job, on_complete))
            self._mu.notify_all()

    def issue_and_wait(self, jobs: List[Any]) -> List[Any]:
        results: List[Any] = [None] * len(jobs)
        remain = [len(jobs)]
        done = threading.Condition()

        def make_cb(i):
            def cb(res):
                results[i] = res
                with done:
                    remain[0] -= 1
                    done.notify_all()
            return cb

        for i, j in enumerate(jobs):
            self.issue(j, make_cb(i))
        with done:
            done.wait_for(lambda: remain[0] == 0)
        self._reraise()
        return results

    def num_remains(self) -> int:
        with self._mu:
            return len(self._pending) + self._running

    def wait(self) -> None:
        """Block until the queue drains (Wait, async_local_tracker.h:77-85)."""
        with self._mu:
            self._mu.wait_for(
                lambda: (not self._pending and self._running == 0)
                or self._error is not None)
        self._reraise()

    def _reraise(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("tracker executor failed") from err

    # ------------------------------------------------------------ executor
    def set_executor(self, fn: Callable[[Any], Any]) -> None:
        self._executor = fn
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def set_monitor(self, fn: Callable[[Any, Any], None]) -> None:
        self._monitor = fn

    def stop(self) -> None:
        with self._mu:
            self._stop = True
            self._mu.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._mu:
                self._mu.wait_for(lambda: self._pending or self._stop)
                if self._stop and not self._pending:
                    return
                job, cb = self._pending.popleft()
                self._running += 1
            try:
                res = self._executor(job)
                if self._monitor is not None:
                    self._monitor(job, res)
                if cb is not None:
                    cb(res)
            except BaseException as e:  # surfaced on wait/issue_and_wait
                with self._mu:
                    self._error = e
                if cb is not None:
                    try:
                        cb(None)  # unblock waiters; _reraise surfaces the error
                    except BaseException:
                        pass
            finally:
                with self._mu:
                    self._running -= 1
                    self._mu.notify_all()
