"""Scheduler-side workload pool: file-part assignment with failure handling.

Equivalent of the reference's WorkloadPool (src/reader/workload_pool.h:28-203)
— the host-side half of data parallelism. Parts (byte ranges of the input,
data/reader.py) are handed to nodes (hosts / pipeline threads) on request;
the pool

- re-queues the in-flight parts of a dead node (``reset``,
  workload_pool.h:88-105 Set(del=false)),
- re-issues parts running longer than max(10 x mean, straggler_timeout)
  once >= 10 completion times are known (``remove_stragglers``,
  workload_pool.h:155-176),
- optionally picks parts at random (``wl_shuffle``).

Thread-safe; the straggler check is called by the owner (no daemon thread —
the caller's dispatch loop invokes ``remove_stragglers`` periodically, which
keeps tests deterministic; the reference used a 2 s poller thread).
"""

from __future__ import annotations

import logging
import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

from ..config import Param
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")


@dataclass
class WorkloadPoolParam(Param):
    straggler_timeout: float = 0.0  # 0 disables straggler re-issue
    wl_shuffle: bool = False
    seed: int = 0


class _Assigned(NamedTuple):
    node: int
    part: int
    start: float


class WorkloadPool:
    def __init__(self, param: Optional[WorkloadPoolParam] = None):
        self.param = param or WorkloadPoolParam()
        self._mu = mutex()
        self._avail: Dict[int, bool] = {}   # part -> available
        self._assigned: List[_Assigned] = []
        self._times: List[float] = []
        self._num_finished = 0
        self._inited = False
        if self.param.wl_shuffle:
            import random
            self._rng = random.Random(self.param.seed)

    def add(self, num_parts: int) -> None:
        with self._mu:
            self._avail = {i: True for i in range(num_parts)}
            self._inited = True

    def clear(self) -> None:
        with self._mu:
            self._avail.clear()
            self._assigned.clear()
            self._times.clear()
            self._num_finished = 0
            self._inited = False

    @property
    def inited(self) -> bool:
        return self._inited

    def get(self, node: int) -> int:
        """Next part for ``node``; -2 when nothing is available
        (GetOne, workload_pool.h:124-152)."""
        with self._mu:
            avail = [k for k, a in self._avail.items() if a]
            if not avail:
                return -2
            part = (self._rng.choice(avail) if self.param.wl_shuffle
                    else avail[0])
            self._avail[part] = False
            self._assigned.append(_Assigned(node, part, _time.monotonic()))
            return part

    def finish(self, node: int) -> None:
        """All of node's in-flight parts completed."""
        self._set(node, done=True)

    def reset(self, node: int) -> None:
        """Node died: its in-flight parts go back to the pool."""
        self._set(node, done=False)

    def reissue_dead(self, node: int) -> List[int]:
        """``reset`` for a node declared DEAD (killed worker process,
        heartbeat-evicted host): re-queue its in-flight parts, count
        them into ``tracker_parts_reissued_total{reason="dead"}`` and
        return the re-queued part ids. The re-queue itself never blocks
        — survivors pick the parts up from their own dispatch loops, so
        a bounded-delay (τ) window keeps draining while the eviction is
        handled (the reference's WorkloadPool::Reset part
        re-advertisement, workload_pool.h:88-105)."""
        with self._mu:
            requeued = [a.part for a in self._assigned if a.node == node]
        self._set(node, done=False)
        if requeued:
            from ..obs import counter
            counter("tracker_parts_reissued_total",
                    "workload parts re-queued after a node death or "
                    "straggler eviction").labels(reason="dead").inc(
                        len(requeued))
        return requeued

    def _set(self, node: int, done: bool) -> None:
        with self._mu:
            rest = []
            for a in self._assigned:
                if a.node != node:
                    rest.append(a)
                    continue
                if done:
                    self._times.append(_time.monotonic() - a.start)
                    self._avail.pop(a.part, None)
                    self._num_finished += 1
                else:
                    self._avail[a.part] = True
                    log.info("%d failed to finish part %d", node, a.part)
            self._assigned = rest

    def touch(self, node: int) -> None:
        """Refresh the assignment clocks of ``node``'s in-flight parts.
        Producers call this while back-pressured (blocked on a full consumer
        queue), so ``remove_stragglers`` measures *stall* time — a healthy
        part waiting for the consumer is not a straggler."""
        with self._mu:
            now = _time.monotonic()
            self._assigned = [a._replace(start=now) if a.node == node else a
                              for a in self._assigned]

    def num_remains(self) -> int:
        """Unfinished parts: available + in-flight, each counted once."""
        with self._mu:
            return (sum(1 for a in self._avail.values() if a)
                    + len(self._assigned))

    @property
    def num_finished(self) -> int:
        return self._num_finished

    def remove_stragglers(self, now: Optional[float] = None) -> List[int]:
        """Re-queue parts exceeding max(10 x mean, straggler_timeout);
        needs >= 10 completion samples (RemoveStraggler,
        workload_pool.h:155-176). Returns the re-queued part ids."""
        if not self.param.straggler_timeout:
            return []
        with self._mu:
            if len(self._times) < 10:
                return []
            mean = sum(self._times) / len(self._times)
            limit = max(mean * 10, self.param.straggler_timeout)
            now = _time.monotonic() if now is None else now
            rest, requeued = [], []
            for a in self._assigned:
                if now - a.start > limit:
                    log.info("part %d on %d ran %.1fs (mean %.1fs); "
                             "re-issuing", a.part, a.node, now - a.start,
                             mean)
                    self._avail[a.part] = True
                    requeued.append(a.part)
                else:
                    rest.append(a)
            self._assigned = rest
        if requeued:
            from ..obs import counter
            counter("tracker_parts_reissued_total",
                    "workload parts re-queued after a node death or "
                    "straggler eviction").labels(reason="straggler").inc(
                        len(requeued))
        return requeued
