"""Unified observability subsystem (ISSUE 4).

One spine for every component's telemetry:

- :mod:`obs.metrics` — process-wide registry of labeled Counters /
  Gauges / fixed-bucket Histograms with per-thread cells and mergeable
  snapshots (``DIFACTO_OBS=off`` flips it to a no-op);
- :mod:`obs.trace` — nestable spans emitting Chrome trace-event JSON
  (``DIFACTO_TRACE=<path>``; open the file in Perfetto), with ids that
  survive the producer process boundary;
- :mod:`obs.export` — Prometheus text renderer (serve's ``#metrics``)
  and the periodic JSONL flusher (``metrics_path`` training knob);
- :mod:`obs.proc` — producer-worker snapshot publishing/absorption, so
  cross-process counters are exact.

See docs/observability.md for the metric catalog and span conventions.
"""

from . import trace  # noqa: F401
from .export import (MetricsFlusher, merged_snapshot,  # noqa: F401
                     render_prometheus)
from .metrics import (DEFAULT_BOUNDS, NOOP, REGISTRY,  # noqa: F401
                      Counter, Gauge, Histogram, Registry, counter,
                      enabled, gauge, hist_quantiles, histogram,
                      merge_into)

__all__ = [
    "REGISTRY", "Registry", "Counter", "Gauge", "Histogram", "NOOP",
    "DEFAULT_BOUNDS", "counter", "gauge", "histogram", "enabled",
    "hist_quantiles", "merge_into", "render_prometheus",
    "merged_snapshot", "MetricsFlusher", "trace",
]
