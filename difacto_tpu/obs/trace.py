"""Nestable trace spans emitting Chrome trace-event JSON (Perfetto).

The timing half of the obs subsystem (metrics.py is the counting half):
``with span("consumer.step", part=3):`` records one complete ("X") event
with microsecond timestamps. Events carry ``pid``/``tid``, so a file
holding events from the parent AND its producer worker processes renders
as one timeline in Perfetto / chrome://tracing — worker parse -> pack ->
ring wait -> consumer unpack -> device step, side by side.

Cross-process story: timestamps come from ``time.perf_counter`` (Linux
CLOCK_MONOTONIC — one clock for every process on the machine), so worker
events align with parent events with no offset bookkeeping. Worker
processes inherit ``DIFACTO_TRACE`` through the environment and collect
events in memory; the producer pool ships them to the parent through the
existing result queues (obs/proc.py) instead of writing files — only the
process that owns the trace writes it (child processes are marked with
``DIFACTO_OBS_CHILD=1`` and never install the atexit save). The pack
span's id additionally rides the shm-ring slot header
(data/shm_ring.py), so the consumer's unpack/step spans can point at the
exact producer span that built their batch (``producer_span`` arg).

Tracing is OFF unless ``DIFACTO_TRACE=<path>`` is set (or ``start()`` is
called); an inactive ``span`` is a single global read plus a no-op yield.
The event buffer is bounded (default 200k events) — overflow drops new
events and counts them, never grows without limit.

Device time (the PR 4 leftover, ROADMAP item 3): with
``DIFACTO_TRACE_DEVICE=<logdir>`` the module also starts the JAX
profiler and wraps every span body in a
``jax.profiler.TraceAnnotation`` (``StepTraceAnnotation`` when the span
carries a ``step_num`` arg), so the XLA device timeline the profiler
writes into ``<logdir>`` carries the SAME span names as the host
Chrome-trace file — load both in Perfetto and host stages line up with
the device programs they dispatched. Annotations are no-ops when the
profiler is off, so the knob composes freely with ``DIFACTO_TRACE``.
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Iterator, List, Optional
from ..utils.locktrace import mutex

_MAX_EVENTS = 200_000

_mu = mutex()
_events: List[dict] = []
_dropped = 0
_active = False
_path: Optional[str] = None
_annotate = None          # jax.profiler module once device tracing is on
_trace_id = 0
_span_ids = itertools.count(1)
_tls = threading.local()  # per-thread span stack


def _now_us() -> float:
    return time.perf_counter() * 1e6


def active() -> bool:
    return _active


def trace_id() -> int:
    return _trace_id


def set_trace_id(tid: int) -> None:
    """Adopt a parent process's trace id (propagated through
    pack_stream.StreamSpec into producer workers)."""
    global _trace_id
    # lint: ok(data-race) write-once setup before producer workers span
    _trace_id = int(tid)


def start(path: Optional[str] = None,
          trace_id_: Optional[int] = None) -> None:
    """Begin collecting span events. ``path`` (optional) is where
    :func:`save` / the atexit hook writes the Chrome trace JSON."""
    global _active, _path, _trace_id
    # lint: ok(data-race) GIL-atomic on/off flip; spans tolerate either
    _active = True
    if path:
        _path = path
    _trace_id = (trace_id_ if trace_id_ is not None
                 else _trace_id or (os.getpid() << 16)
                 # lint: ok(wall-clock) id entropy, not a duration
                 | int(time.time()) % (1 << 16))


def stop() -> None:
    global _active
    _active = False


def start_device(logdir: str) -> bool:
    """Start the JAX profiler into ``logdir`` and annotate every span
    from here on (``DIFACTO_TRACE_DEVICE``). Returns False when jax or
    its profiler is unavailable — span capture still works without."""
    global _annotate
    try:
        import jax
        jax.profiler.start_trace(logdir)
        # lint: ok(data-race) write-once setup before any span thread
        _annotate = jax.profiler
        return True
    except Exception as e:  # pragma: no cover - profiler/backend quirks
        logging.getLogger(__name__).warning(
            "device trace unavailable (%s); host spans continue", e)
        return False


def stop_device() -> None:
    global _annotate
    prof, _annotate = _annotate, None
    if prof is not None:
        try:
            prof.stop_trace()
        except Exception as e:  # pragma: no cover - teardown shield
            logging.getLogger(__name__).warning(
                "device trace stop failed: %s", e)


def current_span_id() -> int:
    """The innermost open span's id on this thread (0 outside any)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else 0


def last_span_id() -> int:
    """The most recently CLOSED span's id on this thread — how a caller
    that consumed a span-wrapped producer (e.g. the ring writer stamping
    the slot header with the pack span) names the span that just ran."""
    return getattr(_tls, "last", 0)


def add_event(ev: dict) -> None:
    global _dropped
    with _mu:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(ev)


def add_events(evs: List[dict]) -> None:
    """Merge events shipped from a child process (obs/proc.py)."""
    global _dropped
    if not evs:
        return
    with _mu:
        room = _MAX_EVENTS - len(_events)
        _events.extend(evs[:room])
        _dropped += max(0, len(evs) - room)


def drain_events() -> List[dict]:
    """Take (and clear) the collected events — how worker processes hand
    their spans to the parent through the result queue."""
    global _events
    with _mu:
        out, _events = _events, []
    return out


@contextlib.contextmanager
def span(name: str, **args) -> Iterator[int]:
    """Record a complete trace event around the body. Nesting is
    per-thread; the event carries its span id, parent span id and the
    run's trace id, plus any keyword args (ints/strings only — they go
    straight into the JSON)."""
    if not _active:
        yield 0
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    sid = next(_span_ids)
    parent = stack[-1] if stack else 0
    stack.append(sid)
    # device-timeline annotation (DIFACTO_TRACE_DEVICE): the profiler
    # stamps the span name onto the XLA trace so Perfetto shows device
    # programs under the same labels as these host events; a span
    # carrying step_num= uses StepTraceAnnotation (JAX's step marker)
    ann = contextlib.nullcontext()
    if _annotate is not None:
        ann = (_annotate.StepTraceAnnotation(
                   name, step_num=args["step_num"])
               if "step_num" in args
               else _annotate.TraceAnnotation(name))
    t0 = _now_us()
    try:
        with ann:
            yield sid
    finally:
        dur = _now_us() - t0
        stack.pop()
        _tls.last = sid
        ev = {"name": name, "ph": "X", "ts": t0, "dur": dur,
              "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFFFFFF,
              "args": {"span_id": sid, "parent": parent,
                       "trace_id": _trace_id, **args}}
        add_event(ev)


def save(path: Optional[str] = None) -> Optional[str]:
    """Write the collected events as Chrome trace JSON (loadable in
    Perfetto: ui.perfetto.dev, or chrome://tracing). Returns the path
    written, or None when there is nowhere to write."""
    path = path or _path
    if not path:
        return None
    with _mu:
        events = list(_events)
        dropped = _dropped
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"trace_id": _trace_id, "dropped_events": dropped}}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _maybe_start_from_env() -> None:
    path = os.environ.get("DIFACTO_TRACE", "")
    dev = os.environ.get("DIFACTO_TRACE_DEVICE", "")
    if not path and not dev:
        return
    if os.environ.get("DIFACTO_OBS_CHILD"):
        # producer worker: collect in memory, ship via the result queue
        # (obs/proc.py) — never write the parent's trace file; the JAX
        # profiler is the parent's too (workers own no device)
        start()
        return
    start(path or None)
    if path:
        atexit.register(save)
    if dev:
        # one profiler session per process, closed at exit so the
        # device trace flushes into <logdir> next to the span file
        if start_device(dev):
            atexit.register(stop_device)


_maybe_start_from_env()
