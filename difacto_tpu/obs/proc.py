"""Child-process metric aggregation: exact cross-process counters.

Producer worker processes (data/producer_pool.py ProcessProducerPool)
instrument their half of the pipeline against their OWN process-global
registry — a fresh spawn starts at zero, so its registry IS this run's
contribution. :func:`publish_blob` packages that registry snapshot plus
any collected trace spans into one picklable blob the worker puts on its
existing result queue (after each finished part, and on clean exit);
:func:`absorb_blob` attaches it in the parent.

Two properties make the totals exact rather than sampled:

- blobs carry CUMULATIVE snapshots and the parent keeps only the NEWEST
  per child (``Registry.set_child``) — a lost or reordered publish can
  only make the parent's view momentarily stale, never double-counted;
- when the pool shuts down it folds the final child snapshots into the
  parent registry's base series (``Registry.fold_children``), so the
  totals survive the pool object and accumulate across epochs.
"""

from __future__ import annotations

from .metrics import REGISTRY, Registry

# env marker the pool sets for its workers: obs runs in collect-only mode
# (trace events ship via the queue; no atexit trace-file write)
CHILD_ENV = "DIFACTO_OBS_CHILD"


def publish_blob() -> dict:
    """The worker side: this process's cumulative registry snapshot plus
    the trace events collected since the last publish."""
    from . import trace
    return {"snap": REGISTRY.snapshot() if REGISTRY.enabled else {},
            "events": trace.drain_events()}


def absorb_blob(registry: Registry, key, blob: dict) -> None:
    """The parent side: replace the child's attached snapshot with the
    newer one and merge its trace events into the local sink."""
    snap = blob.get("snap")
    if snap:
        registry.set_child(key, snap)
    from . import trace
    trace.add_events(blob.get("events") or [])
