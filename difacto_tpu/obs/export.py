"""Exporters: Prometheus text rendering + a periodic JSONL flusher.

The read side of the obs registry (metrics.py): ``render_prometheus``
turns merged snapshots into the Prometheus text exposition format — the
payload behind ``task=serve``'s ``#metrics`` control line — including
derived p50/p95/p99 quantile lines for every histogram (the acceptance
surface: serve latency quantiles without a scrape-and-aggregate step).
``MetricsFlusher`` appends one JSON object per interval to a JSONL event
log (the ``metrics_path`` / ``metrics_interval_s`` training knobs) that
``tools/obs_report.py`` renders into a human summary.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional, Sequence

from .metrics import Registry, hist_quantiles, merge_into

_QS = (0.5, 0.95, 0.99)


def merged_snapshot(registries: Sequence[Registry]) -> dict:
    out: dict = {}
    for r in registries:
        merge_into(out, r.snapshot())
    return out


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(key) -> str:
    if not key:
        return ""
    inner = ",".join(
        f'{_prom_name(k)}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in key)
    return "{" + inner + "}"


def _with_label(key, k: str, v) -> str:
    return _prom_labels(tuple(key) + ((k, str(v)),))


def render_prometheus(snap: dict, namespace: str = "difacto") -> str:
    """Prometheus text format for a (merged) snapshot. Histograms emit
    the standard ``_bucket``/``_sum``/``_count`` triple PLUS derived
    ``<name>_quantile{quantile="0.5|0.95|0.99"}`` gauge lines, so a
    human (or the ``#metrics`` caller) reads p50/p95/p99 directly."""
    lines: List[str] = []
    help_ = snap.get("help", {})
    ns = namespace + "_" if namespace else ""

    def head(name: str, kind: str) -> str:
        full = ns + _prom_name(name)
        if name in help_:
            lines.append(f"# HELP {full} {help_[name]}")
        lines.append(f"# TYPE {full} {kind}")
        return full

    for name in sorted(snap.get("counters", {})):
        full = head(name, "counter")
        for key, v in sorted(snap["counters"][name].items()):
            lines.append(f"{full}{_prom_labels(key)} {v:g}")
    for name in sorted(snap.get("gauges", {})):
        full = head(name, "gauge")
        for key, v in sorted(snap["gauges"][name].items()):
            lines.append(f"{full}{_prom_labels(key)} {v:g}")
    for name in sorted(snap.get("hists", {})):
        full = head(name, "histogram")
        series = snap["hists"][name]
        for key, d in sorted(series.items()):
            cum = 0
            for b, c in zip(d["bounds"], d["counts"]):
                cum += c
                lines.append(
                    f"{full}_bucket{_with_label(key, 'le', f'{b:g}')} {cum}")
            lines.append(
                f"{full}_bucket{_with_label(key, 'le', '+Inf')} {d['count']}")
            lines.append(f"{full}_sum{_prom_labels(key)} {d['sum']:g}")
            lines.append(f"{full}_count{_prom_labels(key)} {d['count']}")
        qfull = full + "_quantile"
        lines.append(f"# TYPE {qfull} gauge")
        for key, d in sorted(series.items()):
            for q, v in hist_quantiles(d, _QS).items():
                lines.append(
                    f"{qfull}{_with_label(key, 'quantile', f'{q:g}')} {v:g}")
    return "\n".join(lines) + "\n"


def jsonable_snapshot(snap: dict) -> dict:
    """Snapshot with label-tuple keys flattened to ``k=v,k2=v2`` strings
    (JSON objects cannot key on tuples); '' is the unlabeled series."""

    def flat(key) -> str:
        return ",".join(f"{k}={v}" for k, v in key)

    out: dict = {"help": dict(snap.get("help", {}))}
    for kind in ("counters", "gauges", "hists"):
        out[kind] = {name: {flat(k): v for k, v in series.items()}
                     for name, series in snap.get(kind, {}).items()}
    return out


class MetricsFlusher:
    """Background thread appending merged registry snapshots to a JSONL
    file every ``interval_s`` (plus a final flush on close). Each line is
    ``{"ts": <epoch seconds>, "metrics": <jsonable snapshot>}`` —
    append-only, crash-tolerant (a torn last line is skipped by readers),
    and diffable across flushes. ``trace_path`` additionally saves the
    collected span events as Chrome trace JSON on close.

    ``max_mb`` > 0 caps the file: when the next flush would push it past
    the cap, the current file rolls to ``<path>.1`` (replacing any
    previous roll) and a fresh file starts — a weeks-long serve process
    holds at most ~2x ``max_mb`` of metrics log instead of growing
    without bound. Readers (tools/obs_report.py) look at the rolled file
    too, so history survives one rotation."""

    def __init__(self, path: str, interval_s: float = 30.0,
                 registries: Optional[Sequence[Registry]] = None,
                 trace_path: str = "", max_mb: float = 0.0) -> None:
        from .metrics import REGISTRY
        self.path = path
        self.interval_s = max(interval_s, 0.1)
        self.registries = list(registries) if registries else [REGISTRY]
        self.trace_path = trace_path
        self.max_mb = max_mb
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsFlusher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="obs-flush", daemon=True)
            self._thread.start()
        return self

    def flush(self) -> None:
        # lint: ok(wall-clock) timestamp-of-record on each JSONL line
        line = json.dumps({"ts": time.time(),
                           "metrics": jsonable_snapshot(
                               merged_snapshot(self.registries))})
        import os
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self.max_mb > 0:
            try:
                # roll BEFORE the write that would breach the cap, so
                # the live file never exceeds max_mb; os.replace is
                # atomic — a reader sees the old or the new roll, never
                # a half file
                if (os.path.exists(self.path)
                        and os.path.getsize(self.path) + len(line) + 1
                        > self.max_mb * (1 << 20)):
                    os.replace(self.path, self.path + ".1")
            except OSError:  # pragma: no cover - rotation must not crash
                pass
        with open(self.path, "a") as f:
            f.write(line + "\n")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        try:
            self.flush()
        except OSError:  # pragma: no cover - flusher must never crash a run
            pass
        if self.trace_path:
            from . import trace
            trace.save(self.trace_path)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except OSError:  # pragma: no cover
                pass
