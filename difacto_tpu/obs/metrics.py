"""Process-wide metric registry: Counters, Gauges, fixed-bucket Histograms.

The observability spine every component reports through (ISSUE 4). Before
this module each subsystem kept its own ad-hoc channel — ``#stats`` dicts
in serve, ``_stage_acc`` dicts in the SGD learner, ``Timer`` strings in
utils/profiling.py — none of which composed, crossed the producer process
boundary, or exported anywhere. The registry gives them one vocabulary:

- :class:`Counter` — monotonically increasing, labeled
  (``counter("x_total").labels(stage="pack").inc(dt)``);
- :class:`Gauge` — last-written value (queue depth, model generation);
- :class:`Histogram` — fixed log-spaced buckets with a mergeable
  (counts, sum) representation; p50/p95/p99 derive from the buckets
  (:func:`hist_quantiles`), so serve latency, batch occupancy, ring-slot
  wait and step time all use ONE type and ONE quantile definition.

Write-path cost is the design constraint — these sit on per-batch and
per-request hot paths. Each labeled series keeps **per-thread cells**
(a thread only ever writes its own cell; the series lock is taken once
per thread at cell creation), so ``inc``/``observe`` are a
``threading.local`` attribute read plus a float add — no contended lock,
no allocation. ``snapshot()`` sums the cells.

Snapshots are plain picklable dicts and MERGE exactly (counters add,
histogram buckets add element-wise), which is what makes cross-process
aggregation honest: producer worker processes publish their registry
snapshots through their result queues (obs/proc.py) and the parent's
merged view reports exact totals, not samples.

``DIFACTO_OBS=off`` (or 0/false) flips the default registry to a no-op:
every ``counter()``/``gauge()``/``histogram()`` call returns the shared
:data:`NOOP` whose methods are empty — the instrumented hot paths keep
only an attribute call. Metrics are ON by default; the tier-1 overhead
guard (tests/test_obs.py) bounds what that costs.
"""

from __future__ import annotations

import os
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple
from ..utils.locktrace import mutex

# label set -> canonical picklable key: sorted ((k, v), ...) string pairs
LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: dict) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _env_enabled() -> bool:
    return os.environ.get("DIFACTO_OBS", "").lower() not in ("off", "0",
                                                             "false")


class _Noop:
    """Shared do-nothing metric handle (the DIFACTO_OBS=off fast path)."""

    __slots__ = ()

    def labels(self, **_kw) -> "_Noop":
        return self

    def inc(self, _v: float = 1.0) -> None:
        pass

    def dec(self, _v: float = 1.0) -> None:
        pass

    def set(self, _v: float) -> None:
        pass

    def observe(self, _v: float) -> None:
        pass

    def value(self, **_kw) -> float:
        return 0.0


NOOP = _Noop()

# default histogram bounds: log-ish spacing from 10us to 100s — wide
# enough for socket latencies, ring waits and device steps alike, small
# enough (26 buckets) that a snapshot stays cheap to merge and render
DEFAULT_BOUNDS: Tuple[float, ...] = tuple(
    b * m for m in (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)
    for b in (1.0, 2.0, 5.0)) + (100.0, 200.0, 500.0, 1000.0, 2000.0)


class _CounterSeries:
    """One labeled counter time series with per-thread cells."""

    __slots__ = ("_local", "_cells", "_mu", "_absorbed")

    def __init__(self) -> None:
        self._local = threading.local()
        self._cells: List[list] = []
        self._mu = mutex()
        self._absorbed = 0.0

    def inc(self, v: float = 1.0) -> None:
        try:
            cell = self._local.cell
        except AttributeError:
            cell = [0.0]
            with self._mu:
                self._cells.append(cell)
            self._local.cell = cell
        cell[0] += v

    def absorb(self, v: float) -> None:
        with self._mu:
            self._absorbed += v

    def value(self) -> float:
        with self._mu:
            return self._absorbed + sum(c[0] for c in self._cells)


class _GaugeSeries:
    """Last-written value; set/inc are locked (gauges are low-rate)."""

    __slots__ = ("_mu", "_v")

    def __init__(self) -> None:
        self._mu = mutex()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._mu:
            self._v += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    def value(self) -> float:
        with self._mu:
            return self._v


class _HistSeries:
    """Fixed-bucket histogram series: per-thread cells of
    [bucket counts..., overflow count, value sum]."""

    __slots__ = ("bounds", "_local", "_cells", "_mu", "_absorbed")

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        self.bounds = bounds
        self._local = threading.local()
        self._cells: List[list] = []
        self._mu = mutex()
        # absorbed child/merged contributions: counts + [sum]
        self._absorbed = [0] * (len(bounds) + 1) + [0.0]

    def observe(self, v: float) -> None:
        try:
            cell = self._local.cell
        except AttributeError:
            cell = [0] * (len(self.bounds) + 1) + [0.0]
            with self._mu:
                self._cells.append(cell)
            self._local.cell = cell
        cell[bisect_left(self.bounds, v)] += 1
        cell[-1] += v

    def absorb(self, counts: Iterable[int], vsum: float) -> None:
        with self._mu:
            for i, c in enumerate(counts):
                self._absorbed[i] += c
            self._absorbed[-1] += vsum

    def data(self) -> dict:
        """{'bounds', 'counts', 'sum', 'count'} — the mergeable form."""
        with self._mu:
            agg = list(self._absorbed)
            for cell in self._cells:
                for i, c in enumerate(cell):
                    agg[i] += c
        counts = [int(c) for c in agg[:-1]]
        return {"bounds": list(self.bounds), "counts": counts,
                "sum": float(agg[-1]), "count": int(sum(counts))}


class _Metric:
    """Labeled metric family: ``labels(**kv)`` resolves (and caches) one
    series; the metric itself doubles as its own unlabeled series."""

    _series_cls: type = _CounterSeries
    kind = "counter"

    def __init__(self, name: str, help: str = "", **series_kw) -> None:
        self.name = name
        self.help = help
        self._series_kw = series_kw
        self._mu = mutex()
        self._series: Dict[LabelsKey, object] = {}

    def labels(self, **labels):
        key = _labels_key(labels)
        s = self._series.get(key)
        if s is None:
            with self._mu:
                s = self._series.setdefault(
                    key, self._series_cls(**self._series_kw))
        return s

    # unlabeled convenience: metric(...).inc(...) etc.
    def inc(self, v: float = 1.0) -> None:
        self.labels().inc(v)

    def value(self, **labels) -> float:
        key = _labels_key(labels)
        s = self._series.get(key)
        return s.value() if s is not None else 0.0

    def series(self) -> Dict[LabelsKey, object]:
        with self._mu:
            return dict(self._series)


class Counter(_Metric):
    pass


class Gauge(_Metric):
    _series_cls = _GaugeSeries
    kind = "gauge"

    def set(self, v: float) -> None:
        self.labels().set(v)

    def dec(self, v: float = 1.0) -> None:
        self.labels().dec(v)


class Histogram(_Metric):
    _series_cls = _HistSeries
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 bounds: Optional[Tuple[float, ...]] = None) -> None:
        super().__init__(name, help,
                         bounds=tuple(bounds or DEFAULT_BOUNDS))

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def data(self, **labels) -> Optional[dict]:
        key = _labels_key(labels)
        s = self._series.get(key)
        return s.data() if s is not None else None


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """A namespace of metrics plus attached child-process snapshots.

    ``snapshot()`` returns a picklable, mergeable dict; ``set_child``
    attaches a child process's LATEST full snapshot under a key (the
    child re-publishes cumulative totals, so storing the newest one —
    rather than summing deltas — keeps cross-process counters exact even
    when publishes are lost); ``fold_children`` retires finished
    children by absorbing their final snapshot into the base series.
    """

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self.enabled = _env_enabled() if enabled is None else enabled
        self._mu = mutex()
        self._metrics: Dict[str, _Metric] = {}
        self._children: Dict[object, dict] = {}

    # -------------------------------------------------------- factories
    def _get(self, cls: type, name: str, help: str, **kw):
        if not self.enabled:
            return NOOP
        m = self._metrics.get(name)
        if m is None:
            with self._mu:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} is a {type(m).__name__}, "
                            f"not a {cls.__name__}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  bounds: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(Histogram, name, help, bounds=bounds)

    # --------------------------------------------------------- children
    def set_child(self, key, snap: dict) -> None:
        with self._mu:
            self._children[key] = snap

    def fold_children(self, prefix=None) -> None:
        """Absorb finished children's snapshots into the base series (so
        their totals survive the child record being dropped). ``prefix``
        limits the fold to keys that are tuples starting with it."""
        with self._mu:
            keys = [k for k in self._children
                    if prefix is None
                    or (isinstance(k, tuple) and k[:len(prefix)] == prefix)]
            snaps = [self._children.pop(k) for k in keys]
        for snap in snaps:
            self.merge(snap)

    # --------------------------------------------------------- snapshot
    def _base_snapshot(self) -> dict:
        out: dict = {"counters": {}, "gauges": {}, "hists": {},
                     "help": {}}
        with self._mu:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                out["help"][m.name] = m.help
            if isinstance(m, Histogram):
                out["hists"][m.name] = {
                    k: s.data() for k, s in m.series().items()}
            elif isinstance(m, Gauge):
                out["gauges"][m.name] = {
                    k: s.value() for k, s in m.series().items()}
            else:
                out["counters"][m.name] = {
                    k: s.value() for k, s in m.series().items()}
        return out

    def snapshot(self) -> dict:
        """Mergeable picklable view: base series plus every attached
        child snapshot."""
        snap = self._base_snapshot()
        with self._mu:
            children = list(self._children.values())
        for c in children:
            merge_into(snap, c)
        return snap

    def merge(self, snap: dict) -> None:
        """Fold an external snapshot into the base series permanently
        (counters/histograms add; gauges keep the larger value)."""
        if not self.enabled or not snap:
            return
        for name, series in snap.get("counters", {}).items():
            c = self.counter(name, snap.get("help", {}).get(name, ""))
            for key, v in series.items():
                c.labels(**dict(key)).absorb(v)
        for name, series in snap.get("gauges", {}).items():
            g = self.gauge(name, snap.get("help", {}).get(name, ""))
            for key, v in series.items():
                s = g.labels(**dict(key))
                s.set(max(s.value(), v))
        for name, series in snap.get("hists", {}).items():
            for key, d in series.items():
                h = self._get(Histogram, name,
                              snap.get("help", {}).get(name, ""),
                              bounds=tuple(d["bounds"]))
                h.labels(**dict(key)).absorb(d["counts"], d["sum"])

    def value(self, name: str, **labels) -> float:
        m = self._metrics.get(name)
        return m.value(**labels) if m is not None else 0.0


def merge_into(dst: dict, src: dict) -> dict:
    """Merge snapshot ``src`` into ``dst`` in place (and return it).
    Counters add; gauges keep the max; histogram buckets add
    element-wise (bounds must agree — one definition per metric name)."""
    for name, series in src.get("counters", {}).items():
        d = dst.setdefault("counters", {}).setdefault(name, {})
        for key, v in series.items():
            d[key] = d.get(key, 0.0) + v
    for name, series in src.get("gauges", {}).items():
        d = dst.setdefault("gauges", {}).setdefault(name, {})
        for key, v in series.items():
            d[key] = max(d.get(key, v), v)
    for name, series in src.get("hists", {}).items():
        d = dst.setdefault("hists", {}).setdefault(name, {})
        for key, h in series.items():
            if key not in d:
                d[key] = {"bounds": list(h["bounds"]),
                          "counts": list(h["counts"]),
                          "sum": h["sum"], "count": h["count"]}
                continue
            cur = d[key]
            if list(cur["bounds"]) != list(h["bounds"]):
                raise ValueError(
                    f"histogram {name!r} bucket bounds diverge across "
                    "snapshots — one bounds definition per metric name")
            cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                   h["counts"])]
            cur["sum"] += h["sum"]
            cur["count"] += h["count"]
    for name, h in src.get("help", {}).items():
        dst.setdefault("help", {}).setdefault(name, h)
    return dst


def hist_quantiles(data: dict, qs=(0.5, 0.95, 0.99)) -> Dict[float, float]:
    """Quantiles from a histogram's (bounds, counts): find the bucket the
    rank lands in, interpolate linearly inside it. The overflow bucket
    reports its lower edge (the honest bound we have). Empty -> 0.0."""
    bounds, counts = data["bounds"], data["counts"]
    total = sum(counts)
    out = {}
    for q in qs:
        if total == 0:
            out[q] = 0.0
            continue
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = bounds[i - 1] if i > 0 else 0.0
                hi = bounds[i] if i < len(bounds) else bounds[-1]
                frac = (rank - cum) / c
                out[q] = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                break
            cum += c
        else:  # pragma: no cover - rank <= total always lands
            out[q] = bounds[-1]
    return out


# the process-wide default registry (DIFACTO_OBS=off makes it no-op)
REGISTRY = Registry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              bounds: Optional[Tuple[float, ...]] = None) -> Histogram:
    return REGISTRY.histogram(name, help, bounds)


def enabled() -> bool:
    return REGISTRY.enabled
