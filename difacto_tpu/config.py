"""Layered configuration system.

Re-creates the reference's config chain (SURVEY §5.6): config file + CLI
``k=v`` overrides -> flat key/value list -> each component consumes the keys it
declares and passes the *remainder* down (reference: ``dmlc::Parameter::
InitAllowUnknown`` + ``src/common/arg_parser.h:12-54``; the chain in
``src/sgd/sgd_learner.cc:26-50``). Leftover keys at the end of the chain are a
warning (src/main.cc:40-46).

Usage::

    @dataclass
    class SGDLearnerParam(Param):
        batch_size: int = field(default=100, metadata=dict(lo=1))
        ...

    param, remain = SGDLearnerParam.init_allow_unknown(kwargs)
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass
from typing import Any, List, Tuple

log = logging.getLogger("difacto_tpu")

KWArgs = List[Tuple[str, str]]


def parse_config_file(path: str) -> KWArgs:
    """Parse a ``key = value`` / ``key=value`` config file into KWArgs.

    Mirrors dmlc::Config as used by ``ArgParser::AddArgFile``
    (src/common/arg_parser.h:20-38): one pair per line, ``#`` comments,
    later keys override nothing (all pairs kept; consumers take the last).
    """
    out: KWArgs = []
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "=" not in line:
                raise ValueError(f"bad config line: {line!r}")
            k, v = line.split("=", 1)
            out.append((k.strip(), v.strip()))
    return out


def parse_cli_args(argv: List[str]) -> KWArgs:
    """Parse CLI arguments: the first non ``k=v`` token is a config file."""
    kwargs: KWArgs = []
    for a in argv:
        if "=" in a:
            k, v = a.split("=", 1)
            kwargs.append((k.strip(), v.strip()))
        else:
            kwargs.extend(parse_config_file(a))
    return kwargs


def _coerce(value: str, ty: type) -> Any:
    if ty is bool:
        if isinstance(value, bool):
            return value
        v = str(value).lower()
        if v in ("1", "true", "yes"):
            return True
        if v in ("0", "false", "no"):
            return False
        raise ValueError(f"cannot parse bool from {value!r}")
    return ty(value)


@dataclass
class Param:
    """Base class for typed parameter structs with range checks.

    Field metadata keys: ``lo``/``hi`` inclusive range bounds, ``enum`` a list
    of allowed values — mirroring DMLC_DECLARE_FIELD's set_range/add_enum.
    """

    @classmethod
    def init_allow_unknown(cls, kwargs: KWArgs) -> tuple["Param", KWArgs]:
        """Consume known keys from kwargs; return (instance, remainder)."""
        fields = {f.name: f for f in dataclasses.fields(cls)}
        taken: dict[str, Any] = {}
        remain: KWArgs = []
        for k, v in kwargs:
            f = fields.get(k)
            if f is None:
                remain.append((k, v))
                continue
            if isinstance(f.type, type):
                ty = f.type
            elif f.type in _FIELD_TYPES:
                ty = _FIELD_TYPES[f.type]
            else:
                raise TypeError(
                    f"{cls.__name__}.{f.name}: unsupported config field type "
                    f"{f.type!r}; use int/float/str/bool")
            taken[k] = _coerce(v, ty)  # last occurrence wins
        inst = cls(**taken)
        inst._validate()
        return inst, remain

    def _validate(self) -> None:
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            lo = f.metadata.get("lo")
            hi = f.metadata.get("hi")
            enum = f.metadata.get("enum")
            if lo is not None and v < lo:
                raise ValueError(f"{f.name}={v} < {lo}")
            if hi is not None and v > hi:
                raise ValueError(f"{f.name}={v} > {hi}")
            if enum is not None and v not in enum:
                raise ValueError(f"{f.name}={v!r} not in {enum}")


# dataclass stores string annotations when `from __future__ import annotations`
# is active in the defining module; map the common ones back to types.
_FIELD_TYPES = {"int": int, "float": float, "str": str, "bool": bool}


def parse_endpoints(spec) -> List[Tuple[str, int]]:
    """``"h1:p1,h2:p2"`` (or a list of ``"h:p"`` strings / ``(h, p)``
    pairs) -> ``[(host, port), ...]``. The one endpoint-list grammar
    shared by the failover client (serve/client.py), tools/loadgen.py
    ``--endpoints`` and tools/takeover.py — a replica list is config, so
    its parser lives with the config layer."""
    parts = ([p for p in spec.split(",") if p.strip()]
             if isinstance(spec, str) else list(spec))
    out: List[Tuple[str, int]] = []
    for p in parts:
        if isinstance(p, (tuple, list)):
            host, port = p
        else:
            host, _, port = str(p).strip().rpartition(":")
            if not host:
                raise ValueError(
                    f"bad endpoint {p!r} (want host:port)")
        out.append((host, int(port)))
    if not out:
        raise ValueError(f"empty endpoint list: {spec!r}")
    return out


def format_endpoints(eps) -> str:
    """Inverse of :func:`parse_endpoints`: ``[(h, p), ...]`` ->
    ``"h1:p1,h2:p2"`` — the grammar fleet reports and the shared
    blacklist keys (serve/fleethealth.py) round-trip through."""
    return ",".join(f"{h}:{int(p)}" for h, p in parse_endpoints(eps))


def warn_unknown(remain: KWArgs) -> None:
    """Log unconsumed keys at the end of the config chain (src/main.cc:40-46)."""
    for k, v in remain:
        log.warning("unknown config key: %s = %s", k, v)
