"""Factorization-machine and logistic losses as pure jit kernels.

Re-derivation of the reference's FMLoss (src/loss/fm_loss.h) in gathered-row
form. The loss receives the batch's *already-gathered* parameter rows — w[U]
and V[U, k] for the batch's U distinct features — mirroring the reference
contract where the loss consumes pulled weight vectors, but with the
variable-length [w, V...] byte layout (fm_loss.h:51-53, sgd_learner.cc:151-165)
replaced by fixed (U,) + (U, k) arrays plus an activation mask ``v_mask``
(1.0 where the reference would have V_pos >= 0, i.e. the embedding exists and
is not l1-shrunk away).

Forward (fm_loss.h:43,67-119):
    pred = X w + 0.5 * sum((X V)^2 - (X.X)(V.V), axis=1), clamped to [-20, 20]

Backward (fm_loss.h:124-126,148-203), with p = -y / (1 + exp(y pred)) * rw:
    gw = X' p
    gV = X' diag(p) X V - diag((X.X)' p) V        (masked by v_mask)

Logistic loss (src/loss/logit_loss.h) is the V_dim=0 special case — same code
path with V=None.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..ops.batch import DeviceBatch
from ..ops.segment import spmm, spmm_t, spmv, spmv_t

PRED_CLAMP = 20.0


class FMParams(NamedTuple):
    """Gathered per-batch parameter rows."""
    w: jnp.ndarray                     # f32[U]
    V: Optional[jnp.ndarray] = None    # f32[U, k] or None (pure LR)
    v_mask: Optional[jnp.ndarray] = None  # f32[U]; None == all active


def _vmask(params: FMParams) -> jnp.ndarray:
    if params.v_mask is None:
        return jnp.ones_like(params.w)
    return params.v_mask


def fm_predict(params: FMParams, batch: DeviceBatch) -> jnp.ndarray:
    """pred[B]; padding rows produce garbage — mask at use sites."""
    B = batch.batch_cap
    pred = spmv(batch.vals, batch.rows, batch.cols, params.w, B)
    if params.V is not None and params.V.shape[1] > 0:
        Vm = params.V * _vmask(params)[:, None]
        XV = spmm(batch.vals, batch.rows, batch.cols, Vm, B)
        XXVV = spmm(batch.vals ** 2, batch.rows, batch.cols, Vm ** 2, B)
        pred = pred + 0.5 * jnp.sum(XV ** 2 - XXVV, axis=1)
    return jnp.clip(pred, -PRED_CLAMP, PRED_CLAMP)


def _p_vector(pred: jnp.ndarray, batch: DeviceBatch) -> jnp.ndarray:
    """p = -y/(1+exp(y*pred)) * row_weight, zeroed on padding rows."""
    y = jnp.where(batch.labels > 0, 1.0, -1.0)
    p = -y / (1.0 + jnp.exp(y * pred))
    return p * batch.rweight * batch.row_mask


def fm_grad(params: FMParams, batch: DeviceBatch, pred: jnp.ndarray
            ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Returns (gw[U], gV[U,k] or None)."""
    U = params.w.shape[0]
    p = _p_vector(pred, batch)
    gw = spmv_t(batch.vals, batch.rows, batch.cols, p, U)
    if params.V is None or params.V.shape[1] == 0:
        return gw, None
    vm = _vmask(params)
    Vm = params.V * vm[:, None]
    XV = spmm(batch.vals, batch.rows, batch.cols, Vm, batch.batch_cap)
    # X' diag(p) X V
    t1 = spmm_t(batch.vals, batch.rows, batch.cols, p[:, None] * XV, U)
    # diag((X.X)'p) V
    xxp = spmv_t(batch.vals ** 2, batch.rows, batch.cols, p, U)
    gV = (t1 - xxp[:, None] * Vm) * vm[:, None]
    return gw, gV


def logit_objv(pred: jnp.ndarray, batch: DeviceBatch) -> jnp.ndarray:
    """sum log(1 + exp(-y*pred)) over real rows (include/difacto/loss.h:57-66).

    Not averaged — the reference accumulates raw sums and lets the progress
    printer divide (sgd_utils.h:100-109)."""
    y = jnp.where(batch.labels > 0, 1.0, -1.0)
    per_row = jnp.log1p(jnp.exp(-y * pred))
    return jnp.sum(per_row * batch.row_mask)
