"""Factorization-machine and logistic losses as pure jit kernels.

Re-derivation of the reference's FMLoss (src/loss/fm_loss.h) in gathered-row
form. The loss receives the batch's *already-gathered* parameter rows — w[U]
and V[U, k] for the batch's U distinct features — mirroring the reference
contract where the loss consumes pulled weight vectors, but with the
variable-length [w, V...] byte layout (fm_loss.h:51-53, sgd_learner.cc:151-165)
replaced by fixed (U,) + (U, k) arrays plus an activation mask ``v_mask``
(1.0 where the reference would have V_pos >= 0, i.e. the embedding exists and
is not l1-shrunk away).

Forward (fm_loss.h:43,67-119):
    pred = X w + 0.5 * sum((X V)^2 - (X.X)(V.V), axis=1), clamped to [-20, 20]

Backward (fm_loss.h:124-126,148-203), with p = -y / (1 + exp(y pred)) * rw:
    gw = X' p
    gV = X' diag(p) X V - diag((X.X)' p) V        (masked by v_mask)

Logistic loss (src/loss/logit_loss.h) is the V_dim=0 special case — same code
path with V=None.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.batch import DeviceBatch
from ..ops.segment import spmm, spmm_t, spmv, spmv_t

PRED_CLAMP = 20.0

# widest panel that takes the unrolled column-loop forward; wider panels
# use the single [B,F]-cell gather (trace size is linear in width for
# the loop, constant for the big gather)
_COLLOOP_MAX_WIDTH = 64


class FMParams(NamedTuple):
    """Gathered per-batch parameter rows."""
    w: jnp.ndarray                     # f32[U]
    V: Optional[jnp.ndarray] = None    # f32[U, k] or None (pure LR)
    v_mask: Optional[jnp.ndarray] = None  # f32[U]; None == all active


def _vmask(params: FMParams) -> jnp.ndarray:
    if params.v_mask is None:
        return jnp.ones_like(params.w)
    return params.v_mask


def fm_predict_xv(params: FMParams, batch: DeviceBatch
                  ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """(pred[B], XV[B,k] or None); padding rows produce garbage — mask at
    use sites. XV is handed to the backward so the fused train step never
    recomputes the X·V SpMM (round-4 profile: the backward's duplicate
    token gather was ~15% of the step)."""
    B = batch.batch_cap
    pred = spmv(batch.vals, batch.rows, batch.cols, params.w, B)
    XV = None
    if params.V is not None and params.V.shape[1] > 0:
        Vm = params.V * _vmask(params)[:, None]
        XV = spmm(batch.vals, batch.rows, batch.cols, Vm, B)
        XXVV = spmm(batch.vals ** 2, batch.rows, batch.cols, Vm ** 2, B)
        pred = pred + 0.5 * jnp.sum(XV ** 2 - XXVV, axis=1)
    return jnp.clip(pred, -PRED_CLAMP, PRED_CLAMP), XV


def fm_predict(params: FMParams, batch: DeviceBatch) -> jnp.ndarray:
    return fm_predict_xv(params, batch)[0]


def _p_vector(pred: jnp.ndarray, batch: DeviceBatch) -> jnp.ndarray:
    """p = -y/(1+exp(y*pred)) * row_weight, zeroed on padding rows."""
    y = jnp.where(batch.labels > 0, 1.0, -1.0)
    p = -y / (1.0 + jnp.exp(y * pred))
    return p * batch.rweight * batch.row_mask


def fm_grad(params: FMParams, batch: DeviceBatch, pred: jnp.ndarray,
            xv: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Returns (gw[U], gV[U,k] or None). ``xv`` is the forward's X·V
    (fm_predict_xv); None recomputes it."""
    U = params.w.shape[0]
    p = _p_vector(pred, batch)
    gw = spmv_t(batch.vals, batch.rows, batch.cols, p, U)
    if params.V is None or params.V.shape[1] == 0:
        return gw, None
    vm = _vmask(params)
    Vm = params.V * vm[:, None]
    XV = xv if xv is not None else spmm(batch.vals, batch.rows, batch.cols,
                                        Vm, batch.batch_cap)
    # X' diag(p) X V
    t1 = spmm_t(batch.vals, batch.rows, batch.cols, p[:, None] * XV, U)
    # diag((X.X)'p) V
    xxp = spmv_t(batch.vals ** 2, batch.rows, batch.cols, p, U)
    gV = (t1 - xxp[:, None] * Vm) * vm[:, None]
    return gw, gV


def fm_predict_panel_xv(params: FMParams, pb
                        ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Panel-layout forward (ops/batch.py PanelBatch): one [B]-row gather
    of combined [w | V] rows PER PANEL COLUMN, accumulated into f32
    running sums — no COO segment machinery. Same arithmetic as
    fm_predict (fm_loss.h:43,67-119). Returns (pred, XV) so the backward
    can skip the duplicate token gather.

    The column loop (vs one [B,F]-cell gather) keeps each per-column
    token block VMEM-resident: the single big gather made XLA materialize
    the [B*F, 1+k] token stream to HBM plus a layout reshape (~10 ms of a
    39 ms step at bench shapes, traced); the unrolled loop measures
    37.8 ms vs 39.4 (docs/perf_notes.md). Panels wider than
    _COLLOOP_MAX_WIDTH fall back to the single-gather form — the loop
    unrolls one gather per column into the jit trace, so program size
    and compile time grow linearly with width."""
    if params.V is None or params.V.shape[1] == 0:
        wc = params.w[pb.idx]                       # [B, F]
        if pb.vals is not None:
            wc = wc * pb.vals
        return jnp.clip(jnp.sum(wc, axis=1), -PRED_CLAMP, PRED_CLAMP), None
    # the [U, 1+k] combined rows keep V's STORAGE dtype: with bf16 V_dtype
    # the per-token gather (the step's largest stream at big batches)
    # moves half the bytes; accumulation is f32 below
    dt = params.V.dtype
    k = params.V.shape[1]
    B, F = pb.idx.shape
    Vm = params.V * _vmask(params).astype(dt)[:, None]
    wv = jnp.concatenate([params.w.astype(dt)[:, None], Vm], axis=1)
    if F > _COLLOOP_MAX_WIDTH:
        tok = wv[pb.idx]                             # [B, F, 1+k]
        wc, t = tok[:, :, 0].astype(jnp.float32), tok[:, :, 1:]
        if pb.vals is not None:
            wc = wc * pb.vals
            t = t * pb.vals[:, :, None].astype(dt)   # t = val * V
        t = t.astype(jnp.float32)
        pred = jnp.sum(wc, axis=1)
        XV = jnp.sum(t, axis=1)
        XXVV = jnp.sum(t * t, axis=1)
    else:
        idxT = pb.idx.T                              # [F, B]
        pred = jnp.zeros((B,), jnp.float32)
        XV = jnp.zeros((B, k), jnp.float32)
        XXVV = jnp.zeros((B, k), jnp.float32)
        for f in range(F):
            tok = wv[idxT[f]]                        # [B, 1+k]
            wc = tok[:, 0].astype(jnp.float32)
            t = tok[:, 1:]
            if pb.vals is not None:
                wc = wc * pb.vals[:, f]
                t = t * pb.vals[:, f, None].astype(dt)  # t = val * V
            t = t.astype(jnp.float32)
            pred = pred + wc
            XV = XV + t
            XXVV = XXVV + t * t
    pred = pred + 0.5 * jnp.sum(XV * XV - XXVV, axis=1)
    return jnp.clip(pred, -PRED_CLAMP, PRED_CLAMP), XV


def fm_predict_panel(params: FMParams, pb) -> jnp.ndarray:
    return fm_predict_panel_xv(params, pb)[0]


def _fm_grad_panel_chunked(params: FMParams, pb, p: jnp.ndarray,
                           XV: Optional[jnp.ndarray],
                           sorted_chunks: bool = True
                           ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Chunked-run backward (pb.chunk_* present, ops/batch.py
    panel_chunk_tokens): the fastest variant. The sorted scatter-add is a
    serial per-token update loop (~10 ns/row — half the fused step at
    bench shapes, the round-4 trace's fusion.9); here the per-lane sums
    are computed as a dense vectorised gather+reduce over fixed-L chunks
    of each lane's token run, and the scatter shrinks to ~U + B*F/L
    partial rows. Measured 53.3 -> 39.4 ms full-step (1.35x faster than
    the sorted path it replaced) at bench shapes (docs/perf_notes.md).

    Padded chunk cells gather row b_cap (out of bounds -> 0); padded
    chunks carry lane u_cap (out of bounds -> dropped).

    ``sorted_chunks`` declares chunk_lane globally ascending — true for
    host-local/single-shard layouts, FALSE for dp-sharded mesh batches
    (each shard's block is sorted but the concatenation is not; lying to
    XLA's scatter lowering would be undefined behavior)."""
    U = params.w.shape[0]
    if params.V is None or params.V.shape[1] == 0:
        toks = p.at[pb.chunk_idx].get(mode="fill", fill_value=0)  # [C, L]
        if pb.chunk_vals is not None:
            toks = toks * pb.chunk_vals
        gw = jnp.zeros((U,), jnp.float32).at[pb.chunk_lane].add(
            jnp.sum(toks, axis=1), indices_are_sorted=sorted_chunks,
            mode="drop")
        return gw, None
    k = params.V.shape[1]
    vm = _vmask(params)
    Vm = (params.V * vm.astype(params.V.dtype)[:, None]).astype(jnp.float32)
    row_q = jnp.concatenate([p[:, None] * XV, p[:, None]], axis=1)  # [B,k+1]
    toks = row_q.at[pb.chunk_idx].get(mode="fill",
                                      fill_value=0)       # [C, L, k+1]
    if pb.chunk_vals is None:
        # binary panel: gw == xxp (x == x^2), k+1 columns serve both
        partial = jnp.sum(toks, axis=1)                    # [C, k+1]
        red = jnp.zeros((U, k + 1), jnp.float32).at[pb.chunk_lane].add(
            partial, indices_are_sorted=sorted_chunks, mode="drop")
        t1, gw = red[:, :k], red[:, k]
        xxp = gw
    else:
        v = pb.chunk_vals[:, :, None]                      # [C, L, 1]
        partial = jnp.concatenate([
            jnp.sum(toks * v, axis=1),                     # t1 | gw (x v)
            jnp.sum(toks[:, :, k:] * (v * v), axis=1),     # xxp   (x v^2)
        ], axis=1)                                         # [C, k+2]
        red = jnp.zeros((U, k + 2), jnp.float32).at[pb.chunk_lane].add(
            partial, indices_are_sorted=sorted_chunks, mode="drop")
        t1, gw, xxp = red[:, :k], red[:, k], red[:, k + 1]
    gV = (t1 - xxp[:, None] * Vm) * vm[:, None]
    return gw, gV


def fm_grad_panel(params: FMParams, pb, pred: jnp.ndarray,
                  xv: Optional[jnp.ndarray] = None,
                  sorted_chunks: bool = True
                  ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Panel-layout backward: per-cell contributions are pure BROADCASTS
    of row quantities (p, p*XV), merged by ONE combined segment reduction
    [B*F, k+2] -> [U, k+2] for (t1 | gw | xxp). Same math as fm_grad
    (fm_loss.h:124-126,148-203). ``xv`` is the forward's X·V
    (fm_predict_panel_xv); None re-gathers the tokens to rebuild it.
    Batches carrying a chunked-run layout (panel_chunk_tokens) take the
    chunked fast path."""
    U = params.w.shape[0]
    B, F = pb.idx.shape
    p = _p_vector(pred, pb)                          # [B]
    if pb.chunk_lane is not None:
        if params.V is not None and params.V.shape[1] > 0 and xv is None:
            _, xv = fm_predict_panel_xv(params, pb)
        return _fm_grad_panel_chunked(params, pb, p, xv, sorted_chunks)
    flat_idx = pb.idx.reshape(B * F)
    if params.V is None or params.V.shape[1] == 0:
        cell = jnp.broadcast_to(p[:, None], (B, F))
        if pb.vals is not None:
            cell = cell * pb.vals
        gw = jax.ops.segment_sum(cell.reshape(B * F), flat_idx,
                                 num_segments=U)
        return gw, None
    k = params.V.shape[1]
    vm = _vmask(params)
    Vm = (params.V * vm.astype(params.V.dtype)[:, None])
    if xv is not None:
        XV = xv
    else:
        t = Vm[pb.idx]
        if pb.vals is not None:
            t = t * pb.vals[:, :, None].astype(t.dtype)
        XV = jnp.sum(t.astype(jnp.float32), axis=1)
    Vm = Vm.astype(jnp.float32)
    pXV = p[:, None] * XV                            # [B, k]
    contrib = jnp.concatenate([
        jnp.broadcast_to(pXV[:, None, :], (B, F, k)),
        jnp.broadcast_to(p[:, None, None], (B, F, 1)),   # -> gw
        jnp.broadcast_to(p[:, None, None], (B, F, 1)),   # -> xxp
    ], axis=2)
    if pb.vals is not None:
        v3 = pb.vals[:, :, None]
        contrib = contrib * jnp.concatenate(
            [jnp.broadcast_to(v3, (B, F, k + 1)), v3 * v3], axis=2)
    # the [B*F, k+2] contribution stream rides the storage dtype (bf16
    # when V_dtype is bf16: per-cell rounding only); accumulation into the
    # per-feature sums stays float32 via the scatter-add's output buffer
    red = jnp.zeros((U, k + 2), jnp.float32).at[flat_idx].add(
        contrib.astype(params.V.dtype).reshape(B * F, k + 2))
    t1, gw, xxp = red[:, :k], red[:, k], red[:, k + 1]
    gV = (t1 - xxp[:, None] * Vm) * vm[:, None]
    return gw, gV


def logit_objv(pred: jnp.ndarray, batch: DeviceBatch) -> jnp.ndarray:
    """sum log(1 + exp(-y*pred)) over real rows (include/difacto/loss.h:57-66).

    Not averaged — the reference accumulates raw sums and lets the progress
    printer divide (sgd_utils.h:100-109)."""
    y = jnp.where(batch.labels > 0, 1.0, -1.0)
    per_row = jnp.log1p(jnp.exp(-y * pred))
    return jnp.sum(per_row * batch.row_mask)
