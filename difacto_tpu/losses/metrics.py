"""Binary classification metrics.

Equivalent of the reference's BinClassMetric (src/loss/bin_class_metric.h),
keeping its exact conventions: metrics are *not* divided by num_examples
(progress merging sums them across jobs and the printer divides); AUC returns
area * n with the < 0.5 flip (bin_class_metric.h:35-57).

Two implementations: numpy (host, for per-batch progress) and jnp (device,
usable inside jit — sort-based, identical semantics).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def auc_times_n(label: np.ndarray, pred: np.ndarray) -> float:
    """Rank-sum AUC scaled by n (bin_class_metric.h:35-57)."""
    n = len(label)
    if n == 0:
        return 0.0
    order = np.argsort(pred, kind="stable")
    lab = label[order] > 0
    cum_tp = np.cumsum(lab)
    npos = cum_tp[-1]
    if npos == 0 or npos == n:
        return 1.0
    area = float(cum_tp[~lab].sum())
    area /= npos * (n - npos)
    return (1.0 - area if area < 0.5 else area) * n


def auc_times_n_jnp(label: jnp.ndarray, pred: jnp.ndarray,
                    mask: jnp.ndarray) -> jnp.ndarray:
    """Device AUC over masked rows; padding rows must have mask==0.

    Padding is sorted to the end (pred := +inf on pads) and excluded from the
    cumulative counts, so the result matches the numpy version on real rows.
    """
    big = jnp.asarray(jnp.inf, pred.dtype)
    key = jnp.where(mask > 0, pred, big)
    order = jnp.argsort(key)
    lab = (label[order] > 0) & (mask[order] > 0)
    neg = (label[order] <= 0) & (mask[order] > 0)
    cum_tp = jnp.cumsum(lab)
    npos = cum_tp[-1]
    n = jnp.sum(mask)
    nneg = n - npos
    area = jnp.sum(jnp.where(neg, cum_tp, 0.0))
    area = area / jnp.maximum(npos * nneg, 1)
    area = jnp.where(area < 0.5, 1.0 - area, area) * n
    return jnp.where((npos == 0) | (nneg == 0), 1.0, area)


def auc_times_n_binned_jnp(label: jnp.ndarray, pred: jnp.ndarray,
                           mask: jnp.ndarray,
                           bins: int = 4096) -> jnp.ndarray:
    """Histogram AUC x n: O(B + bins) instead of the O(B log B) argsort.

    Predictions are clamped to +-20 by every loss (losses/fm.py PRED_CLAMP),
    so linear bins over [-20.5, 20.5] lose only within-bin ordering —
    a <= 1/bins area error, invisible at progress-row precision. Used for
    the per-step TRAINING metric so the hot path never sorts; validation
    keeps the exact sort-based AUC (the reference's early stopping compares
    val-AUC deltas, sgd_learner.cc:92-110).
    """
    lo, hi = -20.5, 20.5
    b = jnp.clip(((pred - lo) * (bins / (hi - lo))).astype(jnp.int32),
                 0, bins - 1)
    is_pos = (label > 0) & (mask > 0)
    is_neg = (label <= 0) & (mask > 0)
    pos = jnp.zeros(bins, jnp.float32).at[b].add(is_pos.astype(jnp.float32))
    neg = jnp.zeros(bins, jnp.float32).at[b].add(is_neg.astype(jnp.float32))
    npos, nneg = jnp.sum(pos), jnp.sum(neg)
    # ascending-pred bins: pairs won = neg below + half of ties in-bin
    cum_pos_below = jnp.cumsum(pos) - pos
    area = jnp.sum(neg * (cum_pos_below + 0.5 * pos))
    # orientation flip matches the exact metric (bin_class_metric.h:35-57):
    # area here counts (pos ranked above neg) pairs from the neg side
    area = area / jnp.maximum(npos * nneg, 1)
    n = npos + nneg
    area = jnp.where(area < 0.5, 1.0 - area, area) * n
    return jnp.where((npos == 0) | (nneg == 0), 1.0, area)


def accuracy_times_n(label: np.ndarray, pred: np.ndarray,
                     threshold: float = 0.0) -> float:
    correct = float(np.sum((label > 0) == (pred > threshold)))
    n = len(label)
    return correct if correct > 0.5 * n else n - correct


def logloss(label: np.ndarray, pred: np.ndarray) -> float:
    y = (label > 0).astype(np.float64)
    p = 1.0 / (1.0 + np.exp(-pred.astype(np.float64)))
    p = np.clip(p, 1e-10, 1.0 - 1e-10)
    return float(-np.sum(y * np.log(p) + (1 - y) * np.log1p(-p)))


def logit_objv_np(label: np.ndarray, pred: np.ndarray) -> float:
    y = np.where(label > 0, 1.0, -1.0)
    return float(np.sum(np.log1p(np.exp(-y * pred.astype(np.float64)))))


def rmse_stub(label: np.ndarray, pred: np.ndarray) -> float:
    """Reference's RMSE sums raw differences (bin_class_metric.h:94-102) —
    kept name-for-name; use logloss/auc for real evaluation."""
    return float(np.sum(label - pred))
