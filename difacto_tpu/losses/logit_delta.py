"""Delta-form logistic loss for block coordinate descent.

Equivalent of the reference's LogitLossDelta (src/loss/logit_loss_delta.h):
consumes feature-major ("transposed") data and per-block delta weights.

- ``delta_grad``: first-order gradient g = X'p with p = -y/(1+exp(y·pred))
  and diagonal Hessian h = (X∘X)'(τ(1-τ)) (logit_loss_delta.h:90-151,
  compute_hession=1). The reference's interleaved grad_pos/h_pos layout
  becomes two dense block-local arrays.
- ``delta_pred_update``: pred += X·Δw (logit_loss_delta.h:63-72).

The hessian upper-bound mode (compute_hession=2) is unimplemented in the
reference too (LOG(FATAL), logit_loss_delta.h:139-146).

FMLossDelta (src/loss/fm_loss_delta.h) is an empty TODO stub in the
reference — BCD is linear-only there and here.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class BlockSlice(NamedTuple):
    """COO slice of one row-tile restricted to one feature block;
    cols are block-local feature indices, padding has vals == 0."""
    rows: jnp.ndarray  # i32[nnz_cap]
    cols: jnp.ndarray  # i32[nnz_cap]
    vals: jnp.ndarray  # f32[nnz_cap]


def delta_grad(pred: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray,
               blk: BlockSlice, nf_cap: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(g, h) over the block's features."""
    y = jnp.where(labels > 0, 1.0, -1.0)
    p = -y / (1.0 + jnp.exp(y * pred)) * mask
    g = jax.ops.segment_sum(blk.vals * p[blk.rows], blk.cols,
                            num_segments=nf_cap)
    p2 = -p * (y * mask + p)  # tau(1-tau), zero on padding rows
    h = jax.ops.segment_sum(blk.vals ** 2 * p2[blk.rows], blk.cols,
                            num_segments=nf_cap)
    return g, h


def delta_pred_update(pred: jnp.ndarray, blk: BlockSlice,
                      d: jnp.ndarray) -> jnp.ndarray:
    """pred += X_blk Δw."""
    return pred + jax.ops.segment_sum(
        blk.vals * d[blk.cols], blk.rows, num_segments=pred.shape[0])
