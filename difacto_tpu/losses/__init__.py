"""Loss factory — parity with Loss::Create (src/loss/loss.cc:13-26).

``create("fm" | "logit", V_dim)`` returns a thin namespace over the pure
kernels in fm.py; "logit" forces V_dim = 0 (src/loss/logit_loss.h is the
linear special case).
"""

from __future__ import annotations

from dataclasses import dataclass

from .fm import (FMParams, fm_grad, fm_grad_panel, fm_predict,
                 fm_predict_panel, fm_predict_panel_xv, fm_predict_xv,
                 logit_objv)
from . import metrics


@dataclass(frozen=True)
class LossSpec:
    name: str
    V_dim: int
    # whether panel chunk_lane arrays are globally ascending — True for
    # host-local/single-dp-shard layouts; the learner flips it False for
    # dp>1 meshes, where each shard's block is sorted but the global
    # concatenation is not (promising sorted indices to XLA's scatter
    # would be undefined behavior; see fm._fm_grad_panel_chunked)
    chunks_sorted: bool = True

    def predict(self, params: FMParams, batch):
        from ..ops.batch import PanelBatch
        if isinstance(batch, PanelBatch):
            return fm_predict_panel(params, batch)
        return fm_predict(params, batch)

    def predict_xv(self, params: FMParams, batch):
        """(pred, XV-or-None): the forward plus its X·V byproduct, which
        calc_grad reuses so the fused train step gathers tokens ONCE."""
        from ..ops.batch import PanelBatch
        if isinstance(batch, PanelBatch):
            return fm_predict_panel_xv(params, batch)
        return fm_predict_xv(params, batch)

    def calc_grad(self, params: FMParams, batch, pred, xv=None):
        from ..ops.batch import PanelBatch
        if isinstance(batch, PanelBatch):
            return fm_grad_panel(params, batch, pred, xv,
                                 self.chunks_sorted)
        return fm_grad(params, batch, pred, xv)

    def evaluate(self, pred, batch):
        return logit_objv(pred, batch)


def create(name: str, V_dim: int = 0) -> LossSpec:
    name = name.lower()
    if name == "logit":
        return LossSpec("logit", 0)
    if name == "fm":
        return LossSpec("fm", V_dim)
    raise ValueError(f"unknown loss type: {name!r}")


__all__ = ["FMParams", "fm_predict", "fm_predict_xv", "fm_grad",
           "fm_predict_panel", "fm_predict_panel_xv", "fm_grad_panel",
           "logit_objv", "LossSpec", "create", "metrics"]
