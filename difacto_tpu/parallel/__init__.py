"""Parallelism: device mesh, shardings, and the ICI parameter-server layout."""

from .mesh import (DP_AXIS, FS_AXIS, batch_sharding, fs_shard_bounds,
                   fs_size, make_mesh, put_dp_local, put_global, replicated,
                   shard_pytree, sharding_tree, state_sharding,
                   validate_fs_capacity)

__all__ = ["DP_AXIS", "FS_AXIS", "make_mesh", "state_sharding",
           "batch_sharding", "replicated", "shard_pytree", "sharding_tree",
           "put_global", "put_dp_local", "fs_size", "fs_shard_bounds",
           "validate_fs_capacity"]
