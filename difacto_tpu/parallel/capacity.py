"""Capacity-scaling measurement for the fs-sharded slot table.

The point of key-range sharding the table (mesh.py fs axis; the
reference's KVStoreDist server sharding) is CAPACITY: an fs-way mesh
holds an fs-times-larger table at the same per-device HBM. This module
is the one measurement of that claim, shared by ``bench.py --multichip``
and the driver's ``__graft_entry__.dryrun_multichip`` leg — for each
``fs`` rung it builds a table of ``base_capacity * fs`` rows sharded
over ``fs`` devices, runs the SAME fused train step the product
dispatches (panel + chunked backward at dp=1), and reports throughput
next to per-device table bytes, so MULTICHIP_r*.json carries a real
scaling trajectory instead of a bare {rc, ok}.

``scaling``: per-device bytes should stay ~flat while max trainable
capacity grows linearly — ``capacity_scaling`` is exact by construction
(cap_fs / cap_1); ``throughput_retention`` (ex/s at fs vs fs=1) is the
honest cost figure, since the gather/scatter turns into cross-shard
collectives.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence


def capacity_scaling_report(fs_values: Optional[Sequence[int]] = None,
                            base_capacity: int = 1 << 12,
                            V_dim: int = 8, batch: int = 1024,
                            nnz_per_row: int = 8, steps: int = 4,
                            v_dtype: str = "float32",
                            slot_dtype: str = "fp32") -> dict:
    """One leg per fs rung: {fs, hash_capacity, table_bytes_per_device,
    examples_per_sec} plus the cross-rung scaling summary. Rungs that
    exceed the visible device count are skipped (reported in
    ``skipped_fs``), so the same call works on the 8-chip bench box and
    a 1-device CPU host."""
    import jax
    import numpy as np

    from ..updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                        make_fns, set_all_live, state_bytes)
    from ..losses import create as create_loss
    from ..step import make_step_fns, state_constrainer
    from ..store.local import pad_slots_oob
    from ..utils import jaxtrace
    from . import (make_mesh, replicated, shard_pytree, sharding_tree,
                   state_sharding)

    n_dev = len(jax.devices())
    if fs_values is None:
        fs_values = [f for f in (1, 2, 4, 8) if f <= n_dev]
    legs = []
    skipped = [f for f in fs_values if f > n_dev]
    rng = np.random.RandomState(0)
    for fs in fs_values:
        if fs > n_dev:
            continue
        cap = base_capacity * fs
        param = SGDUpdaterParam(V_dim=V_dim, V_threshold=0, lr=0.1,
                                l1=1e-4, l2=1e-4, V_dtype=v_dtype,
                                hash_capacity=cap, slot_dtype=slot_dtype)
        fns = make_fns(param)
        loss = create_loss("fm", V_dim)
        state = init_state(param, cap)
        if V_dim:
            state = set_all_live(param, state)
        mesh = make_mesh(dp=1, fs=fs)
        shardings = sharding_tree(state, state_sharding(mesh))
        state = shard_pytree(state, state_sharding(mesh))
        _, train_step, _ = make_step_fns(fns, loss,
                                         state_shardings=shardings)
        # the per-leg compile is intentional: one program per fs rung
        # lint: ok(jax-recompile) one bounded compile per fs rung of the
        # capacity sweep — the loop IS the benchmark matrix
        step = jaxtrace.pjit(train_step, donate_argnums=0)

        # synthetic localized batch: uniform draws over the table
        u_cap = min(cap // 2, max(64, batch * nnz_per_row // 4))
        uniq = np.sort(rng.permutation(cap - 1)[:u_cap] + 1)
        slots = jax.device_put(
            pad_slots_oob(uniq.astype(np.int32), u_cap, cap),
            replicated(mesh))
        from ..data.rowblock import RowBlock
        from ..ops.batch import pad_batch
        idx = rng.randint(0, u_cap, batch * nnz_per_row).astype(np.uint32)
        blk = RowBlock(
            offset=np.arange(batch + 1, dtype=np.int64) * nnz_per_row,
            label=rng.choice([0.0, 1.0], batch).astype(np.float32),
            index=idx, value=None)
        dev = pad_batch(blk, num_uniq=u_cap, batch_cap=batch,
                        nnz_cap=batch * nnz_per_row)
        dev = shard_pytree(dev, lambda x: replicated(mesh))

        # layout-cleanliness proof for the MULTICHIP metric: scan the
        # leg's compiled HLO (utils/hloscan.py) BEFORE the donating
        # warm call — zero table-axis collectives is what makes the
        # throughput numbers mean "sharded", not "secretly gathered"
        from ..utils import hloscan
        leg_hlo = None
        try:
            compiled = step.lower(state, dev, slots).compile()
            one = hloscan.scan_compiled(compiled, rows=cap,
                                        label="train_step")
            hloscan.record(
                getattr(step, "site", "difacto_tpu/parallel/capacity.py"),
                compiled, label="train_step", rows=cap)
            leg_hlo = {
                "table_collectives": one["table_collectives"],
                "peak_temp_bytes": one["peak_temp_bytes"],
            }
        except Exception as e:   # the sweep must survive a scan failure
            import logging
            logging.getLogger("difacto_tpu").warning(
                "capacity: hlo scan of the fs=%d leg failed: %s", fs, e)
            leg_hlo = None

        state, objv, _ = step(state, dev, slots)           # compile
        jaxtrace.fetch(objv, point="capacity.fence")
        t0 = time.perf_counter()
        for _ in range(steps):
            state, objv, _ = step(state, dev, slots)
        jaxtrace.fetch(objv, point="capacity.fence")
        dt = time.perf_counter() - t0
        total = state_bytes(param, cap)
        leg = {
            "fs": fs,
            "hash_capacity": cap,
            "table_bytes_total": int(total),
            "table_bytes_per_device": int(total // fs),
            "examples_per_sec": round(steps * batch / dt, 1),
            "step_ms": round(dt / steps * 1e3, 3),
        }
        if leg_hlo is not None:
            leg["hlo"] = leg_hlo
        legs.append(leg)
        del state
    out = {
        "metric": "multichip_capacity_scaling",
        "n_devices": n_dev,
        "config": {"base_capacity": base_capacity, "V_dim": V_dim,
                   "batch": batch, "nnz_per_row": nnz_per_row,
                   "steps": steps, "V_dtype": v_dtype},
        "legs": legs,
        "skipped_fs": skipped,
    }
    if legs:
        base = legs[0]
        peak = legs[-1]
        out["max_hash_capacity"] = peak["hash_capacity"]
        out["capacity_scaling"] = round(
            peak["hash_capacity"] / base["hash_capacity"], 3)
        out["throughput_retention"] = round(
            peak["examples_per_sec"] / max(base["examples_per_sec"], 1e-9),
            3)
        # near-linear capacity scaling at bounded per-device bytes is
        # the acceptance claim: efficiency 1.0 = fs x capacity at
        # constant per-device residency
        out["scaling_efficiency"] = round(
            (peak["hash_capacity"] / base["hash_capacity"])
            / max(peak["fs"] / base["fs"], 1e-9), 3)
    return out


def bounded_delay_report(hosts_values: Sequence[int] = (1, 2, 4),
                         taus: Sequence[int] = (0, 1, 4),
                         fs: int = 4, base_capacity: int = 1 << 12,
                         V_dim: int = 8, batch: int = 1024,
                         nnz_per_row: int = 8, steps: int = 8,
                         v_dtype: str = "float32",
                         straggle_factor: float = 1.5,
                         auc_legs: bool = True,
                         seed: int = 0) -> dict:
    """Bounded-delay (τ) pipelining legs for ``bench.py --multichip``.

    One REAL fs-sharded fused train step (the same compiled program as
    the capacity sweep) is driven through the real windowed pipeline
    (data/prefetch.prefetch at depth 2+τ) against SIMULATED peer
    clocks: for each ``hosts`` rung a deterministic straggler timeline
    ``peer_done[t]`` (slowest of hosts-1 jittered peers, cumulative) is
    drawn once per rung — the SAME timeline for every τ — and the
    exchange stage sleeps until ``peer_done[s-τ-1]`` before staging
    step ``s``, exactly the wait_clock contract of the live schedule
    (learners/sgd.py _iterate_data_spmd). Because a larger τ waits on a
    strictly earlier (hence never later) peer clock against one fixed
    timeline, ex/s is monotonically non-decreasing in τ by
    construction, and the measured gap IS the synchronization time the
    window hides.

    ``auc_legs`` adds the delay-vs-AUC trajectory leg: short REAL
    trainings on synthetic data through the windowed schedule at each
    τ, reporting ``auc_delta`` vs the τ=0 run — honest support for the
    τ-invariance claim (device steps stay collective-synchronous, so
    the trajectory does not move with τ; see docs/perf_notes.md).
    """
    import jax
    import numpy as np

    from ..updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                        make_fns, set_all_live)
    from ..losses import create as create_loss
    from ..step import make_step_fns
    from ..store.local import pad_slots_oob
    from ..utils import hloscan, jaxtrace
    from ..data.prefetch import prefetch
    from . import (make_mesh, replicated, shard_pytree, sharding_tree,
                   state_sharding)

    n_dev = len(jax.devices())
    fs = min(fs, n_dev)
    cap = base_capacity * fs
    rng = np.random.RandomState(seed)
    param = SGDUpdaterParam(V_dim=V_dim, V_threshold=0, lr=0.1,
                            l1=1e-4, l2=1e-4, V_dtype=v_dtype,
                            hash_capacity=cap)
    fns = make_fns(param)
    loss = create_loss("fm", V_dim)
    state = init_state(param, cap)
    if V_dim:
        state = set_all_live(param, state)
    mesh = make_mesh(dp=1, fs=fs)
    shardings = sharding_tree(state, state_sharding(mesh))
    state = shard_pytree(state, state_sharding(mesh))
    _, train_step, _ = make_step_fns(fns, loss, state_shardings=shardings)
    # lint: ok(jax-recompile) one bounded compile for the whole delay
    # sweep — every (hosts, τ) leg drives the SAME program
    step = jaxtrace.pjit(train_step, donate_argnums=0)

    u_cap = min(cap // 2, max(64, batch * nnz_per_row // 4))
    uniq = np.sort(rng.permutation(cap - 1)[:u_cap] + 1)
    slots = jax.device_put(
        pad_slots_oob(uniq.astype(np.int32), u_cap, cap),
        replicated(mesh))
    from ..data.rowblock import RowBlock
    from ..ops.batch import pad_batch
    idx = rng.randint(0, u_cap, batch * nnz_per_row).astype(np.uint32)
    blk = RowBlock(
        offset=np.arange(batch + 1, dtype=np.int64) * nnz_per_row,
        label=rng.choice([0.0, 1.0], batch).astype(np.float32),
        index=idx, value=None)
    dev = pad_batch(blk, num_uniq=u_cap, batch_cap=batch,
                    nnz_cap=batch * nnz_per_row)
    dev = shard_pytree(dev, lambda x: replicated(mesh))

    hlo = None
    try:
        compiled = step.lower(state, dev, slots).compile()
        one = hloscan.scan_compiled(compiled, rows=cap,
                                    label="train_step_delay")
        hlo = {"table_collectives": one["table_collectives"],
               "peak_temp_bytes": one["peak_temp_bytes"]}
        for tau in taus:
            # per-τ record under a colon-free site: hlomap.build treats
            # it as a non-pjit measurement label, not an unknown site
            hloscan.record(f"capacity.delay/tau{tau}", compiled,
                           label=f"train_step_tau{tau}", rows=cap)
    except Exception as e:   # the sweep must survive a scan failure
        import logging
        logging.getLogger("difacto_tpu").warning(
            "bounded_delay: hlo scan failed: %s", e)

    # warm + base step time (feeds the simulated peer timelines)
    state, objv, _ = step(state, dev, slots)
    jaxtrace.fetch(objv, point="capacity.fence")
    t0 = time.perf_counter()
    for _ in range(max(2, steps // 2)):
        state, objv, _ = step(state, dev, slots)
    jaxtrace.fetch(objv, point="capacity.fence")
    step_s = (time.perf_counter() - t0) / max(2, steps // 2)

    legs = []
    for hosts in hosts_values:
        # one straggler timeline per hosts rung, REUSED across every τ
        # (fresh deterministic seed => identical peer clocks), so the
        # τ column of the matrix measures only the window, never luck
        lrng = np.random.RandomState(seed * 1000 + hosts)
        if hosts > 1:
            jit = lrng.uniform(0.0, straggle_factor * step_s,
                               size=(steps, hosts - 1)).max(axis=1)
        else:
            jit = np.zeros(steps)
        peer_done = np.cumsum(step_s + jit)
        for tau in taus:
            def exchange_sim(peer_done=peer_done, tau=tau, hosts=hosts):
                start = time.perf_counter()
                for s in range(steps):
                    need = s - tau - 1
                    if hosts > 1 and need >= 0:
                        # the wait_clock contract: block until the
                        # slowest peer has dispatched step s-τ-1
                        rem = peer_done[need] - (time.perf_counter()
                                                 - start)
                        if rem > 0:
                            time.sleep(rem)
                    yield s
                # epoch-end barrier: the part drain always joins the
                # slowest peer's LAST step, window or not
                if hosts > 1:
                    rem = peer_done[steps - 1] - (time.perf_counter()
                                                  - start)
                    if rem > 0:
                        time.sleep(rem)

            t0 = time.perf_counter()
            for _ in prefetch(exchange_sim(), depth=2 + tau):
                state, objv, _ = step(state, dev, slots)
            jaxtrace.fetch(objv, point="capacity.fence")
            dt = time.perf_counter() - t0
            leg = {
                "hosts": hosts,
                "tau": tau,
                "examples_per_sec": round(steps * batch / dt, 1),
                "step_ms": round(dt / steps * 1e3, 3),
            }
            if hlo is not None:
                leg["hlo"] = hlo
            legs.append(leg)
    del state

    out = {
        "metric": "bounded_delay_pipelining",
        "n_devices": n_dev,
        "config": {"fs": fs, "base_capacity": base_capacity,
                   "V_dim": V_dim, "batch": batch,
                   "nnz_per_row": nnz_per_row, "steps": steps,
                   "V_dtype": v_dtype, "straggle_factor": straggle_factor,
                   "seed": seed},
        "base_step_ms": round(step_s * 1e3, 3),
        "legs": legs,
    }
    # scaling retention per τ: the slowest rung's ex/s over the mean
    # single-host ex/s (one common denominator, so the τ column inherits
    # the sleep-until monotonicity instead of hosts=1 timing noise) —
    # this is the acceptance figure: retention improves with τ because
    # the window hides the stragglers' sync time
    h1 = [leg for leg in legs if leg["hosts"] == 1]
    hm = [leg for leg in legs if leg["hosts"] == max(hosts_values)]
    if h1 and hm and max(hosts_values) > 1:
        base1 = sum(leg["examples_per_sec"] for leg in h1) / len(h1)
        out["retention_by_tau"] = {
            str(leg["tau"]): round(leg["examples_per_sec"]
                                   / max(base1, 1e-9), 4)
            for leg in hm}
    if auc_legs:
        out["auc"] = _delay_auc_legs(taus, fs, n_dev)
    return out


def _delay_auc_legs(taus: Sequence[int], fs: int, n_dev: int) -> list:
    """Delay-vs-AUC trajectory: short REAL trainings on synthetic data
    at each τ through the windowed schedule; ``auc_delta`` vs τ=0 backs
    the trajectory-invariance claim with measurement (expected ~0 —
    bounded delay moves wait time, not gradients)."""
    import tempfile

    import numpy as np

    dp = 2 if 2 * fs <= n_dev else 1
    if dp * fs > n_dev:
        return [{"skipped": f"needs {dp * fs} devices, have {n_dev}"}]
    rng = np.random.RandomState(7)
    rows, feats = 400, 1 << 12
    with tempfile.NamedTemporaryFile("w", suffix=".libsvm",
                                     delete=False) as f:
        w = rng.randn(64)
        for _ in range(rows):
            ks = np.sort(rng.choice(feats, rng.randint(4, 16),
                                    replace=False))
            y = 1 if w[ks % 64].sum() > 0 else 0
            f.write(str(y) + " "
                    + " ".join(f"{k}:1" for k in ks) + "\n")
        path = f.name

    def train(tau: int) -> float:
        from ..learners import Learner
        conf = {"data_in": path, "V_dim": "2", "V_threshold": "1",
                "lr": "0.1", "l1": "1e-4", "l2": "1e-4",
                "batch_size": "100", "max_num_epochs": "2",
                "shuffle": "0", "report_interval": "0",
                "stop_rel_objv": "0", "stop_val_auc": "-2",
                "num_jobs_per_epoch": "1", "hash_capacity": str(1 << 16),
                "mesh_dp": str(dp), "mesh_fs": str(fs),
                "bounded_delay": str(tau)}
        ln = Learner.create("sgd")
        ln.init(list(conf.items()))
        aucs: list = []
        ln.add_epoch_end_callback(
            lambda e, t, v: aucs.append(t.auc / max(t.nrows, 1.0)))
        ln.run()
        return float(aucs[-1])

    base = None
    legs = []
    try:
        for tau in sorted(set([0, *taus])):
            auc = train(tau)
            if tau == 0:
                base = auc
            legs.append({"tau": tau, "auc": round(auc, 6),
                         "auc_delta": round(auc - base, 6)})
    finally:
        import os as _os
        _os.unlink(path)
    return legs
