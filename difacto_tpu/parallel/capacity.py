"""Capacity-scaling measurement for the fs-sharded slot table.

The point of key-range sharding the table (mesh.py fs axis; the
reference's KVStoreDist server sharding) is CAPACITY: an fs-way mesh
holds an fs-times-larger table at the same per-device HBM. This module
is the one measurement of that claim, shared by ``bench.py --multichip``
and the driver's ``__graft_entry__.dryrun_multichip`` leg — for each
``fs`` rung it builds a table of ``base_capacity * fs`` rows sharded
over ``fs`` devices, runs the SAME fused train step the product
dispatches (panel + chunked backward at dp=1), and reports throughput
next to per-device table bytes, so MULTICHIP_r*.json carries a real
scaling trajectory instead of a bare {rc, ok}.

``scaling``: per-device bytes should stay ~flat while max trainable
capacity grows linearly — ``capacity_scaling`` is exact by construction
(cap_fs / cap_1); ``throughput_retention`` (ex/s at fs vs fs=1) is the
honest cost figure, since the gather/scatter turns into cross-shard
collectives.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence


def capacity_scaling_report(fs_values: Optional[Sequence[int]] = None,
                            base_capacity: int = 1 << 12,
                            V_dim: int = 8, batch: int = 1024,
                            nnz_per_row: int = 8, steps: int = 4,
                            v_dtype: str = "float32") -> dict:
    """One leg per fs rung: {fs, hash_capacity, table_bytes_per_device,
    examples_per_sec} plus the cross-rung scaling summary. Rungs that
    exceed the visible device count are skipped (reported in
    ``skipped_fs``), so the same call works on the 8-chip bench box and
    a 1-device CPU host."""
    import jax
    import numpy as np

    from ..updaters.sgd_updater import (SGDUpdaterParam, init_state,
                                        make_fns, set_all_live, state_bytes)
    from ..losses import create as create_loss
    from ..step import make_step_fns, state_constrainer
    from ..store.local import pad_slots_oob
    from ..utils import jaxtrace
    from . import (make_mesh, replicated, shard_pytree, sharding_tree,
                   state_sharding)

    n_dev = len(jax.devices())
    if fs_values is None:
        fs_values = [f for f in (1, 2, 4, 8) if f <= n_dev]
    legs = []
    skipped = [f for f in fs_values if f > n_dev]
    rng = np.random.RandomState(0)
    for fs in fs_values:
        if fs > n_dev:
            continue
        cap = base_capacity * fs
        param = SGDUpdaterParam(V_dim=V_dim, V_threshold=0, lr=0.1,
                                l1=1e-4, l2=1e-4, V_dtype=v_dtype,
                                hash_capacity=cap)
        fns = make_fns(param)
        loss = create_loss("fm", V_dim)
        state = init_state(param, cap)
        if V_dim:
            state = set_all_live(param, state)
        mesh = make_mesh(dp=1, fs=fs)
        shardings = sharding_tree(state, state_sharding(mesh))
        state = shard_pytree(state, state_sharding(mesh))
        _, train_step, _ = make_step_fns(fns, loss,
                                         state_shardings=shardings)
        # the per-leg compile is intentional: one program per fs rung
        # lint: ok(jax-recompile) one bounded compile per fs rung of the
        # capacity sweep — the loop IS the benchmark matrix
        step = jaxtrace.pjit(train_step, donate_argnums=0)

        # synthetic localized batch: uniform draws over the table
        u_cap = min(cap // 2, max(64, batch * nnz_per_row // 4))
        uniq = np.sort(rng.permutation(cap - 1)[:u_cap] + 1)
        slots = jax.device_put(
            pad_slots_oob(uniq.astype(np.int32), u_cap, cap),
            replicated(mesh))
        from ..data.rowblock import RowBlock
        from ..ops.batch import pad_batch
        idx = rng.randint(0, u_cap, batch * nnz_per_row).astype(np.uint32)
        blk = RowBlock(
            offset=np.arange(batch + 1, dtype=np.int64) * nnz_per_row,
            label=rng.choice([0.0, 1.0], batch).astype(np.float32),
            index=idx, value=None)
        dev = pad_batch(blk, num_uniq=u_cap, batch_cap=batch,
                        nnz_cap=batch * nnz_per_row)
        dev = shard_pytree(dev, lambda x: replicated(mesh))

        # layout-cleanliness proof for the MULTICHIP metric: scan the
        # leg's compiled HLO (utils/hloscan.py) BEFORE the donating
        # warm call — zero table-axis collectives is what makes the
        # throughput numbers mean "sharded", not "secretly gathered"
        from ..utils import hloscan
        leg_hlo = None
        try:
            compiled = step.lower(state, dev, slots).compile()
            one = hloscan.scan_compiled(compiled, rows=cap,
                                        label="train_step")
            hloscan.record(
                getattr(step, "site", "difacto_tpu/parallel/capacity.py"),
                compiled, label="train_step", rows=cap)
            leg_hlo = {
                "table_collectives": one["table_collectives"],
                "peak_temp_bytes": one["peak_temp_bytes"],
            }
        except Exception as e:   # the sweep must survive a scan failure
            import logging
            logging.getLogger("difacto_tpu").warning(
                "capacity: hlo scan of the fs=%d leg failed: %s", fs, e)
            leg_hlo = None

        state, objv, _ = step(state, dev, slots)           # compile
        jaxtrace.fetch(objv, point="capacity.fence")
        t0 = time.perf_counter()
        for _ in range(steps):
            state, objv, _ = step(state, dev, slots)
        jaxtrace.fetch(objv, point="capacity.fence")
        dt = time.perf_counter() - t0
        total = state_bytes(param, cap)
        leg = {
            "fs": fs,
            "hash_capacity": cap,
            "table_bytes_total": int(total),
            "table_bytes_per_device": int(total // fs),
            "examples_per_sec": round(steps * batch / dt, 1),
            "step_ms": round(dt / steps * 1e3, 3),
        }
        if leg_hlo is not None:
            leg["hlo"] = leg_hlo
        legs.append(leg)
        del state
    out = {
        "metric": "multichip_capacity_scaling",
        "n_devices": n_dev,
        "config": {"base_capacity": base_capacity, "V_dim": V_dim,
                   "batch": batch, "nnz_per_row": nnz_per_row,
                   "steps": steps, "V_dtype": v_dtype},
        "legs": legs,
        "skipped_fs": skipped,
    }
    if legs:
        base = legs[0]
        peak = legs[-1]
        out["max_hash_capacity"] = peak["hash_capacity"]
        out["capacity_scaling"] = round(
            peak["hash_capacity"] / base["hash_capacity"], 3)
        out["throughput_retention"] = round(
            peak["examples_per_sec"] / max(base["examples_per_sec"], 1e-9),
            3)
        # near-linear capacity scaling at bounded per-device bytes is
        # the acceptance claim: efficiency 1.0 = fs x capacity at
        # constant per-device residency
        out["scaling_efficiency"] = round(
            (peak["hash_capacity"] / base["hash_capacity"])
            / max(peak["fs"] / base["fs"], 1e-9), 3)
    return out
