"""Device mesh + sharding layout: the ICI "parameter server".

TPU-native replacement for the reference's distributed topology
(SURVEY §2.9): the worker/server split becomes SPMD over a 2-D
``jax.sharding.Mesh`` with axes

- ``fs`` (feature shards) — the slot table [w, z, sqrt_g, cnt, V, Vg, v_live]
  is sharded along its capacity axis. This is the TPU analog of ps-lite's
  key-range sharding across servers (src/store/kvstore_dist.h:90-118): the
  byte-reversed feature-id space maps to slots, contiguous slot ranges live on
  different devices, and the per-batch gather/scatter of unique rows is the
  Push/Pull — XLA inserts the all-gather / reduce-scatter collectives that
  ps-lite implemented as ZMQ messages.
- ``dp`` (data parallel) — the batch COO arrays are sharded along their
  nnz/row axes, the analog of DiFacto's worker data parallelism
  (file parts dispatched by WorkloadPool, src/tracker/dist_tracker.h:136-156).
  Unlike the reference's *asynchronous* per-worker updates, the TPU step is
  synchronous: all dp shards contribute to one gradient segment-sum
  (SURVEY §7 "hard parts (b)").

All shapes are padded to power-of-two buckets (ops/batch.py), so any mesh with
power-of-two axis sizes divides them evenly.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
FS_AXIS = "fs"


def make_mesh(dp: int = 1, fs: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (dp, fs) mesh over the first dp*fs available devices.

    Axis sizes must be powers of two: every sharded dimension (slot-table
    capacity, batch/nnz buckets) is padded to a power of two, so only
    power-of-two axes divide them evenly.
    """
    for name, v in ((DP_AXIS, dp), (FS_AXIS, fs)):
        if v < 1 or (v & (v - 1)) != 0:
            raise ValueError(f"mesh axis {name}={v} must be a power of two")
    n = dp * fs
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, fs)
    return Mesh(arr, (DP_AXIS, FS_AXIS))


def state_sharding(mesh: Mesh):
    """NamedSharding pytree spec for SGDState: capacity axis over fs.

    Applied via tree_map by leaf rank: 1-D [C] -> P('fs'),
    2-D [C, k] -> P('fs', None).
    """
    def spec(x):
        nd = np.ndim(x) if not hasattr(x, "ndim") else x.ndim
        return NamedSharding(mesh, P(FS_AXIS, *([None] * (nd - 1))))
    return spec


def batch_sharding(mesh: Mesh):
    """NamedSharding for DeviceBatch leaves: leading axis over dp,
    scalars replicated."""
    def spec(x):
        nd = np.ndim(x) if not hasattr(x, "ndim") else x.ndim
        if nd == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(DP_AXIS, *([None] * (nd - 1))))
    return spec


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_pytree(tree, spec_fn):
    """device_put every leaf with its NamedSharding from spec_fn(leaf)."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, spec_fn(x)), tree)


def sharding_tree(tree, spec_fn):
    """A pytree of NamedShardings matching ``tree`` (for jit in/out specs)."""
    return jax.tree_util.tree_map(lambda x: spec_fn(x), tree)
