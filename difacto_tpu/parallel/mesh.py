"""Device mesh + sharding layout: the ICI "parameter server".

TPU-native replacement for the reference's distributed topology
(SURVEY §2.9): the worker/server split becomes SPMD over a 2-D
``jax.sharding.Mesh`` with axes

- ``fs`` (feature shards) — the slot table [w, z, sqrt_g, cnt, V, Vg, v_live]
  is sharded along its capacity axis. This is the TPU analog of ps-lite's
  key-range sharding across servers (src/store/kvstore_dist.h:90-118): the
  byte-reversed feature-id space maps to slots, contiguous slot ranges live on
  different devices, and the per-batch gather/scatter of unique rows is the
  Push/Pull — XLA inserts the all-gather / reduce-scatter collectives that
  ps-lite implemented as ZMQ messages.
- ``dp`` (data parallel) — the batch COO arrays are sharded along their
  nnz/row axes, the analog of DiFacto's worker data parallelism
  (file parts dispatched by WorkloadPool, src/tracker/dist_tracker.h:136-156).
  Unlike the reference's *asynchronous* per-worker updates, the TPU step is
  synchronous: all dp shards contribute to one gradient segment-sum
  (SURVEY §7 "hard parts (b)").

All shapes are padded to power-of-two buckets (ops/batch.py), so any mesh with
power-of-two axis sizes divides them evenly.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
FS_AXIS = "fs"


def make_mesh(dp: int = 1, fs: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (dp, fs) mesh over the first dp*fs available devices.

    Axis sizes must be powers of two: every sharded dimension (slot-table
    capacity, batch/nnz buckets) is padded to a power of two, so only
    power-of-two axes divide them evenly.
    """
    for name, v in ((DP_AXIS, dp), (FS_AXIS, fs)):
        if v < 1 or (v & (v - 1)) != 0:
            raise ValueError(f"mesh axis {name}={v} must be a power of two")
    n = dp * fs
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    if jax.process_count() > 1:
        # multi-controller: the fs axis must stay intra-host so every host
        # holds a complete copy of the fs-sharded table (dp replicates it
        # across hosts) — required by checkpointing/evaluate host reads
        # (multihost.to_local_numpy) and by ICI-local table collectives
        lcl = jax.local_device_count()
        if n != len(devices):
            raise ValueError(
                f"multi-host meshes must use every device: dp*fs={n} != "
                f"{len(devices)} global devices")
        if fs > lcl or lcl % fs:
            raise ValueError(
                f"mesh fs={fs} must divide the local device count {lcl} "
                "(the feature-sharded table must be host-complete)")
    arr = np.asarray(devices[:n]).reshape(dp, fs)
    return Mesh(arr, (DP_AXIS, FS_AXIS))


def fs_size(mesh: Optional[Mesh]) -> int:
    """Feature-shard degree of a mesh (1 for no mesh): the number of
    contiguous key-range shards the slot table splits into."""
    return 1 if mesh is None else int(mesh.shape[FS_AXIS])


def validate_fs_capacity(capacity: int, fs: int) -> None:
    """Every sharded dim must divide the fs axis evenly (jax rejects
    uneven NamedShardings): power-of-two capacities always do, but
    ``hash_capacity`` is user-chosen — fail at construction, not at the
    first device_put deep inside a train step."""
    if fs > 1 and capacity % fs:
        raise ValueError(
            f"table capacity {capacity} is not divisible by mesh fs={fs}: "
            "the slot table shards its capacity axis in contiguous "
            "key ranges, one per fs device — pick hash_capacity (or "
            "init_capacity) as a multiple of fs")


def fs_shard_bounds(capacity: int, fs: int):
    """[(lo, hi)] row ranges per fs shard — the contiguous key ranges of
    the table's capacity axis, the TPU analog of ps-lite's per-server
    key ranges (kvstore_dist.h:90-118). Shard i owns slots
    [i*capacity/fs, (i+1)*capacity/fs); per-shard checkpoints
    (store/local.py save) slice and restore exactly these rows."""
    validate_fs_capacity(capacity, fs)
    rows = capacity // fs
    return [(i * rows, (i + 1) * rows) for i in range(fs)]


def state_sharding(mesh: Mesh):
    """NamedSharding pytree spec for SGDState: capacity axis over fs.

    Applied via tree_map by leaf rank: 1-D [C] -> P('fs'),
    2-D [C, k] -> P('fs', None).
    """
    def spec(x):
        nd = np.ndim(x) if not hasattr(x, "ndim") else x.ndim
        return NamedSharding(mesh, P(FS_AXIS, *([None] * (nd - 1))))
    return spec


def batch_sharding(mesh: Mesh):
    """NamedSharding for DeviceBatch leaves: leading axis over dp,
    scalars replicated."""
    def spec(x):
        nd = np.ndim(x) if not hasattr(x, "ndim") else x.ndim
        if nd == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(DP_AXIS, *([None] * (nd - 1))))
    return spec


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_global(arr, sharding: NamedSharding):
    """Place a host array under ``sharding``, working across processes.

    Single-process: plain device_put. Multi-process: the sharding spans
    devices this host cannot address, so each process contributes its
    addressable pieces via make_array_from_callback — every host must pass
    the same value (true for replicated inputs and for deterministic
    same-seed state init)."""
    if all(d.process_index == jax.process_index()
           for d in sharding.device_set):
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def put_dp_local(local_arr, mesh: Mesh):
    """Build the global dp-sharded array from this process's local block.

    The global leading axis is the concatenation of every host's block in
    process order (the mesh's dp axis is laid out host-major).
    """
    local_arr = np.asarray(local_arr)
    sharding = NamedSharding(
        mesh, P(DP_AXIS, *([None] * (local_arr.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(local_arr, sharding)
    global_shape = (local_arr.shape[0] * jax.process_count(),
                    *local_arr.shape[1:])
    return jax.make_array_from_process_local_data(sharding, local_arr,
                                                  global_shape)


def shard_pytree(tree, spec_fn):
    """Place every leaf with its NamedSharding from spec_fn(leaf);
    process-count aware (see put_global)."""
    return jax.tree_util.tree_map(
        lambda x: put_global(x, spec_fn(x)), tree)


def sharding_tree(tree, spec_fn):
    """A pytree of NamedShardings matching ``tree`` (for jit in/out specs)."""
    return jax.tree_util.tree_map(lambda x: spec_fn(x), tree)
