"""Multi-host (multi-controller) support over DCN.

The reference scales out with dmlc-tracker launchers + ps-lite rendezvous
(launch.py, SURVEY §2.10/§5.8). The TPU-native equivalent is JAX
multi-controller SPMD: every host runs the same program,
``jax.distributed.initialize`` performs the rendezvous (the Postoffice
analog), ``jax.devices()`` then spans all hosts, and the existing mesh
shardings (parallel/mesh.py) place collectives on ICI within a pod and DCN
across pods — no learner code changes.

Host-side data parallelism keeps the reference's contract: each host reads
its own byte-range file parts (``host_part`` -> Reader(part_idx,
num_parts)), the WorkloadPool semantics move one level up.

For the model state to be identical across controllers the feature ->
slot mapping must be host-consistent. Both store modes achieve it:
the hashed store (store/local.py ``hash_capacity``) maps ids to slots by
stateless modular hashing of the byte-reversed id (SURVEY §7
"fixed-capacity hashed embedding table"); the exact-id dictionary store
rides the synchronized schedule's control plane — the per-step exchange
ships raw uint64 ids and every host inserts the identical sorted union
into its dictionary in the same order, so replica id->slot maps stay
bit-identical with no extra rounds (learners/sgd.py exchange(); the
reference's servers key the model by exact 64-bit id the same way,
src/sgd/sgd_updater.h:141-176).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

log = logging.getLogger("difacto_tpu")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """jax.distributed rendezvous; None args resolve from the standard env
    (JAX's own vars, or DIFACTO_COORDINATOR / DIFACTO_NPROCS /
    DIFACTO_RANK as set by launch.py)."""
    import jax
    coordinator_address = coordinator_address or os.environ.get(
        "DIFACTO_COORDINATOR")
    if num_processes is None and "DIFACTO_NPROCS" in os.environ:
        num_processes = int(os.environ["DIFACTO_NPROCS"])
    if process_id is None and "DIFACTO_RANK" in os.environ:
        process_id = int(os.environ["DIFACTO_RANK"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    log.info("multi-host initialized: process %d of %d, %d global devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()))


def allgather_np(arr) -> "np.ndarray":
    """Gather a fixed-shape host numpy array from every process ->
    [n_procs, *shape]. The DCN control channel of the synchronized-step
    schedule (the analog of ps-lite's scheduler barrier + key exchange,
    src/store/kvstore_dist.h:61-70). Single process: adds the leading axis.

    NOTE this gather is itself a DEVICE program (process_allgather jits a
    collective over the global devices), so it must be issued in exactly
    the same order as every other device program on every host — only
    call it from the thread that dispatches the device steps. A lookahead
    thread must use :func:`control_allgather_np` instead.
    """
    import jax
    import numpy as np
    _fire_dcn_fault()
    if jax.process_count() == 1:
        return np.asarray(arr)[None]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(np.asarray(arr)))


def _fire_dcn_fault() -> None:
    """Chaos-harness injection point ``dcn.collective``: traversed before
    every cross-host control exchange (device or KV-store flavor), BEFORE
    the single-process early return so chaos tests exercise it without a
    cluster. ``err`` models a dead coordinator / partitioned DCN link
    surfacing as the same OSError a real gRPC failure raises; fires count
    into ``faults_fired_total{point,kind}``."""
    from ..utils import faultinject
    faultinject.act_default(faultinject.fire("dcn.collective"))


# --------------------------------------------------------------- control
# Deviceless control plane over the jax.distributed KV store.

_CTRL_TIMEOUT_MS = 600_000
_ctrl_seq = 0
_ctrl_bar = 0
_ctrl_written: list = []


def control_allgather_np(arr) -> "np.ndarray":
    """Deviceless allgather over the jax.distributed KV store (pure gRPC
    to the coordinator — the ps-lite-analog wire, SURVEY §5.8).

    Unlike :func:`allgather_np`, this touches NO device, so the SPMD
    schedule may run it from a lookahead thread and overlap the DCN
    round trip with device execution (learners/sgd.py ``exchange()``).
    Interleaving a device-collective allgather with the step stream from
    two threads deadlocks — hosts would enqueue the same device programs
    in different orders (measured: a 2-process virtual-mesh run hangs at
    epoch 1 once compiles stop serializing the race).

    All processes must call this the same number of times with the same
    shape/dtype (one lookahead thread per host preserves that). Keys
    accumulate in the coordinator until :func:`control_cleanup`.
    """
    import jax
    import numpy as np
    global _ctrl_seq
    _fire_dcn_fault()
    from ..obs import REGISTRY
    REGISTRY.counter(
        "dcn_collectives_total",
        "cross-host control-plane exchanges issued").inc()
    a = np.ascontiguousarray(np.asarray(arr))
    if jax.process_count() == 1:
        return a[None]
    from jax._src import distributed
    client = distributed.global_state.client
    rank, n = jax.process_index(), jax.process_count()
    key = f"difacto/ctrl/{_ctrl_seq}"
    _ctrl_seq += 1
    client.key_value_set_bytes(f"{key}/{rank}", a.tobytes())
    _ctrl_written.append(f"{key}/{rank}")
    out = np.empty((n,) + a.shape, a.dtype)
    for r in range(n):
        if r == rank:
            out[r] = a
        else:
            b = client.blocking_key_value_get_bytes(f"{key}/{r}",
                                                    _CTRL_TIMEOUT_MS)
            out[r] = np.frombuffer(b, a.dtype).reshape(a.shape)
    return out


def _fire_push_stale() -> None:
    """Chaos-harness injection point ``push.stale``: traversed when a
    host PUBLISHES its step clock under a bounded-delay (τ>0) window —
    the moment a delayed gradient push becomes visible to peers that may
    already be up to τ steps ahead. Fired BEFORE the single-process
    early return so chaos tests exercise the stale-push path without a
    cluster; fires count into ``faults_fired_total{point,kind}``."""
    from ..utils import faultinject
    faultinject.act_default(faultinject.fire("push.stale"))


# Bounded-delay (τ) step clocks for the windowed exchange
# (learners/sgd.py _iterate_data_spmd). Each host POSTS its clock after
# dispatching step t (non-blocking KV set); a host whose exchange
# pipeline would exceed the τ-window blocks on the SPECIFIC peer clock
# key it needs (present => the get returns immediately, else it blocks
# until the peer posts) — a pairwise wait, not a symmetric collective,
# so hosts need not agree on how many waits each issues and the
# protocol is deadlock-free (every wait targets a strictly earlier
# step). Keys are namespaced by the launcher's restart attempt
# (fault.restart_attempt): a relaunched cluster rejoins at a fresh
# clock epoch consistent across all survivors, never observing the
# previous attempt's stale clocks. Clock keys ride ``_ctrl_written``
# and are reclaimed by :func:`control_cleanup` at the part drain.

_clock_gen = 0


def clock_open() -> int:
    """New clock generation for one windowed part. Every host opens
    generations in the same order (the part loop is the same program),
    so the returned ids agree across hosts without communication."""
    global _clock_gen
    _clock_gen += 1
    return _clock_gen


def post_clock(gen: int, t: int) -> None:
    """Publish "this host has dispatched windowed step ``t``" (steps
    number from 0 within generation ``gen``). Non-blocking."""
    import jax
    _fire_push_stale()
    if jax.process_count() == 1:
        return
    from .fault import restart_attempt
    from jax._src import distributed
    client = distributed.global_state.client
    key = (f"difacto/clock/{restart_attempt()}/{gen}/"
           f"{jax.process_index()}/{t}")
    client.key_value_set_bytes(key, b"1")
    _ctrl_written.append(key)


def wait_clock(gen: int, peer: int, t: int) -> float:
    """Block until ``peer`` has posted windowed step ``t`` of generation
    ``gen``; returns the seconds spent blocked (0.0 when the clock was
    already posted, and always on a single process). Callers route this
    through the dead-host monitor (``monitor.guarded``) so a peer dying
    mid-wait aborts for restart instead of hanging to the timeout."""
    import time as _time

    import jax
    if jax.process_count() == 1:
        return 0.0
    from .fault import restart_attempt
    from jax._src import distributed
    client = distributed.global_state.client
    key = f"difacto/clock/{restart_attempt()}/{gen}/{peer}/{t}"
    t0 = _time.monotonic()
    client.blocking_key_value_get_bytes(key, _CTRL_TIMEOUT_MS)
    return _time.monotonic() - t0


def control_cleanup() -> None:
    """Delete this process's control keys once every peer has consumed
    them. Call at a quiesce point all hosts reach together (the part
    drain in the SPMD schedule); the barrier makes consumption global
    before deletion, keeping the coordinator's KV memory bounded by one
    part's payloads instead of the whole run's."""
    import jax
    global _ctrl_bar
    if jax.process_count() == 1:
        _ctrl_written.clear()
        return
    from jax._src import distributed
    client = distributed.global_state.client
    bar = _ctrl_bar
    _ctrl_bar += 1
    client.wait_at_barrier(f"difacto/ctrlbar/{bar}", _CTRL_TIMEOUT_MS)
    for k in _ctrl_written:
        client.key_value_delete(k)
    _ctrl_written.clear()


def to_local_numpy(arr) -> "np.ndarray":
    """Assemble a (possibly multi-host) jax.Array into a full host numpy
    array from this process's addressable shards.

    Valid when every piece of the array is present on some local device —
    true for our layout, where the table is sharded over the intra-host
    ``fs`` axis and replicated over the cross-host ``dp`` axis. np.asarray
    would refuse (the sharding spans non-addressable devices) even though
    the data is all here.
    """
    import numpy as np
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    out = np.empty(arr.shape, dtype=arr.dtype)
    seen = np.zeros(arr.shape[0] if arr.ndim else 1, dtype=bool)
    for sh in arr.addressable_shards:
        out[sh.index] = np.asarray(sh.data)
        seen[sh.index[0] if sh.index else slice(None)] = True
    if not seen.all():
        raise ValueError(
            "array is not host-complete: some shards live only on other "
            "hosts (expected fs-sharded-within-host layout)")
    return out


def local_rows(arr, lo: int, hi: int) -> "np.ndarray":
    """Rows [lo, hi) of a (possibly dp-sharded) global array, assembled
    from this process's addressable shards — np.asarray would refuse on a
    multi-host sharding even though these rows live here."""
    import numpy as np

    from ..utils import jaxtrace
    if getattr(arr, "is_fully_addressable", True):
        # declared device->host sync (jaxtrace counts it): callers slice
        # prediction rows out for the pred writer
        return jaxtrace.fetch(arr, point="multihost.local_rows")[lo:hi]
    out = np.zeros((hi - lo,) + arr.shape[1:], dtype=arr.dtype)
    filled = np.zeros(hi - lo, dtype=bool)
    for sh in arr.addressable_shards:
        sl = sh.index[0] if sh.index else slice(None)
        start = sl.start or 0
        stop = arr.shape[0] if sl.stop is None else sl.stop
        s, e = max(start, lo), min(stop, hi)
        if s < e:
            data = np.asarray(sh.data)
            out[s - lo:e - lo] = data[s - start:e - start]
            filled[s - lo:e - lo] = True
    if not filled.all():
        raise ValueError(
            f"rows [{lo}, {hi}) are not all addressable on this host")
    return out


def host_part() -> Tuple[int, int]:
    """(part_idx, num_parts) for this host's share of the input files —
    the multi-controller analog of the reference's Rank()/NumWorkers()
    reader sharding (src/lbfgs/lbfgs_learner.cc:148-150)."""
    import jax
    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:
        return 0, 1


def global_kv_union(ids, cnts):
    """Union per-host sorted-unique (id, count) dictionaries across all
    processes: counts sum, ids union (the reference's servers own one
    global key space). uint64 ids ride the DCN gather as uint32 pairs —
    process_allgather goes through jax, which silently truncates uint64
    with x64 disabled. Single process: returns the inputs."""
    import numpy as np

    from ..ops.kv import kv_union
    sizes = allgather_np(np.array([len(ids)], dtype=np.int32))[:, 0]
    cap = int(sizes.max())
    ids_p = np.zeros(cap, dtype=np.uint64)
    ids_p[:len(ids)] = ids
    cnt_p = np.zeros(cap, dtype=np.float32)
    cnt_p[:len(cnts)] = cnts
    all_ids = allgather_np(ids_p.view(np.uint32))
    all_cnt = allgather_np(cnt_p)
    out_ids = np.empty(0, dtype=ids.dtype)
    out_cnt = np.empty(0, dtype=np.float32)
    for h in range(len(sizes)):
        k = int(sizes[h])
        h_ids = np.ascontiguousarray(
            all_ids[h]).view(np.uint64)[:k].astype(ids.dtype)
        out_ids, out_cnt = kv_union(out_ids, out_cnt, h_ids, all_cnt[h, :k])
    return out_ids, out_cnt


def allreduce_np(buf, monitor=None, sum_dtype=None):
    """Sum a host array across all processes over DCN.

    64-bit dtypes ride the wire as uint32 views — the jax transport
    canonicalizes 64-bit to 32-bit with x64 disabled, which would
    silently truncate them (same hazard global_kv_union guards for ids).
    ``sum_dtype`` widens the host-side summation (e.g. gather float32
    partials, accumulate in float64). ``monitor`` arms the dead-host
    watchdog around the collective (parallel/fault.py).

    This is allgather-based (every host materializes [n_hosts, len]); at
    very large vector sizes a device psum over a global mesh would halve
    the wire cost, but the control plane deliberately avoids requiring a
    collective mesh.
    """
    import numpy as np
    buf = np.ascontiguousarray(buf)
    wide = buf.dtype.itemsize == 8
    wire = buf.view(np.uint32) if wide else buf
    if monitor is not None:
        g = monitor.guarded(allgather_np, wire)
    else:
        g = allgather_np(wire)
    if wide:
        g = np.ascontiguousarray(g).view(buf.dtype)
    return g.sum(axis=0, dtype=sum_dtype)
