"""Multi-host (multi-controller) support over DCN.

The reference scales out with dmlc-tracker launchers + ps-lite rendezvous
(launch.py, SURVEY §2.10/§5.8). The TPU-native equivalent is JAX
multi-controller SPMD: every host runs the same program,
``jax.distributed.initialize`` performs the rendezvous (the Postoffice
analog), ``jax.devices()`` then spans all hosts, and the existing mesh
shardings (parallel/mesh.py) place collectives on ICI within a pod and DCN
across pods — no learner code changes.

Host-side data parallelism keeps the reference's contract: each host reads
its own byte-range file parts (``host_part`` -> Reader(part_idx,
num_parts)), the WorkloadPool semantics move one level up.

For the model state to be identical across controllers the feature ->
slot mapping must be deterministic without cross-host chatter — use the
hashed store mode (store/local.py ``hash_capacity``), which maps ids to
slots by modular hashing of the byte-reversed id (SURVEY §7 "fixed-capacity
hashed embedding table").
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

log = logging.getLogger("difacto_tpu")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """jax.distributed rendezvous; None args resolve from the standard env
    (JAX's own vars, or DIFACTO_COORDINATOR / DIFACTO_NPROCS /
    DIFACTO_RANK as set by launch.py)."""
    import jax
    coordinator_address = coordinator_address or os.environ.get(
        "DIFACTO_COORDINATOR")
    if num_processes is None and "DIFACTO_NPROCS" in os.environ:
        num_processes = int(os.environ["DIFACTO_NPROCS"])
    if process_id is None and "DIFACTO_RANK" in os.environ:
        process_id = int(os.environ["DIFACTO_RANK"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    log.info("multi-host initialized: process %d of %d, %d global devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()))


def host_part() -> Tuple[int, int]:
    """(part_idx, num_parts) for this host's share of the input files —
    the multi-controller analog of the reference's Rank()/NumWorkers()
    reader sharding (src/lbfgs/lbfgs_learner.cc:148-150)."""
    import jax
    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:
        return 0, 1
