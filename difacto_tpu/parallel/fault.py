"""Failure detection and recovery for multi-host runs.

Reference analog: DistTracker's Monitoring thread polls
``ps::Postoffice::GetDeadNodes`` every 2 s — the scheduler re-queues a dead
worker's parts via ``WorkloadPool::Reset`` and non-scheduler nodes kill
themselves when the scheduler dies (src/tracker/dist_tracker.h:164-186).

On TPU the data plane is XLA collectives, which cannot lose a member
mid-flight: a dead host leaves every peer blocked in the collective
forever. The TPU-native recovery contract therefore splits into three
pieces:

- **detection** — a UDP heartbeat mesh (:class:`HeartbeatMonitor`): every
  process beats every ``interval`` seconds; a peer silent for ``timeout``
  is dead (the GetDeadNodes analog);
- **escape** — a watchdog turns "blocked in a DCN collective while a peer
  is dead" into a fast, clean abort (:data:`EXIT_PEER_DEAD`) instead of an
  infinite hang — the moral equivalent of the reference's self `kill -9`
  on scheduler death;
- **recovery** — the launcher (launch.py ``--max-restarts``) relaunches
  with the dead host evicted; byte-range input sharding
  (multihost.host_part) re-partitions the data over the survivors (the
  ``WorkloadPool::Reset`` part re-advertisement, one level up) and
  training resumes from the latest epoch checkpoint (SGDLearner
  ``ckpt_interval`` + ``auto_resume``). As in the reference — where a
  dead server's shard is gone and recovery means reloading a saved model
  (SURVEY §5.3) — lost progress is bounded by the checkpoint cadence.

Configuration rides the environment (set by launch.py): DIFACTO_HB_PORT
(base UDP port; rank i binds base+i), DIFACTO_HB_TIMEOUT (seconds),
DIFACTO_HB_PEERS (comma-separated ``host`` list when ranks are not all on
localhost; defaults to 127.0.0.1 for every rank).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import List, Optional
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")

EXIT_PEER_DEAD = 42  # process exit code for "aborted because a peer died"


def restart_attempt() -> int:
    """The launcher's recovery-attempt counter (DIFACTO_RESTART, set by
    launch.py; 0 on the first launch). The bounded-delay clock keys
    (multihost.post_clock/wait_clock) are namespaced by it so a
    relaunched cluster REJOINS AT THE CURRENT CLOCK: every survivor and
    the evicted host's replacement restart in the same fresh clock
    epoch, and stale clock keys a dead attempt left in a lingering
    coordinator can never satisfy a new attempt's window waits.

    The durability ladder (durability/recover.py) composes with this
    unchanged: a relaunched attempt's ``auto_resume`` climbs local
    checkpoint → peer fetch → WAL replay exactly like a first launch —
    nothing here knows about WAL state, and the attempt counter never
    namespaces durable artifacts (checkpoints, ``.wal/`` chains,
    replicas), which must survive relaunches by design."""
    try:
        return int(os.environ.get("DIFACTO_RESTART", "0"))
    except ValueError:
        return 0


def exit_code_for(dead: List[int]) -> int:
    """Exit code that also TELLS the launcher which peer died, so it can
    evict the right host: 100 + min(dead_rank) for ranks < 28 (codes
    101..127 — still below the shell's 128+signo band), else the generic
    EXIT_PEER_DEAD."""
    r = min(dead) if dead else -1
    return 100 + r if 0 <= r < 28 else EXIT_PEER_DEAD


class HostFailure(RuntimeError):
    """A peer host is dead; the synchronized schedule cannot continue."""

    def __init__(self, dead: List[int]):
        super().__init__(f"dead peer host(s): {dead}")
        self.dead = dead


def from_env(rank: int, nprocs: int) -> Optional["HeartbeatMonitor"]:
    """Build + start a monitor from DIFACTO_HB_* (None when unset or
    single-process)."""
    port = os.environ.get("DIFACTO_HB_PORT")
    if not port or nprocs <= 1:
        return None
    timeout = float(os.environ.get("DIFACTO_HB_TIMEOUT", "5"))
    hosts = None
    if os.environ.get("DIFACTO_HB_PEERS"):
        hosts = os.environ["DIFACTO_HB_PEERS"].split(",")
    mon = HeartbeatMonitor(rank, nprocs, int(port), timeout=timeout,
                           peer_hosts=hosts)
    mon.start()
    return mon


class HeartbeatMonitor:
    """UDP heartbeat mesh + blocked-collective watchdog.

    Every process sends a beat to every peer each ``interval`` and records
    when it last heard from each. ``dead_peers()`` lists ranks silent for
    longer than ``timeout``. While the owner is inside a collective
    (``collective()`` context), the watchdog thread aborts the process
    with :data:`EXIT_PEER_DEAD` as soon as a peer is declared dead —
    a blocked XLA/DCN collective cannot be cancelled from Python, so a
    fast process exit is the only way to hand control back to the
    launcher's recovery path.
    """

    def __init__(self, rank: int, nprocs: int, port_base: int,
                 interval: float = 0.5, timeout: float = 5.0,
                 peer_hosts: Optional[List[str]] = None):
        self.rank = rank
        self.nprocs = nprocs
        self.interval = interval
        self.timeout = timeout
        hosts = peer_hosts or ["127.0.0.1"] * nprocs
        if len(hosts) != nprocs:
            raise ValueError(
                f"DIFACTO_HB_PEERS lists {len(hosts)} hosts for {nprocs} "
                "processes")
        self._addrs = [(hosts[r], port_base + r) for r in range(nprocs)]
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("", port_base + rank))
        self._sock.settimeout(interval)
        now = time.monotonic()
        self._last_seen = {r: now for r in range(nprocs) if r != rank}
        self._stop = threading.Event()
        self._in_collective_since: Optional[float] = None
        self._collective_depth = 0
        self._depth_lock = mutex()
        self._threads = [
            threading.Thread(target=self._send_loop, daemon=True),
            threading.Thread(target=self._recv_loop, daemon=True),
        ]

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ threads
    def _send_loop(self) -> None:
        from ..obs import counter
        beats = counter("hb_beats_sent_total",
                        "UDP heartbeats sent to peers")
        msg = str(self.rank).encode()
        while not self._stop.is_set():
            for r, addr in enumerate(self._addrs):
                if r == self.rank:
                    continue
                try:
                    self._sock.sendto(msg, addr)
                    beats.inc()
                except OSError:
                    pass
            self._stop.wait(self.interval)

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, _ = self._sock.recvfrom(64)
                r = int(data)
                if r in self._last_seen:
                    self._last_seen[r] = time.monotonic()
            except socket.timeout:
                pass
            except (OSError, ValueError):
                if self._stop.is_set():
                    return
            self._watchdog()

    def _watchdog(self) -> None:
        """Abort a hang: blocked in a collective while a peer is dead."""
        if self._in_collective_since is None:
            return
        dead = self.dead_peers()
        if dead:
            from ..obs import counter
            counter("hb_peer_dead_total",
                    "peers declared dead by heartbeat silence").inc(
                        len(dead))
            code = exit_code_for(dead)
            log.error(
                "host %d: peer(s) %s dead while blocked in a collective "
                "— aborting for restart (exit %d)", self.rank, dead, code)
            os._exit(code)

    # ------------------------------------------------------------ queries
    def dead_peers(self) -> List[int]:
        now = time.monotonic()
        return [r for r, t in self._last_seen.items()
                if now - t > self.timeout]

    def check(self) -> None:
        """Raise HostFailure if any peer is dead (call before entering a
        collective — cheaper than entering and relying on the watchdog)."""
        dead = self.dead_peers()
        if dead:
            from ..obs import counter
            counter("hb_peer_dead_total",
                    "peers declared dead by heartbeat silence").inc(
                        len(dead))
            raise HostFailure(dead)

    def collective(self):
        """Context manager marking a collective in flight for the
        watchdog. Depth-counted and therefore REENTRANT: an epoch-long
        outer guard (the cached-replay loop) stays armed when inner
        guarded() calls exit. The depth is lock-protected because the
        SPMD control-plane pipeline issues its allgathers from a
        prefetch thread while the main thread may hold the epoch-long
        drain guard."""
        mon = self

        class _Ctx:
            def __enter__(self):
                with mon._depth_lock:
                    mon._collective_depth += 1
                    if mon._collective_depth == 1:
                        mon._in_collective_since = time.monotonic()

            def __exit__(self, *exc):
                with mon._depth_lock:
                    mon._collective_depth -= 1
                    if mon._collective_depth == 0:
                        mon._in_collective_since = None
                return False

        return _Ctx()

    def guarded(self, fn, *args):
        """check() + run ``fn`` under the collective watchdog."""
        self.check()
        with self.collective():
            return fn(*args)
