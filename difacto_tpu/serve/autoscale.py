"""Elastic capacity: a control loop that sizes the replica fleet.

The reference system's scheduler owns membership — workers and servers
join, die, and are replaced while the job runs (PAPER.md: parameter-
server roles under failure). Our serving fleet got the *mechanisms* in
PRs 5/6/18 — ready-file spawns, drains, rolling restarts, a routing
ring adjustable at runtime (``#backends`` + ``endpoints_file``) — but
no *policy*: capacity was whatever the operator started. This module is
the policy: a hysteresis-damped control loop over the fleet's own
health signals that spawns replicas into the ring under load and drains
them back out when the load leaves.

Signals, per poll (EWMA-smoothed so one deep queue sample cannot flap
the fleet):

- **queue_frac** — summed admission queue depth over summed capacity
  across reachable replicas (``#health``): the leading indicator, rises
  before shed does;
- **shed_rate** — the worst replica's shed rate (``#health``): rows are
  already being refused, capacity is late;
- **p99_ms** — optional, from ``latency_fn`` (the caller's client-side
  view, e.g. the loadgen's window p99): the SLO itself.

Decisions, with hysteresis and bounds:

- ``up_ticks`` consecutive polls with ANY signal past its ``up_*``
  threshold -> **scale up** (bounded by ``max_replicas``): fire the
  ``autoscale.spawn`` chaos point, call ``spawn_fn(index)`` for a fresh
  READY endpoint, publish it (endpoints_file rewrite + ``#backends
  add`` nudge to every router group member);
- ``down_ticks`` consecutive polls with EVERY signal under its
  ``down_*`` threshold -> **scale down** (bounded by
  ``min_replicas``): un-publish the newest replica first (ring nudge +
  endpoints_file), THEN drain it with a bare ``#handoff`` — the ring
  stops routing to it before it stops serving, so the drain sheds
  nothing;
- every action opens a ``cooldown_s`` window in which no further action
  fires — the fleet settles before the next measurement is believed.

Decisions are observable: ``autoscale_{spawns,drains,aborts}_total``
counters and ``autoscale_{replicas,queue_frac,shed_rate,p99_ms}``
gauges on the process-global registry, so a router's ``#metrics``
(which merges that registry) shows the autoscaler's history next to
the traffic it reacted to. ``tools/fleet.py scale`` is the CLI;
tests drive :class:`Autoscaler` in-process with an in-process
``spawn_fn``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..config import parse_endpoints
from ..utils import faultinject
from ..utils.locktrace import mutex
from .fleet import drain_endpoint, fresh_health, notify_backends

log = logging.getLogger("difacto_tpu")


class Autoscaler:
    """One control loop instance. ``endpoints`` is the starting fleet;
    ``spawn_fn(index) -> (host, port)`` must return a replica that is
    already serving (ready-file waited) — the loop publishes it.
    ``router=(host, port)`` names the router group's shared port for
    ``#backends`` nudges (None = endpoints_file only)."""

    def __init__(self, endpoints, spawn_fn: Callable[[int], Tuple[str, int]],
                 router: Optional[Tuple[str, int]] = None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 poll_s: float = 0.5, ewma: float = 0.4,
                 up_queue_frac: float = 0.6, up_shed_rate: float = 0.02,
                 up_p99_ms: Optional[float] = None,
                 down_queue_frac: float = 0.1,
                 down_shed_rate: float = 0.0,
                 up_ticks: int = 2, down_ticks: int = 6,
                 cooldown_s: float = 5.0,
                 latency_fn: Optional[Callable[[], float]] = None,
                 endpoints_file: str = "", timeout: float = 5.0,
                 obs=None):
        from ..obs import REGISTRY
        self._eps: List[Tuple[str, int]] = list(parse_endpoints(endpoints))
        self.spawn_fn = spawn_fn
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.poll_s = poll_s
        self.ewma = ewma
        self.up_queue_frac = up_queue_frac
        self.up_shed_rate = up_shed_rate
        self.up_p99_ms = up_p99_ms
        self.down_queue_frac = down_queue_frac
        self.down_shed_rate = down_shed_rate
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown_s = cooldown_s
        self.latency_fn = latency_fn
        self.endpoints_file = endpoints_file
        self.timeout = timeout
        reg = obs if obs is not None else REGISTRY
        self._spawn_c = reg.counter(
            "autoscale_spawns_total",
            "replicas spawned into the ring by the autoscaler")
        self._drain_c = reg.counter(
            "autoscale_drains_total",
            "replicas drained out of the ring by the autoscaler")
        self._abort_c = reg.counter(
            "autoscale_aborts_total",
            "scale-ups refused (injected autoscale.spawn fault or "
            "spawn_fn failure)")
        self._replicas_g = reg.gauge(
            "autoscale_replicas", "current published fleet size")
        self._qf_g = reg.gauge(
            "autoscale_queue_frac",
            "EWMA fleet admission-queue fill fraction")
        self._shed_g = reg.gauge(
            "autoscale_shed_rate", "EWMA worst-replica shed rate")
        self._p99_g = reg.gauge(
            "autoscale_p99_ms", "EWMA client-side p99 (latency_fn)")
        self._mu = mutex()
        self._qf = self._shed = self._p99 = 0.0
        self._primed = False
        self._up_streak = self._down_streak = 0
        self._cool_until = 0.0
        self.events: List[dict] = []   # (t, action, endpoint, replicas)
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._replicas_g.set(len(self._eps))
        self._write_endpoints_file()

    # ------------------------------------------------------------ state
    def endpoints(self) -> List[Tuple[str, int]]:
        with self._mu:
            return list(self._eps)

    def _write_endpoints_file(self) -> None:
        """Durable membership: rewrite atomically so a router's
        ``(mtime, size)`` re-fold never reads a half-written ring."""
        if not self.endpoints_file:
            return
        with self._mu:
            body = "".join(f"{h}:{p}\n" for h, p in self._eps)
        tmp = self.endpoints_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, self.endpoints_file)

    def _notify(self, op: str, host: str, port: int) -> None:
        if self.router is None:
            return
        rh, rp = self.router
        try:
            notify_backends(rh, rp, op, f"{host}:{port}",
                            timeout=self.timeout)
        except (OSError, ConnectionError, ValueError) as e:
            log.warning("autoscale: router nudge %s %s:%d failed (%s); "
                        "endpoints_file re-fold will catch up",
                        op, host, port, e)

    # ------------------------------------------------------------- poll
    def poll(self) -> dict:
        """One measurement: fold every reachable replica's ``#health``
        into the EWMA signals (an unreachable replica contributes
        nothing — ejection is the router's job, not the scaler's)."""
        depth = cap = 0
        shed = 0.0
        reachable = 0
        for host, port in self.endpoints():
            try:
                h = fresh_health(host, port, timeout=self.timeout)
            except (OSError, ConnectionError, ValueError):
                continue
            reachable += 1
            depth += int(h.get("queue_depth", 0))
            cap += int(h.get("queue_cap", 0))
            shed = max(shed, float(h.get("shed_rate", 0.0)))
        qf = depth / cap if cap else 0.0
        p99 = float(self.latency_fn()) if self.latency_fn else 0.0
        a = self.ewma
        with self._mu:
            if not self._primed:
                self._qf, self._shed, self._p99 = qf, shed, p99
                self._primed = True
            else:
                self._qf += a * (qf - self._qf)
                self._shed += a * (shed - self._shed)
                self._p99 += a * (p99 - self._p99)
            out = {"replicas": len(self._eps), "reachable": reachable,
                   "queue_frac": self._qf, "shed_rate": self._shed,
                   "p99_ms": self._p99}
        self._qf_g.set(out["queue_frac"])
        self._shed_g.set(out["shed_rate"])
        self._p99_g.set(out["p99_ms"])
        return out

    # --------------------------------------------------------- decision
    def _overloaded(self, m: dict) -> bool:
        if m["reachable"] < len(self.endpoints()):
            # a hole in the fleet IS missing capacity
            return True
        return (m["queue_frac"] > self.up_queue_frac
                or m["shed_rate"] > self.up_shed_rate
                or (self.up_p99_ms is not None and self.latency_fn
                    and m["p99_ms"] > self.up_p99_ms))

    def _idle(self, m: dict) -> bool:
        return (m["reachable"] >= len(self.endpoints())
                and m["queue_frac"] < self.down_queue_frac
                and m["shed_rate"] <= self.down_shed_rate)

    def step(self) -> dict:
        """Poll, update streaks, maybe act. Returns the measurement plus
        ``action`` (``"up"``/``"down"``/None) and ``endpoint`` when an
        action fired."""
        m = self.poll()
        m["action"] = None
        now = time.monotonic()
        over, idle = self._overloaded(m), self._idle(m)
        with self._mu:
            self._up_streak = self._up_streak + 1 if over else 0
            self._down_streak = self._down_streak + 1 if idle else 0
            up_streak, down_streak = self._up_streak, self._down_streak
            cooling = now < self._cool_until
            n = len(self._eps)
        if cooling:
            return m
        if up_streak >= self.up_ticks and n < self.max_replicas:
            return self._scale_up(m)
        if down_streak >= self.down_ticks and n > self.min_replicas:
            return self._scale_down(m)
        return m

    def _scale_up(self, m: dict) -> dict:
        # chaos point: an injected err models the spawn path failing
        # (no binary, no ports, quota) — the decision is refused,
        # counted, and the loop keeps measuring; it does NOT crash
        try:
            faultinject.act_default(faultinject.fire("autoscale.spawn"))
        except faultinject.FaultInjected as e:
            self._abort_c.inc()
            log.warning("autoscale: scale-up refused: %s", e)
            m["action"] = "abort"
            return self._settle(m)
        with self._mu:
            idx = len(self._eps)
        try:
            host, port = self.spawn_fn(idx)
        except Exception as e:   # spawn_fn is caller code: stay serving
            self._abort_c.inc()
            log.warning("autoscale: spawn_fn failed: %s", e)
            m["action"] = "abort"
            return self._settle(m)
        with self._mu:
            self._eps.append((host, int(port)))
            n = len(self._eps)
        self._write_endpoints_file()
        self._notify("add", host, int(port))
        self._spawn_c.inc()
        self._replicas_g.set(n)
        log.info("autoscale: UP -> %d replicas (+%s:%d) "
                 "[queue_frac=%.3f shed=%.4f p99=%.1fms]",
                 n, host, port, m["queue_frac"], m["shed_rate"],
                 m["p99_ms"])
        m.update(action="up", endpoint=f"{host}:{port}", replicas=n)
        return self._settle(m)

    def _scale_down(self, m: dict) -> dict:
        with self._mu:
            host, port = self._eps.pop()   # newest first
            n = len(self._eps)
        self._write_endpoints_file()
        self._notify("remove", host, port)
        try:
            drain_endpoint(host, port, timeout=self.timeout)
        except (OSError, ConnectionError, ValueError) as e:
            log.warning("autoscale: drain of %s:%d failed (%s) — "
                        "already gone?", host, port, e)
        self._drain_c.inc()
        self._replicas_g.set(n)
        log.info("autoscale: DOWN -> %d replicas (-%s:%d) "
                 "[queue_frac=%.3f shed=%.4f]", n, host, port,
                 m["queue_frac"], m["shed_rate"])
        m.update(action="down", endpoint=f"{host}:{port}", replicas=n)
        return self._settle(m)

    def _settle(self, m: dict) -> dict:
        with self._mu:
            self._cool_until = time.monotonic() + self.cooldown_s
            self._up_streak = self._down_streak = 0
            self.events.append({"t": time.monotonic() - self._t0,
                                "action": m["action"],
                                "endpoint": m.get("endpoint"),
                                "replicas": m["replicas"]})
        return m

    # ------------------------------------------------------------- loop
    def run(self, duration_s: Optional[float] = None) -> dict:
        end = (time.monotonic() + duration_s
               if duration_s is not None else None)
        while not self._stop.is_set():
            self.step()
            if end is not None and time.monotonic() >= end:
                break
            self._stop.wait(self.poll_s)
        with self._mu:
            return {"replicas": len(self._eps),
                    "events": list(self.events)}

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self.run,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
