"""Bucketed read-only predict executor — the serving device path.

One small set of pre-jitted predict programs serves every request batch:
rows / nnz / distinct-feature counts are padded up to STICKY bucket caps
(data/pack_stream.ShapeSchedule over ops/batch.py bucket rungs) — each
dim pads to the largest bucket seen so far, so micro-batch occupancy
jitter collapses onto one compiled program per traffic regime instead of
compiling every (rows, nnz, uniq) bucket combination the arrival process
happens to produce. Caps only grow (log-many compiles over a server's
life, each at a shape's first occurrence); after warmup every dispatch
is a bucket HIT — the ISSUE 2 acceptance gate — and ``stats`` proves it.

The same executor backs ``task=pred`` (learners/sgd.py routes its batch
path here) and ``task=serve`` (serve/server.py): identical localization,
identical packing (ops/batch.py pack_batch), identical jitted program
(step.py make_predict_fn) — which is what makes offline prediction files
and online responses bit-identical for the same rows.

The executor never mutates the store: dictionary lookups use
``insert=False`` (unknown feature ids resolve to the all-zero TRASH row
and contribute nothing), so it composes with the read-only weights-only
stores serving loads (store/local.py) as well as a learner's live store.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.localizer import compact
from ..data.pack_stream import ShapeSchedule
from ..data.rowblock import RowBlock
from ..losses import LossSpec, create as create_loss
from ..ops.batch import pack_batch, unpack_batch
from ..step import make_predict_fn
from ..store.local import SlotStore, pad_slots_oob
from ..utils import jaxtrace
from ..utils.locktrace import mutex


def sigmoid(pred: np.ndarray) -> np.ndarray:
    """Raw margin -> probability, shared by _save_pred-style writers and
    the serve response formatter (one definition, identical bytes)."""
    return 1.0 / (1.0 + np.exp(-np.asarray(pred)))


class PredictExecutor:
    """Shape-bucketed batch scoring over a SlotStore.

    ``predict(blk)`` -> (scores[:rows] np.float32 raw margins, objv, auc)
    with objv/auc left as device scalars so callers batch the fetch.
    Dispatch is single-threaded by contract (the micro-batcher owns it in
    serving; the pred loop in batch mode); the stats counters are locked
    so observer threads (#stats requests) read them safely.
    """

    def __init__(self, store: SlotStore, loss: Optional[LossSpec] = None):
        self.store = store
        self.loss = loss if loss is not None \
            else create_loss("fm", store.param.V_dim)
        predict_step = make_predict_fn(store.fns, self.loss)
        # serve-path gather traffic: u_cap fused rows in+out per dispatch
        # (updaters.gather_bytes; docs/observability.md catalog)
        from ..obs import counter
        self._gather_c = counter(
            "store_gather_bytes_total",
            "slot-table row bytes gathered+scattered per dispatched "
            "device program").labels(path="serve")

        def packed_predict(state, i32, f32, b_cap, nnz_cap, u_cap, binary):
            batch, slots, _ = unpack_batch(i32, f32, b_cap, nnz_cap, u_cap,
                                           binary=binary)
            return predict_step(state, batch, slots)

        # jaxtrace.jit: identical to jax.jit when DIFACTO_JAXTRACE is
        # off; traced, this is THE serve jit site the tier-1 gate holds
        # to "zero steady-state recompiles" (analysis/jaxflow.py)
        self._packed = jaxtrace.jit(packed_predict,
                                    static_argnums=(3, 4, 5, 6))
        # fs-sharded stores (serve_mesh_fs > 1): batch buffers ride
        # replicated over the mesh so the jitted gather pulls key-range
        # rows across shards; flat stores keep the plain asarray put
        if store.mesh is not None:
            from ..parallel import put_global, replicated
            repl = replicated(store.mesh)
            self._put = lambda a: put_global(np.asarray(a), repl)
        else:
            self._put = jnp.asarray
        self._shapes = ShapeSchedule()
        self._mu = mutex()
        self._buckets: dict = {}   # statics key -> dispatch count
        self._dispatches = 0
        self._warmed = 0           # buckets compiled by warm_bucket()
        # hot-reload bookkeeping (serve/reload.py swaps stores in)
        self.generation = 1

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """{'buckets_compiled', 'bucket_hits', 'dispatches',
        'model_generation'}: compiled grows only at a bucket's first
        occurrence; a steady-state window adds hits only (zero
        recompiles); model_generation advances once per hot reload.
        Warm-replayed buckets (warm_bucket) compiled without consuming a
        dispatch, so they don't deflate the hit count."""
        with self._mu:
            return {
                "buckets_compiled": len(self._buckets),
                "bucket_hits": self._dispatches
                - (len(self._buckets) - self._warmed),
                "dispatches": self._dispatches,
                "model_generation": self.generation,
            }

    # ------------------------------------------------------- warm replay
    def warm_set(self) -> Tuple[dict, list]:
        """(shape-cap snapshot, compiled bucket keys) — everything a
        blue/green successor needs to pre-compile the exact programs this
        executor serves with (serve/reload.py): the caps make future
        batches pad to the same buckets, the keys are the buckets to
        compile before the swap."""
        with self._mu:
            return self._shapes.snapshot(), list(self._buckets)

    def seed_caps(self, caps: dict) -> None:
        """Adopt another executor's sticky shape caps, so every batch
        shape the predecessor served maps to the same bucket here (a
        batch that was a HIT there stays a hit after the swap)."""
        self._shapes.absorb(caps)

    def warm_bucket(self, key: Tuple[int, int, int, bool]) -> None:
        """Compile the predict program for one recorded bucket key by
        dispatching a synthetic single-row batch padded to its caps —
        identical statics to a real dispatch, so the jit cache entry a
        later request needs already exists. Registers the key without
        counting a dispatch (stats arithmetic stays honest)."""
        b_cap, nnz_cap, u_cap, binary = key
        store = self.store
        blk = RowBlock(
            offset=np.array([0, 1], dtype=np.int64),
            label=np.zeros(1, dtype=np.float32),
            index=np.zeros(1, dtype=np.uint32),
            value=None if binary else np.ones(1, dtype=np.float32),
            weight=None)
        padded = pad_slots_oob(np.zeros(1, dtype=np.int32), u_cap,
                               store.state.capacity)
        i32, f32, _ = pack_batch(blk, 1, padded, b_cap, nnz_cap, u_cap)
        # lint: ok(jax-recompile) warm replay iterates PREVIOUSLY
        # RECORDED bucket keys (warm_set) — a subset of the compiled
        # set by construction, so no key here is ever a fresh compile
        # on the predecessor's model and at most one on the successor's
        pred, _, _ = self._packed(store.state, self._put(i32),
                                  self._put(f32), b_cap, nnz_cap, u_cap,
                                  binary)
        jax.block_until_ready(pred)
        with self._mu:
            if key not in self._buckets:
                self._buckets[key] = 0
                self._warmed += 1

    # ------------------------------------------------------------- swap
    def swap_store(self, store: SlotStore) -> int:
        """Atomically swap a freshly-loaded store under the executor (the
        serve hot-reload commit point). The jitted programs were built
        from make_fns(param) — pure functions of the updater params — so
        the replacement must match the geometry they were compiled
        against; a mismatched reload is rejected here (the old model
        keeps serving) and the caller routes it through the blue/green
        second-executor swap instead (serve/reload.py). The swap itself
        is one attribute assignment: ``predict`` snapshots ``self.store``
        once per call, so in-flight batches finish on the model they
        started with."""
        from .model import store_geometry
        old = self.store
        if store_geometry(store.param) != store_geometry(old.param):
            raise ValueError(
                f"hot-reload geometry mismatch: serving "
                f"(V_dim={old.param.V_dim}, "
                f"hash_capacity={old.param.hash_capacity}) vs new model "
                f"(V_dim={store.param.V_dim}, "
                f"hash_capacity={store.param.hash_capacity}); in-place "
                "swap keeps the compiled programs, so a geometry change "
                "must go through the blue/green executor swap "
                "(serve/reload.py, requires a server-attached reloader)")
        if store.fs_count != old.fs_count:
            # the compiled predict programs bake the table's sharding
            # layout; a different fs degree is a geometry change too
            raise ValueError(
                f"hot-reload geometry mismatch: serving an "
                f"fs={old.fs_count}-sharded table, new store is "
                f"fs={store.fs_count}; pass the same serve_mesh_fs on "
                "the reload path (run_serve threads it automatically) "
                "or go through the blue/green executor swap")
        with self._mu:
            # lint: ok(data-race) atomic reference swap (hot-reload commit
            # point): predict/warm snapshot self.store once per call
            self.store = store
            self.generation += 1
            return self.generation

    # ---------------------------------------------------------- predict
    def predict(self, blk: RowBlock) -> Tuple[np.ndarray, jnp.ndarray,
                                              jnp.ndarray]:
        """Score a raw-id row block. Returns (scores, objv, auc): scores
        are the clamped raw margins for the real rows (host numpy),
        objv/auc stay on device for deferred fetch."""
        if blk.size == 0:
            z = jnp.float32(0.0)
            return np.zeros(0, dtype=np.float32), z, z
        # ONE store snapshot per batch: a concurrent hot-reload swap
        # (swap_store) must never split a batch across two models —
        # in-flight batches finish on the store they started with
        store = self.store
        cblk, uniq, _ = compact(blk)
        # read-only mapping: never insert (unknown ids -> TRASH row 0,
        # whose weights are zero); sort + dedup the slot set because the
        # device kernels declare sorted unique indices, and rewrite the
        # localized columns through the permutation (the host-dedup
        # contract, store.map_keys_dedup)
        slots = store.map_keys(uniq, insert=False)
        uniq_slots, remap = np.unique(slots, return_inverse=True)
        cblk = RowBlock(offset=cblk.offset, label=cblk.label,
                        index=remap[cblk.index].astype(np.uint32),
                        value=cblk.value, weight=cblk.weight)
        n_uniq = len(uniq_slots)
        b_cap = self._shapes.cap("serve.b", blk.size)
        nnz_cap = self._shapes.cap("serve.nnz", blk.nnz)
        u_cap = self._shapes.cap("serve.u", n_uniq)
        padded = pad_slots_oob(uniq_slots.astype(np.int32), u_cap,
                               store.state.capacity)
        i32, f32, binary = pack_batch(cblk, n_uniq, padded, b_cap, nnz_cap,
                                      u_cap)
        key = (b_cap, nnz_cap, u_cap, binary)
        with self._mu:
            self._buckets[key] = self._buckets.get(key, 0) + 1
            self._dispatches += 1
        from ..updaters.sgd_updater import gather_bytes
        self._gather_c.inc(gather_bytes(store.param, store.state.capacity,
                                        u_cap))
        # lint: ok(jax-recompile) `binary` is a bool from pack_batch —
        # two compile keys by construction (the caps above are proven)
        pred, objv, auc = self._packed(store.state, self._put(i32),
                                       self._put(f32), b_cap, nnz_cap,
                                       u_cap, binary)
        # the ONE declared device->host sync of the serve dispatch loop:
        # scores must reach the response formatter; objv/auc stay on
        # device for deferred fetch. DIFACTO_JAXTRACE counts this site,
        # and the tier-1 gate asserts it is the only one.
        return jaxtrace.fetch(pred, point="serve.scores")[:blk.size], \
            objv, auc

    def predict_scores(self, blk: RowBlock) -> np.ndarray:
        """Scores only — the micro-batcher's entry."""
        return self.predict(blk)[0]
