"""Fleet orchestration: health-gated rolling restarts over N replicas.

tools/takeover.py proved the single-replica primitive — spawn a
successor on the shared SO_REUSEPORT port, warm it, ``#handoff``, drain
the incumbent. This module generalizes it to the fleet the reference
runs (PAPER.md: a scheduler supervising many servers/workers): restart N
replicas **one at a time behind a health gate**, so a model rollout (or
a binary upgrade) never takes more than one replica's capacity out of
rotation, and a rollout that makes things worse stops *before* it
spreads.

The sequencing per replica is exactly the takeover driver's — hold a
connection to the incumbent while it is the only listener on its port,
spawn the successor (``serve_takeover=1``, ready-file signaled), send
``#handoff <ready_file>`` on the held connection, poll fresh
connections until the successor's ``server_id`` answers ready. What the
fleet layer adds is the **gate** around every handoff:

- ``#health`` of EVERY replica is polled before a handoff starts and
  after it completes;
- the rollout **aborts, leaving the incumbent serving**, on any health
  regression: a replica not ``ready``, queue depth past
  ``queue_frac`` of its cap, shed rate spiking past the baseline
  captured at rollout start, or the successor's ready file never
  appearing within ``wait_s``;
- an abort before the ``#handoff`` line is sent costs nothing — the
  incumbent never stopped serving; an abort after replica *i*'s handoff
  leaves replicas ``0..i`` on the new generation and ``i+1..N-1``
  untouched (the report says exactly which).

``fleet.handoff`` is a chaos injection point fired at each replica's
handoff step (utils/faultinject.py): ``err`` models a botched rotation
and must abort the rollout with the incumbent intact —
tests/test_chaos.py asserts exactly that.

CLI: ``tools/fleet.py roll`` (and ``tools/takeover.py`` remains the
single-replica wrapper). In-process tests drive ``run_rolling_restart``
with a ``spawn_fn`` instead of subprocess successors.

Router HA (ISSUE 18) generalizes the roll to the routing tier:
``run_router_group_roll`` replaces every member of an N-router
SO_REUSEPORT group — members share ONE port, so the driver cannot
address them by endpoint; instead it redials the shared port until the
connection it HOLDS answers ``#health`` with the ``server_id`` it
means, then sends ``#handoff`` on that held connection (established
connections stay with their owner — the EndpointRpc invariant above).
``notify_backends`` is the autoscaler's membership nudge: the same
redial trick, one ``#backends add|remove`` per distinct member, with
the router's ``endpoints_file`` re-fold as the durable backstop for a
member the kernel's hashing never hands us. ``drain_endpoint`` (a bare
``#handoff``) is the scale-down primitive.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..config import parse_endpoints
from ..utils import faultinject

log = logging.getLogger("difacto_tpu")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class EndpointRpc:
    """One newline-JSON control channel over a held TCP connection.

    Holding matters under SO_REUSEPORT: a FRESH connection hashes to any
    listener on the port, but an ESTABLISHED one stays with its owner —
    so a ``#handoff`` sent on a connection opened while the incumbent was
    the only listener provably reaches the incumbent."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.rfile = self.sock.makefile("rb")

    def call(self, line: str) -> dict:
        self.sock.sendall(line.encode() + b"\n")
        resp = self.rfile.readline()
        if not resp:
            raise ConnectionError("connection closed")
        if resp.startswith(b"!err"):
            raise ConnectionError(resp.rstrip(b"\n").decode())
        return json.loads(resp)

    def close(self) -> None:
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


def fresh_health(host: str, port: int, timeout: float = 5.0) -> dict:
    """#health over a throwaway connection — what a load balancer (or
    the gate below) polls; under a takeover it answers from whichever
    replica currently owns fresh connections."""
    rpc = EndpointRpc(host, port, timeout=timeout)
    try:
        return rpc.call("#health")
    finally:
        rpc.close()


def fresh_stats(host: str, port: int, timeout: float = 5.0) -> dict:
    """#stats over a throwaway connection — the autoscaler's per-poll
    read of a replica's serving counters."""
    rpc = EndpointRpc(host, port, timeout=timeout)
    try:
        return rpc.call("#stats")
    finally:
        rpc.close()


def drain_endpoint(host: str, port: int, timeout: float = 10.0) -> dict:
    """Scale-down primitive: a bare ``#handoff`` (no ready file) tells
    the replica or router to drain NOW — in-flight work finishes, fresh
    connections land elsewhere, the process leaves its serve loop. The
    caller removes the endpoint from the routing ring first
    (:func:`notify_backends`), so the drain window sheds nothing."""
    rpc = EndpointRpc(host, port, timeout=timeout)
    try:
        return rpc.call("#handoff")
    finally:
        rpc.close()


def notify_backends(host: str, port: int, op: str, target: str,
                    max_dials: int = 16, settle: int = 4,
                    timeout: float = 5.0) -> dict:
    """Tell every member of a router group about a ring change:
    ``#backends <op> <target>`` (op ``add``/``remove``, target
    ``host:port``). Fresh connections hash across the SO_REUSEPORT
    group, so dial until ``settle`` consecutive dials reach only
    already-acked members; the op is idempotent per member. Best-effort
    by design — the routers' ``endpoints_file`` re-fold is the durable
    channel, this is the low-latency nudge."""
    acks: Dict[str, dict] = {}
    misses = dials = 0
    line = f"#backends {op} {target}".strip()
    while dials < max_dials and misses < settle:
        dials += 1
        try:
            rpc = EndpointRpc(host, port, timeout=timeout)
            try:
                r = rpc.call(line)
            finally:
                rpc.close()
        except (OSError, ConnectionError, ValueError):
            misses += 1
            continue
        sid = str(r.get("server_id", f"dial-{dials}"))
        if sid in acks:
            misses += 1
        else:
            misses = 0
            acks[sid] = r
    return {"ok": bool(acks), "routers": acks}


class HealthGate:
    """The regression detector around every handoff.

    One instance spans a rollout: the first sighting of each endpoint
    records its baseline shed rate, so "spike" means *worse than when
    this rollout started*, not worse than zero (a fleet already shedding
    at its admission bound is not a reason to freeze rollouts — getting
    MORE shed during one is)."""

    def __init__(self, queue_frac: float = 0.9, shed_spike: float = 0.25,
                 timeout: float = 5.0):
        self.queue_frac = queue_frac
        self.shed_spike = shed_spike
        self.timeout = timeout
        self._baseline_shed: Dict[str, float] = {}

    def check_one(self, host: str, port: int) -> Optional[str]:
        """None when healthy, else the human-readable regression."""
        ep = f"{host}:{port}"
        try:
            h = fresh_health(host, port, timeout=self.timeout)
        except (OSError, ConnectionError, ValueError) as e:
            return f"{ep} unreachable: {e}"
        if h.get("status") != "ready":
            return f"{ep} not ready (status={h.get('status')!r})"
        depth, cap = h.get("queue_depth", 0), h.get("queue_cap", 0)
        if cap and depth > self.queue_frac * cap:
            return (f"{ep} queue depth blowup: {depth}/{cap} rows "
                    f"(gate at {self.queue_frac:.0%})")
        shed = float(h.get("shed_rate", 0.0))
        base = self._baseline_shed.setdefault(ep, shed)
        if shed > base + self.shed_spike:
            return (f"{ep} shed-rate spike: {shed:.4f} vs baseline "
                    f"{base:.4f} (gate at +{self.shed_spike})")
        return None

    def check(self, endpoints: List[Tuple[str, int]]) -> Optional[str]:
        """First regression across the fleet, or None."""
        for host, port in endpoints:
            reason = self.check_one(host, port)
            if reason is not None:
                return reason
        return None

    def check_settled(self, endpoints: List[Tuple[str, int]],
                      wait_s: float = 10.0,
                      poll_s: float = 0.2) -> Optional[str]:
        """``check`` with a settle window: a handoff's transient blip —
        the incumbent's dying listener resetting a probe that raced into
        its backlog, a queue momentarily deep while the tail fails over
        — is not a regression; STAYING unhealthy for ``wait_s`` is. The
        rollout gates on this, so it halts on real damage without
        flapping on the rotation it is itself causing."""
        t0 = time.monotonic()
        reason = self.check(endpoints)
        while reason is not None and time.monotonic() - t0 < wait_s:
            time.sleep(poll_s)
            reason = self.check(endpoints)
        return reason


def spawn_successor(model: str, port: int, ready_file: str, extra=(),
                    host: str = "127.0.0.1") -> "subprocess.Popen":
    """Default successor: a fresh task=serve process on the shared port
    (serve_takeover=1 so the kernel accepts the second binding). Its
    output goes to ``<ready_file>.log`` — NOT the driver's inherited
    pipes, which a parent capturing the driver's output would otherwise
    wait on for the whole life of the successor."""
    args = [sys.executable, "-m", "difacto_tpu", "task=serve",
            f"model_in={model}", f"serve_host={host}",
            f"serve_port={port}", "serve_takeover=1",
            f"serve_ready_file={ready_file}", *extra]
    logf = open(ready_file + ".log", "ab")
    try:
        return subprocess.Popen(args, cwd=REPO, stdin=subprocess.DEVNULL,
                                stdout=logf, stderr=logf,
                                start_new_session=True)
    finally:
        logf.close()   # the child holds its own descriptor


def _wait_ready_file(ready_file: str, proc, wait_s: float,
                     poll_s: float) -> float:
    """Block until the successor writes its ready file; returns the warm
    seconds. Raises on successor exit or timeout — BEFORE any handoff,
    so the incumbent is untouched."""
    t0 = time.monotonic()
    while not os.path.exists(ready_file):
        if proc is not None and getattr(proc, "poll", None) \
                and proc.poll() is not None:
            raise RuntimeError(
                f"successor exited rc={proc.poll()} before ready")
        if time.monotonic() - t0 > wait_s:
            raise TimeoutError(
                f"successor not ready after {wait_s:.0f}s")
        time.sleep(poll_s)
    return time.monotonic() - t0


def _wait_takeover(host: str, port: int, incumbent_id: str,
                   wait_s: float, poll_s: float) -> dict:
    """Poll fresh connections until the successor answers ready."""
    t0 = time.monotonic()
    while True:
        try:
            h = fresh_health(host, port)
            if h.get("server_id") != incumbent_id \
                    and h.get("status") == "ready":
                return h
        except (OSError, ConnectionError, ValueError):
            pass
        if time.monotonic() - t0 > wait_s:
            raise TimeoutError(
                "takeover never completed: fresh connections still "
                "reach the incumbent (or nothing)")
        time.sleep(poll_s)


# ------------------------------------------------- single replica (PR 5)

def run_takeover(host: str, port: int, model: str = "", extra=(),
                 spawn_fn=None, wait_s: float = 180.0,
                 poll_s: float = 0.05) -> dict:
    """Sequence ONE takeover; returns the report dict. ``spawn_fn``
    (ready_file -> handle with .poll(), or None) overrides the
    subprocess successor for in-process tests. This is the primitive the
    rolling restart below gates and repeats."""
    # 1. hold a connection to the incumbent while it is the only
    #    listener — #handoff later rides this connection, immune to
    #    SO_REUSEPORT's fresh-connection hashing
    incumbent = EndpointRpc(host, port)
    try:
        h0 = incumbent.call("#health")
        if not h0.get("takeover"):
            raise SystemExit(
                "incumbent is not running serve_takeover=1 — restart it "
                "once with the knob before zero-downtime handoffs work")
        incumbent_id = h0["server_id"]

        # 2. spawn the successor; it loads + warms, binds the shared
        #    port, then writes its ready file
        fd, ready_file = tempfile.mkstemp(suffix=".ready")
        os.close(fd)
        os.unlink(ready_file)   # the successor's write IS the signal
        proc = (spawn_fn(ready_file) if spawn_fn is not None
                else spawn_successor(model, port, ready_file, extra,
                                     host=host))
        warm_s = _wait_ready_file(ready_file, proc, wait_s, poll_s)

        # 3. handoff: the incumbent confirms the ready file, drains and
        #    exits; its established connections finish first
        t1 = time.monotonic()
        res = incumbent.call(f"#handoff {ready_file}")

        # 4. fresh connections answer from the successor, ready
        h = _wait_takeover(host, port, incumbent_id, wait_s, poll_s)
        out = {"ok": True, "incumbent": incumbent_id,
               "successor": h["server_id"],
               "model_generation": h.get("model_generation"),
               "warm_s": round(warm_s, 3), "handoff": res,
               "takeover_gap_ms":
                   round((time.monotonic() - t1) * 1e3, 1)}
        if spawn_fn is None:
            out["successor_log"] = ready_file + ".log"
        return out
    finally:
        incumbent.close()


# --------------------------------------------------- rolling restart (N)

def run_rolling_restart(
        endpoints, model: str = "", extra=(),
        spawn_fn: Optional[Callable] = None,
        wait_s: float = 180.0, poll_s: float = 0.05,
        gate: Optional[HealthGate] = None,
        gate_wait_s: float = 10.0) -> dict:
    """Health-gated rolling restart: replace every replica in
    ``endpoints`` (``"h1:p1,h2:p2"`` or pairs), one at a time, each
    behind a fleet-wide ``#health`` gate. ``spawn_fn(i, host, port,
    ready_file)`` overrides the subprocess successor for in-process
    tests.

    Returns ``{"ok": True, "replicas": [per-replica reports]}`` on a
    complete rollout, or ``{"ok": False, "aborted_at": i, "endpoint":
    "h:p", "reason": ..., "completed": [...]}`` — with replica *i*'s
    incumbent still serving — on the first regression."""
    eps = parse_endpoints(endpoints)
    gate = gate if gate is not None else HealthGate()
    completed: List[dict] = []

    def abort(i: int, reason: str) -> dict:
        host, port = eps[i]
        log.warning("rolling restart ABORTED at replica %d (%s:%d): %s",
                    i, host, port, reason)
        return {"ok": False, "aborted_at": i,
                "endpoint": f"{host}:{port}", "reason": reason,
                "completed": completed}

    for i, (host, port) in enumerate(eps):
        # pre-handoff gate: the WHOLE fleet must be healthy before this
        # replica gives up its port — a rollout never compounds an
        # outage already in progress (settled: the previous handoff's
        # transient blip must not masquerade as one)
        reason = gate.check_settled(eps, wait_s=gate_wait_s)
        if reason is not None:
            return abort(i, f"pre-handoff health gate: {reason}")
        # chaos point: an injected err here models a botched rotation
        # (scheduler bug, mis-addressed handoff) — the rollout must stop
        # with the incumbent serving, and the fire is counted in
        # faults_fired_total{point="fleet.handoff"}
        try:
            faultinject.act_default(faultinject.fire("fleet.handoff"))
        except faultinject.FaultInjected as e:
            return abort(i, f"injected fleet.handoff fault: {e}")
        try:
            incumbent = EndpointRpc(host, port)
        except OSError as e:
            return abort(i, f"cannot reach incumbent: {e}")
        try:
            h0 = incumbent.call("#health")
            if not h0.get("takeover"):
                return abort(i, "incumbent not running serve_takeover=1")
            incumbent_id = h0["server_id"]
            fd, ready_file = tempfile.mkstemp(suffix=".ready")
            os.close(fd)
            os.unlink(ready_file)
            proc = (spawn_fn(i, host, port, ready_file)
                    if spawn_fn is not None
                    else spawn_successor(model, port, ready_file, extra,
                                         host=host))
            try:
                warm_s = _wait_ready_file(ready_file, proc, wait_s,
                                          poll_s)
            except (RuntimeError, TimeoutError) as e:
                # the successor never made it: nothing was handed off,
                # the incumbent is still serving — stop the rollout and
                # reap the half-up successor
                if proc is not None and hasattr(proc, "terminate"):
                    try:
                        proc.terminate()
                    except OSError:  # pragma: no cover
                        pass
                return abort(i, f"successor ready-file: {e}")
            res = incumbent.call(f"#handoff {ready_file}")
            try:
                h = _wait_takeover(host, port, incumbent_id, wait_s,
                                   poll_s)
            except TimeoutError as e:
                return abort(i, str(e))
        except (OSError, ConnectionError, ValueError) as e:
            return abort(i, f"handoff failed: {e}")
        finally:
            incumbent.close()
        report = {"endpoint": f"{host}:{port}",
                  "incumbent": incumbent_id,
                  "successor": h["server_id"],
                  "model_generation": h.get("model_generation"),
                  "warm_s": round(warm_s, 3), "handoff": res}
        if spawn_fn is None:
            report["successor_log"] = ready_file + ".log"
        completed.append(report)
        # post-handoff gate: the successor (and the rest of the fleet)
        # must be healthy before the next incumbent gives up its port
        reason = gate.check_settled(eps, wait_s=gate_wait_s)
        if reason is not None:
            return abort(min(i + 1, len(eps) - 1),
                         f"post-handoff health gate after "
                         f"{host}:{port}: {reason}")
        log.info("rolling restart: replica %d/%d (%s:%d) -> %s "
                 "(warm %.1fs)", i + 1, len(eps), host, port,
                 h["server_id"], warm_s)
    return {"ok": True, "replicas": completed}


# ------------------------------------------- router group roll (ISSUE 18)

def spawn_router(endpoints: str, port: int, ready_file: str, extra=(),
                 host: str = "127.0.0.1") -> "subprocess.Popen":
    """Default router successor: ``tools/fleet.py route --takeover`` on
    the shared group port, ready-file signaled, log next to the ready
    file (same detachment rules as :func:`spawn_successor`)."""
    args = [sys.executable, os.path.join(REPO, "tools", "fleet.py"),
            "route", "--host", host, "--port", str(port),
            "--endpoints", endpoints, "--takeover",
            "--ready-file", ready_file, *extra]
    logf = open(ready_file + ".log", "ab")
    try:
        return subprocess.Popen(args, cwd=REPO, stdin=subprocess.DEVNULL,
                                stdout=logf, stderr=logf,
                                start_new_session=True)
    finally:
        logf.close()   # the child holds its own descriptor


def _dial_member(host: str, port: int, want: Optional[str] = None,
                 avoid=(), max_dials: int = 32,
                 timeout: float = 5.0):
    """Hold a connection to a SPECIFIC member of a SO_REUSEPORT router
    group. Fresh connections hash over the group, so redial until the
    connection we HOLD answers ``#health`` with the ``server_id`` we
    mean (``want``), or with any id not in ``avoid`` (``want=None``).
    Returns ``(rpc, health)`` — the caller owns the rpc — or
    ``(None, None)`` after ``max_dials``."""
    for _ in range(max_dials):
        try:
            rpc = EndpointRpc(host, port, timeout=timeout)
        except OSError:
            time.sleep(0.05)
            continue
        try:
            h = rpc.call("#health")
        except (OSError, ConnectionError, ValueError):
            rpc.close()
            time.sleep(0.05)
            continue
        sid = h.get("server_id")
        if sid == want or (want is None and sid not in avoid):
            return rpc, h
        rpc.close()
    return None, None


def _discover_group(host: str, port: int, group_size: int,
                    max_dials: int, timeout: float = 5.0) -> Dict[str, dict]:
    """Enumerate a router group's members by server_id: dial the shared
    port until ``group_size`` distinct ids answered (or the dial budget
    ran out — the caller decides whether a partial census aborts)."""
    seen: Dict[str, dict] = {}
    for _ in range(max_dials):
        if len(seen) >= group_size:
            break
        try:
            h = fresh_health(host, port, timeout=timeout)
        except (OSError, ConnectionError, ValueError):
            time.sleep(0.05)
            continue
        sid = h.get("server_id")
        if sid:
            seen[str(sid)] = h
    return seen


def run_router_group_roll(
        host: str, port: int, group_size: int,
        spawn_fn: Optional[Callable] = None, endpoints: str = "",
        extra=(), wait_s: float = 180.0, poll_s: float = 0.05,
        max_dials: int = 64) -> dict:
    """Roll every member of an N-router SO_REUSEPORT group, one at a
    time, with zero client-visible errors — the routing-tier analog of
    :func:`run_rolling_restart`, reusing its ready-file/handoff
    sequencing with one twist: group members share ONE port, so each
    step (a) spawns the successor and waits for its ready file, (b)
    learns the successor's server_id (the first NEW id fresh dials
    reach), (c) redials until it holds a connection to the incumbent it
    means and sends ``#handoff <ready_file>`` there (the router refuses
    a handoff naming its own ready file, so a misrouted dial is caught
    even if the census raced), then (d) polls fresh connections until
    the incumbent has left the group. ``spawn_fn(i, host, port,
    ready_file)`` overrides the subprocess successor for in-process
    tests; the default spawns ``tools/fleet.py route`` with
    ``endpoints``/``extra``."""
    census = _discover_group(host, port, group_size, max_dials)
    if len(census) < group_size:
        return {"ok": False, "aborted_at": 0,
                "reason": (f"discovered {len(census)} of {group_size} "
                           "group members"), "completed": []}
    completed: List[dict] = []
    known = set(census)

    def abort(i: int, sid: str, reason: str) -> dict:
        log.warning("router group roll ABORTED at member %d (%s): %s",
                    i, sid, reason)
        return {"ok": False, "aborted_at": i, "incumbent": sid,
                "reason": reason, "completed": completed}

    for i, sid in enumerate(list(census)):
        fd, ready_file = tempfile.mkstemp(suffix=".ready")
        os.close(fd)
        os.unlink(ready_file)
        proc = (spawn_fn(i, host, port, ready_file)
                if spawn_fn is not None
                else spawn_router(endpoints, port, ready_file, extra,
                                  host=host))
        try:
            warm_s = _wait_ready_file(ready_file, proc, wait_s, poll_s)
        except (RuntimeError, TimeoutError) as e:
            if proc is not None and hasattr(proc, "terminate"):
                try:
                    proc.terminate()
                except OSError:  # pragma: no cover
                    pass
            return abort(i, sid, f"successor ready-file: {e}")
        succ_rpc, succ_h = _dial_member(host, port, avoid=known,
                                        max_dials=max_dials)
        if succ_rpc is None:
            return abort(i, sid,
                         "successor wrote its ready file but never "
                         "answered a fresh dial")
        succ_id = str(succ_h.get("server_id"))
        succ_rpc.close()
        known.add(succ_id)
        rpc, _h = _dial_member(host, port, want=sid,
                               max_dials=max_dials)
        if rpc is None:
            return abort(i, sid, "could not re-reach the incumbent "
                         "on the shared port")
        try:
            res = rpc.call(f"#handoff {ready_file}")
        except (OSError, ConnectionError, ValueError) as e:
            return abort(i, sid, f"handoff failed: {e}")
        finally:
            rpc.close()
        # (d) the incumbent leaves: fresh dials stop reaching its id
        # (probabilistic under kernel hashing, so count consecutive
        # non-sightings, bounded by the wait budget)
        t0 = time.monotonic()
        gone_after = 2 * group_size + 4
        gone = 0
        while gone < gone_after:
            if time.monotonic() - t0 > wait_s:
                return abort(i, sid,
                             "incumbent still answering fresh "
                             "connections after handoff")
            try:
                h = fresh_health(host, port)
            except (OSError, ConnectionError, ValueError):
                time.sleep(poll_s)
                continue
            if h.get("server_id") == sid \
                    and h.get("status") != "draining":
                gone = 0
                time.sleep(poll_s)
            else:
                gone += 1
        completed.append({"incumbent": sid, "successor": succ_id,
                          "warm_s": round(warm_s, 3), "handoff": res})
        log.info("router group roll: member %d/%d %s -> %s "
                 "(warm %.1fs)", i + 1, group_size, sid, succ_id,
                 warm_s)
    return {"ok": True, "routers": completed}
