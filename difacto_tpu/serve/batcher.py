"""Dynamic micro-batcher: amortize device dispatch over many small requests.

The standard adaptive-batching design (Clipper / TF-Serving style): a
bounded admission queue feeds one batching thread that collects requests
until ``batch_size`` rows or ``max_delay_ms`` elapse — whichever first —
then concatenates them into ONE RowBlock and runs the bucketed predict
executor once. Overload is explicit, never silent: a full queue SHEDS the
request at admission (``submit`` returns None, the front-end answers
``!shed``), so queue depth — and therefore worst-case queueing latency —
stays bounded at ``queue_cap`` rows of work instead of growing without
limit.

``ServeStats`` is the observability half: per-request latency percentiles
(p50/p95/p99 over a sliding window), batch occupancy, queue depth and
shed counters, published through the utils/reporter.py contract (the
reference's out-of-band progress channel) on a time throttle, and
snapshot-able on demand (the server's ``#stats`` control line,
bench.py --serve, tools/loadgen.py).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

import numpy as np
import queue

from ..data.rowblock import RowBlock
from ..utils import faultinject, shared
from ..utils.reporter import Reporter
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")


class ServeStats:
    """Serving counters + latency, REGISTRY-BACKED (difacto_tpu/obs).

    The counters behind ``#stats`` now live in an obs registry — one per
    server instance so concurrent servers in a process never blur — which
    is also what the ``#metrics`` Prometheus endpoint renders (serve/
    server.py). The ``snapshot()`` wire format is byte-compatible with
    the hand-rolled counters it replaced: same keys, same meanings; the
    exact sliding-window percentiles (p50/p95/p99 over the last
    ``window`` responses) are kept for ``#stats``, while the registry's
    ``serve_latency_seconds`` histogram carries the whole-run quantiles
    Prometheus-side. This registry is always enabled — ``#stats`` is a
    wire contract, not optional telemetry — so ``DIFACTO_OBS=off`` only
    disables the default-registry instrumentation, never serving stats.
    """

    # RACETRACE opt-in (utils/shared.py): the statically GuardedBy
    # fields of this class, traced when DIFACTO_RACETRACE=1
    _lat = shared.attr()
    _last_report = shared.attr()

    def __init__(self, reporter: Optional[Reporter] = None,
                 report_every_s: float = 30.0, window: int = 8192,
                 registry=None):
        from ..obs import Registry
        self.obs = registry if registry is not None \
            else Registry(enabled=True)
        self._mu = mutex()              # latency window + report throttle
        self._lat = collections.deque(maxlen=window)  # seconds
        self._t0 = time.monotonic()
        self._last_report = self._t0
        self._report_every = report_every_s
        self.reporter = reporter
        self._req_c = self.obs.counter(
            "serve_requests_total", "rows admitted into the micro-batcher").labels()
        self._resp_c = self.obs.counter(
            "serve_responses_total", "rows scored and answered")
        self._shed_c = self.obs.counter(
            "serve_shed_total", "rows shed at admission (queue full or "
            "draining)")
        self._err_c = self.obs.counter(
            "serve_errors_total", "rows rejected or failed")
        self._batch_c = self.obs.counter(
            "serve_batches_total", "micro-batches dispatched")
        self._rows_c = self.obs.counter(
            "serve_rows_batched_total", "rows across dispatched "
            "micro-batches")
        self._lat_h = self.obs.histogram(
            "serve_latency_seconds",
            "admit-to-answer latency per scored row")
        self._occ_h = self.obs.histogram(
            "serve_batch_rows", "micro-batch occupancy (rows per batch)",
            bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                    4096))
        self._qd_g = self.obs.gauge(
            "serve_queue_depth", "admission queue depth at the last "
            "batch flush")
        self._qdm_g = self.obs.gauge(
            "serve_queue_depth_max", "high-water admission queue depth")

    def record_admit(self, rows: int = 1) -> None:
        self._req_c.inc(rows)

    def record_shed(self, rows: int = 1) -> None:
        self._shed_c.inc(rows)

    def record_error(self, rows: int = 1) -> None:
        self._err_c.inc(rows)

    def record_batch(self, rows: int, queue_depth: int) -> None:
        self._batch_c.inc()
        self._rows_c.inc(rows)
        self._occ_h.observe(rows)
        self._qd_g.set(queue_depth)
        s = self._qdm_g.labels()
        s.set(max(s.value(), queue_depth))

    def shed_rate(self) -> float:
        """Lifetime shed fraction — cheap enough for every ``#health``
        poll (two counter reads), which is where the rolling-restart
        gate (serve/fleet.py) watches for a shed spike."""
        n_shed = self._shed_c.value()
        offered = self._req_c.value() + n_shed
        return round(n_shed / max(offered, 1), 4)

    def record_latency(self, seconds: float) -> None:
        self._resp_c.inc()
        self._lat_h.observe(seconds)
        with self._mu:
            self._lat.append(seconds)

    def snapshot(self) -> dict:
        with self._mu:
            lat = np.asarray(self._lat, dtype=np.float64)
        n_requests = int(self._req_c.value())
        n_responses = int(self._resp_c.value())
        n_shed = int(self._shed_c.value())
        n_batches = int(self._batch_c.value())
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        offered = n_requests + n_shed
        out = {
            "requests": n_requests,
            "responses": n_responses,
            "shed": n_shed,
            "errors": int(self._err_c.value()),
            "shed_rate": round(n_shed / max(offered, 1), 4),
            "qps": round(n_responses / elapsed, 1),
            "batches": n_batches,
            "batch_occupancy": round(
                self._rows_c.value() / max(n_batches, 1), 2),
            "queue_depth": int(self._qd_g.value()),
            "queue_depth_max": int(self._qdm_g.value()),
        }
        if len(lat):
            p50, p95, p99 = np.percentile(lat, [50, 95, 99]) * 1e3
            out.update(p50_ms=round(float(p50), 3),
                       p95_ms=round(float(p95), 3),
                       p99_ms=round(float(p99), 3),
                       max_ms=round(float(lat.max() * 1e3), 3))
        return out

    def maybe_report(self) -> None:
        """Throttled publish through the Reporter channel — the serving
        analog of the training progress rows."""
        if self.reporter is None:
            return
        now = time.monotonic()
        with self._mu:
            if now - self._last_report < self._report_every:
                return
            self._last_report = now
        self.reporter.report(self.snapshot())


class MicroBatcher:
    """Collect -> concat -> score, with explicit shed on overload.

    ``predict_fn(blk) -> scores[blk.size]`` runs on the single batching
    thread (the executor's dispatch contract). ``queue_cap`` bounds
    admission in ROWS of queued work, the quantity that actually sets
    queueing delay (a row costs what a row costs, however the requests
    arrive grouped). ``predict_fn`` is re-read at every flush, which is
    what makes the blue/green executor swap one attribute assignment
    (server.swap_executor): the in-flight batch finishes on the function
    it started with, the next flush dispatches on the replacement.
    """

    # RACETRACE opt-in (utils/shared.py): `_rows_queued`/`_busy` are
    # statically GuardedBy _mu, `_alive` is a suppressed stop flag —
    # the tier-1 gate cross-checks real accesses against those facts
    _rows_queued = shared.attr()
    _busy = shared.attr()
    _alive = shared.attr()

    def __init__(self, predict_fn: Callable[[RowBlock], np.ndarray],
                 batch_size: int = 256, max_delay_ms: float = 2.0,
                 queue_cap: int = 1024,
                 stats: Optional[ServeStats] = None):
        self.predict_fn = predict_fn
        self.batch_size = batch_size
        self.max_delay_s = max_delay_ms / 1e3
        self.queue_cap = queue_cap
        self.stats = stats if stats is not None else ServeStats()
        self._q: "queue.Queue" = queue.Queue()
        self._rows_queued = 0          # admission-bounded under _mu
        self._mu = mutex()
        self._alive = False
        self._busy = False             # a batch is being scored right now
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- control
    def start(self) -> None:
        # lint: ok(data-race) monotonic stop flag (GIL-atomic bool): the
        # loop observes the False from close() on its next iteration
        self._alive = True
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._alive = False
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # fail any requests still queued so connection writers never hang
        while True:
            try:
                _, fut, _rows = self._q.get_nowait()
            except queue.Empty:
                break
            fut.set_exception(RuntimeError("serve batcher shut down"))

    # ----------------------------------------------------------- submit
    def submit(self, blk: RowBlock) -> Optional[Future]:
        """Admit a request (one or more rows). Returns a Future resolving
        to scores[blk.size], or None when the queue is full — the caller
        must surface the shed to the client (backpressure is explicit).
        ``batcher.enqueue`` is a chaos-harness injection point
        (utils/faultinject.py): ``err`` surfaces through the server as an
        ``!err`` reply, ``delay_ms`` models a stalled admission path."""
        faultinject.act_default(faultinject.fire("batcher.enqueue"))
        with self._mu:
            if self._rows_queued + blk.size > self.queue_cap:
                self.stats.record_shed(blk.size)
                return None
            self._rows_queued += blk.size
        fut: Future = Future()
        self.stats.record_admit(blk.size)
        self._q.put((blk, fut, blk.size))
        return fut

    @property
    def rows_queued(self) -> int:
        with self._mu:
            return self._rows_queued

    @property
    def idle(self) -> bool:
        """No queued rows and no batch mid-score — the drain loop's
        "all admitted work has resolved" condition (server.drain). One
        atomic snapshot under ``_mu``: reading the two fields unlocked
        could observe the decrement of a batch that is not busy YET and
        report idle with work in flight."""
        with self._mu:
            return self._rows_queued == 0 and not self._busy

    # ------------------------------------------------------------- loop
    def _collect(self):
        """One micro-batch: block for the first request, then fill until
        batch_size rows or the delay budget expires."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        rows = first[2]
        deadline = time.monotonic() + self.max_delay_s
        while rows < self.batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            batch.append(item)
            rows += item[2]
        return batch

    def _loop(self) -> None:
        while self._alive:
            batch = self._collect()
            if not batch:
                continue
            # busy BEFORE the queued-row decrement, in one _mu region:
            # the drain loop must never observe (rows_queued == 0,
            # busy == False) while this batch is still unscored
            rows = sum(r for _, _, r in batch)
            with self._mu:
                self._busy = True
                self._rows_queued -= rows
                depth = self._rows_queued
            try:
                self.stats.record_batch(rows, depth)
                try:
                    # one attribute read per flush: a concurrent
                    # swap_executor retargets the NEXT flush, never
                    # splits this one
                    scores = self.predict_fn(
                        RowBlock.concat([b for b, _, _ in batch]))
                except Exception as e:  # pragma: no cover - executor bug
                    log.exception("serve batch failed")
                    self.stats.record_error(rows)
                    for _, fut, _ in batch:
                        fut.set_exception(e)
                    continue
                o = 0
                for b, fut, r in batch:
                    fut.set_result(scores[o:o + r])
                    o += r
                self.stats.maybe_report()
            finally:
                with self._mu:
                    self._busy = False
