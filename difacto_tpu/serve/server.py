"""Threaded TCP serving front-end.

Wire protocol (newline-delimited, UTF-8/ASCII):

- request line = one data row in the configured ``data_format`` (default
  libsvm: ``label idx:val idx:val ...`` — the label is ignored for
  scoring but keeps the row grammar identical to training files);
- response line = ``%g``-formatted probability (``pred_prob=False``: the
  raw clamped margin) for that row, in request order per connection;
- ``#stats`` -> one JSON line of serving + executor counters;
- ``#metrics`` -> Prometheus text exposition of the server's obs
  registry (difacto_tpu/obs): latency histogram + derived p50/p95/p99
  quantiles, queue depth, shed/error counters, model_generation — ends
  with a blank line so line-oriented clients know where it stops;
- ``!shed`` -> the admission queue was full (overload backpressure —
  resend later or slow down);
- ``!err <reason>`` -> the row was rejected (malformed, oversized);
- ``#handoff [ready_file]`` -> zero-downtime replica takeover: reply
  immediately, then (on a background thread) wait for the successor's
  ready file and drain. With ``takeover=True`` the listening socket is
  bound ``SO_REUSEPORT``, so a successor process binds the SAME port
  while the incumbent drains — established connections stay with their
  owner, new connections land on whichever replica still listens
  (tools/takeover.py sequences spawn -> warm -> handoff -> exit;
  ``serve.handoff`` is a chaos injection point);
- ``#score <id> <row>`` -> score ``row`` exactly like a plain request
  line AND (when an online training log is attached) log it under the
  client-chosen integer id, so the client can later report the row's
  true label; the response is the plain ``%g`` score line;
- ``#label <id> <y>`` -> feedback join for the online log: attach the
  delayed label ``y`` to the still-pending logged row ``id``
  (online/log.py). One JSON line back: ``{"ok": true}`` joined,
  ``{"ok": false}`` the row already resolved (past its
  ``label_delay_s`` horizon) — best-effort by design. Plain rows are
  logged too (auto-assigned ids) and resolve via the horizon default.

One reader + one writer thread per connection: the reader parses and
admits rows into the shared MicroBatcher, the writer resolves futures in
request order — so a pipelined client (send N rows, then read N
responses) never deadlocks against the batching delay. All threads are
joined on ``close()``; a clean shutdown leaves no threads or sockets
behind (tests/test_serve.py asserts exactly that).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import threading
import time
from typing import Optional

from ..data.parsers import get_parser
from ..utils import faultinject, stream
from ..utils.reporter import Reporter
from .batcher import MicroBatcher, ServeStats
from .executor import PredictExecutor, sigmoid
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")

from ..obs import counter as _counter  # noqa: E402

_c_log_drops = _counter(
    "online_log_drops_total",
    "served rows the online training log failed to append (the row was "
    "still answered — serving never fails because logging failed)")


class ServeServer:
    def __init__(self, store, host: str = "127.0.0.1", port: int = 0,
                 loss=None, batch_size: int = 256,
                 max_delay_ms: float = 2.0, queue_cap: int = 1024,
                 pred_prob: bool = True, data_format: str = "libsvm",
                 max_row_nnz: int = 4096, report_every_s: float = 30.0,
                 reporter: Optional[Reporter] = None,
                 drain_timeout_s: float = 10.0, takeover: bool = False,
                 handoff_wait_s: float = 30.0, online_log=None):
        self.executor = PredictExecutor(store, loss=loss)
        if reporter is None:
            reporter = Reporter(every=1)
            reporter.set_monitor(
                lambda _node, payload: log.info("serve: %s", payload))
        self.stats = ServeStats(reporter, report_every_s=report_every_s)
        # the server's obs registry (ServeStats owns it): #metrics
        # renders it merged with the process-global registry (faults,
        # pipeline counters) — per-server series never blur across
        # servers in one process
        self.obs = self.stats.obs
        self.batcher = MicroBatcher(self.executor.predict_scores,
                                    batch_size=batch_size,
                                    max_delay_ms=max_delay_ms,
                                    queue_cap=queue_cap, stats=self.stats)
        self.pred_prob = pred_prob
        self.max_row_nnz = max_row_nnz
        self.drain_timeout_s = drain_timeout_s
        # attached by run_serve / bench: a reload.ModelReloader serving
        # the #reload control line and the background model watcher
        self.reloader = None
        # the serve→log→train loop (online/log.py): every admitted row
        # is appended (plain rows under auto ids, #score rows under the
        # client's id); #label joins delayed feedback. None = no logging.
        self.online_log = online_log
        self.draining = False
        # takeover state (#handoff): ready_file is set by run_serve so a
        # handoff addressed at "our own" ready file is recognized as
        # mis-routed (SO_REUSEPORT may hash a fresh connection to the
        # successor); successor_ready surfaces through #health
        self.takeover = takeover
        self.handoff_wait_s = handoff_wait_s
        self.ready_file = ""
        self.successor_ready = False
        self._successor_file: Optional[str] = None
        self._handoff_thread: Optional[threading.Thread] = None
        self._parser = get_parser(data_format)
        # SO_REUSEPORT (takeover): every replica of a takeover pair must
        # bind with it set, incumbent included — the kernel rejects mixed
        # bindings — so the knob is on the server, not the handoff
        self._sock = socket.create_server((host, port),
                                          reuse_port=takeover)
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._alive = False
        self._closed = False
        self._done = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conn_threads: list = []
        self._mu = mutex()
        # serve_generation_age_s bookkeeping: when the served generation
        # last advanced (detected at #metrics render time, under _mu)
        self._gen_seen = self.executor.generation
        self._gen_ts = time.monotonic()

    # ---------------------------------------------------------- control
    def start(self) -> "ServeServer":
        self.batcher.start()
        # lint: ok(data-race) monotonic stop flag; accept loop re-checks
        self._alive = True
        # lint: ok(data-race) lifecycle handle: start() happens-before
        # close()/drain() by operator sequencing
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True)
        self._accept_thread.start()
        log.info("serving on %s:%d (batch<=%d rows, delay<=%.1fms, "
                 "queue<=%d rows)", self.host, self.port,
                 self.batcher.batch_size, self.batcher.max_delay_s * 1e3,
                 self.batcher.queue_cap)
        return self

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until close() (or the timeout elapses)."""
        self._done.wait(timeout)

    def _is_closed(self) -> bool:
        with self._mu:
            return self._closed

    def close(self) -> None:
        """Stop accepting, drop connections, join every thread, unlink
        the socket — idempotent and safe to race from a signal handler
        against the normal shutdown path."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
        self._alive = False
        self._done.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        if self._accept_thread is not None:
            self._accept_thread.join()
            self._accept_thread = None
        with self._mu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        # a conn thread can reach here through #handoff -> drain ->
        # close; never join the calling thread itself. Snapshot under
        # _mu: the accept loop appends under the same lock until its
        # join above, and a late handler registration must not be lost
        # to an unlocked list read
        me = threading.current_thread()
        with self._mu:
            threads = list(self._conn_threads)
            self._conn_threads = []
        for t in threads:
            if t is not me:
                t.join()
        self.batcher.close()

    def drain(self, timeout_s: Optional[float] = None) -> float:
        """Graceful shutdown: stop accepting NEW connections, answer new
        rows with ``!shed draining`` (retry-elsewhere backpressure), wait
        for every admitted row — queued and mid-batch — to resolve, then
        close. Bounded by ``drain_timeout_s``: a wedged batch can delay
        exit by at most that much, never hang it. Returns the seconds the
        drain took; idempotent with close(). This is what the SIGTERM/
        SIGINT handlers (run_serve) call so a load balancer rotating a
        replica out never sees admitted work dropped."""
        timeout = self.drain_timeout_s if timeout_s is None else timeout_s
        t0 = time.monotonic()
        # lint: ok(data-race) monotonic False->True flip (GIL-atomic);
        # handlers and #health tolerate reading either side
        self.draining = True
        self._alive = False   # accept loop exits; close() joins it
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if self.batcher.idle:
                break
            time.sleep(0.02)
        else:
            log.warning("drain timed out after %.1fs with %d rows queued",
                        timeout, self.batcher.rows_queued)
        # one beat for connection writer threads to flush resolved
        # futures before connections are shut down
        time.sleep(0.05)
        self.close()
        return time.monotonic() - t0

    def swap_executor(self, new) -> None:
        """Blue/green commit point (serve/reload.py): retarget the
        server AND the batcher at the green executor in two attribute
        assignments. The batcher reads ``predict_fn`` afresh per flush,
        so the in-flight batch finishes on blue and the next flush runs
        on green; blue's store/buffers drop with the last reference."""
        # lint: ok(data-race) atomic reference swap (blue/green commit):
        # stats/health snapshot self.executor once per call
        self.executor = new
        self.batcher.predict_fn = new.predict_scores

    def stats_snapshot(self) -> dict:
        """Serving counters + executor bucket stats (incl.
        model_generation) + reload counters, one flat dict."""
        out = dict(self.stats.snapshot(), **self.executor.stats())
        if self.reloader is not None:
            out.update(self.reloader.stats())
        return out

    def health_snapshot(self) -> dict:
        """The ``#health`` payload: readiness for load-balancer rotation
        plus the queue depth that predicts admission latency. ``pid`` /
        ``server_id`` identify WHICH replica answered — under a
        SO_REUSEPORT takeover two processes share the port, and the
        handoff driver polls this one endpoint until the successor's id
        answers ready. ``swap_state`` (idle/warming/swapping) and
        ``successor_ready`` (present once a #handoff is pending) let one
        poll loop watch both continuity paths."""
        with self._mu:
            successor_file = self._successor_file
            successor_ready = self.successor_ready
        out = {
            "status": "draining" if self.draining else "ready",
            "queue_depth": self.batcher.rows_queued,
            "queue_cap": self.batcher.queue_cap,
            # the third regression signal the rolling-restart health
            # gate (serve/fleet.py) reads, next to ready + queue depth
            "shed_rate": self.stats.shed_rate(),
            "model_generation": self.executor.generation,
            "pid": os.getpid(),
            "server_id": f"{os.getpid()}.{id(self):x}",
            "takeover": self.takeover,
            "swap_state": (self.reloader.swap_state
                           if self.reloader is not None else "idle"),
        }
        if successor_file is not None:
            out["successor_ready"] = successor_ready
        return out

    # ------------------------------------------------------- connection
    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(None)
            try:
                # response lines are tiny; never let Nagle hold them
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:  # pragma: no cover - non-TCP transports
                pass
            with self._mu:
                self._conns.add(conn)
                # prune finished handler threads so a long-lived server
                # doesn't accumulate one record per past connection
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="serve-conn", daemon=True)
            t.start()
            # append AFTER start: close() joins the accept thread before
            # walking this list, so it can never see an unstarted thread
            with self._mu:
                self._conn_threads.append(t)

    def _handle(self, conn: socket.socket) -> None:
        """Per-connection reader: parse + admit each line, hand ordered
        reply slots to the writer thread."""
        replies: "queue.Queue" = queue.Queue()
        writer = threading.Thread(target=self._writer,
                                  args=(conn, replies),
                                  name="serve-conn-writer", daemon=True)
        writer.start()
        try:
            rfile = conn.makefile("rb")
            for line in rfile:
                # chaos harness: an injected ``close`` here models the
                # peer/kernel tearing the connection down mid-request
                if faultinject.fire("serve.sock.read") == "close":
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                line = line.strip()
                if not line:
                    continue
                if line.startswith(b"#"):
                    # #score rides the batcher (its reply is a scored
                    # future, not control bytes), so it is admitted
                    # here, not in _control
                    if line.startswith(b"#score "):
                        replies.put(self._admit_scored(line))
                    else:
                        replies.put(("raw", self._control(line), 0.0))
                    continue
                replies.put(self._admit(line))
        except (OSError, ValueError):
            pass
        finally:
            replies.put(None)
            writer.join()
            try:
                conn.close()
            except OSError:
                pass
            with self._mu:
                self._conns.discard(conn)

    def _admit(self, row: bytes, row_id: Optional[int] = None):
        """Parse + admit one data row into the micro-batcher; returns
        the writer-queue item (``("fut", future, t0)`` or a raw reply).
        Shared by the plain request path and ``#score``."""
        t0 = time.monotonic()
        if self.draining:
            # starts with !shed so every client treats it as the
            # retry-elsewhere backpressure signal it is
            self.stats.record_shed()
            return ("raw", b"!shed draining\n", 0.0)
        try:
            blk = self._parser(row)
        except Exception as e:
            log.debug("bad row %r: %s", row[:80], e)
            blk = None
        if blk is None or blk.size != 1:
            self.stats.record_error()
            return ("raw", b"!err bad row\n", 0.0)
        if blk.nnz > self.max_row_nnz:
            self.stats.record_error()
            return ("raw",
                    b"!err row exceeds serve_max_row_nnz=%d\n"
                    % self.max_row_nnz, 0.0)
        try:
            fut = self.batcher.submit(blk)
        except faultinject.FaultInjected as e:
            self.stats.record_error()
            return ("raw", b"!err %s\n" % str(e).encode(), 0.0)
        if fut is None:
            return ("raw", b"!shed\n", 0.0)
        # log AFTER a successful admit: the training log records rows
        # that were actually served, not shed/rejected ones
        self._log_row(blk, row_id)
        return ("fut", fut, t0)

    def _admit_scored(self, line: bytes):
        """``#score <id> <row>``: score exactly like a plain row, logged
        under the client-chosen id so ``#label <id> <y>`` can join."""
        parts = line.split(None, 2)
        if len(parts) != 3:
            self.stats.record_error()
            return ("raw", b"!err bad #score line\n", 0.0)
        try:
            rid = int(parts[1])
        except ValueError:
            self.stats.record_error()
            return ("raw", b"!err bad #score id\n", 0.0)
        return self._admit(parts[2], row_id=rid)

    def _log_row(self, blk, row_id: Optional[int]) -> None:
        """Append a served row to the online training log. A logging
        failure (injected ``online.log.append``, disk trouble) is
        counted and the row is still answered — the serve path never
        fails because the training log did."""
        online_log = self.online_log
        if online_log is None:
            return
        try:
            online_log.append(blk, row_id=row_id)
        except Exception as e:
            _c_log_drops.inc()
            log.debug("online log append dropped row: %s", e)

    def metrics_text(self) -> str:
        """Prometheus text for the ``#metrics`` control line: the
        server's registry (latency histogram + quantiles, queue/shed
        counters) with the executor/reloader state mirrored into gauges
        at render time, merged with the process-global registry (fault
        fires, pipeline counters)."""
        from ..obs import REGISTRY, merge_into, render_prometheus
        ex = self.executor.stats()
        self.obs.gauge("serve_model_generation",
                       "generation of the model currently serving"
                       ).set(ex["model_generation"])
        self.obs.gauge("serve_buckets_compiled",
                       "predict shape buckets compiled so far"
                       ).set(ex["buckets_compiled"])
        self.obs.gauge("serve_dispatches",
                       "predict executor dispatches").set(ex["dispatches"])
        self.obs.gauge("serve_queue_cap", "admission bound in rows"
                       ).set(self.batcher.queue_cap)
        self.obs.gauge("serve_draining",
                       "1 while draining for shutdown"
                       ).set(1.0 if self.draining else 0.0)
        # freshness SLO (docs/serving.md "Continuous learning"): how
        # stale the serving model is — seconds since the served
        # generation last advanced, detected at render time
        now = time.monotonic()
        with self._mu:
            if ex["model_generation"] != self._gen_seen:
                self._gen_seen = ex["model_generation"]
                self._gen_ts = now
            gen_age = now - self._gen_ts
        self.obs.gauge("serve_generation_age_s",
                       "seconds since the serving model generation "
                       "last advanced").set(gen_age)
        if self.reloader is not None:
            rs = self.reloader.stats()
            self.obs.gauge("serve_reloads",
                           "successful model hot-reloads"
                           ).set(rs["reloads"])
            self.obs.gauge("serve_reload_failures",
                           "failed model hot-reloads (old model kept)"
                           ).set(rs["reload_failures"])
            self.obs.gauge("serve_swap_warming",
                           "1 while a blue/green warm or swap is in "
                           "flight").set(
                0.0 if rs["swap_state"] == "idle" else 1.0)
        snap = merge_into(self.obs.snapshot(), REGISTRY.snapshot())
        return render_prometheus(snap)

    def _control(self, line: bytes) -> bytes:
        if line == b"#stats":
            return (json.dumps(self.stats_snapshot()) + "\n").encode()
        if line == b"#metrics":
            # multi-line payload, terminated by one blank line (the text
            # format never emits blank lines itself)
            return self.metrics_text().encode() + b"\n"
        if line == b"#health":
            return (json.dumps(self.health_snapshot()) + "\n").encode()
        if line.startswith(b"#label "):
            return self._control_label(line)
        if line == b"#handoff" or line.startswith(b"#handoff "):
            return self._control_handoff(line)
        if line == b"#reload" or line.startswith(b"#reload "):
            # synchronous on THIS connection's reader thread: scoring
            # traffic on other connections keeps flowing through the
            # batcher while the new model loads; the swap is atomic
            if self.reloader is None:
                return b"!err no reloader configured (set model_in)\n"
            path = line[len(b"#reload"):].strip().decode() or None
            return (json.dumps(self.reloader.reload(path)) + "\n").encode()
        return b"!err unknown control %s\n" % line[:32]

    def _control_label(self, line: bytes) -> bytes:
        """``#label <id> <y>``: delayed-feedback join onto the online
        training log. Typed replies for every failure shape — a label
        for a row past its horizon is ``{"ok": false}``, not an error."""
        online_log = self.online_log
        if online_log is None:
            return b"!err no online log attached\n"
        parts = line.split()
        if len(parts) != 3:
            return b"!err bad #label line\n"
        try:
            rid, y = int(parts[1]), float(parts[2])
        except ValueError:
            return b"!err bad #label args\n"
        try:
            joined = online_log.label(rid, y)
        except faultinject.FaultInjected as e:
            self.stats.record_error()
            return b"!err %s\n" % str(e).encode()
        return (json.dumps({"ok": joined}) + "\n").encode()

    def _control_handoff(self, line: bytes) -> bytes:
        """``#handoff [ready_file]``: acknowledge, then wait for the
        successor and drain on a BACKGROUND thread — the drain path
        close()s connections and joins their threads, so it must never
        run on the requesting connection's own reader thread."""
        try:
            faultinject.act_default(faultinject.fire("serve.handoff"))
        except faultinject.FaultInjected as e:
            return b"!err %s\n" % str(e).encode()
        arg = line[len(b"#handoff"):].strip().decode()
        if arg and self.ready_file and \
                os.path.abspath(arg) == os.path.abspath(self.ready_file):
            # SO_REUSEPORT hashed this connection to the successor: the
            # named ready file is OUR OWN — refuse, the driver retries
            # on the connection it holds to the incumbent
            return (b"!err handoff addressed to the successor "
                    b"(this replica owns the ready file)\n")
        with self._mu:
            if self._handoff_thread is not None:
                return (json.dumps({"ok": True, "state": "draining"})
                        + "\n").encode()
            self._successor_file = arg
            t = threading.Thread(target=self._handoff, args=(arg,),
                                 name="serve-handoff", daemon=True)
            self._handoff_thread = t
        t.start()
        return (json.dumps({"ok": True, "state": "handoff",
                            "successor_file": arg}) + "\n").encode()

    def _handoff(self, ready_file: str) -> None:
        """Wait (bounded by ``handoff_wait_s``) for the successor's
        ready file, then drain. A successor that never appears does not
        pin the incumbent forever: the handoff was an explicit operator
        request to leave, so after the wait budget we drain anyway —
        loudly."""
        end = time.monotonic() + self.handoff_wait_s
        if ready_file:
            while (not stream.isfile(ready_file)
                   and time.monotonic() < end
                   and not self._is_closed()):
                time.sleep(0.05)
            ready = stream.isfile(ready_file)
            if not ready and not self._is_closed():
                log.warning("handoff: successor never became ready "
                            "(%s); draining anyway", ready_file)
        else:
            ready = True
        with self._mu:
            self.successor_ready = ready
        log.info("handoff: draining incumbent (successor_ready=%s)",
                 ready)
        self.drain()

    def _writer(self, conn: socket.socket, replies: "queue.Queue") -> None:
        try:
            while True:
                item = replies.get()
                if item is None:
                    return
                # chaos harness: an injected ``close`` drops the
                # connection mid-response-stream — the exact failure the
                # retrying client must survive
                if faultinject.fire("serve.sock.write") == "close":
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return
                kind, payload, t0 = item
                if kind == "raw":
                    conn.sendall(payload)
                    continue
                try:
                    scores = payload.result(timeout=60.0)
                except Exception as e:
                    conn.sendall(b"!err %s\n"
                                 % str(e).encode("utf-8", "replace")[:200])
                    continue
                out = sigmoid(scores) if self.pred_prob else scores
                # "%g" of the scored row — the SAME formatting
                # learners/sgd.py _save_pred applies, so serve responses
                # are byte-identical to task=pred output columns
                self.stats.record_latency(time.monotonic() - t0)
                conn.sendall(("%g\n" % float(out[0])).encode())
        except OSError:  # client went away mid-reply
            pass
