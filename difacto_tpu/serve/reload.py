"""Serve hot-reload: pick up a newly-trained model without a restart.

The trainer's side of the lifecycle ends at a checkpoint on disk; before
this module the server's side began with a process restart — a cold
executor, recompiled buckets, and a dropped listening socket. The
``ModelReloader`` closes that gap:

- a **watcher** thread polls the model path (manifest generation first,
  mtime/size as the legacy fallback) every ``poll_s`` seconds and
  triggers a reload when the fingerprint moves, so a `model_out` that the
  trainer re-saves is picked up automatically;
- the ``#reload [path]`` control line triggers the same reload on demand
  (handled on the requesting connection's reader thread — scoring never
  stalls behind a load);
- the reload itself loads the new model **weights-only in the
  background** through ``open_serving_store(fallback=False)`` — full
  manifest verification, no silent walk-back — and only then swaps it
  into the executor atomically (``PredictExecutor.swap_store``:
  in-flight batches finish on the old model; the compiled predict
  programs survive because the geometry is checked);
- a failed or corrupt load **keeps the old model serving** and records
  ``reload_failures``; ``#stats`` carries ``model_generation`` /
  ``reloads`` / ``reload_failures`` so a fleet can alert on a replica
  that's stuck behind the model it should be serving.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Tuple

from ..utils import stream

log = logging.getLogger("difacto_tpu")


class ModelReloader:
    def __init__(self, executor, model_uri: str, poll_s: float = 0.0,
                 kwargs=()):
        self.executor = executor
        self.model_uri = model_uri
        self.poll_s = poll_s
        self._kwargs = list(kwargs)
        self.reloads = 0
        self.reload_failures = 0
        self._reload_mu = threading.Lock()   # serialize concurrent reloads
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cur = self._fingerprint()

    # ------------------------------------------------------------ watch
    def _fingerprint(self) -> Optional[Tuple]:
        """(path, manifest generation, mtime, size) of the current model
        file; None while unresolvable. Generation is the real signal —
        mtime/size only cover legacy manifest-less files."""
        from ..utils import manifest as mft
        from .model import resolve_model_path
        try:
            path = resolve_model_path(self.model_uri)
            man = mft.read(path)
            gen = man.get("generation") if man else None
            return (path, gen, stream.getmtime(path), stream.getsize(path))
        except (FileNotFoundError, OSError, mft.CheckpointCorrupt):
            return None

    def start(self) -> "ModelReloader":
        if self.poll_s > 0 and self._thread is None:
            self._thread = threading.Thread(target=self._watch,
                                            name="serve-reload-watch",
                                            daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _changed(self, fp: Optional[Tuple]) -> bool:
        """When both fingerprints carry a manifest generation, only a
        generation move counts — the npz lands before its manifest, so a
        new mtime under the old generation is a save in progress, not a
        model to load (reloading mid-write would burn a failure)."""
        if fp is None or fp == self._cur:
            return False
        if self._cur is None:
            return True
        if fp[1] is not None and self._cur[1] is not None:
            return fp[0] != self._cur[0] or fp[1] != self._cur[1]
        return True

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            fp = self._fingerprint()
            if self._changed(fp):
                log.info("model watcher: %s changed (generation %s); "
                         "reloading", fp[0], fp[1])
                self.reload()

    # ----------------------------------------------------------- reload
    def reload(self, path: Optional[str] = None) -> dict:
        """Load + verify + swap, synchronously on the calling thread.
        Returns {'ok', 'model_generation'} or {'ok': False, 'error'} —
        the old model keeps serving on any failure."""
        from .model import open_serving_store
        target = path or self.model_uri
        with self._reload_mu:
            fp = self._fingerprint() if path is None else None
            try:
                # fallback=False: reloading must never silently regress
                # to an older generation — the current in-memory model IS
                # the fallback
                store, meta, _ = open_serving_store(target, self._kwargs,
                                                    fallback=False)
                gen = self.executor.swap_store(store)
            except Exception as e:
                self.reload_failures += 1
                from ..obs import counter
                counter("model_reload_failures_total",
                        "failed hot-reloads (old model kept)").inc()
                log.warning("model reload from %s failed; keeping the "
                            "current model: %s", target, e)
                return {"ok": False, "error": str(e)}
            self.reloads += 1
            from ..obs import counter
            counter("model_reloads_total",
                    "successful model hot-reloads").inc()
            if fp is not None:
                self._cur = fp
            log.info("model reloaded from %s: generation %d",
                     meta["path"], gen)
            return {"ok": True, "model_generation": gen,
                    "path": meta["path"]}

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {"reloads": self.reloads,
                "reload_failures": self.reload_failures}
