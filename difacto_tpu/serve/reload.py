"""Serve hot-reload: pick up a newly-trained model without a restart.

The trainer's side of the lifecycle ends at a checkpoint on disk; before
this module the server's side began with a process restart — a cold
executor, recompiled buckets, and a dropped listening socket. The
``ModelReloader`` closes that gap:

- a **watcher** thread polls the model path (manifest generation first,
  mtime/size as the legacy fallback) every ``poll_s`` seconds and
  triggers a reload when the fingerprint moves, so a `model_out` that the
  trainer re-saves is picked up automatically;
- the ``#reload [path]`` control line triggers the same reload on demand
  (handled on the requesting connection's reader thread — scoring never
  stalls behind a load);
- the reload itself loads the new model **weights-only in the
  background** through ``open_serving_store(fallback=False)`` — full
  manifest verification, no silent walk-back — and only then swaps it
  into the executor atomically (``PredictExecutor.swap_store``:
  in-flight batches finish on the old model; the compiled predict
  programs survive because the geometry is checked);
- a failed or corrupt load **keeps the old model serving** and records
  ``reload_failures``; ``#stats`` carries ``model_generation`` /
  ``reloads`` / ``reload_failures`` so a fleet can alert on a replica
  that's stuck behind the model it should be serving;
- a **geometry change** (``V_dim`` / ``hash_capacity`` moved between
  generations) no longer forces a restart: when the reloader is attached
  to a server it runs a **blue/green executor swap** — a second
  ``PredictExecutor`` is built against the new store, seeded with the
  live executor's sticky shape caps and warmed on every bucket the live
  executor has compiled (its recorded warm-set, so no request ever pays
  a compile on green), then the server's executor reference is swapped
  atomically: in-flight batches finish on blue, the next flush runs on
  green, and blue's store/buffers drop with the last reference.
  ``swap_state`` (idle/warming/swapping) rides ``#health``/``#stats``
  and ``serve_bluegreen_swaps_total`` counts the swaps; ``reload.warm``
  is a chaos injection point inside the warm loop
  (utils/faultinject.py).
- the warm-set pre-compilation runs on a small **thread pool**
  (``warm_workers``, default 4): bucket compiles are independent XLA
  compilations that release the GIL, so a live executor with many
  recorded buckets no longer stretches the swap window by compiling
  them one at a time. The swap itself stays atomic and any worker
  failure aborts the whole swap with blue serving; ``last_warm_ms``
  records the wall-clock warm cost (``bench.py --serve`` emits it as
  ``serve.warm_parallel_ms``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Tuple

from ..utils import faultinject, stream
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")


class ModelReloader:
    def __init__(self, executor, model_uri: str, poll_s: float = 0.0,
                 kwargs=(), server=None, warm_workers: int = 4):
        # server=None (bench/unit use): same-geometry swaps only — there
        # is no batcher whose executor reference a blue/green swap could
        # retarget, so a geometry change stays a reload failure
        self._executor = executor
        self._server = server
        self.model_uri = model_uri
        self.poll_s = poll_s
        self._kwargs = list(kwargs)
        self.warm_workers = warm_workers
        self.reloads = 0
        self.reload_failures = 0
        self.bluegreen_swaps = 0
        self.last_warm_ms = 0.0              # wall cost of the last warm
        self.swap_state = "idle"             # idle | warming | swapping
        self._reload_mu = mutex()            # serialize concurrent reloads
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cur = self._fingerprint()

    @property
    def executor(self):
        """The LIVE executor — read through the server when attached,
        because a blue/green swap replaces the server's executor object
        and a cached reference would keep reloading into a dead blue."""
        return (self._server.executor if self._server is not None
                else self._executor)

    # ------------------------------------------------------------ watch
    def _fingerprint(self) -> Optional[Tuple]:
        """(path, manifest generation, mtime, size) of the current model
        file; None while unresolvable. Generation is the real signal —
        mtime/size only cover legacy manifest-less files."""
        from ..utils import manifest as mft
        from .model import resolve_model_path
        try:
            path = resolve_model_path(self.model_uri)
            man = mft.read(path)
            gen = man.get("generation") if man else None
            return (path, gen, stream.getmtime(path), stream.getsize(path))
        except (FileNotFoundError, OSError, mft.CheckpointCorrupt):
            return None

    def start(self) -> "ModelReloader":
        if self.poll_s > 0 and self._thread is None:
            self._thread = threading.Thread(target=self._watch,
                                            name="serve-reload-watch",
                                            daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _changed(self, fp: Optional[Tuple]) -> bool:
        """When both fingerprints carry a manifest generation, only a
        generation move counts — the npz lands before its manifest, so a
        new mtime under the old generation is a save in progress, not a
        model to load (reloading mid-write would burn a failure)."""
        if fp is None or fp == self._cur:
            return False
        if self._cur is None:
            return True
        if fp[1] is not None and self._cur[1] is not None:
            return fp[0] != self._cur[0] or fp[1] != self._cur[1]
        return True

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            fp = self._fingerprint()
            if self._changed(fp):
                log.info("model watcher: %s changed (generation %s); "
                         "reloading", fp[0], fp[1])
                self.reload()

    # ----------------------------------------------------------- reload
    def reload(self, path: Optional[str] = None) -> dict:
        """Load + verify + swap, synchronously on the calling thread.
        Returns {'ok', 'model_generation'} or {'ok': False, 'error'} —
        the old model keeps serving on any failure."""
        from .model import open_serving_store, store_geometry
        target = path or self.model_uri
        with self._reload_mu:
            fp = self._fingerprint() if path is None else None
            try:
                # fallback=False: reloading must never silently regress
                # to an older generation — the current in-memory model IS
                # the fallback
                store, meta, _ = open_serving_store(target, self._kwargs,
                                                    fallback=False)
                blue = self.executor
                if (store_geometry(store.param)
                        != store_geometry(blue.store.param)
                        and self._server is not None):
                    gen = self._bluegreen_swap(blue, store)
                else:
                    gen = blue.swap_store(store)
            except Exception as e:
                # lint: ok(data-race) monotonic counter for #stats; stats()
                # must not block on _reload_mu held across loads
                self.reload_failures += 1
                from ..obs import counter
                counter("model_reload_failures_total",
                        "failed hot-reloads (old model kept)").inc()
                log.warning("model reload from %s failed; keeping the "
                            "current model: %s", target, e)
                return {"ok": False, "error": str(e)}
            # lint: ok(data-race) monotonic counter for #stats (see above)
            self.reloads += 1
            from ..obs import counter
            counter("model_reloads_total",
                    "successful model hot-reloads").inc()
            if fp is not None:
                self._cur = fp
            log.info("model reloaded from %s: generation %d",
                     meta["path"], gen)
            return {"ok": True, "model_generation": gen,
                    "path": meta["path"]}

    # ------------------------------------------------------- blue/green
    def _bluegreen_swap(self, blue, store) -> int:
        """Geometry-changing swap: build + warm a green executor, then
        retarget the server atomically. Runs on the reloading thread
        (watcher or a connection reader) — scoring keeps flowing through
        blue on the batcher thread the whole time. Any failure (corrupt
        warm, injected ``reload.warm`` fault) propagates to the reload
        failure path: green is dropped, blue keeps serving."""
        from concurrent.futures import ThreadPoolExecutor

        from .executor import PredictExecutor
        # lint: ok(data-race) status tag for #stats/#health: GIL-atomic
        # str assignment; stats() must not block on _reload_mu mid-warm
        self.swap_state = "warming"
        try:
            caps, keys = blue.warm_set()
            workers = max(1, min(self.warm_workers, len(keys) or 1))
            log.info("blue/green: warming %d buckets on %d threads for "
                     "geometry (V_dim=%d, hash_capacity=%d)", len(keys),
                     workers, store.param.V_dim,
                     store.param.hash_capacity)
            green = PredictExecutor(store)
            green.seed_caps(caps)

            def _warm_one(key):
                # chaos point: err aborts the swap (blue keeps serving),
                # delay_ms stretches the warm window (the drain-vs-
                # reload race tests live here)
                faultinject.fire("reload.warm")
                green.warm_bucket(key)

            t0 = time.monotonic()
            if workers == 1:
                for key in keys:
                    _warm_one(key)
            else:
                # independent XLA compilations release the GIL, so the
                # warm-set compiles overlap instead of queueing — the
                # swap window shrinks with the pool. Any worker failure
                # propagates out of the result iteration and aborts the
                # swap before the commit point below.
                with ThreadPoolExecutor(
                        max_workers=workers,
                        thread_name_prefix="bluegreen-warm") as pool:
                    for _ in pool.map(_warm_one, keys):
                        pass
            # lint: ok(data-race) gauge for #stats (see swap_state)
            self.last_warm_ms = (time.monotonic() - t0) * 1e3
            self.swap_state = "swapping"
            green.generation = blue.generation + 1
            self._server.swap_executor(green)
            # lint: ok(data-race) monotonic counter for #stats (see above)
            self.bluegreen_swaps += 1
            self._server.obs.counter(
                "serve_bluegreen_swaps_total",
                "geometry-changing blue/green executor swaps").inc()
            log.info("blue/green: swapped to generation %d (%d buckets "
                     "warm)", green.generation, len(keys))
            return green.generation
        finally:
            self.swap_state = "idle"

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {"reloads": self.reloads,
                "reload_failures": self.reload_failures,
                "bluegreen_swaps": self.bluegreen_swaps,
                "last_warm_ms": round(self.last_warm_ms, 3),
                "swap_state": self.swap_state}
