"""Shared endpoint health: one blacklist file the whole fleet reads.

Before this module, endpoint health was private per client: every
``ServeClient`` (and the router) re-discovered a dead replica through its
own consecutive-failure ejection — N clients x ``eject_after`` failed
connects per bad replica, each paying the timeout. This module makes the
first discovery fleet-wide: whoever ejects an endpoint appends a ``down``
mark to a shared **append-only, advisory-locked** file, and every other
reader (router backends, fresh clients) skips that endpoint without ever
dialing it.

Design constraints, in order:

- **Crash-safe under concurrent writers.** Marks are single JSON lines
  appended under ``fcntl.flock(LOCK_EX)`` with ``O_APPEND``; a writer
  dying mid-line can at worst leave one torn tail line, which readers
  skip (and the next compaction drops). There is no read-modify-write of
  shared state — the file is a log, the state is the fold over it.
- **Self-clearing.** A ``down`` mark carries its wall-clock timestamp and
  only suppresses the endpoint for ``down_s`` seconds — the same timed
  re-probe contract as the in-memory ejection (the first use after the
  window IS the probe). A client whose probe succeeds appends a ``clear``
  mark so the whole fleet un-ejects early instead of each waiting out its
  own copy of the window.
- **Bounded.** Past ``max_bytes`` the appender compacts under the same
  lock: the log is folded and rewritten (atomic rename) with only the
  marks that still matter.
- **Advisory everywhere.** A reader never blocks a writer and malformed
  or stale files degrade to "nothing is down" — shared health is an
  optimization over per-client discovery, never a correctness
  dependency (clients keep their own ejection state regardless).

Wall-clock (`time.time`) timestamps are deliberate: the file is shared
across processes (and potentially hosts over a shared filesystem), where
monotonic clocks don't compare.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import time
from typing import Dict, Optional, Tuple

try:
    from ..utils.locktrace import mutex
except ImportError:
    # tests/fleethealth_worker.py loads this file standalone (no
    # package) to drive two-process concurrent writers
    from threading import Lock as mutex

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None

log = logging.getLogger("difacto_tpu")

Endpoint = Tuple[str, int]


def _key(host: str, port: int) -> str:
    return f"{host}:{int(port)}"


class FleetHealth:
    """Reader/writer handle on one shared blacklist file.

    ``mark_down``/``mark_up`` append; ``down_endpoints``/``is_down``/
    ``down_remaining`` fold the log (cached on (mtime, size) so polling
    per connect attempt costs a stat, not a read). The file appears on
    first write — constructing a handle never touches the filesystem, so
    a client can be pointed at a path that no process has written yet.
    """

    def __init__(self, path: str, down_s: float = 5.0,
                 max_bytes: int = 256 * 1024):
        self.path = path
        self.down_s = down_s
        self.max_bytes = max_bytes
        # one handle is polled from every router/client thread: the
        # fold cache must swap (stamp, cache) atomically or a reader
        # can pair a fresh stamp with a stale fold
        self._cache_mu = mutex()
        self._cache_stamp: Optional[Tuple[float, int]] = None
        self._cache: Dict[str, Tuple[str, float]] = {}  # key -> (op, ts)

    # ---------------------------------------------------------- writing
    def _append(self, op: str, host: str, port: int) -> None:
        # wall clock by design: marks are compared across PROCESSES and
        # hosts through a shared file; monotonic clocks do not compare
        rec = json.dumps(
            {"ts": round(time.time(), 3),  # lint: ok(wall-clock)
             "op": op,
                          "ep": _key(host, port), "pid": os.getpid()},
                         separators=(",", ":")) + "\n"
        # open-then-lock can race a peer's compaction: if the path was
        # os.replace()d while we waited on the OLD inode's lock, our
        # append would land on the orphan and vanish — so after locking,
        # verify the fd still names the path, else reopen
        for _attempt in range(5):
            try:
                # O_RDWR (not O_WRONLY): the torn-tail check below
                # reads; O_APPEND still forces every write to the end
                fd = os.open(self.path,
                             os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
            except OSError as e:  # pragma: no cover - unwritable path
                log.warning("fleethealth: cannot open %s: %s",
                            self.path, e)
                return
            try:
                if fcntl is not None:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                try:
                    if os.fstat(fd).st_ino != os.stat(self.path).st_ino:
                        continue   # compacted under us; reopen fresh
                except OSError:
                    continue       # path vanished entirely; recreate
                # heal a torn tail: a writer that died mid-append left
                # no newline, and appending onto it would glue THIS
                # record into the garbage line too — one leading newline
                # contains the damage to the dead writer's line
                size = os.fstat(fd).st_size
                if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
                    os.write(fd, b"\n")
                os.write(fd, rec.encode())
                if os.fstat(fd).st_size > self.max_bytes:
                    self._compact_locked()
                return
            except OSError as e:  # pragma: no cover - disk full etc.
                log.warning("fleethealth: append to %s failed: %s",
                            self.path, e)
                return
            finally:
                os.close(fd)   # closing drops the flock

    def mark_down(self, host: str, port: int) -> None:
        """Record a consecutive-failure ejection for the whole fleet."""
        self._append("down", host, port)

    def mark_up(self, host: str, port: int) -> None:
        """A probe succeeded: clear the endpoint fleet-wide, early."""
        self._append("clear", host, port)

    def _compact_locked(self) -> None:
        """Rewrite the log as its fold (atomic rename), caller holds the
        lock. Only currently-down marks survive; clears and expired downs
        are the compactible majority."""
        downs = self._fold(self._read_lines())
        now = time.time()  # lint: ok(wall-clock) cross-process file ts
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for ep, (op, ts) in downs.items():
                if op == "down" and now - ts < self.down_s:
                    f.write(json.dumps(
                        {"ts": ts, "op": "down", "ep": ep,
                         "pid": os.getpid()},
                        separators=(",", ":")) + "\n")
        os.replace(tmp, self.path)
        with self._cache_mu:
            self._cache_stamp = None

    # ---------------------------------------------------------- reading
    def _read_lines(self) -> list:
        try:
            with open(self.path, "rb") as f:
                return f.read().splitlines()
        except OSError as e:
            if e.errno != errno.ENOENT:  # pragma: no cover
                log.warning("fleethealth: read %s failed: %s",
                            self.path, e)
            return []

    @staticmethod
    def _fold(lines: list) -> Dict[str, Tuple[str, float]]:
        """Latest mark per endpoint; torn/garbage lines are skipped (a
        writer may have died mid-append — the log survives it)."""
        state: Dict[str, Tuple[str, float]] = {}
        for ln in lines:
            try:
                rec = json.loads(ln)
                state[rec["ep"]] = (rec["op"], float(rec["ts"]))
            except (ValueError, KeyError, TypeError):
                continue
        return state

    def _state(self) -> Dict[str, Tuple[str, float]]:
        try:
            st = os.stat(self.path)
            stamp = (st.st_mtime, st.st_size)
        except OSError:
            with self._cache_mu:
                self._cache_stamp, self._cache = None, {}
                return self._cache
        with self._cache_mu:
            if stamp != self._cache_stamp:
                self._cache = self._fold(self._read_lines())
                self._cache_stamp = stamp
            return self._cache

    def down_endpoints(self) -> Dict[str, float]:
        """{'host:port': seconds_remaining} for every endpoint currently
        suppressed — a `down` mark younger than ``down_s`` with no later
        `clear`."""
        now = time.time()  # lint: ok(wall-clock) cross-process file ts
        out: Dict[str, float] = {}
        for ep, (op, ts) in self._state().items():
            remaining = self.down_s - (now - ts)
            if op == "down" and remaining > 0:
                out[ep] = remaining
        return out

    def stamp(self) -> Optional[Tuple[float, int]]:
        """The file's current ``(mtime, size)`` — one os.stat, no read.
        Long-lived holders (ServeClient) compare stamps per endpoint
        selection and re-fold only on change, so marks written AFTER
        they connected still reach them (the PR 6 seed-once bug)."""
        try:
            st = os.stat(self.path)
            return (st.st_mtime, st.st_size)
        except OSError:
            return None

    def down_remaining(self, host: str, port: int) -> float:
        """Seconds the endpoint stays suppressed (0.0 = not down)."""
        return self.down_endpoints().get(_key(host, port), 0.0)

    def is_down(self, host: str, port: int) -> bool:
        return self.down_remaining(host, port) > 0.0


def open_blacklist(blacklist, down_s: float = 5.0) -> Optional[FleetHealth]:
    """Coerce a constructor argument — None | path str | FleetHealth —
    into a handle; the one adapter client/router/loadgen all share."""
    if blacklist is None or isinstance(blacklist, FleetHealth):
        return blacklist
    return FleetHealth(str(blacklist), down_s=down_s)
