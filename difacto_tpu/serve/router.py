"""Thin routing tier: one address in front of a replica fleet.

PR 5's client-side failover works, but it scales per CLIENT: every
client holds the replica list, discovers dead replicas itself, and
balances only by accident (whichever endpoint it happens to sit on).
The router centralizes that: clients speak the exact same
libsvm/control wire protocol to ONE address, and the router

- **balances** rows across replicas with power-of-two-choices over live
  per-endpoint stats — two random live backends, send to the one with
  the lower (in-flight, recent-latency-EWMA) score. P2C is the standard
  load-balancing result: it gets within a constant of least-loaded
  while sampling only two queues, and never herds onto one backend the
  way stale least-loaded does;
- **retries the unanswered tail on a peer** exactly like
  ``ServeClient._failover``: backend responses are in request order, so
  a dropped backend connection splits the chunk at the exact answered
  boundary and only the tail is resent — to a DIFFERENT replica,
  immediately. Per-forward retry budgets exhausted across every backend
  degrade to explicit ``!shed`` backpressure (retryable), never a hang;
- **absorbs drain windows**: a replica mid-rotation answers ``!shed
  draining`` over a perfectly healthy connection, so connection-level
  failover alone would keep feeding it for the whole drain. The router
  reads the signal: the draining backend is side-stepped for a short
  window and the shed rows get ONE re-forward to a peer — a rolling
  restart behind the router costs clients neither errors nor sheds;
- **shares endpoint health**: ``eject_after`` consecutive failures
  eject a backend for ``reprobe_s`` (timed re-probe), and the ejection
  is written through the shared blacklist file (fleethealth.py) so
  every other router/client skips the endpoint without dialing it;
- serves **aggregated control lines** for the whole fleet: ``#health``
  (fleet-wide status + per-replica payloads), ``#stats`` (router
  counters + per-backend balance state + summed replica counters),
  ``#metrics`` (Prometheus text of the router registry, per-endpoint
  labeled).

Ordering contract: per client connection, responses come back in
request order — data rows are forwarded in arrival-order chunks (a
chunk closes at ``chunk`` rows, at a control line, or when the reader
has nothing more buffered), and control replies are emitted in line
with the rows around them.

``router.forward`` is a chaos injection point in the forward path
(utils/faultinject.py): ``err``/``close`` model a backend failing
mid-chunk and must surface as a peer retry, not a client error.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import parse_endpoints
from ..utils import faultinject
from .fleethealth import open_blacklist
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")


class _Backend:
    """Shared balance/health state for one replica endpoint (the
    connections themselves are per client handler — two client
    connections never interleave on one backend socket)."""

    __slots__ = ("host", "port", "in_flight", "ewma_ms", "fails",
                 "down_until", "rows", "ejections")

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.in_flight = 0
        self.ewma_ms = 0.0      # recent per-row latency, milliseconds
        self.fails = 0          # consecutive failures
        self.down_until = 0.0   # monotonic ejection deadline
        self.rows = 0           # rows answered by this backend
        self.ejections = 0

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"


class RouterServer:
    def __init__(self, endpoints, host: str = "127.0.0.1", port: int = 0,
                 chunk: int = 64, retries: int = 2, eject_after: int = 3,
                 reprobe_s: float = 5.0, blacklist=None,
                 timeout: float = 30.0, probe_timeout: float = 2.0,
                 drain_eject_s: float = 1.0):
        from ..obs import Registry
        self._backends = [_Backend(h, p)
                          for h, p in parse_endpoints(endpoints)]
        self.chunk = chunk
        self.retries = retries
        self.eject_after = eject_after
        self.reprobe_s = reprobe_s
        self.timeout = timeout
        self.probe_timeout = probe_timeout
        self.drain_eject_s = drain_eject_s
        self.blacklist = open_blacklist(blacklist, down_s=reprobe_s)
        self._rng = random.Random(0x20072)
        self.obs = Registry(enabled=True)
        self._rows_c = self.obs.counter(
            "router_rows_forwarded_total",
            "rows answered through the router, per backend endpoint")
        self._retry_c = self.obs.counter(
            "router_retries_total",
            "chunk tails retried on a peer after a backend failure")
        self._shed_c = self.obs.counter(
            "router_shed_total",
            "rows answered !shed because no backend was available")
        self._err_c = self.obs.counter(
            "router_errors_total", "rows rejected at the router")
        self._mu = mutex()               # backend stats
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._alive = False
        self._closed = False
        self._done = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conn_threads: list = []
        self._cmu = mutex()              # connection bookkeeping

    # ---------------------------------------------------------- control
    def start(self) -> "RouterServer":
        # lint: ok(data-race) monotonic stop flag; accept loop re-checks
        self._alive = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True)
        self._accept_thread.start()
        log.info("routing %s:%d -> %s", self.host, self.port,
                 ",".join(b.key for b in self._backends))
        return self

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def close(self) -> None:
        with self._cmu:
            if self._closed:
                return
            self._closed = True
        self._alive = False
        self._done.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        if self._accept_thread is not None:
            self._accept_thread.join()
            self._accept_thread = None
        with self._cmu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        # snapshot under _cmu: the accept loop appends under the same
        # lock until its join above
        with self._cmu:
            threads = list(self._conn_threads)
            self._conn_threads = []
        for t in threads:
            t.join()

    # ------------------------------------------------------- accept loop
    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(None)
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:  # pragma: no cover
                pass
            with self._cmu:
                self._conns.add(conn)
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="router-conn", daemon=True)
            t.start()
            with self._cmu:
                self._conn_threads.append(t)

    # ---------------------------------------------------- client handler
    def _handle(self, conn: socket.socket) -> None:
        """Order-preserving per-connection loop: a reader thread feeds a
        queue; this thread folds consecutive data rows into chunks,
        forwards them, and interleaves control replies in arrival
        order."""
        q: "queue.Queue" = queue.Queue()

        def reader() -> None:
            try:
                rfile = conn.makefile("rb")
                for line in rfile:
                    line = line.strip()
                    if line:
                        q.put(line)
            except (OSError, ValueError):
                pass
            finally:
                q.put(None)

        rt = threading.Thread(target=reader, name="router-conn-reader",
                              daemon=True)
        rt.start()
        pool: Dict[int, Tuple[socket.socket, object]] = {}
        try:
            eof = False
            while not eof:
                item = q.get()
                if item is None:
                    break
                if item.startswith(b"#"):
                    conn.sendall(self._control(item))
                    continue
                # fold the contiguous data-row run the reader has already
                # buffered (bounded by chunk) into one backend forward
                rows = [item]
                carry = None
                while len(rows) < self.chunk:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        eof = True
                        break
                    if nxt.startswith(b"#"):
                        carry = nxt
                        break
                    rows.append(nxt)
                conn.sendall(b"".join(self._forward(rows, pool)))
                if carry is not None:
                    conn.sendall(self._control(carry))
        except OSError:   # client went away mid-reply
            pass
        finally:
            for s, rf in pool.values():
                try:
                    rf.close()
                    s.close()
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass
            with self._cmu:
                self._conns.discard(conn)
            rt.join()

    # -------------------------------------------------------- balancing
    def _refresh_blacklist(self) -> None:
        """Fold fleet-wide down marks into the local ejection windows, so
        an ejection ANY client recorded suppresses the backend here too."""
        if self.blacklist is None:
            return
        downs = self.blacklist.down_endpoints()
        if not downs:
            return
        now = time.monotonic()
        with self._mu:
            for b in self._backends:
                rem = downs.get(b.key, 0.0)
                if rem > 0:
                    b.down_until = max(b.down_until, now + rem)

    def _pick(self, attempts: Dict[int, int]) -> Optional[int]:
        """Power-of-two-choices over live backends still inside this
        forward's retry budget; all-ejected falls back to the least-
        recently-ejected (the router never deadlocks itself into "no
        replicas" while one might answer). None = budget exhausted."""
        self._refresh_blacklist()
        cands = [i for i in range(len(self._backends))
                 if attempts.get(i, 0) <= self.retries]
        if not cands:
            return None
        now = time.monotonic()
        with self._mu:
            live = [i for i in cands
                    if self._backends[i].down_until <= now]
            if not live:
                return min(cands,
                           key=lambda i: self._backends[i].down_until)
            if len(live) == 1:
                return live[0]
            a, b = self._rng.sample(live, 2)
            ba, bb = self._backends[a], self._backends[b]
            return a if (ba.in_flight, ba.ewma_ms) <= \
                (bb.in_flight, bb.ewma_ms) else b

    def _conn(self, pool: dict, i: int):
        got = pool.get(i)
        if got is not None:
            return got
        b = self._backends[i]
        s = socket.create_connection((b.host, b.port),
                                     timeout=self.timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover
            pass
        pool[i] = (s, s.makefile("rb"))
        return pool[i]

    def _drop(self, pool: dict, i: int) -> None:
        got = pool.pop(i, None)
        if got is not None:
            try:
                got[1].close()
                got[0].close()
            except OSError:
                pass

    def _note_success(self, i: int, rows: int, dt_s: float) -> None:
        b = self._backends[i]
        with self._mu:
            was_down = b.down_until > 0.0 or b.fails >= self.eject_after
            b.fails = 0
            b.down_until = 0.0
            b.rows += rows
            per_row_ms = dt_s * 1e3 / max(rows, 1)
            b.ewma_ms = (per_row_ms if b.ewma_ms == 0.0
                         else 0.8 * b.ewma_ms + 0.2 * per_row_ms)
        self._rows_c.labels(endpoint=b.key).inc(rows)
        if was_down and self.blacklist is not None:
            self.blacklist.mark_up(b.host, b.port)

    def _note_failure(self, i: int, attempts: Dict[int, int],
                      err: BaseException) -> None:
        b = self._backends[i]
        attempts[i] = attempts.get(i, 0) + 1
        self._retry_c.inc()
        ejected = False
        with self._mu:
            b.fails += 1
            if b.fails >= self.eject_after:
                b.down_until = time.monotonic() + self.reprobe_s
                b.ejections += 1
                ejected = True
        if ejected:
            log.warning("router: ejecting backend %s for %.1fs (%s)",
                        b.key, self.reprobe_s, err)
            if self.blacklist is not None:
                self.blacklist.mark_down(b.host, b.port)

    def _note_draining(self, i: int) -> None:
        """The backend said ``!shed draining``: it is mid-rotation, not
        dead — side-step it briefly (no blacklist write, no ejection
        count; its successor inherits the endpoint within seconds)."""
        b = self._backends[i]
        with self._mu:
            b.down_until = max(b.down_until,
                               time.monotonic() + self.drain_eject_s)

    def _retry_shed(self, rows: List[bytes], out: List[bytes],
                    pool: dict) -> List[bytes]:
        """One re-forward of the rows a backend shed: under a rolling
        restart the shed came from a draining replica (now side-stepped
        by _note_draining), so the peer pass usually converts the whole
        drain window into ordinary answers. Positions are exact — one
        response line per row — so the splice preserves ordering."""
        idx = [k for k, line in enumerate(out)
               if line.startswith(b"!shed")]
        if not idx:
            return out
        sub = self._forward([rows[k] for k in idx], pool,
                            _retry_shed=False)
        for k, line in zip(idx, sub):
            out[k] = line
        return out

    # ---------------------------------------------------------- forward
    def _forward(self, rows: List[bytes], pool: dict,
                 _retry_shed: bool = True) -> List[bytes]:
        """Forward one chunk; returns one newline-terminated response
        line per row, in order. Backend failures resend the unanswered
        tail on a peer; exhausting every backend's budget answers the
        remainder ``!shed`` (retryable backpressure — the fleet may be
        mid-rotation, the rows are not wrong)."""
        pending = [r + b"\n" for r in rows]
        out: List[bytes] = []
        attempts: Dict[int, int] = {}
        while pending:
            i = self._pick(attempts)
            if i is None:
                self._shed_c.inc(len(pending))
                out.extend([b"!shed router: no backend available\n"]
                           * len(pending))
                return out
            answered = 0
            b = self._backends[i]
            n = len(pending)
            with self._mu:
                b.in_flight += n
            try:
                # chaos point: ``close`` tears this backend connection
                # down mid-chunk, ``err`` raises — both must surface as
                # a tail retry on a peer, never a client-visible error
                kind = faultinject.fire("router.forward")
                if kind == "close":
                    self._drop(pool, i)
                    raise ConnectionError(
                        "injected router.forward close")
                faultinject.act_default(kind)
                s, rf = self._conn(pool, i)
                t0 = time.monotonic()
                s.sendall(b"".join(pending))
                saw_draining = False
                for _ in range(len(pending)):
                    resp = rf.readline()
                    if not resp:
                        raise ConnectionError(
                            "backend closed the connection")
                    if resp.startswith(b"!shed draining"):
                        saw_draining = True
                    out.append(resp)
                    answered += 1
                self._note_success(i, answered,
                                   time.monotonic() - t0)
                if saw_draining:
                    self._note_draining(i)
                return (self._retry_shed(rows, out, pool)
                        if _retry_shed else out)
            except (OSError, ConnectionError) as e:
                # in-order responses: answered rows in ``out`` stand
                # (credited to this backend); only the tail travels to
                # a peer. Crediting does NOT clear the failure streak —
                # _note_failure below still advances the ejection.
                pending = pending[answered:]
                if answered:
                    with self._mu:
                        b.rows += answered
                    self._rows_c.labels(endpoint=b.key).inc(answered)
                self._drop(pool, i)
                self._note_failure(i, attempts, e)
            finally:
                with self._mu:
                    b.in_flight -= n
        return out

    # ------------------------------------------------------ aggregation
    def _probe_json(self, b: _Backend, line: bytes) -> dict:
        """One-shot control call on a fresh connection (fresh on purpose:
        under a SO_REUSEPORT takeover it reaches whichever replica
        currently owns fresh connections — the thing a health poll is
        supposed to measure)."""
        s = socket.create_connection((b.host, b.port),
                                     timeout=self.probe_timeout)
        try:
            s.sendall(line + b"\n")
            rf = s.makefile("rb")
            resp = rf.readline()
            if not resp or resp.startswith(b"!err"):
                raise ConnectionError(
                    resp.rstrip(b"\n").decode() or "connection closed")
            return json.loads(resp)
        finally:
            try:
                s.close()
            except OSError:  # pragma: no cover
                pass

    def backends_snapshot(self) -> List[dict]:
        now = time.monotonic()
        with self._mu:
            return [{"endpoint": b.key, "in_flight": b.in_flight,
                     "ewma_ms": round(b.ewma_ms, 3), "fails": b.fails,
                     "ejected": b.down_until > now, "rows": b.rows,
                     "ejections": b.ejections}
                    for b in self._backends]

    def health_snapshot(self) -> dict:
        """Fleet-wide #health: ready while ANY replica is ready (that is
        what a router buys you), per-replica payloads attached so one
        poll shows which replica is the problem."""
        replicas = []
        ready = queue_depth = 0
        for b in self._backends:
            try:
                h = self._probe_json(b, b"#health")
            except (OSError, ConnectionError, ValueError) as e:
                replicas.append({"endpoint": b.key, "error": str(e)})
                continue
            replicas.append(dict(h, endpoint=b.key))
            if h.get("status") == "ready":
                ready += 1
            queue_depth += int(h.get("queue_depth", 0))
        return {"status": "ready" if ready else "down",
                "router": True, "pid": os.getpid(),
                "server_id": f"router.{os.getpid()}.{id(self):x}",
                "replicas_live": ready,
                "replicas_total": len(self._backends),
                "queue_depth": queue_depth,
                "replicas": replicas}

    def stats_snapshot(self) -> dict:
        """Router counters + balance state + the fleet's summed serving
        counters (each replica's #stats, best-effort)."""
        fleet: Dict[str, float] = {}
        replicas = []
        for b in self._backends:
            try:
                st = self._probe_json(b, b"#stats")
            except (OSError, ConnectionError, ValueError) as e:
                replicas.append({"endpoint": b.key, "error": str(e)})
                continue
            replicas.append(dict(st, endpoint=b.key))
            for k in ("requests", "responses", "shed", "errors",
                      "batches"):
                if k in st:
                    fleet[k] = fleet.get(k, 0) + st[k]
        with self._mu:
            rows = sum(b.rows for b in self._backends)
        return {"router": True,
                "rows": rows,
                "retries": int(self._retry_c.value()),
                "shed": int(self._shed_c.value()),
                "errors": int(self._err_c.value()),
                "backends": self.backends_snapshot(),
                "fleet": fleet, "replicas": replicas}

    def metrics_text(self) -> str:
        """Prometheus text for ``#metrics``: the router registry
        (per-endpoint labeled forward counters + balance gauges) merged
        with the process-global registry (fault fires)."""
        from ..obs import REGISTRY, merge_into, render_prometheus
        now = time.monotonic()
        up = self.obs.gauge("router_backend_up",
                            "1 while the backend is not ejected")
        infl = self.obs.gauge("router_backend_in_flight",
                              "rows currently forwarded to the backend")
        ewma = self.obs.gauge("router_backend_ewma_ms",
                              "recent per-row backend latency (EWMA)")
        with self._mu:
            for b in self._backends:
                up.labels(endpoint=b.key).set(
                    0.0 if b.down_until > now else 1.0)
                infl.labels(endpoint=b.key).set(b.in_flight)
                ewma.labels(endpoint=b.key).set(b.ewma_ms)
        snap = merge_into(self.obs.snapshot(), REGISTRY.snapshot())
        return render_prometheus(snap)

    def _control(self, line: bytes) -> bytes:
        if line == b"#health":
            return (json.dumps(self.health_snapshot()) + "\n").encode()
        if line == b"#stats":
            return (json.dumps(self.stats_snapshot()) + "\n").encode()
        if line == b"#metrics":
            # multi-line payload, blank-line terminated (server.py
            # contract — ServeClient.metrics() works unchanged)
            return self.metrics_text().encode() + b"\n"
        self._err_c.inc()
        return b"!err router: unsupported control %s\n" % line[:32]
