"""Thin routing tier: one address in front of a replica fleet.

PR 5's client-side failover works, but it scales per CLIENT: every
client holds the replica list, discovers dead replicas itself, and
balances only by accident (whichever endpoint it happens to sit on).
The router centralizes that: clients speak the exact same
libsvm/control wire protocol to ONE address, and the router

- **balances** rows across replicas with power-of-two-choices over live
  per-endpoint stats — two random live backends, send to the one with
  the lower (in-flight, recent-latency-EWMA) score. P2C is the standard
  load-balancing result: it gets within a constant of least-loaded
  while sampling only two queues, and never herds onto one backend the
  way stale least-loaded does;
- **retries the unanswered tail on a peer** exactly like
  ``ServeClient._failover``: backend responses are in request order, so
  a dropped backend connection splits the chunk at the exact answered
  boundary and only the tail is resent — to a DIFFERENT replica,
  immediately. Per-forward retry budgets exhausted across every backend
  degrade to explicit ``!shed`` backpressure (retryable), never a hang;
- **absorbs drain windows**: a replica mid-rotation answers ``!shed
  draining`` over a perfectly healthy connection, so connection-level
  failover alone would keep feeding it for the whole drain. The router
  reads the signal: the draining backend is side-stepped for a short
  window and the shed rows get ONE re-forward to a peer — a rolling
  restart behind the router costs clients neither errors nor sheds;
- **shares endpoint health**: ``eject_after`` consecutive failures
  eject a backend for ``reprobe_s`` (timed re-probe), and the ejection
  is written through the shared blacklist file (fleethealth.py) so
  every other router/client skips the endpoint without dialing it;
- serves **aggregated control lines** for the whole fleet: ``#health``
  (fleet-wide status + per-replica payloads), ``#stats`` (router
  counters + per-backend balance state + summed replica counters),
  ``#metrics`` (Prometheus text of the router registry, per-endpoint
  labeled).

Ordering contract: per client connection, responses come back in
request order — data rows are forwarded in arrival-order chunks (a
chunk closes at ``chunk`` rows, at a control line, or when the reader
has nothing more buffered), and control replies are emitted in line
with the rows around them.

``router.forward`` is a chaos injection point in the forward path
(utils/faultinject.py): ``err``/``close`` model a backend failing
mid-chunk and must surface as a peer retry, not a client error.

Router HA (ISSUE 18): with ``takeover=True`` the listener binds
``SO_REUSEPORT``, so N router processes share ONE advertised port — the
kernel spreads fresh client connections across the group, every member
folds the same ``FleetHealth`` blacklist, and one member dying loses
only the connections it held (clients fail over and reconnect onto a
surviving member). Routers roll like replicas: ``#handoff
[ready_file]`` waits for the successor's ready file, then ``drain()``
stops accepting (fresh connections shift to the group), finishes the
chunk in flight on every held connection and closes at a line boundary
— a clean EOF the failover client answers by resending its unanswered
tail elsewhere. ``router.takeover`` is the chaos point on that path.

Balance policies: ``balance="p2c"`` (default, above) or
``balance="affinity"`` — consistent-hash rows by their leading feature
key so a key's requests pin to one replica's warm cache, mirroring the
store's ``hash_slots`` + ``fs_shard_bounds`` arithmetic when
``affinity_capacity`` is set (the replica whose fs-shard owns the key
serves it). The owner being ejected/draining falls back to p2c —
affinity is cache placement, never correctness (every replica serves
the full model, so routed scores stay byte-identical regardless).

Elastic membership: ``#backends [add|remove host:port]`` adjusts the
ring at runtime (the autoscaler's nudge), and an ``endpoints_file``
re-folds on ``(mtime, size)`` change — durable membership a relaunched
router recovers without having seen the nudges.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..config import parse_endpoints
from ..utils import faultinject
from .fleethealth import open_blacklist
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")


class _Backend:
    """Shared balance/health state for one replica endpoint (the
    connections themselves are per client handler — two client
    connections never interleave on one backend socket)."""

    __slots__ = ("host", "port", "in_flight", "ewma_ms", "fails",
                 "down_until", "rows", "ejections", "removed")

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.in_flight = 0
        self.ewma_ms = 0.0      # recent per-row latency, milliseconds
        self.fails = 0          # consecutive failures
        self.down_until = 0.0   # monotonic ejection deadline
        self.rows = 0           # rows answered by this backend
        self.ejections = 0
        self.removed = False    # tombstone (indices stay stable)

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"


class RouterServer:
    def __init__(self, endpoints, host: str = "127.0.0.1", port: int = 0,
                 chunk: int = 64, retries: int = 2, eject_after: int = 3,
                 reprobe_s: float = 5.0, blacklist=None,
                 timeout: float = 30.0, probe_timeout: float = 2.0,
                 drain_eject_s: float = 1.0, takeover: bool = False,
                 ready_file: str = "", handoff_wait_s: float = 30.0,
                 balance: str = "p2c", affinity_capacity: int = 0,
                 endpoints_file: str = ""):
        from ..obs import Registry
        if balance not in ("p2c", "affinity"):
            raise ValueError(f"unknown balance policy {balance!r} "
                             "(want p2c or affinity)")
        self._backends = ([_Backend(h, p)
                           for h, p in parse_endpoints(endpoints)]
                          if endpoints else [])
        self.chunk = chunk
        self.retries = retries
        self.eject_after = eject_after
        self.reprobe_s = reprobe_s
        self.timeout = timeout
        self.probe_timeout = probe_timeout
        self.drain_eject_s = drain_eject_s
        self.takeover = bool(takeover)
        self.ready_file = ready_file
        self.handoff_wait_s = handoff_wait_s
        self.balance = balance
        self.affinity_capacity = int(affinity_capacity)
        self.endpoints_file = endpoints_file
        self.blacklist = open_blacklist(blacklist, down_s=reprobe_s)
        self._rng = random.Random(0x20072)
        self.obs = Registry(enabled=True)
        self._rows_c = self.obs.counter(
            "router_rows_forwarded_total",
            "rows answered through the router, per backend endpoint")
        self._retry_c = self.obs.counter(
            "router_retries_total",
            "chunk tails retried on a peer after a backend failure")
        self._shed_c = self.obs.counter(
            "router_shed_total",
            "rows answered !shed because no backend was available")
        self._err_c = self.obs.counter(
            "router_errors_total", "rows rejected at the router")
        self._aff_hit_c = self.obs.counter(
            "router_affinity_hits_total",
            "affinity forwards that landed on the ring owner")
        self._aff_miss_c = self.obs.counter(
            "router_affinity_misses_total",
            "affinity forwards diverted off the owner (ejected/draining)")
        self._mu = mutex()               # backend stats + membership
        self._eps_stamp: Optional[tuple] = None
        self._eps_next_poll = 0.0
        # SO_REUSEPORT group bind: N routers share this port; fresh
        # connections hash across whichever members still listen
        self._sock = socket.create_server((host, port),
                                          reuse_port=takeover)
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._alive = False
        self._closed = False
        self._draining = False
        self.successor_ready = False
        self._successor_file: Optional[str] = None
        self._handoff_thread: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conn_threads: list = []
        self._cmu = mutex()              # connection + handoff state
        self._refresh_endpoints(force=True)
        if not self._backends:
            raise ValueError(
                "router needs endpoints (inline or endpoints_file)")

    # ---------------------------------------------------------- control
    def start(self) -> "RouterServer":
        # lint: ok(data-race) monotonic stop flag; accept loop re-checks
        self._alive = True
        # lint: ok(data-race) written once in start(); drain() only runs
        # after start() returned (callers hold the instance)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True)
        self._accept_thread.start()
        log.info("routing %s:%d -> %s", self.host, self.port,
                 ",".join(b.key for b in self._backends))
        return self

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def drain(self) -> None:
        """Leave the SO_REUSEPORT group gracefully: close the listener
        (the kernel shifts fresh connections onto the surviving
        members), finish the chunk in flight on every held connection,
        and close each at a line boundary — the failover client sees a
        clean EOF and resends its unanswered tail on a reconnect that
        lands on a group peer."""
        with self._cmu:
            if self._draining or self._closed:
                return
            self._draining = True
        self._alive = False
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        t = self._accept_thread
        if t is not None:
            t.join()
            self._accept_thread = None
        with self._cmu:
            threads = list(self._conn_threads)
        for t in threads:
            t.join()
        self.close()

    def close(self) -> None:
        with self._cmu:
            if self._closed:
                return
            self._closed = True
        self._alive = False
        self._done.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        if self._accept_thread is not None:
            self._accept_thread.join()
            self._accept_thread = None
        with self._cmu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        # snapshot under _cmu: the accept loop appends under the same
        # lock until its join above
        with self._cmu:
            threads = list(self._conn_threads)
            self._conn_threads = []
        for t in threads:
            t.join()

    # ------------------------------------------------------- accept loop
    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(None)
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:  # pragma: no cover
                pass
            with self._cmu:
                self._conns.add(conn)
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="router-conn", daemon=True)
            t.start()
            with self._cmu:
                self._conn_threads.append(t)

    # ---------------------------------------------------- client handler
    def _handle(self, conn: socket.socket) -> None:
        """Order-preserving per-connection loop: a reader thread feeds a
        queue; this thread folds consecutive data rows into chunks,
        forwards them, and interleaves control replies in arrival
        order."""
        q: "queue.Queue" = queue.Queue()

        def reader() -> None:
            try:
                rfile = conn.makefile("rb")
                for line in rfile:
                    line = line.strip()
                    if line:
                        q.put(line)
            except (OSError, ValueError):
                pass
            finally:
                q.put(None)

        rt = threading.Thread(target=reader, name="router-conn-reader",
                              daemon=True)
        rt.start()
        pool: Dict[int, Tuple[socket.socket, object]] = {}
        forward = (self._forward_affinity if self.balance == "affinity"
                   else self._forward)
        try:
            eof = False
            while not eof:
                try:
                    item = q.get(timeout=0.25)
                except queue.Empty:
                    # idle moment: a draining router leaves here — the
                    # connection closes at a line boundary, nothing owed
                    if self._drain_pending():
                        break
                    continue
                if item is None:
                    break
                if item.startswith(b"#"):
                    conn.sendall(self._control(item))
                    continue
                # fold the contiguous data-row run the reader has already
                # buffered (bounded by chunk) into one backend forward
                rows = [item]
                carry = None
                while len(rows) < self.chunk:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        eof = True
                        break
                    if nxt.startswith(b"#"):
                        carry = nxt
                        break
                    rows.append(nxt)
                conn.sendall(b"".join(forward(rows, pool)))
                if carry is not None:
                    conn.sendall(self._control(carry))
                if self._drain_pending():
                    # the chunk in flight was answered; a pipelining
                    # client never pins a draining router past one chunk
                    break
        except OSError:   # client went away mid-reply
            pass
        finally:
            for s, rf in pool.values():
                try:
                    rf.close()
                    s.close()
                except OSError:
                    pass
            try:
                # shutdown (not just close) so the blocked reader thread
                # wakes with EOF when WE end the connection (drain path)
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._cmu:
                self._conns.discard(conn)
            rt.join()

    def _drain_pending(self) -> bool:
        with self._cmu:
            return self._draining or self._closed

    # -------------------------------------------------------- balancing
    def _refresh_blacklist(self) -> None:
        """Fold fleet-wide down marks into the local ejection windows, so
        an ejection ANY client recorded suppresses the backend here too."""
        if self.blacklist is None:
            return
        downs = self.blacklist.down_endpoints()
        if not downs:
            return
        now = time.monotonic()
        with self._mu:
            for b in self._backends:
                rem = downs.get(b.key, 0.0)
                if rem > 0:
                    b.down_until = max(b.down_until, now + rem)

    # ------------------------------------------------------- membership
    def _live_backends(self) -> List[_Backend]:
        with self._mu:
            return [b for b in self._backends if not b.removed]

    def _add_backend(self, host: str, port: int) -> None:
        """Join (or un-tombstone) an endpoint. The backend list is
        append-only — indices held by in-flight forwards stay valid."""
        key = f"{host}:{int(port)}"
        with self._mu:
            for b in self._backends:
                if b.key == key:
                    b.removed = False
                    return
            self._backends.append(_Backend(host, port))
        log.info("router: backend %s joined the ring", key)

    def _remove_backend(self, host: str, port: int) -> None:
        key = f"{host}:{int(port)}"
        with self._mu:
            for b in self._backends:
                if b.key == key and not b.removed:
                    b.removed = True
                    log.info("router: backend %s left the ring", key)

    def _refresh_endpoints(self, force: bool = False) -> None:
        """Durable group membership: when an ``endpoints_file`` is
        configured, a ``(mtime, size)`` change re-folds the file into
        the backend ring (one ``host:port`` per whitespace-separated
        token) — a relaunched router recovers autoscaler decisions it
        never saw as ``#backends`` nudges. One os.stat per check,
        throttled to ~2/s off the hot path."""
        if not self.endpoints_file:
            return
        now = time.monotonic()
        with self._mu:
            if not force and now < self._eps_next_poll:
                return
            self._eps_next_poll = now + 0.5
        try:
            st = os.stat(self.endpoints_file)
        except OSError:
            return
        stamp = (st.st_mtime, st.st_size)
        with self._mu:
            if stamp == self._eps_stamp:
                return
            self._eps_stamp = stamp
        try:
            with open(self.endpoints_file) as f:
                toks = [t for t in f.read().split() if t]
            eps = parse_endpoints(",".join(toks)) if toks else []
        except (OSError, ValueError) as e:
            log.warning("router: unreadable endpoints file %s (%s)",
                        self.endpoints_file, e)
            return
        want = {f"{h}:{int(p)}" for h, p in eps}
        for h, p in eps:
            self._add_backend(h, p)
        with self._mu:
            stale = [b for b in self._backends
                     if b.key not in want and not b.removed]
            for b in stale:
                b.removed = True

    def _pick(self, attempts: Dict[int, int],
              prefer: Optional[int] = None) -> Optional[int]:
        """Power-of-two-choices over live backends still inside this
        forward's retry budget; all-ejected falls back to the least-
        recently-ejected (the router never deadlocks itself into "no
        replicas" while one might answer). None = budget exhausted.
        ``prefer`` (affinity owner) wins while it is live and untried —
        after its first failure the pick degrades to plain p2c."""
        self._refresh_endpoints()
        self._refresh_blacklist()
        now = time.monotonic()
        with self._mu:
            cands = [i for i in range(len(self._backends))
                     if not self._backends[i].removed
                     and attempts.get(i, 0) <= self.retries]
            if not cands:
                return None
            live = [i for i in cands
                    if self._backends[i].down_until <= now]
            if prefer is not None and prefer in live \
                    and attempts.get(prefer, 0) == 0:
                return prefer
            if not live:
                return min(cands,
                           key=lambda i: self._backends[i].down_until)
            if len(live) == 1:
                return live[0]
            a, b = self._rng.sample(live, 2)
            ba, bb = self._backends[a], self._backends[b]
            return a if (ba.in_flight, ba.ewma_ms) <= \
                (bb.in_flight, bb.ewma_ms) else b

    def _conn(self, pool: dict, i: int):
        got = pool.get(i)
        if got is not None:
            return got
        b = self._backends[i]
        s = socket.create_connection((b.host, b.port),
                                     timeout=self.timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover
            pass
        pool[i] = (s, s.makefile("rb"))
        return pool[i]

    def _drop(self, pool: dict, i: int) -> None:
        got = pool.pop(i, None)
        if got is not None:
            try:
                got[1].close()
                got[0].close()
            except OSError:
                pass

    def _note_success(self, i: int, rows: int, dt_s: float) -> None:
        b = self._backends[i]
        with self._mu:
            was_down = b.down_until > 0.0 or b.fails >= self.eject_after
            b.fails = 0
            b.down_until = 0.0
            b.rows += rows
            per_row_ms = dt_s * 1e3 / max(rows, 1)
            b.ewma_ms = (per_row_ms if b.ewma_ms == 0.0
                         else 0.8 * b.ewma_ms + 0.2 * per_row_ms)
        self._rows_c.labels(endpoint=b.key).inc(rows)
        if was_down and self.blacklist is not None:
            self.blacklist.mark_up(b.host, b.port)

    def _note_failure(self, i: int, attempts: Dict[int, int],
                      err: BaseException) -> None:
        b = self._backends[i]
        attempts[i] = attempts.get(i, 0) + 1
        self._retry_c.inc()
        ejected = False
        with self._mu:
            b.fails += 1
            if b.fails >= self.eject_after:
                b.down_until = time.monotonic() + self.reprobe_s
                b.ejections += 1
                ejected = True
        if ejected:
            log.warning("router: ejecting backend %s for %.1fs (%s)",
                        b.key, self.reprobe_s, err)
            if self.blacklist is not None:
                self.blacklist.mark_down(b.host, b.port)

    def _note_draining(self, i: int) -> None:
        """The backend said ``!shed draining``: it is mid-rotation, not
        dead — side-step it briefly (no blacklist write, no ejection
        count; its successor inherits the endpoint within seconds)."""
        b = self._backends[i]
        with self._mu:
            b.down_until = max(b.down_until,
                               time.monotonic() + self.drain_eject_s)

    def _retry_shed(self, rows: List[bytes], out: List[bytes],
                    pool: dict) -> List[bytes]:
        """One re-forward of the rows a backend shed: under a rolling
        restart the shed came from a draining replica (now side-stepped
        by _note_draining), so the peer pass usually converts the whole
        drain window into ordinary answers. Positions are exact — one
        response line per row — so the splice preserves ordering."""
        idx = [k for k, line in enumerate(out)
               if line.startswith(b"!shed")]
        if not idx:
            return out
        sub = self._forward([rows[k] for k in idx], pool,
                            _retry_shed=False)
        for k, line in zip(idx, sub):
            out[k] = line
        return out

    # --------------------------------------------------------- affinity
    def _affinity_key(self, row: bytes) -> int:
        """Consistent-hash key of a libsvm row (``label idx:val ...``):
        its leading feature index. Per-key/per-user request streams put
        the identifying feature first, so the whole stream pins to one
        replica's warm cache and fs-shard."""
        parts = row.split(None, 2)
        if len(parts) < 2:
            return 0
        tok = parts[1].split(b":", 1)[0]
        try:
            return int(tok)
        except ValueError:
            return zlib.crc32(tok)

    def _affinity_owner(self, row: bytes, ring: List[int]) -> int:
        """Backend index that owns the row's key. With
        ``affinity_capacity`` set this mirrors the store's hashed-slot
        plus contiguous-range arithmetic (store/local.py ``hash_slots``,
        parallel/mesh.py ``fs_shard_bounds``): slot = key %% (cap-1) + 1
        and shard i owns slots [i*cap/n, (i+1)*cap/n) — the row lands on
        the replica whose fs-shard holds its leading key. capacity=0
        hashes the key straight onto the ring (splitmix64 finalizer, so
        adjacent integer keys spread)."""
        n = len(ring)
        key = self._affinity_key(row)
        cap = self.affinity_capacity
        if cap > 1:
            slot = key % (cap - 1) + 1
            return ring[min(slot * n // cap, n - 1)]
        z = (key + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return ring[(z ^ (z >> 31)) % n]

    def _forward_affinity(self, rows: List[bytes],
                          pool: dict) -> List[bytes]:
        """Partition the chunk by ring owner, forward each partition
        with its owner preferred, splice responses back into arrival
        order (positions are exact — ``_forward`` answers one line per
        row, always). Owner ejected/draining degrades that partition to
        p2c (counted as affinity misses), never to an error."""
        with self._mu:
            ring = [i for i, b in enumerate(self._backends)
                    if not b.removed]
        if not ring:
            return self._forward(rows, pool)
        groups: Dict[int, List[int]] = {}
        for k, r in enumerate(rows):
            groups.setdefault(self._affinity_owner(r, ring),
                              []).append(k)
        out: List[bytes] = [b""] * len(rows)
        for owner, ks in sorted(groups.items()):
            sub = self._forward([rows[k] for k in ks], pool,
                                prefer=owner)
            for k, resp in zip(ks, sub):
                out[k] = resp
        return out

    # ---------------------------------------------------------- forward
    def _forward(self, rows: List[bytes], pool: dict,
                 _retry_shed: bool = True,
                 prefer: Optional[int] = None) -> List[bytes]:
        """Forward one chunk; returns one newline-terminated response
        line per row, in order. Backend failures resend the unanswered
        tail on a peer; exhausting every backend's budget answers the
        remainder ``!shed`` (retryable backpressure — the fleet may be
        mid-rotation, the rows are not wrong)."""
        pending = [r + b"\n" for r in rows]
        out: List[bytes] = []
        attempts: Dict[int, int] = {}
        first_pick = prefer is not None
        while pending:
            i = self._pick(attempts, prefer)
            if first_pick and i is not None:
                (self._aff_hit_c if i == prefer
                 else self._aff_miss_c).inc(len(pending))
                first_pick = False
            if i is None:
                self._shed_c.inc(len(pending))
                out.extend([b"!shed router: no backend available\n"]
                           * len(pending))
                return out
            answered = 0
            b = self._backends[i]
            n = len(pending)
            with self._mu:
                b.in_flight += n
            try:
                # chaos point: ``close`` tears this backend connection
                # down mid-chunk, ``err`` raises — both must surface as
                # a tail retry on a peer, never a client-visible error
                kind = faultinject.fire("router.forward")
                if kind == "close":
                    self._drop(pool, i)
                    raise ConnectionError(
                        "injected router.forward close")
                faultinject.act_default(kind)
                s, rf = self._conn(pool, i)
                t0 = time.monotonic()
                s.sendall(b"".join(pending))
                saw_draining = False
                for _ in range(len(pending)):
                    resp = rf.readline()
                    if not resp:
                        raise ConnectionError(
                            "backend closed the connection")
                    if resp.startswith(b"!shed draining"):
                        saw_draining = True
                    out.append(resp)
                    answered += 1
                self._note_success(i, answered,
                                   time.monotonic() - t0)
                if saw_draining:
                    self._note_draining(i)
                return (self._retry_shed(rows, out, pool)
                        if _retry_shed else out)
            except (OSError, ConnectionError) as e:
                # in-order responses: answered rows in ``out`` stand
                # (credited to this backend); only the tail travels to
                # a peer. Crediting does NOT clear the failure streak —
                # _note_failure below still advances the ejection.
                pending = pending[answered:]
                if answered:
                    with self._mu:
                        b.rows += answered
                    self._rows_c.labels(endpoint=b.key).inc(answered)
                self._drop(pool, i)
                self._note_failure(i, attempts, e)
            finally:
                with self._mu:
                    b.in_flight -= n
        return out

    # ------------------------------------------------------ aggregation
    def _probe_json(self, b: _Backend, line: bytes) -> dict:
        """One-shot control call on a fresh connection (fresh on purpose:
        under a SO_REUSEPORT takeover it reaches whichever replica
        currently owns fresh connections — the thing a health poll is
        supposed to measure)."""
        s = socket.create_connection((b.host, b.port),
                                     timeout=self.probe_timeout)
        try:
            s.sendall(line + b"\n")
            rf = s.makefile("rb")
            resp = rf.readline()
            if not resp or resp.startswith(b"!err"):
                raise ConnectionError(
                    resp.rstrip(b"\n").decode() or "connection closed")
            return json.loads(resp)
        finally:
            try:
                s.close()
            except OSError:  # pragma: no cover
                pass

    def backends_snapshot(self) -> List[dict]:
        now = time.monotonic()
        with self._mu:
            return [{"endpoint": b.key, "in_flight": b.in_flight,
                     "ewma_ms": round(b.ewma_ms, 3), "fails": b.fails,
                     "ejected": b.down_until > now, "rows": b.rows,
                     "ejections": b.ejections}
                    for b in self._backends if not b.removed]

    def health_snapshot(self) -> dict:
        """Fleet-wide #health: ready while ANY replica is ready (that is
        what a router buys you), per-replica payloads attached so one
        poll shows which replica is the problem. ``server_id`` names
        WHICH group member answered — the roll driver dials the shared
        port until it holds a connection to the member it means."""
        replicas = []
        ready = queue_depth = 0
        live = self._live_backends()
        for b in live:
            try:
                h = self._probe_json(b, b"#health")
            except (OSError, ConnectionError, ValueError) as e:
                replicas.append({"endpoint": b.key, "error": str(e)})
                continue
            replicas.append(dict(h, endpoint=b.key))
            if h.get("status") == "ready":
                ready += 1
            queue_depth += int(h.get("queue_depth", 0))
        with self._cmu:
            draining = self._draining
            successor_file = self._successor_file
            successor_ready = self.successor_ready
        out = {"status": ("draining" if draining
                          else "ready" if ready else "down"),
               "router": True, "pid": os.getpid(),
               "server_id": f"router.{os.getpid()}.{id(self):x}",
               "takeover": self.takeover,
               "balance": self.balance,
               "replicas_live": ready,
               "replicas_total": len(live),
               "queue_depth": queue_depth,
               "replicas": replicas}
        if successor_file is not None:
            out["successor_ready"] = successor_ready
        return out

    def stats_snapshot(self) -> dict:
        """Router counters + balance state + the fleet's summed serving
        counters (each replica's #stats, best-effort)."""
        fleet: Dict[str, float] = {}
        replicas = []
        for b in self._live_backends():
            try:
                st = self._probe_json(b, b"#stats")
            except (OSError, ConnectionError, ValueError) as e:
                replicas.append({"endpoint": b.key, "error": str(e)})
                continue
            replicas.append(dict(st, endpoint=b.key))
            for k in ("requests", "responses", "shed", "errors",
                      "batches"):
                if k in st:
                    fleet[k] = fleet.get(k, 0) + st[k]
        with self._mu:
            rows = sum(b.rows for b in self._backends)
        return {"router": True,
                "rows": rows,
                "balance": self.balance,
                "retries": int(self._retry_c.value()),
                "shed": int(self._shed_c.value()),
                "errors": int(self._err_c.value()),
                "affinity_hits": int(self._aff_hit_c.value()),
                "affinity_misses": int(self._aff_miss_c.value()),
                "backends": self.backends_snapshot(),
                "fleet": fleet, "replicas": replicas}

    def metrics_text(self) -> str:
        """Prometheus text for ``#metrics``: the router registry
        (per-endpoint labeled forward counters + balance gauges) merged
        with the process-global registry (fault fires)."""
        from ..obs import REGISTRY, merge_into, render_prometheus
        now = time.monotonic()
        up = self.obs.gauge("router_backend_up",
                            "1 while the backend is not ejected")
        infl = self.obs.gauge("router_backend_in_flight",
                              "rows currently forwarded to the backend")
        ewma = self.obs.gauge("router_backend_ewma_ms",
                              "recent per-row backend latency (EWMA)")
        with self._mu:
            for b in self._backends:
                if b.removed:
                    continue
                up.labels(endpoint=b.key).set(
                    0.0 if b.down_until > now else 1.0)
                infl.labels(endpoint=b.key).set(b.in_flight)
                ewma.labels(endpoint=b.key).set(b.ewma_ms)
        hits = self._aff_hit_c.value()
        misses = self._aff_miss_c.value()
        self.obs.gauge(
            "router_affinity_hit_rate",
            "fraction of affinity forwards landing on the ring owner"
        ).set(hits / (hits + misses) if (hits + misses) else 0.0)
        snap = merge_into(self.obs.snapshot(), REGISTRY.snapshot())
        return render_prometheus(snap)

    # ----------------------------------------------------- handoff roll
    def _control_handoff(self, line: bytes) -> bytes:
        """``#handoff [ready_file]``: acknowledge, then wait for the
        successor's ready file and drain out of the SO_REUSEPORT group
        on a BACKGROUND thread — drain joins connection threads, so it
        must never run on the requesting connection's own thread.
        ``router.takeover`` is the chaos point: an injected err refuses
        the roll before any state changes."""
        try:
            faultinject.act_default(faultinject.fire("router.takeover"))
        except faultinject.FaultInjected as e:
            self._err_c.inc()
            return b"!err %s\n" % str(e).encode()
        arg = line[len(b"#handoff"):].strip().decode()
        if arg and self.ready_file and \
                os.path.abspath(arg) == os.path.abspath(self.ready_file):
            # the group port hashed this connection to the successor:
            # the named ready file is OUR OWN — refuse, the roll driver
            # redials until it holds a connection to the incumbent
            return (b"!err handoff addressed to the successor "
                    b"(this router owns the ready file)\n")
        with self._cmu:
            if self._handoff_thread is not None:
                return (json.dumps({"ok": True, "state": "draining"})
                        + "\n").encode()
            self._successor_file = arg
            t = threading.Thread(target=self._handoff, args=(arg,),
                                 name="router-handoff", daemon=True)
            self._handoff_thread = t
        t.start()
        return (json.dumps({"ok": True, "state": "handoff",
                            "successor_file": arg}) + "\n").encode()

    def _handoff(self, ready_file: str) -> None:
        """Wait (bounded by ``handoff_wait_s``) for the successor's
        ready file, then drain. An empty ready_file drains immediately —
        the autoscaler's scale-down primitive. A successor that never
        appears does not pin the incumbent: the handoff was an explicit
        operator request to leave, so after the budget we drain anyway —
        loudly."""
        ready = True
        if ready_file:
            end = time.monotonic() + self.handoff_wait_s
            while (not os.path.isfile(ready_file)
                   and time.monotonic() < end
                   and not self._drain_pending()):
                time.sleep(0.05)
            ready = os.path.isfile(ready_file)
            if not ready and not self._drain_pending():
                log.warning("router handoff: successor never became "
                            "ready (%s); draining anyway", ready_file)
        with self._cmu:
            self.successor_ready = ready
        log.info("router handoff: draining (successor_ready=%s)", ready)
        self.drain()

    def _control_backends(self, line: bytes) -> bytes:
        """``#backends [add|remove host:port]``: runtime ring
        membership — the autoscaler's nudge to every group member. A
        bare ``#backends`` just lists the live ring."""
        arg = line[len(b"#backends"):].strip().decode()
        if arg:
            parts = arg.split()
            if len(parts) != 2 or parts[0] not in ("add", "remove"):
                self._err_c.inc()
                return b"!err router: want add|remove host:port\n"
            try:
                host, port = parse_endpoints(parts[1])[0]
            except ValueError as e:
                self._err_c.inc()
                return b"!err router: %s\n" % str(e).encode()
            if parts[0] == "add":
                self._add_backend(host, port)
            else:
                self._remove_backend(host, port)
        return (json.dumps(
            {"ok": True,
             "server_id": f"router.{os.getpid()}.{id(self):x}",
             "backends": [b.key for b in self._live_backends()]})
            + "\n").encode()

    def _control(self, line: bytes) -> bytes:
        if line == b"#health":
            return (json.dumps(self.health_snapshot()) + "\n").encode()
        if line == b"#stats":
            return (json.dumps(self.stats_snapshot()) + "\n").encode()
        if line == b"#metrics":
            # multi-line payload, blank-line terminated (server.py
            # contract — ServeClient.metrics() works unchanged)
            return self.metrics_text().encode() + b"\n"
        if line == b"#handoff" or line.startswith(b"#handoff "):
            return self._control_handoff(line)
        if line == b"#backends" or line.startswith(b"#backends "):
            return self._control_backends(line)
        self._err_c.inc()
        return b"!err router: unsupported control %s\n" % line[:32]
