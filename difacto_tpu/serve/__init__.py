"""Online serving subsystem: low-latency batched inference (ISSUE 2)
with a resilient model lifecycle (ISSUE 3).

The missing vertical between "trains the model" and the north star's
"serves heavy traffic": load a trained model weights-only into a
read-only SlotStore (model.py — manifest-verified, walking back to the
newest good generation if the latest is torn), score through a small set
of pre-jitted shape-bucketed predict programs (executor.py — zero
steady-state recompiles), amortize accelerator dispatch over many small
requests with a dynamic micro-batcher (batcher.py — bounded queue,
explicit shed on overload), and speak newline-delimited data rows over
threaded TCP (server.py, client.py — retrying, with `#health` /
`#reload` control lines). Hot-reload swaps a newly-trained model in
without a restart (reload.py); SIGTERM drains admitted work and exits 0
(server.py drain). The continuity layer (ISSUE 5) removes the last
restarts: a geometry-changing reload runs a blue/green executor swap
(reload.py), `#handoff` + SO_REUSEPORT hand the port to a successor
process with zero dropped traffic (server.py, tools/takeover.py), and
ServeClient fails over across a replica endpoint list (client.py).
The fleet layer (ISSUE 6) scales continuity from one replica pair to N:
a health-gated rolling-restart orchestrator replaces replicas one at a
time and aborts on any `#health` regression (fleet.py, tools/fleet.py),
a thin router balances rows with power-of-two-choices and retries
unanswered tails on a peer (router.py), and a shared advisory-locked
blacklist file propagates one client's endpoint ejection to the whole
fleet (fleethealth.py). ``task=serve`` (__main__.py) is the CLI entry;
tools/loadgen.py drives it open-loop; bench.py --serve tracks the
latency/throughput/resilience trajectory; tests/test_chaos.py proves the
failure paths under injected faults (utils/faultinject.py).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..config import KWArgs, Param
from ..utils.manifest import CheckpointCorrupt
from .autoscale import Autoscaler
from .batcher import MicroBatcher, ServeStats
from .client import ServeClient
from .executor import PredictExecutor, sigmoid
from .fleet import (HealthGate, drain_endpoint, notify_backends,
                    run_rolling_restart, run_router_group_roll,
                    run_takeover)
from .fleethealth import FleetHealth
from .model import model_meta, open_serving_store, resolve_model_path
from .reload import ModelReloader
from .router import RouterServer
from .server import ServeServer

log = logging.getLogger("difacto_tpu")


@dataclass
class ServeParam(Param):
    """task=serve knobs (docs/serving.md)."""
    model_in: str = ""
    serve_host: str = "127.0.0.1"
    serve_port: int = 0                 # 0 = ephemeral, logged at startup
    # flush a micro-batch at this many rows ...
    serve_batch_size: int = field(default=256, metadata=dict(lo=1))
    # ... or when the oldest queued request has waited this long
    serve_max_delay_ms: float = field(default=2.0, metadata=dict(lo=0))
    # admission bound, in ROWS of queued work; beyond it requests shed
    serve_queue_cap: int = field(default=1024, metadata=dict(lo=1))
    # reject single rows wider than this before they reach the executor
    # (bounds the shape buckets a hostile/buggy client can compile)
    serve_max_row_nnz: int = field(default=4096, metadata=dict(lo=1))
    # throttle for the reporter stats row (seconds)
    serve_report_every: float = 30.0
    # exit after this many seconds; 0 = serve until interrupted
    serve_max_seconds: float = 0.0
    # write "host port\n" here once listening (scripts/tests poll it)
    serve_ready_file: str = ""
    # graceful shutdown: on SIGTERM/SIGINT stop accepting, answer new
    # rows "!shed draining", wait this long for admitted work to
    # resolve, then exit 0 (serve/server.py drain)
    serve_drain_timeout_s: float = field(default=10.0, metadata=dict(lo=0))
    # hot-reload watcher: poll model_in every this many seconds and swap
    # a new generation in without a restart (0 = off; `#reload` over the
    # wire works either way — serve/reload.py)
    serve_reload_poll_s: float = field(default=0.0, metadata=dict(lo=0))
    # bind the listening socket SO_REUSEPORT so a successor process can
    # bind the SAME port while this replica drains (`#handoff`,
    # tools/takeover.py). Every replica of a takeover pair needs it set,
    # incumbent included — the kernel rejects mixed bindings.
    serve_takeover: bool = False
    # `#handoff <ready_file>`: wait at most this long for the successor
    # before draining anyway (the handoff asked this replica to leave)
    serve_handoff_wait_s: float = field(default=30.0, metadata=dict(lo=0))
    # online continuous learning (online/, docs/serving.md "Continuous
    # learning"): append every served row to this training-log
    # directory; the tailing trainer (task=online) consumes it. Empty =
    # no logging. NOTE: one log instance per directory — CLI replicas
    # need per-replica directories (or share one in-process OnlineLog
    # built by the embedding harness, as bench/tests do).
    online_log_dir: str = ""
    # rows per sealed rec2 segment
    online_segment_rows: int = field(default=256, metadata=dict(lo=1))
    # feedback-join horizon: how long a served row waits for its
    # delayed label before resolving to the default
    label_delay_s: float = field(default=1.0, metadata=dict(lo=0))
    # what an unlabeled row becomes past the horizon: drop it, or keep
    # it with label 0 (the ad-click non-click convention)
    label_default: str = field(default="negative", metadata=dict(
        enum=["drop", "negative"]))
    data_format: str = "libsvm"
    pred_prob: bool = True


def run_serve(kwargs: KWArgs) -> KWArgs:
    """CLI entry for task=serve (__main__.py): build the read-only store
    from the model file's own metadata (walking back to the newest
    generation that verifies if the latest is torn), start the server
    with the hot-reload and drain machinery attached, block. SIGTERM and
    SIGINT trigger a graceful drain and a zero exit so orchestrators see
    a clean rotation, not a crash."""
    import signal
    import threading

    param, remain = ServeParam.init_allow_unknown(kwargs)
    if not param.model_in:
        raise ValueError("please set model_in")
    # the store-construction kwargs (updater overrides + serve_mesh_fs)
    # also go to the reloader: a hot reload must rebuild the SAME store
    # geometry — in particular the same fs-sharded mesh — or the swap
    # would silently de-shard the table
    store_kwargs = list(remain)
    store, meta, remain = open_serving_store(param.model_in, remain)
    online_log = None
    if param.online_log_dir:
        from ..online.log import OnlineLog
        online_log = OnlineLog(param.online_log_dir,
                               segment_rows=param.online_segment_rows,
                               label_delay_s=param.label_delay_s,
                               label_default=param.label_default)
    server = ServeServer(
        store, host=param.serve_host, port=param.serve_port,
        batch_size=param.serve_batch_size,
        max_delay_ms=param.serve_max_delay_ms,
        queue_cap=param.serve_queue_cap,
        pred_prob=param.pred_prob, data_format=param.data_format,
        max_row_nnz=param.serve_max_row_nnz,
        report_every_s=param.serve_report_every,
        drain_timeout_s=param.serve_drain_timeout_s,
        takeover=param.serve_takeover,
        handoff_wait_s=param.serve_handoff_wait_s,
        online_log=online_log)
    server.ready_file = param.serve_ready_file
    # server= attaches the blue/green path: a geometry-changing reload
    # warms a second executor and swaps it under the batcher instead of
    # failing (serve/reload.py)
    reloader = ModelReloader(server.executor, param.model_in,
                             poll_s=param.serve_reload_poll_s,
                             kwargs=store_kwargs, server=server)
    server.reloader = reloader
    # signal.signal only works on the main thread; tests drive run_serve
    # from worker threads and manage shutdown themselves
    if threading.current_thread() is threading.main_thread():
        def _graceful(signum, _frame):
            log.info("signal %d: draining (timeout %.1fs)", signum,
                     param.serve_drain_timeout_s)
            server.drain()
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    server.start()
    reloader.start()
    if param.serve_ready_file:
        from ..utils import stream
        with stream.open_stream(param.serve_ready_file, "w") as f:
            f.write(f"{server.host} {server.port}\n")
    try:
        server.wait(param.serve_max_seconds or None)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        log.info("interrupted; shutting down")
    finally:
        reloader.close()
        server.close()
        if online_log is not None:
            # flush, do NOT end(): a restarting replica must not
            # terminate the trainer's tail — only the operator (or the
            # harness driving the loop) ends the log
            online_log.flush()
        log.info("serve done: %s", server.stats_snapshot())
    return remain


__all__ = ["ServeParam", "run_serve", "ServeServer", "ServeClient",
           "PredictExecutor", "MicroBatcher", "ServeStats", "sigmoid",
           "model_meta", "open_serving_store", "resolve_model_path",
           "ModelReloader", "CheckpointCorrupt", "RouterServer",
           "FleetHealth", "HealthGate", "run_rolling_restart",
           "run_takeover", "Autoscaler", "run_router_group_roll",
           "notify_backends", "drain_endpoint"]
