"""Python client for the serving front-end (serve/server.py), with
transparent retry and multi-endpoint failover.

Speaks the newline protocol: send data rows, read one response line per
row in order. ``predict`` returns probabilities (or raw margins when the
server runs pred_prob=false) as floats.

Resilience contract (the client half of the serve lifecycle):

- **connect/read failures retry** with capped exponential backoff + full
  jitter, up to ``retries`` reconnect attempts PER ENDPOINT and never
  past the per-call ``deadline_s``. Responses arrive in request order,
  so on a dropped connection the client knows exactly which rows were
  answered and resends only the tail (scoring is pure — a row scored
  twice server-side is harmless).
- **multi-endpoint failover**: construct with ``endpoints=`` (a list of
  ``(host, port)`` pairs or an ``"h1:p1,h2:p2"`` string —
  config.parse_endpoints) and a failure fails the unanswered tail over
  to the NEXT replica immediately, no backoff nap while a healthy
  replica is available. Per-endpoint health is tracked: ``eject_after``
  consecutive failures eject an endpoint for ``reprobe_s`` seconds
  (timed re-probe — the first use after the window IS the probe); when
  every endpoint is ejected the least-recently-ejected one is tried
  anyway (a client never deadlocks itself into "no replicas").
- **shared endpoint health**: pass ``blacklist=`` (a path or a
  fleethealth.FleetHealth handle) and ejections propagate fleet-wide —
  this client seeds its ejection windows from the shared file at
  construction (a blacklisted endpoint is skipped on the FIRST connect,
  no timeout paid) and on every failover, writes its own ejections
  down, and clears an entry early when its re-probe succeeds. The
  router (serve/router.py) reads and writes the same file, so one
  discovery of a dead replica serves every client.
- ``!shed`` (queue full, or a draining replica) is **retryable**: the
  server explicitly asked for the row again later, so ``predict`` backs
  off and resends just the shed rows within the same budget.
- ``!err`` (malformed row, oversized row, executor error) is **not
  retryable**: the same bytes would fail the same way; it surfaces as
  None immediately.

``retries=0`` (default) keeps the old fail-fast behavior byte-for-byte
for a single endpoint; with N endpoints it means one try per replica.
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import List, Optional, Sequence, Union

from ..config import parse_endpoints

Line = Union[str, bytes]


def _to_bytes(line: Line) -> bytes:
    b = line.encode() if isinstance(line, str) else line
    return b if b.endswith(b"\n") else b + b"\n"


class _Endpoint:
    """Per-replica health: consecutive failures + ejection window, plus
    the per-endpoint tallies a rollout chaos run reads back (which
    replica absorbed the handoff traffic)."""

    __slots__ = ("host", "port", "fails", "down_until", "rows",
                 "ejections")

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.fails = 0
        self.down_until = 0.0
        self.rows = 0         # response lines answered by this endpoint
        self.ejections = 0    # times the ejection window opened


class ServeClient:
    def __init__(self, host: Optional[str] = None,
                 port: Optional[int] = None, timeout: float = 60.0,
                 retries: int = 0, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 deadline_s: Optional[float] = None,
                 endpoints=None, eject_after: int = 3,
                 reprobe_s: float = 5.0, blacklist=None):
        from .fleethealth import open_blacklist
        if endpoints is not None:
            eps = parse_endpoints(endpoints)
        elif host is not None and port is not None:
            eps = [(host, int(port))]
        else:
            raise ValueError("pass host+port or endpoints=[(h, p), ...]")
        self._eps = [_Endpoint(h, p) for h, p in eps]
        self._cur = 0
        self.eject_after = eject_after
        self.reprobe_s = reprobe_s
        self.blacklist = open_blacklist(blacklist, down_s=reprobe_s)
        self._bl_stamp = None
        # seed ejection windows from the fleet's shared discoveries and
        # start on a replica nobody has marked down — a blacklisted
        # endpoint is skipped on the FIRST connect, before any timeout
        self._refresh_blacklist()
        now = time.monotonic()
        for k, ep in enumerate(self._eps):
            if ep.down_until <= now:
                self._cur = k
                break
        self.failovers = 0           # times the active endpoint moved
        self.timeout = timeout
        self.retries = retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.deadline_s = deadline_s
        self._rng = random.Random(0x5E12E)
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        # constructor connect honors the same retry/failover budget: a
        # client racing a replica restart should wait for it, not crash
        self._ensure_conn(self._deadline(), {})

    # ------------------------------------------------------------- conn
    @property
    def host(self) -> str:
        """Host of the endpoint currently in use."""
        return self._eps[self._cur].host

    @property
    def port(self) -> int:
        return self._eps[self._cur].port

    def endpoints_health(self) -> List[dict]:
        """Per-endpoint view: rows answered, consecutive failures,
        ejection count/state — what a fleet debugger (and
        tools/loadgen.py --endpoints) prints when a replica list
        degrades: which replica absorbed the traffic, which got
        ejected."""
        now = time.monotonic()
        return [{"host": e.host, "port": e.port, "rows": e.rows,
                 "fails": e.fails, "ejections": e.ejections,
                 "ejected": e.down_until > now,
                 "active": i == self._cur}
                for i, e in enumerate(self._eps)]

    def _absorb_blacklist(self) -> None:
        """Fold fleet-wide down marks into the local ejection windows —
        another client's consecutive-failure discovery suppresses the
        endpoint here without this client ever dialing it."""
        if self.blacklist is None:
            return
        downs = self.blacklist.down_endpoints()
        if not downs:
            return
        now = time.monotonic()
        for ep in self._eps:
            rem = downs.get(f"{ep.host}:{ep.port}", 0.0)
            if rem > 0:
                ep.down_until = max(ep.down_until, now + rem)

    def _refresh_blacklist(self) -> None:
        """Absorb only when the shared file actually MOVED — one os.stat
        per endpoint selection. This closes the PR 6 seed-once bug: a
        long-lived client (the online loop's push_reload) folded the
        blacklist at construction and on failover only, so marks written
        after it connected never reached it; now every reconnect path
        re-folds on a ``(mtime, size)`` change."""
        if self.blacklist is None:
            return
        stamp = self.blacklist.stamp()
        if stamp == self._bl_stamp:
            return
        # lint: ok(data-race) single-owner instance (see _failover)
        self._bl_stamp = stamp
        self._absorb_blacklist()

    def _deadline(self) -> Optional[float]:
        return (time.monotonic() + self.deadline_s
                if self.deadline_s is not None else None)

    def _backoff(self, attempt: int, deadline: Optional[float]) -> None:
        """Sleep exp(attempt) * jitter, capped; raises ConnectionError
        instead of sleeping past the deadline (fail before burning the
        caller's whole budget on a nap)."""
        delay = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        delay *= 0.5 + self._rng.random()  # full jitter in [0.5, 1.5)
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ConnectionError(
                    f"deadline_s={self.deadline_s} exhausted retrying "
                    f"{self.host}:{self.port}")
            delay = min(delay, remaining)
        time.sleep(delay)

    def _note_success(self, rows: int = 0) -> None:
        ep = self._eps[self._cur]
        was_down = ep.down_until > 0.0
        ep.fails = 0
        ep.down_until = 0.0
        ep.rows += rows
        if was_down and self.blacklist is not None:
            # the re-probe succeeded: clear the entry fleet-wide early
            # instead of every client waiting out its own window
            self.blacklist.mark_up(ep.host, ep.port)

    def _failover(self, attempts: dict, deadline: Optional[float],
                  err: BaseException) -> None:
        """Record a failure on the active endpoint and pick the next one
        for this call. Ejects the endpoint after ``eject_after``
        consecutive failures (re-probed after ``reprobe_s``). Moving to
        a fresh replica is immediate; re-trying one already attempted
        this call backs off on ITS attempt count (per-endpoint backoff
        semantics). Re-raises ``err`` once every endpoint is out of
        budget."""
        i = self._cur
        ep = self._eps[i]
        ep.fails += 1
        if ep.fails >= self.eject_after:
            if ep.down_until <= time.monotonic():
                ep.ejections += 1
                if self.blacklist is not None:
                    # first discovery: every other client/router reading
                    # the shared file now skips this endpoint
                    self.blacklist.mark_down(ep.host, ep.port)
            ep.down_until = time.monotonic() + self.reprobe_s
        self._refresh_blacklist()  # learn the fleet's discoveries too
        attempts[i] = attempts.get(i, 0) + 1
        n = len(self._eps)
        order = [(i + k) % n for k in range(1, n + 1)]  # others first
        cands = [j for j in order if attempts.get(j, 0) <= self.retries]
        if not cands:
            raise err
        now = time.monotonic()
        healthy = [j for j in cands if self._eps[j].down_until <= now]
        j = healthy[0] if healthy else \
            min(cands, key=lambda k: self._eps[k].down_until)
        if j != i:
            # lint: ok(data-race) a ServeClient instance is owned by ONE
            # thread; the roots are distinct instances (counter likewise)
            self.failovers += 1
        self._cur = j  # lint: ok(data-race) single-owner instance
        a = attempts.get(j, 0)
        if a > 0:
            self._backoff(a - 1, deadline)

    def _drop_conn(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            # lint: ok(data-race) single-owner instance (see _failover)
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            # lint: ok(data-race) single-owner instance (see _failover)
            self._sock = None

    def _ensure_conn(self, deadline: Optional[float],
                     attempts: Optional[dict] = None) -> None:
        if self._sock is not None:
            return
        if attempts is None:
            attempts = {}
        # a mark that arrived since we last looked side-steps the
        # current endpoint WITHOUT burning a failure or a failover on
        # it — the fleet already paid that discovery, we just route
        # around it before dialing
        self._refresh_blacklist()
        now = time.monotonic()
        if self._eps[self._cur].down_until > now:
            n = len(self._eps)
            for j in ((self._cur + k) % n for k in range(1, n)):
                if self._eps[j].down_until <= now:
                    # lint: ok(data-race) single-owner instance (see
                    # _failover)
                    self._cur = j
                    break
        while True:
            ep = self._eps[self._cur]
            try:
                self._sock = socket.create_connection(
                    (ep.host, ep.port), timeout=self.timeout)
                try:
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                except OSError:  # pragma: no cover
                    pass
                self._rfile = self._sock.makefile("rb")
                return
            except OSError as e:
                self._drop_conn()
                self._failover(attempts, deadline, e)

    # ------------------------------------------------------------- io
    def score_lines(self, lines: Sequence[Line]) -> List[bytes]:
        """Pipeline a batch of request rows; returns the raw response
        line per row (no trailing newline), in request order. For very
        large batches prefer several calls — the whole request block is
        written before responses are drained. Reconnects — to the next
        replica when more than one endpoint is configured — and resends
        the unanswered tail on connection failures (module docstring)."""
        pending = [_to_bytes(l) for l in lines]
        out: List[bytes] = []
        deadline = self._deadline()
        attempts: dict = {}
        while pending:
            answered = 0
            try:
                self._ensure_conn(deadline, attempts)
                self._sock.sendall(b"".join(pending))
                for _ in range(len(pending)):
                    resp = self._rfile.readline()
                    if not resp:
                        raise ConnectionError(
                            "server closed the connection")
                    out.append(resp.rstrip(b"\n"))
                    answered += 1
                self._note_success(answered)
                return out
            except (OSError, ConnectionError) as e:
                # in-order responses: rows already appended to ``out``
                # are answered for good (credited to the endpoint that
                # answered them); only the tail resends
                pending = pending[answered:]
                self._eps[self._cur].rows += answered
                self._drop_conn()
                self._failover(attempts, deadline, e)
        return out

    def predict(self, lines: Sequence[Line]) -> List[Optional[float]]:
        """Scores per row; None where the server rejected the row
        (``!err`` — not retryable) or kept shedding it past the retry
        budget (``!shed`` — retried with backoff when ``retries`` > 0;
        inspect score_lines for raw reasons)."""
        out: List[Optional[float]] = [None] * len(lines)
        todo = list(range(len(lines)))
        deadline = self._deadline()
        attempt = 0
        while todo:
            resp = self.score_lines([lines[i] for i in todo])
            shed = []
            for i, r in zip(todo, resp):
                if r.startswith(b"!shed"):
                    shed.append(i)
                elif not r.startswith(b"!err"):
                    out[i] = float(r)
            if not shed or attempt >= self.retries:
                break
            try:
                self._backoff(attempt, deadline)
            except ConnectionError:
                break   # deadline spent: exhausted sheds surface as None
            attempt += 1
            todo = shed
        return out

    def stats(self) -> dict:
        """The server's live serving + executor counters (#stats)."""
        return json.loads(self.score_lines([b"#stats"])[0])

    def health(self) -> dict:
        """Readiness + queue depth (#health) — what a load balancer
        polls to rotate a draining replica out before it exits."""
        return json.loads(self.score_lines([b"#health"])[0])

    def metrics(self) -> str:
        """Prometheus-format metric text (#metrics): the one multi-line
        control reply — the server terminates it with a single blank
        line (the exposition format never emits blank lines itself), so
        this reads until that sentinel instead of one line per request."""
        deadline = self._deadline()
        attempts: dict = {}
        while True:
            try:
                self._ensure_conn(deadline, attempts)
                self._sock.sendall(b"#metrics\n")
                lines = []
                while True:
                    resp = self._rfile.readline()
                    if not resp:
                        raise ConnectionError(
                            "server closed the connection")
                    if resp == b"\n":
                        self._note_success()
                        return b"".join(lines).decode()
                    if not lines and resp.startswith(b"!err"):
                        raise RuntimeError(resp.rstrip(b"\n").decode())
                    lines.append(resp)
            except (OSError, ConnectionError) as e:
                self._drop_conn()
                self._failover(attempts, deadline, e)

    def reload(self, path: Optional[str] = None) -> dict:
        """Trigger a synchronous model hot-reload (#reload [path]);
        returns the server's {'ok', 'model_generation'|'error'} verdict."""
        line = b"#reload" if path is None else b"#reload " + path.encode()
        return json.loads(self.score_lines([line])[0])

    def handoff(self, ready_file: str = "") -> dict:
        """Ask THIS connection's replica to hand its port off
        (#handoff): it waits for ``ready_file`` (the successor's
        serve_ready_file), then drains. Hold the connection open from
        before the successor binds so the request provably reaches the
        incumbent (tools/takeover.py)."""
        line = b"#handoff" + (b" " + ready_file.encode()
                              if ready_file else b"")
        return json.loads(self.score_lines([line])[0])

    def close(self) -> None:
        self._drop_conn()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
