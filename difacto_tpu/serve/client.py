"""Python client for the serving front-end (serve/server.py), with
transparent retry.

Speaks the newline protocol: send data rows, read one response line per
row in order. ``predict`` returns probabilities (or raw margins when the
server runs pred_prob=false) as floats.

Resilience contract (the client half of the serve lifecycle):

- **connect/read failures retry** with capped exponential backoff + full
  jitter, up to ``retries`` reconnect attempts per call and never past
  the per-call ``deadline_s``. Responses arrive in request order, so on a
  dropped connection the client knows exactly which rows were answered
  and resends only the tail (scoring is pure — a row scored twice
  server-side is harmless).
- ``!shed`` (queue full, or a draining replica) is **retryable**: the
  server explicitly asked for the row again later, so ``predict`` backs
  off and resends just the shed rows within the same budget.
- ``!err`` (malformed row, oversized row, executor error) is **not
  retryable**: the same bytes would fail the same way; it surfaces as
  None immediately.

``retries=0`` (default) keeps the old fail-fast behavior byte-for-byte.
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import List, Optional, Sequence, Union

Line = Union[str, bytes]


def _to_bytes(line: Line) -> bytes:
    b = line.encode() if isinstance(line, str) else line
    return b if b.endswith(b"\n") else b + b"\n"


class ServeClient:
    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 retries: int = 0, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 deadline_s: Optional[float] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.retries = retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.deadline_s = deadline_s
        self._rng = random.Random(0x5E12E)
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        # constructor connect honors the same retry budget: a client
        # racing a replica restart should wait for it, not crash
        self._ensure_conn(self._deadline())

    # ------------------------------------------------------------- conn
    def _deadline(self) -> Optional[float]:
        return (time.monotonic() + self.deadline_s
                if self.deadline_s is not None else None)

    def _backoff(self, attempt: int, deadline: Optional[float]) -> None:
        """Sleep exp(attempt) * jitter, capped; raises ConnectionError
        instead of sleeping past the deadline (fail before burning the
        caller's whole budget on a nap)."""
        delay = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        delay *= 0.5 + self._rng.random()  # full jitter in [0.5, 1.5)
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ConnectionError(
                    f"deadline_s={self.deadline_s} exhausted retrying "
                    f"{self.host}:{self.port}")
            delay = min(delay, remaining)
        time.sleep(delay)

    def _drop_conn(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _ensure_conn(self, deadline: Optional[float]) -> None:
        if self._sock is not None:
            return
        attempt = 0
        while True:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                try:
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                except OSError:  # pragma: no cover
                    pass
                self._rfile = self._sock.makefile("rb")
                return
            except OSError:
                self._drop_conn()
                if attempt >= self.retries:
                    raise
                self._backoff(attempt, deadline)
                attempt += 1

    # ------------------------------------------------------------- io
    def score_lines(self, lines: Sequence[Line]) -> List[bytes]:
        """Pipeline a batch of request rows; returns the raw response
        line per row (no trailing newline), in request order. For very
        large batches prefer several calls — the whole request block is
        written before responses are drained. Reconnects and resends the
        unanswered tail on connection failures (see module docstring)."""
        pending = [_to_bytes(l) for l in lines]
        out: List[bytes] = []
        deadline = self._deadline()
        attempt = 0
        while pending:
            answered = 0
            try:
                self._ensure_conn(deadline)
                self._sock.sendall(b"".join(pending))
                for _ in range(len(pending)):
                    resp = self._rfile.readline()
                    if not resp:
                        raise ConnectionError(
                            "server closed the connection")
                    out.append(resp.rstrip(b"\n"))
                    answered += 1
                return out
            except (OSError, ConnectionError):
                # in-order responses: rows already appended to ``out``
                # are answered for good; only the tail resends
                pending = pending[answered:]
                self._drop_conn()
                if attempt >= self.retries:
                    raise
                self._backoff(attempt, deadline)
                attempt += 1
        return out

    def predict(self, lines: Sequence[Line]) -> List[Optional[float]]:
        """Scores per row; None where the server rejected the row
        (``!err`` — not retryable) or kept shedding it past the retry
        budget (``!shed`` — retried with backoff when ``retries`` > 0;
        inspect score_lines for raw reasons)."""
        out: List[Optional[float]] = [None] * len(lines)
        todo = list(range(len(lines)))
        deadline = self._deadline()
        attempt = 0
        while todo:
            resp = self.score_lines([lines[i] for i in todo])
            shed = []
            for i, r in zip(todo, resp):
                if r.startswith(b"!shed"):
                    shed.append(i)
                elif not r.startswith(b"!err"):
                    out[i] = float(r)
            if not shed or attempt >= self.retries:
                break
            try:
                self._backoff(attempt, deadline)
            except ConnectionError:
                break   # deadline spent: exhausted sheds surface as None
            attempt += 1
            todo = shed
        return out

    def stats(self) -> dict:
        """The server's live serving + executor counters (#stats)."""
        return json.loads(self.score_lines([b"#stats"])[0])

    def health(self) -> dict:
        """Readiness + queue depth (#health) — what a load balancer
        polls to rotate a draining replica out before it exits."""
        return json.loads(self.score_lines([b"#health"])[0])

    def metrics(self) -> str:
        """Prometheus-format metric text (#metrics): the one multi-line
        control reply — the server terminates it with a single blank
        line (the exposition format never emits blank lines itself), so
        this reads until that sentinel instead of one line per request."""
        deadline = self._deadline()
        attempt = 0
        while True:
            try:
                self._ensure_conn(deadline)
                self._sock.sendall(b"#metrics\n")
                lines = []
                while True:
                    resp = self._rfile.readline()
                    if not resp:
                        raise ConnectionError(
                            "server closed the connection")
                    if resp == b"\n":
                        return b"".join(lines).decode()
                    if not lines and resp.startswith(b"!err"):
                        raise RuntimeError(resp.rstrip(b"\n").decode())
                    lines.append(resp)
            except (OSError, ConnectionError):
                self._drop_conn()
                if attempt >= self.retries:
                    raise
                self._backoff(attempt, deadline)
                attempt += 1

    def reload(self, path: Optional[str] = None) -> dict:
        """Trigger a synchronous model hot-reload (#reload [path]);
        returns the server's {'ok', 'model_generation'|'error'} verdict."""
        line = b"#reload" if path is None else b"#reload " + path.encode()
        return json.loads(self.score_lines([line])[0])

    def close(self) -> None:
        self._drop_conn()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
