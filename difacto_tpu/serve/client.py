"""Tiny Python client for the serving front-end (serve/server.py).

Speaks the newline protocol: send data rows, read one response line per
row in order. ``predict`` returns probabilities (or raw margins when the
server runs pred_prob=false) as floats; shed/error responses surface as
None entries so callers can retry just those rows.
"""

from __future__ import annotations

import json
import socket
from typing import List, Optional, Sequence, Union

Line = Union[str, bytes]


def _to_bytes(line: Line) -> bytes:
    b = line.encode() if isinstance(line, str) else line
    return b if b.endswith(b"\n") else b + b"\n"


class ServeClient:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover
            pass
        self._rfile = self._sock.makefile("rb")

    # ------------------------------------------------------------- io
    def score_lines(self, lines: Sequence[Line]) -> List[bytes]:
        """Pipeline a batch of request rows; returns the raw response
        line per row (no trailing newline), in request order. For very
        large batches prefer several calls — the whole request block is
        written before responses are drained."""
        payload = b"".join(_to_bytes(l) for l in lines)
        self._sock.sendall(payload)
        out = []
        for _ in range(len(lines)):
            resp = self._rfile.readline()
            if not resp:
                raise ConnectionError("server closed the connection")
            out.append(resp.rstrip(b"\n"))
        return out

    def predict(self, lines: Sequence[Line]) -> List[Optional[float]]:
        """Scores per row; None where the server shed or rejected the
        row (inspect score_lines for the reason)."""
        out: List[Optional[float]] = []
        for resp in self.score_lines(lines):
            out.append(None if resp.startswith((b"!shed", b"!err"))
                       else float(resp))
        return out

    def stats(self) -> dict:
        """The server's live serving + executor counters (#stats)."""
        return json.loads(self.score_lines([b"#stats"])[0])

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
